"""Thread-root discovery: every entry point concurrency can start from.

A *root* is a function some mechanism runs on its own thread (or
asynchronously on an existing one): ``threading.Thread(target=)``,
``.submit()`` worker functions, ``BaseHTTPRequestHandler`` ``do_*``
handlers under a ``ThreadingHTTPServer``, ``signal.signal`` hooks, plus
the two declared mains (the pipeline loop and the daemon loop). Lockset
traversal (:mod:`.locksets`) starts from each root with an EMPTY held
set — a worker never inherits its spawner's locks.

Unresolvable targets (e.g. ``target=self._httpd.serve_forever`` — stdlib
code) still appear in the inventory with ``func=None`` so the README's
thread-root table and ``--json`` consumers see every spawn site, but
nothing is traversed for them.
"""

from __future__ import annotations

import ast
import dataclasses

from tools.graftlint.astutil import dotted_name
from tools.graftrace.index import FuncInfo, Index

#: qname suffixes that are roots by declaration: the pipeline's per-run
#: body (owns arming, the stage loop, every guard) and the daemon loop
MAIN_ROOTS = (
    ("pipeline.run._run_with_config", "pipeline-loop"),
    ("serve.daemon.Daemon.serve_forever", "daemon-loop"),
)


@dataclasses.dataclass(frozen=True)
class Root:
    name: str            # stable display name, e.g. "thread:Watchdog._monitor"
    kind: str            # main | thread | pool | http | signal
    func: str | None     # qname of the entry FuncInfo (None: external code)
    path: str
    line: int

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _is_http_handler(index: Index, cls: str) -> bool:
    return any(b.rsplit(".", 1)[-1] == "BaseHTTPRequestHandler"
               for b in index.class_bases.get(cls, ()))


def discover_roots(index: Index) -> list[Root]:
    roots: dict[tuple[str, str | None], Root] = {}

    def add(kind: str, func: FuncInfo | None, path: str, line: int,
            fallback: str = "?") -> None:
        label = func.short if func is not None else f"<external {fallback}>"
        root = Root(f"{kind}:{label}", kind,
                    func.qname if func else None, path, line)
        roots.setdefault((kind, root.name), root)

    # declared mains
    for suffix, label in MAIN_ROOTS:
        for qname, fi in index.funcs.items():
            if qname.endswith(suffix):
                r = Root(f"main:{label}", "main", qname,
                         fi.ctx.path, fi.node.lineno)
                roots.setdefault(("main", r.name), r)

    # http handler methods
    for cls, (node, ctx, _mod) in index.classes.items():
        if not _is_http_handler(index, cls):
            continue
        for method in node.body:
            if isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and method.name.startswith("do_"):
                fi = index.methods[(cls, method.name)]
                add("http", fi, ctx.path, method.lineno)

    # spawn sites: Thread(target=), .submit(fn), signal.signal(sig, fn)
    for fi in index.funcs.values():
        ltypes = index.local_types(fi)
        for call in ast.walk(fi.node):
            if not isinstance(call, ast.Call):
                continue
            full = fi.imports.resolve_call_target(call.func) or ""
            if full.endswith("threading.Thread") or full == "Thread":
                target = next((kw.value for kw in call.keywords
                               if kw.arg == "target"), None)
                if target is not None:
                    hit = index.resolve_callable(target, fi, ltypes)
                    add("thread", hit, fi.ctx.path, call.lineno,
                        fallback=dotted_name(target) or "?")
            elif isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "submit":
                for arg in call.args:
                    hit = index.resolve_callable(arg, fi, ltypes)
                    if hit is not None:
                        add("pool", hit, fi.ctx.path, call.lineno)
            elif full.endswith("signal.signal") and len(call.args) >= 2:
                hit = index.resolve_callable(call.args[1], fi, ltypes)
                if hit is not None:
                    add("signal", hit, fi.ctx.path, call.lineno)

    return sorted(roots.values(), key=lambda r: (r.kind, r.name))
