"""graftrace CLI — graftcheck's ``--expect`` discipline over race findings.

Exit codes: 0 clean, 1 findings or expected-list drift (either
direction), 2 internal/usage error. Never a traceback.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.graftlint.core import Project, apply_baseline, load_baseline
from tools.graftrace.callgraph import discover_roots
from tools.graftrace.index import Index
from tools.graftrace.locksets import Analyzer

DEFAULT_EXPECT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "expected_findings.json")
DEFAULT_PATHS = ["ont_tcrconsensus_tpu"]


def analyze_paths(paths: list[str]):
    """(findings, roots) for a tree — the library entry point."""
    project = Project(paths)
    index = Index(project)
    roots = discover_roots(index)
    analyzer = Analyzer(index, roots)
    analyzer.run()
    findings = sorted(analyzer.findings,
                      key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, roots


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftrace",
        description="whole-program static race & deadlock analysis "
                    "(see tools/graftrace/__init__.py)",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files or directories (default: {DEFAULT_PATHS})")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output (body carries exit_code)")
    ap.add_argument("--roots", action="store_true", dest="roots_only",
                    help="print the thread-root inventory and exit")
    ap.add_argument("--expect", nargs="?", const=DEFAULT_EXPECT,
                    help="compare findings against an expected list "
                         "(default: the committed one); findings matching "
                         "an entry pass, NEW findings and stale entries "
                         "both fail")
    ap.add_argument("--write-expect", metavar="FILE",
                    help="write the current findings as the expected list "
                         "(add a justification: to each entry before "
                         "committing)")
    try:
        args = ap.parse_args(argv)
    except SystemExit as exc:  # argparse exits 2 on usage errors
        return int(exc.code or 0)

    try:
        paths = args.paths or DEFAULT_PATHS
        for path in paths:
            if not os.path.exists(path):
                print(f"graftrace: no such path: {path}", file=sys.stderr)
                return 2

        findings, roots = analyze_paths(paths)

        if args.roots_only:
            if args.as_json:
                print(json.dumps({"roots": [r.to_dict() for r in roots]},
                                 indent=2))
            else:
                for r in roots:
                    print(f"{r.kind:7s} {r.name:45s} {r.path}:{r.line}")
            return 0

        if args.write_expect:
            with open(args.write_expect, "w", encoding="utf-8") as fh:
                json.dump({"findings": [
                    {**f.to_dict(), "justification": ""} for f in findings
                ]}, fh, indent=2)
                fh.write("\n")
            print(f"graftrace: wrote {len(findings)} finding(s) to "
                  f"{args.write_expect}", file=sys.stderr)
            return 0

        baselined, stale = [], set()
        if args.expect:
            try:
                known = load_baseline(args.expect)
            except (OSError, ValueError) as exc:
                print(f"graftrace: cannot read expected list "
                      f"{args.expect}: {exc}", file=sys.stderr)
                return 2
            findings, baselined, stale = apply_baseline(findings, known)

        rc = 1 if (findings or stale) else 0
        if args.as_json:
            print(json.dumps({
                "findings": [f.to_dict() for f in findings],
                "count": len(findings),
                "baselined": [f.to_dict() for f in baselined],
                "stale_expected": [
                    {"path": p, "rule": r, "message": m}
                    for p, r, m in sorted(stale)
                ],
                "roots": [r.to_dict() for r in roots],
                "exit_code": rc,
            }, indent=2))
        else:
            for finding in findings:
                print(finding.format())
            for finding in baselined:
                print(f"{finding.format()} [expected]")
            for p, r, m in sorted(stale):
                print(f"graftrace: expected finding no longer reported "
                      f"(fixed? remove it): {p}: {r} {m}", file=sys.stderr)
            if findings:
                print(f"graftrace: {len(findings)} new finding(s)",
                      file=sys.stderr)
        return rc
    except Exception as exc:  # never-crash contract: no tracebacks
        print(f"graftrace: internal error: {type(exc).__name__}: {exc}",
              file=sys.stderr)
        return 2
