"""Eraser-style lockset analysis + lock-order graph over the call graph.

From each discovered root (:mod:`.callgraph`) the analyzer walks the
interprocedural call graph with a *held lockset*: ``with self._lock:``
adds ``"Class._lock"`` (a Condition adds its underlying lock), a module
lock adds ``"module:name"``, and calls into other objects' methods carry
the set along — so ``JobQueue.submit`` calling the armed metrics wrapper
observes ``{JobQueue._lock, MetricsRegistry._lock}`` inside the
registry, which is exactly how the lock-order edge is found.

Recorded along the way:

- **accesses** to every LOCK_OWNERSHIP location (``self.attr`` on a
  registered class) and every module-level mutable table — root, held
  set, location, read/write;
- **order edges**: acquiring L while holding H adds H→L with a witness
  site;
- **blocking sites**: file I/O / sleep / join / result / device get /
  subprocess / HTTP while holding a lock — and *any* lock acquisition or
  blocking call when the root is a signal handler.

Findings (rule ids are the baseline contract):

- ``race-unlocked-write``: a location with accesses from ≥2 roots and ≥1
  write whose write-lockset intersection is empty. Reads don't shrink
  the lockset — the registries tolerate torn reads by doctrine — but
  they do count toward the ≥2-root reach.
- ``deadlock-order-inversion``: a cycle in the order graph.
- ``blocking-under-lock`` / ``signal-unsafe-call``: per site.

Boundaries, matching graftlint's lock-discipline rule: nested ``def``s
and lambdas do not inherit the held set (they may run later on another
thread); ``Thread(target=...)`` / ``.submit(fn)`` arguments are separate
roots and are not traversed at the spawn site. Module-global *rebinds*
(``_ACTIVE = wd``) are exempt — atomic-reference hand-off is the
documented arming discipline; only container mutations are tracked.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Finding
from tools.graftrace.callgraph import Root
from tools.graftrace.index import FuncInfo, Index

_MUTATING_METHODS = {
    "append", "add", "update", "setdefault", "pop", "popitem", "clear",
    "remove", "discard", "extend", "insert", "appendleft", "popleft",
    "__setitem__",
}

#: fully-resolved call targets that block (or do I/O)
_BLOCKING_CALLS = {
    "open", "gzip.open", "time.sleep", "urllib.request.urlopen",
    "subprocess.run", "subprocess.check_call", "subprocess.check_output",
    "subprocess.Popen", "socket.create_connection", "requests.get",
    "requests.post", "jax.device_get", "os.replace", "json.dump",
    "shutil.copyfile",
}

#: method names that block on any receiver (join/result only bare or with
#: a timeout — ``", ".join(parts)`` is string formatting, not blocking)
_BLOCKING_METHODS = {"result", "block_until_ready", "serve_forever",
                     "acquire", "wait"}


class Access:
    __slots__ = ("location", "root", "held", "path", "line", "write")

    def __init__(self, location, root, held, path, line, write):
        self.location = location
        self.root = root
        self.held = held
        self.path = path
        self.line = line
        self.write = write

    def key(self):
        return (self.location, self.root, self.held, self.path, self.line,
                self.write)


class Analyzer:
    """One whole-tree analysis: traverse every root, then report."""

    def __init__(self, index: Index, roots: list[Root]):
        self.index = index
        self.roots = roots
        self.accesses: dict[str, dict[tuple, Access]] = {}
        #: (from_lock, to_lock) -> (path, line) first witness
        self.order_edges: dict[tuple[str, str], tuple[str, int]] = {}
        self.findings: list[Finding] = []
        self._finding_keys: set[tuple] = set()
        self._memo: set[tuple] = set()

    # --- recording ----------------------------------------------------------

    def _record_access(self, location, root, held, path, line, write):
        acc = Access(location, root.name, frozenset(held), path, line, write)
        self.accesses.setdefault(location, {})[acc.key()] = acc

    def _add_finding(self, path, line, col, rule, message):
        key = (path, rule, message)
        if key not in self._finding_keys:
            self._finding_keys.add(key)
            self.findings.append(Finding(path, line, col, rule, message))

    # --- traversal ----------------------------------------------------------

    def run(self) -> None:
        for root in self.roots:
            if root.func is None:
                continue
            fi = self.index.funcs.get(root.func)
            if fi is not None:
                self._memo = set()
                is_sig = root.kind == "signal"  # graftlint: disable=chaos-unknown-kind
                self._visit_func(fi, frozenset(), root, signal_ctx=is_sig)
        self._report_races()
        self._report_order_cycles()

    def _visit_func(self, fi: FuncInfo, held: frozenset, root: Root,
                    signal_ctx: bool) -> None:
        key = (fi.qname, held)
        if key in self._memo:
            return
        self._memo.add(key)
        walker = _FuncWalker(self, fi, set(held), root, signal_ctx)
        walker.walk(fi.node.body)

    # --- reporting ----------------------------------------------------------

    def _report_races(self) -> None:
        for location in sorted(self.accesses):
            accs = list(self.accesses[location].values())
            roots = sorted({a.root for a in accs})
            writes = [a for a in accs if a.write]
            if len(roots) < 2 or not writes:
                continue
            common = frozenset.intersection(*[a.held for a in writes])
            if common:
                continue
            anchor = next((w for w in writes if not w.held), writes[0])
            self._add_finding(
                anchor.path, anchor.line, 0, "race-unlocked-write",
                f"{location} is written with an empty lockset intersection "
                f"across roots [{', '.join(roots)}] — an Eraser-style data "
                "race; guard every write with one common lock")

    def _report_order_cycles(self) -> None:
        graph: dict[str, set[str]] = {}
        for (a, b) in self.order_edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        # iterative Tarjan SCC
        idx, low, stack, on_stack = {}, {}, [], set()
        sccs, counter = [], [0]

        def strongconnect(v0):
            work = [(v0, iter(sorted(graph[v0])))]
            idx[v0] = low[v0] = counter[0]
            counter[0] += 1
            stack.append(v0)
            on_stack.add(v0)
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    if w not in idx:
                        idx[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(graph[w]))))
                        advanced = True
                        break
                    if w in on_stack:
                        low[v] = min(low[v], idx[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    pv = work[-1][0]
                    low[pv] = min(low[pv], low[v])
                if low[v] == idx[v]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == v:
                            break
                    sccs.append(scc)

        for v in sorted(graph):
            if v not in idx:
                strongconnect(v)

        for scc in sccs:
            if len(scc) < 2:
                continue
            members = sorted(scc)
            edges = sorted(
                (a, b) for (a, b) in self.order_edges
                if a in scc and b in scc
            )
            witness = [
                f"{a}->{b} at {self.order_edges[(a, b)][0]}:"
                f"{self.order_edges[(a, b)][1]}" for a, b in edges
            ]
            path, line = self.order_edges[edges[0]]
            self._add_finding(
                path, line, 0, "deadlock-order-inversion",
                f"lock-order cycle among [{', '.join(members)}]: "
                f"{'; '.join(witness)} — two threads taking these in "
                "opposite orders deadlock")


class _FuncWalker:
    """Statement walker for one function body under one held set."""

    def __init__(self, analyzer: Analyzer, fi: FuncInfo, held: set,
                 root: Root, signal_ctx: bool):
        self.an = analyzer
        self.ix = analyzer.index
        self.fi = fi
        self.held = held
        self.root = root
        self.signal_ctx = signal_ctx
        self.ltypes = self.ix.local_types(fi)
        self.owned = self.ix.ownership.get(fi.cls or "", {})

    # --- lock identity ------------------------------------------------------

    def _lock_id(self, expr: ast.expr) -> str | None:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and self.fi.cls):
            cls = self.fi.cls
            under = self.ix.condition_map.get((cls, expr.attr))
            if under is not None:
                return f"{cls}.{under}"
            if expr.attr in self.ix.class_locks.get(cls, ()):
                return f"{cls}.{expr.attr}"
            return None
        if isinstance(expr, ast.Name) \
                and (self.fi.module, expr.id) in self.ix.module_locks:
            return f"{self.fi.module}:{expr.id}"
        return None

    def _acquire(self, lock: str, node: ast.AST) -> set:
        if self.signal_ctx:
            self.an._add_finding(
                self.fi.ctx.path, node.lineno, node.col_offset,
                "signal-unsafe-call",
                f"{self.fi.short} acquires {lock} in signal-handler "
                f"context ({self.root.name}) — deadlocks if the "
                "interrupted frame holds it")
        added = set()
        if lock not in self.held:
            for h in sorted(self.held):
                self.an.order_edges.setdefault(
                    (h, lock), (self.fi.ctx.path, node.lineno))
            self.held.add(lock)
            added.add(lock)
        return added

    # --- statements ---------------------------------------------------------

    def walk(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self.visit(stmt)

    def visit(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs run later, possibly on another thread
        if isinstance(stmt, ast.With):
            added: set = set()
            for item in stmt.items:
                lock = self._lock_id(item.context_expr)
                if lock is not None:
                    added |= self._acquire(lock, item.context_expr)
                self._scan_expr(item.context_expr)
            self.walk(stmt.body)
            self.held -= added
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                self._check_store(t, stmt)
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._check_store(t, stmt)
        self._scan_expr(stmt)
        for field in ("body", "orelse", "finalbody"):
            for sub in getattr(stmt, field, ()):
                self.visit(sub)
        for handler in getattr(stmt, "handlers", ()):
            for sub in handler.body:
                self.visit(sub)

    def _check_store(self, target: ast.expr, stmt: ast.stmt) -> None:
        subscripted = False
        node = target
        while isinstance(node, ast.Subscript):
            node = node.value
            subscripted = True
        attr = None
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            attr = node.attr
        if attr is not None and attr in self.owned:
            self._record(f"{self.fi.cls}.{attr}", stmt, write=True)
            return
        # module table: NAME[k] = v / NAME += / del NAME[k] mutate; a
        # plain NAME = v rebind is the exempt atomic-reference hand-off
        if isinstance(node, ast.Name):
            key = (self.fi.module, node.id)
            if key in self.ix.module_tables and (
                    subscripted or isinstance(stmt, ast.AugAssign)):
                self._record(f"{self.fi.module}:{node.id}", stmt, write=True)

    def _record(self, location: str, node: ast.AST, write: bool) -> None:
        self.an._record_access(location, self.root, self.held,
                               self.fi.ctx.path, node.lineno, write)

    # --- expressions --------------------------------------------------------

    def _scan_expr(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.stmt, ast.excepthandler, ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                self._handle_call(child)
            elif isinstance(child, ast.Attribute) \
                    and isinstance(child.ctx, ast.Load) \
                    and isinstance(child.value, ast.Name) \
                    and child.value.id == "self" \
                    and child.attr in self.owned:
                self._record(f"{self.fi.cls}.{child.attr}", child,
                             write=False)
            elif isinstance(child, ast.Name) \
                    and isinstance(child.ctx, ast.Load) \
                    and (self.fi.module, child.id) in self.ix.module_tables:
                self._record(f"{self.fi.module}:{child.id}", child,
                             write=False)
            self._scan_expr(child)

    def _handle_call(self, call: ast.Call) -> None:
        full = self.fi.imports.resolve_call_target(call.func) or ""
        attr = call.func.attr if isinstance(call.func, ast.Attribute) \
            else None
        is_thread_ctor = full.endswith("threading.Thread") or full == "Thread"

        # mutating method on a registered self.attr or a module table
        if attr in _MUTATING_METHODS and isinstance(call.func,
                                                    ast.Attribute):
            recv = call.func.value
            base = recv
            while isinstance(base, ast.Subscript):
                base = base.value
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"
                    and base.attr in self.owned):
                self._record(f"{self.fi.cls}.{base.attr}", call, write=True)
            elif isinstance(base, ast.Name) and \
                    (self.fi.module, base.id) in self.ix.module_tables:
                self._record(f"{self.fi.module}:{base.id}", call, write=True)

        self._check_blocking(call, full, attr)

        if is_thread_ctor:
            return  # target= is a separate root; the ctor runs nothing
        spawned: set[int] = set()
        if attr == "submit":
            # a pool submit iff some arg is a known callable (it becomes a
            # worker root); JobQueue.submit and friends take data args and
            # are ordinary synchronous calls
            spawned = {
                id(a) for a in call.args
                if self.ix.resolve_callable(a, self.fi, self.ltypes)
                is not None
            }
        if not spawned:
            callee = self.ix.resolve_callable(call.func, self.fi,
                                              self.ltypes)
            if callee is not None:
                self.an._visit_func(callee, frozenset(self.held),
                                    self.root, self.signal_ctx)
        # callbacks handed to other code run without our held set later;
        # traverse them with an EMPTY set so their own discipline is still
        # checked under this root (e.g. on_done=self._note_done)
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if id(arg) in spawned:
                continue
            cb = self.ix.resolve_callable(arg, self.fi, self.ltypes)
            if cb is not None and not isinstance(arg, ast.Call):
                self.an._visit_func(cb, frozenset(), self.root,
                                    self.signal_ctx)

    def _check_blocking(self, call: ast.Call, full: str,
                        attr: str | None) -> None:
        desc = None
        if full in _BLOCKING_CALLS and not full.startswith("self."):
            desc = f"{full}()"
        elif attr == "join":
            # Thread.join() blocks; ", ".join(parts) does not — require a
            # bare call or a receiver that is not a string-ish constant
            recv = call.func.value
            if not call.args and not isinstance(recv, ast.Constant):
                desc = ".join()"
        elif attr in _BLOCKING_METHODS:
            if attr == "wait":
                # Condition.wait on a HELD lock releases it while waiting:
                # that is the correct pattern, not a block-under-lock
                recv = call.func.value
                if (isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self" and self.fi.cls):
                    under = self.ix.condition_map.get(
                        (self.fi.cls, recv.attr))
                    if under is not None \
                            and f"{self.fi.cls}.{under}" in self.held:
                        return
            desc = f".{attr}()"
        if desc is None:
            return
        if self.signal_ctx:
            self.an._add_finding(
                self.fi.ctx.path, call.lineno, call.col_offset,
                "signal-unsafe-call",
                f"{self.fi.short} calls blocking {desc} in signal-handler "
                f"context ({self.root.name})")
        if self.held:
            locks = ", ".join(sorted(self.held))
            self.an._add_finding(
                self.fi.ctx.path, call.lineno, call.col_offset,
                "blocking-under-lock",
                f"{self.fi.short} calls blocking {desc} while holding "
                f"[{locks}] — stalls every thread contending for the lock")
