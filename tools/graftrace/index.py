"""Whole-tree symbol index: the name-resolution substrate for graftrace.

Built once per scan from graftlint's :class:`~tools.graftlint.core.Project`
(same file collection, same parse error handling). Everything here is
approximate-by-name — the tree has globally unique class names, so
``(class, method)`` and ``module.function`` resolution is exact in
practice while staying jax-free and import-free.
"""

from __future__ import annotations

import ast
import dataclasses
import os

from tools.graftlint.astutil import ImportMap, dotted_name
from tools.graftlint.core import FileCtx, Project
from tools.graftlint.rules.lock_discipline import ownership

#: constructor tails that produce a lock (graftrace treats a Condition as
#: its underlying lock — acquiring it acquires that lock)
_LOCK_CTOR_TAILS = ("threading.Lock", "threading.RLock",
                    "threading.Condition", "lockcheck.make_lock")

#: constructor tails that produce a shared-mutation-hazard container
_CONTAINER_CTORS = {
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict", "Counter",
}


def module_name(path: str) -> str:
    """Dotted module path for a scanned file ('pkg/obs/live.py' ->
    'pkg.obs.live'); scans run from the repo root so relative paths are
    package-rooted."""
    p = path.replace(os.sep, "/").lstrip("./")
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


@dataclasses.dataclass
class FuncInfo:
    """One function or method definition, locatable and resolvable."""

    qname: str           # "pkg.obs.live.FlightRecorder.add_span"
    module: str
    cls: str | None
    name: str
    node: ast.FunctionDef
    ctx: FileCtx
    imports: ImportMap

    @property
    def short(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else \
            f"{self.module.rsplit('.', 1)[-1]}.{self.name}"


def _is_lock_ctor(call: ast.expr, imports: ImportMap) -> bool:
    if not isinstance(call, ast.Call):
        return False
    full = imports.resolve_call_target(call.func) or ""
    return any(full == t or full.endswith("." + t) for t in _LOCK_CTOR_TAILS)


def _is_container_value(node: ast.expr) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.SetComp, ast.DictComp)):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        tail = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None)
        return tail in _CONTAINER_CTORS
    return False


class Index:
    """Symbols of the scanned tree, keyed for interprocedural traversal."""

    def __init__(self, project: Project):
        #: {class: {attr: lock_attr}} — the LOCK_OWNERSHIP universe
        self.ownership = ownership(project)
        self.funcs: dict[str, FuncInfo] = {}
        self.module_funcs: dict[tuple[str, str], FuncInfo] = {}
        self.methods: dict[tuple[str, str], FuncInfo] = {}
        #: class name -> (node, ctx, module, base dotted-name tails)
        self.classes: dict[str, tuple[ast.ClassDef, FileCtx, str]] = {}
        self.class_bases: dict[str, list[str]] = {}
        #: (class, attr) -> class of the object stored there
        self.attr_types: dict[tuple[str, str], str] = {}
        #: (module, global name) -> class (from AnnAssign or ctor assign)
        self.global_types: dict[tuple[str, str], str] = {}
        #: class -> lock attr names on self
        self.class_locks: dict[str, set[str]] = {}
        #: (class, condition attr) -> underlying lock attr
        self.condition_map: dict[tuple[str, str], str] = {}
        #: module-level locks: (module, name)
        self.module_locks: set[tuple[str, str]] = set()
        #: module-level mutable containers: (module, name) -> (ctx, node)
        self.module_tables: dict[tuple[str, str], tuple[FileCtx, ast.AST]] = {}
        self.imports: dict[str, ImportMap] = {}

        for ctx in project.files:
            self.imports[ctx.path] = ImportMap(ctx.tree)

        # pass A: classes, methods, module functions
        for ctx in project.files:
            mod = module_name(ctx.path)
            imp = self.imports[ctx.path]
            for stmt in ctx.tree.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fi = FuncInfo(f"{mod}.{stmt.name}", mod, None, stmt.name,
                                  stmt, ctx, imp)
                    self.funcs[fi.qname] = fi
                    self.module_funcs[(mod, stmt.name)] = fi
                elif isinstance(stmt, ast.ClassDef):
                    self.classes[stmt.name] = (stmt, ctx, mod)
                    self.class_bases[stmt.name] = [
                        d for d in (dotted_name(b) for b in stmt.bases)
                        if d is not None
                    ]
                    for sub in stmt.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            fi = FuncInfo(f"{mod}.{stmt.name}.{sub.name}",
                                          mod, stmt.name, sub.name,
                                          sub, ctx, imp)
                            self.funcs[fi.qname] = fi
                            self.methods[(stmt.name, sub.name)] = fi

        # registry lock attrs exist even where the ctor is indirect
        for cls, attrs in self.ownership.items():
            for lock in attrs.values():
                self.class_locks.setdefault(cls, set()).add(lock)

        # pass B: types, locks, module tables
        for ctx in project.files:
            mod = module_name(ctx.path)
            imp = self.imports[ctx.path]
            for stmt in ctx.tree.body:
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    name = stmt.targets[0].id
                    if _is_lock_ctor(stmt.value, imp):
                        self.module_locks.add((mod, name))
                    elif _is_container_value(stmt.value):
                        self.module_tables[(mod, name)] = (ctx, stmt)
                    else:
                        c = self._class_of_ctor(stmt.value, imp)
                        if c is not None:
                            self.global_types[(mod, name)] = c
                elif isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    c = self._class_in_annotation(stmt.annotation)
                    if c is not None:
                        self.global_types[(mod, stmt.target.id)] = c
                    elif stmt.value is not None \
                            and _is_container_value(stmt.value):
                        self.module_tables[(mod, stmt.target.id)] = (ctx, stmt)

        for cls, (node, ctx, mod) in self.classes.items():
            imp = self.imports[ctx.path]
            for method in node.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                for sub in ast.walk(method):
                    if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                        continue
                    targets = (sub.targets if isinstance(sub, ast.Assign)
                               else [sub.target])
                    for t in targets:
                        if not (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            continue
                        value = sub.value
                        if value is None:
                            continue
                        if _is_lock_ctor(value, imp):
                            self.class_locks.setdefault(cls, set()).add(t.attr)
                            if isinstance(value, ast.Call) and value.args:
                                base = value.args[0]
                                if (isinstance(base, ast.Attribute)
                                        and isinstance(base.value, ast.Name)
                                        and base.value.id == "self"
                                        and "Condition" in (
                                            dotted_name(value.func) or "")):
                                    self.condition_map[(cls, t.attr)] = \
                                        base.attr
                        else:
                            c = self._class_of_ctor(value, imp)
                            if c is not None:
                                self.attr_types[(cls, t.attr)] = c

    # --- resolution helpers -------------------------------------------------

    def _class_of_ctor(self, value: ast.expr, imp: ImportMap) -> str | None:
        if not isinstance(value, ast.Call):
            return None
        full = imp.resolve_call_target(value.func)
        if full is None:
            return None
        tail = full.rsplit(".", 1)[-1]
        return tail if tail in self.classes else None

    def _class_in_annotation(self, ann: ast.expr | None) -> str | None:
        """First known class named anywhere in a type annotation
        (``Watchdog | None`` and ``"Watchdog | None"`` both resolve)."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        for node in ast.walk(ann):
            name = None
            if isinstance(node, ast.Name):
                name = node.id
            elif isinstance(node, ast.Attribute):
                name = node.attr
            if name in self.classes:
                return name
        return None

    def local_types(self, fi: FuncInfo) -> dict[str, str]:
        """{var: class} for ``v = C(...)`` / ``v = self.attr`` /
        ``v = MODULE_GLOBAL`` in one function body (no flow sensitivity)."""
        out: dict[str, str] = {}
        for sub in ast.walk(fi.node):
            if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Name)):
                continue
            var = sub.targets[0].id
            value = sub.value
            c = self._class_of_ctor(value, fi.imports)
            if c is None and isinstance(value, ast.Attribute) \
                    and isinstance(value.value, ast.Name) \
                    and value.value.id == "self" and fi.cls:
                c = self.attr_types.get((fi.cls, value.attr))
            if c is None and isinstance(value, ast.Name):
                c = self.global_types.get((fi.module, value.id))
            if c is not None:
                out[var] = c
        return out

    def resolve_callable(self, expr: ast.expr, fi: FuncInfo,
                         ltypes: dict[str, str]) -> FuncInfo | None:
        """The FuncInfo an expression refers to, or None: ``self.meth``,
        ``self.attr.meth``, ``var.meth``, ``name``, ``mod.func``."""
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id == "self" and fi.cls:
                    hit = self.methods.get((fi.cls, expr.attr))
                    if hit is not None:
                        return hit
                    via = self.attr_types.get((fi.cls, expr.attr))
                    if via is not None:
                        return None  # self.attr is an object, not callable
                cls = ltypes.get(base.id) or \
                    self.global_types.get((fi.module, base.id))
                if cls is not None:
                    return self.methods.get((cls, expr.attr))
            elif (isinstance(base, ast.Attribute)
                  and isinstance(base.value, ast.Name)
                  and base.value.id == "self" and fi.cls):
                cls = self.attr_types.get((fi.cls, base.attr))
                if cls is not None:
                    return self.methods.get((cls, expr.attr))
            full = fi.imports.resolve_call_target(expr)
            if full is not None and not full.startswith("self."):
                hit = self.funcs.get(full)
                if hit is not None:
                    return hit
            return None
        if isinstance(expr, ast.Name):
            hit = self.module_funcs.get((fi.module, expr.id))
            if hit is not None:
                return hit
            full = fi.imports.from_imports.get(expr.id)
            if full is not None:
                return self.funcs.get(full)
        return None
