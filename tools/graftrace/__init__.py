"""graftrace — whole-program static race & deadlock analyzer.

graftlint's ``lock-discipline`` rule is lexical and per-method: it proves
each declared registry mutation sits inside ``with self.<lock>:``. What
it cannot see is the *whole-program* picture the serving/mesh stack now
has — ~10 concurrent thread roots (overlap workers, the watchdog monitor
and its async-exc cancel path, the live HTTP plane, the daemon loop,
signal handlers) sharing the LOCK_OWNERSHIP state. graftrace promotes
lock discipline from lint to proof, the way graftcheck did for the
device graph:

1. **Thread-root discovery** (:mod:`.callgraph`) — every
   ``threading.Thread(target=)``, ``.submit()`` worker,
   ``BaseHTTPRequestHandler`` ``do_*`` method, ``signal.signal`` hook,
   plus the pipeline loop and daemon loop, becomes a named root of an
   interprocedural call graph.
2. **Lockset analysis** (:mod:`.locksets`) — Eraser-style (Savage et
   al.): for every shared location in the consolidated LOCK_OWNERSHIP
   registry (ont_tcrconsensus_tpu/robustness/locks.py) plus every
   module-level mutable table, compute the set of locks held on each
   access path from each root. A location written from ≥2 roots whose
   write-lockset intersection is empty is ``race-unlocked-write``.
   (Unlocked *reads* are tolerated by doctrine — the registries accept
   torn reads for display — so the intersection runs over writes.)
3. **Lock-order graph** — every acquire-while-holding edge across all
   roots; any cycle is ``deadlock-order-inversion``.
4. ``signal-unsafe-call`` — lock acquisition or blocking calls reachable
   from a signal handler (the SIGUSR1 flush path is the known, baselined
   case). ``blocking-under-lock`` — file I/O, sleeps, joins, device
   gets, HTTP while holding a registry lock (a ``Condition.wait`` on the
   held lock is exempt: wait releases it).

Jax-free by construction (pure AST over :mod:`tools.graftlint.core`'s
visitor core — the tier-1 run itself proves it imports nothing heavy).

Exit codes (same contract as graftlint/graftcheck): 0 clean, 1 findings
(or ``--expect`` drift in either direction), 2 internal/usage error —
never a traceback. ``--json`` carries ``exit_code`` in the body.

The committed expected list (``expected_findings.json``) pins the known
findings with one-line justifications; tier-1 runs ``--expect`` so a new
race/inversion/unsafe-call fails CI the day it is introduced.

The dynamic twin lives in ont_tcrconsensus_tpu/robustness/lockcheck.py:
``TCR_LOCKCHECK=1`` arms runtime owner-assertions on the same locks, so
chaos e2es validate this static model against real interleavings.
"""

from tools.graftrace.cli import main  # noqa: F401
