"""First-party developer tooling (not shipped in the wheel)."""
