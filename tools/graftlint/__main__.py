"""``python -m tools.graftlint`` entry point."""

import sys

from tools.graftlint.core import main

if __name__ == "__main__":
    sys.exit(main())
