"""Visitor core: file collection, parsing, suppressions, the runner.

Rules are project-scoped: each rule module exposes ``RULES`` (id ->
one-line description) and ``check(project)`` yielding :class:`Finding`s.
Cross-file rules (chaos sites, config fields) see every scanned file
through :class:`Project`; per-file rules just iterate ``project.files``.
Rules locate their anchors (``class RunConfig``, ``KNOWN_SITES``) inside
the scanned set itself, so fixture trees in tests exercise the identical
code path as the shipped tree.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import sys
import tokenize

_SKIP_DIRS = {
    "__pycache__", ".git", "build", "dist", ".pytest_cache", ".scratch",
    ".jax_cache", ".jax_kernel_cache", "node_modules",
}

_MAGIC = "graftlint:"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    path: str
    line: int
    col: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class FileCtx:
    """One parsed source file + its suppression comments."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)  # SyntaxError propagates
        # line -> set of rule ids disabled on that line; "all" disables all
        self.line_disables: dict[int, set[str]] = {}
        self.file_disables: set[str] = set()
        self._scan_comments()

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            comments = [t for t in tokens if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError):  # pragma: no cover
            return
        for tok in comments:
            text = tok.string.lstrip("#").strip()
            if not text.startswith(_MAGIC):
                continue
            directive = text[len(_MAGIC):].strip()
            for part in directive.split():
                if part.startswith("disable-file="):
                    self.file_disables.update(
                        r.strip() for r in part[len("disable-file="):].split(",") if r.strip()
                    )
                elif part.startswith("disable="):
                    ids = {r.strip() for r in part[len("disable="):].split(",") if r.strip()}
                    self.line_disables.setdefault(tok.start[0], set()).update(ids)

    def suppressed(self, finding: Finding) -> bool:
        if finding.rule in self.file_disables or "all" in self.file_disables:
            return True
        ids = self.line_disables.get(finding.line, ())
        return finding.rule in ids or "all" in ids


class Project:
    """Every scanned file, plus the findings for unparseable ones."""

    def __init__(self, paths: list[str]):
        self.files: list[FileCtx] = []
        self.parse_findings: list[Finding] = []
        for path in collect_py_files(paths):
            try:
                with open(path, encoding="utf-8", errors="replace") as fh:
                    source = fh.read()
                self.files.append(FileCtx(path, source))
            except SyntaxError as exc:
                self.parse_findings.append(Finding(
                    path, exc.lineno or 1, (exc.offset or 1) - 1,
                    "parse-error", f"file does not parse: {exc.msg}",
                ))
            except ValueError as exc:
                # ast.parse raises bare ValueError for NUL bytes in source
                self.parse_findings.append(Finding(
                    path, 1, 0, "parse-error", f"file does not parse: {exc}",
                ))

    def file_named(self, basename: str) -> list[FileCtx]:
        return [f for f in self.files if os.path.basename(f.path) == basename]


def collect_py_files(paths: list[str]) -> list[str]:
    out: set[str] = set()
    for path in paths:
        if os.path.isfile(path):
            out.add(path)
            continue
        for root, dirs, names in os.walk(path):
            dirs[:] = sorted(
                d for d in dirs
                if d not in _SKIP_DIRS and not d.endswith(".egg-info")
            )
            for name in names:
                if name.endswith(".py"):
                    out.add(os.path.join(root, name))
    return sorted(out)


def run_paths(paths: list[str]) -> list[Finding]:
    """Lint ``paths`` with every registered rule; returns surviving findings
    sorted by location (suppressions already applied)."""
    from tools.graftlint import rules

    project = Project(paths)
    findings = list(project.parse_findings)  # parse errors: not suppressible
    by_path = {f.path: f for f in project.files}
    for check in rules.CHECKS:
        for finding in check(project):
            ctx = by_path.get(finding.path)
            if ctx is not None and ctx.suppressed(finding):
                continue
            findings.append(finding)
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))


def _baseline_key(entry: dict) -> tuple:
    """Identity of a known finding: location-insensitive (line/col drift
    from unrelated edits must not churn the baseline), message-sensitive
    (a rule firing differently IS a new finding)."""
    return (entry.get("path", ""), entry.get("rule", ""),
            entry.get("message", ""))


def load_baseline(path: str) -> set[tuple]:
    """Known-finding keys from a ``--write-baseline`` file.  Each entry
    may carry a free-form ``justification`` the tool ignores."""
    with open(path, encoding="utf-8") as fh:
        body = json.load(fh)
    entries = body.get("findings", []) if isinstance(body, dict) else body
    return {_baseline_key(e) for e in entries if isinstance(e, dict)}


def apply_baseline(findings: list[Finding], known: set[tuple],
                   ) -> tuple[list[Finding], list[Finding], set[tuple]]:
    """(new, baselined, stale-keys) split of ``findings`` vs the baseline."""
    new: list[Finding] = []
    old: list[Finding] = []
    seen: set[tuple] = set()
    for f in findings:
        key = _baseline_key(f.to_dict())
        if key in known:
            old.append(f)
            seen.add(key)
        else:
            new.append(f)
    return new, old, known - seen


def main(argv: list[str] | None = None) -> int:
    import argparse

    from tools.graftlint import rules

    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="repo-native static analysis (see tools/graftlint/__init__.py)",
    )
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--baseline", metavar="FILE",
                    help="known-findings file: findings matching an entry "
                    "(by path+rule+message; lines may drift) are reported "
                    "but do not fail the run — only NEW findings exit 1")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="write the current findings as a baseline file "
                    "(add a justification: to each entry before committing)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule_id, desc in sorted(rules.RULE_CATALOGUE.items()):
            print(f"{rule_id:24s} {desc}")
        return 0
    if not args.paths:
        ap.print_usage(sys.stderr)
        print("graftlint: no paths given", file=sys.stderr)
        return 2
    for path in args.paths:
        if not os.path.exists(path):
            print(f"graftlint: no such path: {path}", file=sys.stderr)
            return 2

    findings = run_paths(args.paths)

    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as fh:
            json.dump({"findings": [
                {**f.to_dict(), "justification": ""} for f in findings
            ]}, fh, indent=2)
            fh.write("\n")
        print(f"graftlint: wrote {len(findings)} finding(s) to "
              f"{args.write_baseline}", file=sys.stderr)
        return 0

    baselined: list[Finding] = []
    stale: set[tuple] = set()
    if args.baseline:
        try:
            known = load_baseline(args.baseline)
        except (OSError, ValueError) as exc:
            print(f"graftlint: cannot read baseline {args.baseline}: {exc}",
                  file=sys.stderr)
            return 2
        findings, baselined, stale = apply_baseline(findings, known)

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "count": len(findings),
            "baselined": [f.to_dict() for f in baselined],
            "stale_baseline": [
                {"path": p, "rule": r, "message": m}
                for p, r, m in sorted(stale)
            ],
        }, indent=2))
    else:
        for finding in findings:
            print(finding.format())
        for finding in baselined:
            print(f"{finding.format()} [baselined]")
        for p, r, m in sorted(stale):
            print(f"graftlint: stale baseline entry (fixed? remove it): "
                  f"{p}: {r} {m}", file=sys.stderr)
        if findings:
            print(f"graftlint: {len(findings)} new finding(s)",
                  file=sys.stderr)
    return 1 if findings else 0
