"""graftlint — repo-native static analysis for the jax_graft codebase.

Generic linters know nothing about this repo's proven bug classes: host
syncs inside ``@jax.jit`` functions, ``except`` guards that can swallow
:class:`~ont_tcrconsensus_tpu.robustness.shutdown.Preempted`, chaos-site
literals that drift from ``faults.KNOWN_SITES``, and ``cfg.<typo>``
accesses that only fail at runtime on rare paths. graftlint encodes each
of those as an AST rule and gates them in ``scripts/tier1.sh``.

Usage::

    python -m tools.graftlint ont_tcrconsensus_tpu tests scripts
    python -m tools.graftlint --json path/to/file.py
    python -m tools.graftlint --list-rules

Suppress a finding inline with ``# graftlint: disable=<rule-id>`` on the
offending line (comma-separate several ids, or ``all``); suppress a rule
for a whole file with ``# graftlint: disable-file=<rule-id>`` on any line.
Exit status: 0 clean, 1 findings, 2 usage/internal error.
"""

from tools.graftlint.core import Finding, Project, run_paths  # noqa: F401
from tools.graftlint.rules import RULE_CATALOGUE  # noqa: F401
