"""Small shared AST helpers used by several rules."""

from __future__ import annotations

import ast


def dotted_name(node: ast.AST) -> str | None:
    """'jax.jit' for Attribute/Name chains; None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class ImportMap:
    """Per-file import aliasing: which local names refer to which modules.

    ``modules`` maps a local name to the dotted module it binds
    (``import numpy as np`` -> {'np': 'numpy'}); ``from_imports`` maps a
    local name to 'module.attr' (``from time import perf_counter`` ->
    {'perf_counter': 'time.perf_counter'}).
    """

    def __init__(self, tree: ast.AST):
        self.modules: dict[str, str] = {}
        self.from_imports: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    self.modules[local] = alias.name if alias.asname else local
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    self.from_imports[local] = f"{node.module}.{alias.name}"

    def resolve_call_target(self, func: ast.AST) -> str | None:
        """Fully-qualified dotted target of a call's ``func`` node, through
        the file's import aliases ('np.asarray' -> 'numpy.asarray')."""
        dotted = dotted_name(func)
        if dotted is None:
            return None
        head, _, rest = dotted.partition(".")
        if head in self.modules:
            base = self.modules[head]
            return f"{base}.{rest}" if rest else base
        if head in self.from_imports:
            base = self.from_imports[head]
            return f"{base}.{rest}" if rest else base
        return dotted


def func_defs_by_name(tree: ast.AST) -> dict[str, list[ast.FunctionDef]]:
    """Every (possibly nested) function definition in the module, by name."""
    out: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def str_constants(node: ast.AST) -> list[str]:
    """All string literals anywhere under ``node``."""
    return [
        n.value for n in ast.walk(node)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    ]
