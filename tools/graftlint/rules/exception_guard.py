"""exception-guard: except clauses that can swallow a shutdown request.

PR 2's root-cause bug: the per-library ``except Exception`` skip guard
silently swallowed the shutdown coordinator's ``Preempted`` into "library
failed, skipped" — the fix was deriving ``Preempted`` from
``BaseException`` so the broad guard structurally cannot catch it. These
rules pin that invariant and its neighbors:

- ``bare-except``          — ``except:`` catches BaseException, so it
  swallows ``Preempted`` (and KeyboardInterrupt); write
  ``except Exception`` for degradation guards;
- ``broad-except-swallow`` — ``except BaseException`` whose handler
  neither re-raises nor lets the exception escape (stored/queued/passed
  on): the caught preemption dies there;
- ``preempted-base``       — a class named ``Preempted`` must derive
  directly from ``BaseException``; subclassing ``Exception`` reintroduces
  the PR 2 bug at every ``except Exception`` guard in the tree;
- ``preempted-swallow``    — an except clause naming ``Preempted`` whose
  handler neither re-raises nor stores it for re-raise.

"Escapes" recognized: a ``raise`` anywhere in the handler, or the caught
name used in an assignment / call argument / return (the overlap executor
stores worker exceptions and re-raises them at commit on the main thread).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.core import Finding, Project

RULES = {
    "bare-except": "bare `except:` swallows Preempted/KeyboardInterrupt; "
                   "catch Exception (or narrower)",
    "broad-except-swallow": "`except BaseException` that neither re-raises "
                            "nor lets the exception escape",
    "preempted-base": "class Preempted must derive directly from "
                      "BaseException, not Exception",
    "preempted-swallow": "except clause catching Preempted without "
                         "re-raising or storing it",
}


def _type_mentions(type_node: ast.AST | None, name: str) -> bool:
    if type_node is None:
        return False
    for node in ast.walk(type_node):
        if isinstance(node, ast.Name) and node.id == name:
            return True
        if isinstance(node, ast.Attribute) and node.attr == name:
            return True
    return False


def _handler_lets_exception_escape(handler: ast.ExceptHandler) -> bool:
    caught = handler.name
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if caught is None:
            continue
        if isinstance(node, ast.Assign) and any(
            isinstance(n, ast.Name) and n.id == caught
            for n in ast.walk(node.value)
        ):
            return True
        if isinstance(node, ast.Return) and node.value is not None and any(
            isinstance(n, ast.Name) and n.id == caught
            for n in ast.walk(node.value)
        ):
            return True
        if isinstance(node, ast.Call) and any(
            isinstance(n, ast.Name) and n.id == caught
            for a in list(node.args) + [k.value for k in node.keywords]
            for n in ast.walk(a)
        ):
            return True
    return False


def check(project: Project) -> Iterator[Finding]:
    for ctx in project.files:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef) and node.name == "Preempted":
                if not any(
                    (isinstance(b, ast.Name) and b.id == "BaseException")
                    or (isinstance(b, ast.Attribute) and b.attr == "BaseException")
                    for b in node.bases
                ):
                    yield Finding(
                        ctx.path, node.lineno, node.col_offset, "preempted-base",
                        "class Preempted must subclass BaseException directly "
                        "so `except Exception` degradation guards can never "
                        "swallow a preemption",
                    )
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield Finding(
                    ctx.path, node.lineno, node.col_offset, "bare-except",
                    "bare `except:` catches BaseException and swallows "
                    "Preempted/KeyboardInterrupt; catch Exception or narrower",
                )
                continue
            escapes = _handler_lets_exception_escape(node)
            if _type_mentions(node.type, "BaseException") and not escapes:
                yield Finding(
                    ctx.path, node.lineno, node.col_offset,
                    "broad-except-swallow",
                    "`except BaseException` without re-raise/escape swallows "
                    "Preempted; re-raise, store it, or catch Exception",
                )
            if _type_mentions(node.type, "Preempted") and not escapes:
                yield Finding(
                    ctx.path, node.lineno, node.col_offset, "preempted-swallow",
                    "Preempted caught but neither re-raised nor stored; the "
                    "shutdown request dies here",
                )
