"""donation-use-after-donate: a donated buffer referenced after the call.

``jax.jit(fn, donate_argnums=...)`` hands the argument's device buffer to
XLA for reuse: after the call dispatches, the caller's array aliases
freed (or overwritten) memory, and touching it raises a
``RuntimeError: invalid buffer`` — but only on backends that honor
donation, so the bug ships silently from CPU dev boxes.  The rule tracks
every binding of a donating jit in a file —

- ``f = jax.jit(fn, donate_argnums=(0,))`` assignments,
- ``@partial(jax.jit, donate_argnums=...)`` / ``@jax.jit(...)``
  decorated defs,
- immediate ``jax.jit(fn, donate_argnums=(0,))(x)`` calls —

and then walks each scope (module body, every function body) in
statement order: a Name passed in a donated position is poisoned from
the statement after the call until it is rebound or deleted; any load of
a poisoned name is a finding.  Scope-local and syntactic by design —
donation through containers or across files is out of reach, but the
pattern the rule targets (donate, then log/assert/reuse the input) is
exactly the one SNIPPETS-class production stacks ban.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.astutil import ImportMap, dotted_name
from tools.graftlint.core import FileCtx, Finding, Project

RULES = {
    "donation-use-after-donate": "argument passed under donate_argnums "
                                 "referenced after the call (its device "
                                 "buffer has been handed to XLA)",
}

_JIT_TARGETS = {"jax.jit", "jax.api.jit"}
_PARTIAL_TARGETS = {"functools.partial", "partial"}


def _donate_positions(call: ast.Call) -> tuple[int, ...]:
    """donate_argnums literal positions from a jax.jit(...) call."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        out = []
        for node in ast.walk(kw.value):
            if isinstance(node, ast.Constant) and isinstance(node.value, int):
                out.append(node.value)
        return tuple(sorted(set(out)))
    return ()


def _is_jit(node: ast.AST, imports: ImportMap) -> bool:
    return (imports.resolve_call_target(node) in _JIT_TARGETS
            or dotted_name(node) in _JIT_TARGETS)


def _donating_jit_call(node: ast.AST, imports: ImportMap,
                       ) -> tuple[int, ...] | None:
    """donate positions when ``node`` is a jax.jit/partial(jax.jit) call
    carrying donate_argnums; None otherwise."""
    if not isinstance(node, ast.Call):
        return None
    if _is_jit(node.func, imports):
        pos = _donate_positions(node)
        return pos or None
    target = imports.resolve_call_target(node.func)
    if target in _PARTIAL_TARGETS and node.args and _is_jit(node.args[0],
                                                           imports):
        pos = _donate_positions(node)
        return pos or None
    return None


def _donating_bindings(ctx: FileCtx, imports: ImportMap) -> dict[str, tuple]:
    """{name: donated positions} for every donating binding in the file."""
    out: dict[str, tuple] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign):
            pos = _donating_jit_call(node.value, imports)
            if pos:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = pos
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                pos = _donating_jit_call(deco, imports)
                if pos:
                    out[node.name] = pos
    return out


def _scopes(tree: ast.Module):
    """(body, label) for the module and every function, innermost last."""
    yield tree.body, "<module>"
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.body, node.name


class _ScopeWalker:
    """Statement-order walk of one scope body with a poisoned-name set."""

    def __init__(self, ctx: FileCtx, imports: ImportMap,
                 bindings: dict[str, tuple]):
        self.ctx = ctx
        self.imports = imports
        self.bindings = bindings
        # name -> (call line, callee label)
        self.poisoned: dict[str, tuple[int, str]] = {}
        self.findings: list[Finding] = []

    def _donations_in(self, stmt: ast.stmt) -> list[tuple[str, int, str]]:
        """(arg name, line, callee) per donated Name argument in ``stmt``."""
        out = []
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            pos: tuple[int, ...] | None = None
            label = None
            if isinstance(node.func, ast.Name):
                pos = self.bindings.get(node.func.id)
                label = node.func.id
            if pos is None:
                pos = _donating_jit_call(node.func, self.imports)
                label = "jax.jit(...)"
            if not pos:
                continue
            for p in pos:
                if p < len(node.args) and isinstance(node.args[p], ast.Name):
                    out.append((node.args[p].id, node.lineno, label))
        return out

    def _check_loads(self, stmt: ast.stmt) -> None:
        for node in ast.walk(stmt):
            if (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in self.poisoned):
                line, callee = self.poisoned[node.id]
                self.findings.append(Finding(
                    self.ctx.path, node.lineno, node.col_offset,
                    "donation-use-after-donate",
                    f"`{node.id}` was donated to `{callee}` on line {line}; "
                    "its device buffer belongs to XLA now — reorder the "
                    "use before the call or drop donate_argnums",
                ))

    def _clear_stores(self, stmt: ast.stmt) -> None:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                self.poisoned.pop(node.id, None)

    def walk(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # own scope; walked separately by _scopes
            # whole-statement granularity: loads are checked against the
            # poison set from PRIOR statements, so `x = f(x)` (donate and
            # rebind in one statement) stays clean; a donation and a use
            # inside the same compound statement is conservatively missed
            self._check_loads(stmt)
            for name, line, callee in self._donations_in(stmt):
                self.poisoned[name] = (line, callee)
            self._clear_stores(stmt)


def check(project: Project) -> Iterator[Finding]:
    for ctx in project.files:
        imports = ImportMap(ctx.tree)
        bindings = _donating_bindings(ctx, imports)
        has_inline = any(
            _donating_jit_call(n.func, imports)
            for n in ast.walk(ctx.tree) if isinstance(n, ast.Call)
        )
        if not bindings and not has_inline:
            continue
        for body, _label in _scopes(ctx.tree):
            walker = _ScopeWalker(ctx, imports, bindings)
            walker.walk(body)
            yield from walker.findings
