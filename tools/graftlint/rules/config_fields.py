"""config-field cross-check: every ``cfg.<attr>`` must exist on RunConfig.

``RunConfig.from_dict`` rejects unknown *keys*, but an attribute typo on
the read side (``cfg.read_batchsize``) is an AttributeError that only
fires when the code path runs — on rare paths, that is production. This
rule resolves attribute accesses on provably-RunConfig values against the
fields, properties and methods declared on the class.

A name is "provably RunConfig" when it is a parameter annotated
``RunConfig`` (string annotations included), assigned from
``RunConfig(...)`` / ``RunConfig.from_dict(...)`` / ``from_json(...)``,
or assigned from ``dataclasses.replace(<runconfig>, ...)``. Anything
else (untyped test helpers, dicts named cfg) is out of scope — the rule
trades recall for zero false positives.

The class definition is located inside the scanned files (any
``class RunConfig``), so fixtures exercise the same path; with no
definition in scope the rule no-ops.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.astutil import dotted_name
from tools.graftlint.core import Finding, Project

RULES = {
    "config-unknown-field": "attribute access on a RunConfig value that "
                            "matches no declared field/property/method",
}

_CLASS_NAME = "RunConfig"
_CTORS = {"RunConfig", "RunConfig.from_dict", "RunConfig.from_json"}


def _allowed_attrs(project: Project) -> set[str] | None:
    """Declared attributes of every ``class RunConfig`` in scope (fields,
    class vars, methods, properties); None when no class is found."""
    allowed: set[str] | None = None
    for ctx in project.files:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.ClassDef) and node.name == _CLASS_NAME):
                continue
            allowed = set() if allowed is None else allowed
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                    allowed.add(stmt.target.id)
                elif isinstance(stmt, ast.Assign):
                    allowed.update(
                        t.id for t in stmt.targets if isinstance(t, ast.Name)
                    )
                elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    allowed.add(stmt.name)
    return allowed


def _is_runconfig_annotation(node: ast.AST | None) -> bool:
    if node is None:
        return False
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.split(".")[-1] == _CLASS_NAME
    name = dotted_name(node)
    if name is not None and name.split(".")[-1] == _CLASS_NAME:
        return True
    # Optional[RunConfig] / RunConfig | None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        return _is_runconfig_annotation(node.left) or _is_runconfig_annotation(node.right)
    if isinstance(node, ast.Subscript):
        return _is_runconfig_annotation(node.slice)
    return False


class _ScopeChecker(ast.NodeVisitor):
    """One function (or module) scope: track RunConfig-typed names, check
    attribute accesses on them."""

    def __init__(self, ctx, allowed: set[str], findings: list[Finding]):
        self.ctx = ctx
        self.allowed = allowed
        self.findings = findings
        self.typed: set[str] = set()

    def _is_runconfig_value(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.typed
        if isinstance(node, ast.Call):
            target = dotted_name(node.func)
            if target in _CTORS:
                return True
            if target in ("dataclasses.replace", "replace") and node.args:
                return self._is_runconfig_value(node.args[0])
        return False

    def _bind(self, target: ast.AST, is_cfg: bool) -> None:
        if isinstance(target, ast.Name):
            (self.typed.add if is_cfg else self.typed.discard)(target.id)

    def visit_Assign(self, node: ast.Assign) -> None:
        is_cfg = self._is_runconfig_value(node.value)
        for target in node.targets:
            self._bind(target, is_cfg)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if _is_runconfig_annotation(node.annotation):
            self._bind(node.target, True)
        elif node.value is not None:
            self._bind(node.target, self._is_runconfig_value(node.value))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self._is_runconfig_value(node.value) and node.attr not in self.allowed:
            if not (node.attr.startswith("__") and node.attr.endswith("__")):
                self.findings.append(Finding(
                    self.ctx.path, node.lineno, node.col_offset,
                    "config-unknown-field",
                    f"RunConfig has no field `{node.attr}` — this is an "
                    "AttributeError on whatever rare path reaches it",
                ))
        self.generic_visit(node)

    # nested functions get their own scope (fresh typed-name set seeded
    # from annotated params; outer locals are not tracked across scopes)
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        _check_function(self.ctx, node, self.allowed, self.findings)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if node.name == _CLASS_NAME:
            return  # the class's own body accesses self.<field> dynamically
        self.generic_visit(node)


def _check_function(ctx, fn, allowed: set[str], findings: list[Finding]) -> None:
    checker = _ScopeChecker(ctx, allowed, findings)
    for arg in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
        if _is_runconfig_annotation(arg.annotation):
            checker.typed.add(arg.arg)
    for stmt in fn.body:
        checker.visit(stmt)


def check(project: Project) -> Iterator[Finding]:
    allowed = _allowed_attrs(project)
    if not allowed:
        return
    for ctx in project.files:
        findings: list[Finding] = []
        checker = _ScopeChecker(ctx, allowed, findings)
        for stmt in ctx.tree.body:
            checker.visit(stmt)
        yield from findings
