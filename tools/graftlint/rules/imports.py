"""unused-import: pyflakes' highest-value check, natively.

The container may not ship ruff (tier1.sh skips that stage when absent),
so the one ruff rule that regularly catches real drift — an import kept
after its last use was refactored away — is enforced here too. A name
counts as used when it appears as a load anywhere in the file (including
inside annotations and f-strings), when it is re-exported via
``__all__``, or when the import is a documented side-effect import
(suppress with ``# graftlint: disable=unused-import``).

``__init__.py`` files are exempt: their imports ARE the public API, and
an import statement carrying ``# noqa`` (bare or ``F401``) is honored as
a re-export marker for ruff/pyflakes interop.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from tools.graftlint.core import Finding, Project

RULES = {
    "unused-import": "imported name is never used in the file",
}

_NOQA_RE = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z]+[0-9]+(?:[,\s]+[A-Z]+[0-9]+)*))?",
                      re.IGNORECASE)


def _noqa_exempts(line: str) -> bool:
    """True for a bare ``# noqa`` or one whose code list includes F401 —
    a ``# noqa: E501`` must NOT exempt unused-import."""
    m = _NOQA_RE.search(line)
    if m is None:
        return False
    codes = m.group("codes")
    return codes is None or "F401" in codes.upper()


def _used_names(tree: ast.AST) -> set[str]:
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            base = node
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                used.add(base.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            # quoted annotations ("RunConfig") and __all__ entries
            used.add(node.value.split(".")[0])
    return used


def check(project: Project) -> Iterator[Finding]:
    for ctx in project.files:
        if os.path.basename(ctx.path) == "__init__.py":
            continue
        used = _used_names(ctx.tree)
        lines = ctx.source.splitlines()
        for node in ast.walk(ctx.tree):
            if (isinstance(node, (ast.Import, ast.ImportFrom))
                    and node.lineno <= len(lines)
                    and _noqa_exempts(lines[node.lineno - 1])):
                continue
            if isinstance(node, ast.Import):
                bindings = [
                    (alias, alias.asname or alias.name.split(".")[0])
                    for alias in node.names
                ]
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                bindings = [
                    (alias, alias.asname or alias.name)
                    for alias in node.names if alias.name != "*"
                ]
            else:
                continue
            for alias, local in bindings:
                if local not in used:
                    yield Finding(
                        ctx.path, node.lineno, node.col_offset, "unused-import",
                        f"`{alias.name}` is imported but never used",
                    )
