"""lock-registry: the LOCK_OWNERSHIP table swept in both directions.

The consolidated lock registry (ont_tcrconsensus_tpu/robustness/locks.py)
is only trustworthy if it cannot rot. Same discipline as the chaos/obs
site cross-checks, applied to lock ownership:

- **declared-but-unused** (``lock-registry-unknown-attr``): every
  ``"ClassName.attr": "lock_attr"`` entry — and every ``LOCK_EXEMPT``
  key — must name a class that exists in the scanned tree, an attr that
  class actually assigns on ``self``, and a lock attr that exists too.
  A rename that orphans a declaration fails here instead of silently
  un-protecting the attr (the discipline rule no-ops on unknown names).
- **used-but-undeclared** (``lock-registry-undeclared-attr``): within a
  class that appears in the registry, any ``self.x = <mutable
  container>`` in ``__init__`` must be in LOCK_OWNERSHIP or LOCK_EXEMPT
  (with its one-line reason). A new table added to a guarded class
  cannot dodge the analyzers by just not being declared.

Like lock-discipline, the rule keys off dict literals named
``LOCK_OWNERSHIP`` / ``LOCK_EXEMPT`` anywhere in the scanned set, so
fixture trees are self-contained and a scan with no registry no-ops.
Only classes named in the registry are swept for undeclared containers —
ordinary classes with plain dict/list state are not this rule's
business.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.core import Finding, Project
from tools.graftlint.rules.lock_discipline import _TABLE_NAME

RULES = {
    "lock-registry-unknown-attr": "LOCK_OWNERSHIP/LOCK_EXEMPT entry names "
                                  "a class, attr, or lock that does not "
                                  "exist in the scanned tree",
    "lock-registry-undeclared-attr": "mutable container on a registered "
                                     "class missing from both "
                                     "LOCK_OWNERSHIP and LOCK_EXEMPT",
}

_EXEMPT_NAME = "LOCK_EXEMPT"

#: constructor calls whose result is a shared-mutation hazard
_CONTAINER_CTORS = {
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict", "Counter",
}


def _dict_literal_entries(project: Project, name: str):
    """Yield (ctx, key_node, key, value) for every ``name = {...}`` literal."""
    for ctx in project.files:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets
            ) and isinstance(node.value, ast.Dict)):
                continue
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    yield ctx, k, k.value, v


def _is_container_value(node: ast.expr) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.SetComp, ast.DictComp)):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        tail = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None)
        return tail in _CONTAINER_CTORS
    return False


def _class_attrs(project: Project) -> dict[str, dict]:
    """{class: {"attrs": {attr}, "containers": {attr: assign_node},
    "ctx": FileCtx}} — every ``self.x = ...`` in each ClassDef body."""
    out: dict[str, dict] = {}
    for ctx in project.files:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            info = out.setdefault(
                node.name, {"attrs": set(), "containers": {}, "ctx": ctx})
            for method in node.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                in_init = method.name == "__init__"
                for sub in ast.walk(method):
                    if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                        continue
                    targets = (sub.targets if isinstance(sub, ast.Assign)
                               else [sub.target])
                    for t in targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            info["attrs"].add(t.attr)
                            if in_init and _is_container_value(sub.value):
                                info["containers"].setdefault(t.attr, sub)
    return out


def check(project: Project) -> Iterator[Finding]:
    owned_entries = list(_dict_literal_entries(project, _TABLE_NAME))
    exempt_entries = list(_dict_literal_entries(project, _EXEMPT_NAME))
    if not owned_entries:
        return
    classes = _class_attrs(project)

    declared: dict[str, set[str]] = {}
    # direction 1: every declared entry must resolve in the tree. The
    # lock-attr check applies to LOCK_OWNERSHIP only — LOCK_EXEMPT
    # values are prose reasons, not lock attrs.
    for is_exempt, entries in ((False, owned_entries),
                               (True, exempt_entries)):
        for ctx, key_node, key, value in entries:
            if "." not in key:
                continue
            cls, attr = key.rsplit(".", 1)
            declared.setdefault(cls, set()).add(attr)
            info = classes.get(cls)
            if info is None:
                yield Finding(
                    ctx.path, key_node.lineno, key_node.col_offset,
                    "lock-registry-unknown-attr",
                    f"registry entry {key!r} names class {cls!r} which "
                    "does not exist in the scanned tree — stale after a "
                    "rename?")
                continue
            if attr not in info["attrs"]:
                yield Finding(
                    ctx.path, key_node.lineno, key_node.col_offset,
                    "lock-registry-unknown-attr",
                    f"registry entry {key!r}: {cls} never assigns "
                    f"self.{attr} — stale after a rename?")
            lock = (value.value if isinstance(value, ast.Constant)
                    and isinstance(value.value, str) else None)
            if not is_exempt and lock is not None \
                    and lock not in info["attrs"]:
                yield Finding(
                    ctx.path, key_node.lineno, key_node.col_offset,
                    "lock-registry-unknown-attr",
                    f"registry entry {key!r} names lock {lock!r} which "
                    f"{cls} never assigns")

    # direction 2: every container on a registered class must be declared
    for cls, attrs in declared.items():
        info = classes.get(cls)
        if info is None:
            continue
        for attr, node in sorted(info["containers"].items()):
            if attr in attrs:
                continue
            yield Finding(
                info["ctx"].path, node.lineno, node.col_offset,
                "lock-registry-undeclared-attr",
                f"{cls}.{attr} is a mutable container on a registered "
                "class but is in neither LOCK_OWNERSHIP nor LOCK_EXEMPT "
                "— declare its lock or exempt it with a reason")
