"""Rule registry: every rule module's ``check`` + the combined catalogue."""

from __future__ import annotations

from tools.graftlint.rules import (
    chaos_sites,
    config_fields,
    donation_use,
    exception_guard,
    graph_sites,
    imports,
    jit_hygiene,
    lock_discipline,
    lock_registry,
    obs_sites,
    recompile_hazard,
)

_MODULES = (jit_hygiene, exception_guard, chaos_sites, obs_sites,
            graph_sites, config_fields, imports, donation_use,
            recompile_hazard, lock_discipline, lock_registry)

CHECKS = tuple(m.check for m in _MODULES)

RULE_CATALOGUE: dict[str, str] = {
    "parse-error": "file does not parse (not suppressible)",
}
for _m in _MODULES:
    RULE_CATALOGUE.update(_m.RULES)
