"""obs-site cross-check vs the telemetry registry (``obs.KNOWN_SITES``).

Mirror of the chaos-site rule (:mod:`.chaos_sites`) for the telemetry
layer: a typo in a planted metric/span/dispatch-site literal is silent
forever — the counter/trace row simply never appears under the name a
dashboard or A/B script greps for — and a registry entry nothing plants
anymore leaves ``telemetry.json`` consumers reading a field that can
never be populated. Both directions, cross-file:

- ``obs-unknown-site``   — a site literal passed to a telemetry plant
  function (``counter_add`` / ``gauge_max`` / ``observe`` / ``span`` /
  ``instant`` / ``dispatch`` / ``timed_get`` / ``StageTimer.stage``, plus
  the live-plane plants ``ring_event`` and the ``progress_node_*``
  family from ``obs/live.py``) that is not an ``obs.KNOWN_SITES`` entry;
- ``obs-unplanted-site`` — a registry entry never planted in the scanned
  tree (reported at the entry's own line).

The registry is read from the scanned files themselves — the
``OBS_SITES = frozenset({...})`` assignment in ``obs/__init__.py`` (that
module aliases it to the public ``KNOWN_SITES`` name; the distinct
assignment name keeps the chaos rule, which collects every
``KNOWN_SITES = ...`` literal in scope, from merging the two
vocabularies). With no definition in scope the checks no-op, so partial
fixture trees lint quietly.

Dynamically-built names (f-strings like the overlap workers'
``f"{name}_bg"``, the recorder's per-event instants) are out of scope by
construction: only string literals are checked, exactly like the chaos
rule.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.core import FileCtx, Finding, Project

RULES = {
    "obs-unknown-site": "telemetry site literal (counter_add/gauge_max/"
                        "observe/pool_add/span/instant/dispatch/timed_get/"
                        "stage/ring_event/progress_node_*/h2d/d2h) not in "
                        "obs.KNOWN_SITES (dead metric/span name)",
    "obs-unplanted-site": "obs.KNOWN_SITES entry not planted at any "
                          "telemetry call site in the scanned tree",
}

_PLANT_FUNCS = {
    "counter_add", "gauge_max", "observe",  # obs.metrics
    "gauge_set",                            # obs.metrics (live last-value
    # gauges — serve queue depth; reject_add is NOT here because its
    # argument is a rejection reason label, not an OBS_SITES site)
    "pool_add",                             # obs.metrics (worker-pool
    # busy/idle split, planted by pipeline.overlap.StageExecutor)
    "span", "instant",                      # obs.trace
    "dispatch", "timed_get",                # obs.device
    "stage",                                # qc.timing.StageTimer.stage
    "add_node",                             # graph.ir.GraphBuilder — the
    # executor derives span/timer names from the declared node name, so a
    # declaration IS a telemetry plant (graph node names must be
    # OBS_SITES entries; see rules/graph_sites.py)
    "ring_event",                           # obs.live — flight-recorder
    # instants; literal event names are site names a --report reader
    # greps for, so they live in the same vocabulary
    "progress_node_start", "progress_node_finish",  # obs.live — the
    "progress_node_skip",                   # /progress plane keys its
    # node map by graph node name (literal plants only; the executor's
    # node.name args are dynamic and out of scope, like f-string sites)
    "h2d", "d2h",                           # obs.transfers — device
    # data-plane ledger plants at device_put/device_get boundaries;
    # timed_get feeds d2h with its own (already-checked) site, so only
    # literal transfer.* plants surface here
}

_REGISTRY_NAME = "OBS_SITES"


def known_sites(project: Project) -> dict[str, tuple[str, int]]:
    """{site: (path, line)} from every ``OBS_SITES = ...`` assignment whose
    value contains string constants (set/frozenset/tuple literals)."""
    sites: dict[str, tuple[str, int]] = {}
    for ctx in project.files:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == _REGISTRY_NAME
                for t in node.targets
            )):
                continue
            for const in ast.walk(node.value):
                if isinstance(const, ast.Constant) and isinstance(const.value, str):
                    sites[const.value] = (ctx.path, const.lineno)
    return sites


def _plant_calls(ctx: FileCtx) -> Iterator[tuple[ast.Call, str]]:
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None
        )
        if name not in _PLANT_FUNCS:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            yield node, first.value


def check(project: Project) -> Iterator[Finding]:
    known = known_sites(project)
    if not known:
        return
    planted: set[str] = set()
    for ctx in project.files:
        for node, site in _plant_calls(ctx):
            planted.add(site)
            if site not in known:
                yield Finding(
                    ctx.path, node.lineno, node.col_offset, "obs-unknown-site",
                    f"site {site!r} is not in obs.KNOWN_SITES — this "
                    "metric/span can never be found under a registered "
                    "name (typo?)",
                )
    for site, (path, line) in sorted(known.items()):
        if site not in planted:
            yield Finding(
                path, line, 0, "obs-unplanted-site",
                f"obs.KNOWN_SITES entry {site!r} is planted nowhere in the "
                "scanned tree — telemetry consumers reading it get nothing",
            )
