"""graph-node cross-checks vs the stage-graph registry (``graph.GRAPH_NODES``).

The graph executor derives every per-node attachment from the node's
declared name: the trace span / stage-timing row (``timer.stage(name)``),
the watchdog guard (``watchdog.guard(name, ...)``), the telemetry
``graph.nodes`` entry, and the ``f"{name}_bg"`` overlap span. A typo'd
declaration therefore silently detaches a node from every dashboard and
deadline at once. Mirroring the chaos-site rule, three directions,
cross-file:

- ``graph-unknown-node``      — a name literal passed to
  ``GraphBuilder.add_node`` that is not a ``GRAPH_NODES`` entry;
- ``graph-undeclared-node``   — a ``GRAPH_NODES`` entry never declared by
  any ``add_node`` literal in the scanned tree (a node the vocabulary
  promises but no graph builds);
- ``graph-unattributed-node`` — a ``GRAPH_NODES`` entry missing from
  ``obs.OBS_SITES``: the executor would emit that node's span/timer rows
  under a name the obs rule does not police, so the heartbeat/timer
  vocabulary and the graph vocabulary drift apart.

Chaos coverage needs no per-node direction: every critical node body
shares the single ``graph.node`` injection site and every overlapped node
runs under ``overlap.worker`` — both policed by the chaos-site rule.

The registry is read from the scanned files themselves — the
``GRAPH_NODES = frozenset({...})`` assignment in ``graph/__init__.py``
(its own name so the chaos rule, which collects every
``KNOWN_SITES = ...`` literal, does not merge the vocabularies). With no
definition in scope the checks no-op, so fixture trees lint quietly;
test graphs passing node names through variables are out of scope by
construction, exactly like the chaos rule.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.core import FileCtx, Finding, Project
from tools.graftlint.rules import obs_sites

RULES = {
    "graph-unknown-node": "add_node name literal not in graph.GRAPH_NODES "
                          "(node invisible to the graph-name vocabulary)",
    "graph-undeclared-node": "GRAPH_NODES entry never declared by any "
                             "add_node literal in the scanned tree",
    "graph-unattributed-node": "GRAPH_NODES entry missing from "
                               "obs.OBS_SITES — the executor's per-node "
                               "spans/timers would be unpoliced",
}

_REGISTRY_NAME = "GRAPH_NODES"
_PLANT_FUNC = "add_node"


def known_nodes(project: Project) -> dict[str, tuple[str, int]]:
    """{node: (path, line)} from every ``GRAPH_NODES = ...`` assignment
    whose value contains string constants."""
    nodes: dict[str, tuple[str, int]] = {}
    for ctx in project.files:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == _REGISTRY_NAME
                for t in node.targets
            )):
                continue
            for const in ast.walk(node.value):
                if isinstance(const, ast.Constant) and isinstance(const.value, str):
                    nodes[const.value] = (ctx.path, const.lineno)
    return nodes


def _declare_calls(ctx: FileCtx) -> Iterator[tuple[ast.Call, str]]:
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None
        )
        if name != _PLANT_FUNC:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            yield node, first.value


def check(project: Project) -> Iterator[Finding]:
    known = known_nodes(project)
    if not known:
        return
    declared: set[str] = set()
    for ctx in project.files:
        for node, name in _declare_calls(ctx):
            declared.add(name)
            if name not in known:
                yield Finding(
                    ctx.path, node.lineno, node.col_offset,
                    "graph-unknown-node",
                    f"node {name!r} is not in graph.GRAPH_NODES — its "
                    "spans/guards/telemetry land under an unregistered "
                    "name (typo?)",
                )
    for name, (path, line) in sorted(known.items()):
        if name not in declared:
            yield Finding(
                path, line, 0, "graph-undeclared-node",
                f"GRAPH_NODES entry {name!r} is declared by no add_node "
                "call in the scanned tree — the vocabulary promises a "
                "node nothing builds",
            )
    obs = obs_sites.known_sites(project)
    if not obs:
        return
    for name, (path, line) in sorted(known.items()):
        if name not in obs:
            yield Finding(
                path, line, 0, "graph-unattributed-node",
                f"GRAPH_NODES entry {name!r} is missing from "
                "obs.OBS_SITES — the executor's per-node span/timer/guard "
                "names would escape the obs-site checks",
            )
