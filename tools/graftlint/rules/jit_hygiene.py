"""jit-hygiene: host-sync / impurity / retrace hazards inside jitted code.

A ``@jax.jit`` function's non-static parameters are tracers. Touching one
with host-side machinery either crashes at trace time on a rare path or —
worse — silently forces a device sync / retrace on every call. Three
hazards, each a rule:

- ``jit-host-sync``   — ``np.*`` calls, ``float()/int()/bool()``,
  ``.item()/.tolist()`` applied to a traced value;
- ``jit-impure-call`` — ``time.*`` / ``random.*`` / ``np.random.*``
  calls anywhere in a jitted body (impure: baked in at trace time, then
  frozen — the classic "why is my jitted timestamp constant" bug);
- ``jit-tracer-branch`` — Python ``if``/``while``/``assert``/ternary (or a
  ``for`` loop's iterable) on a traced value: a concretization error at
  trace time, or an unrolled retrace bomb.

Taint model: non-static parameters of a jitted function (and of every
function nested inside it — ``lax.scan``/``vmap`` bodies) are tainted;
assignments propagate taint through expressions. Reading ``.shape`` /
``.ndim`` / ``.dtype`` / ``.size`` or calling ``len()`` on a tracer
yields a static Python value, so those strip taint, as do ``is None``
comparisons. Jitted functions are found both by decorator
(``@jax.jit``, ``@partial(jax.jit, static_argnames=...)``) and by call
site (``jax.jit(fn)``, ``jax.jit(shard_map(fn, ...))`` — any local
function named inside the wrapped expression).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.astutil import ImportMap, dotted_name, func_defs_by_name
from tools.graftlint.core import FileCtx, Finding, Project

RULES = {
    "jit-host-sync": "numpy/float/int/bool/.item() applied to a traced value "
                     "inside a jitted function",
    "jit-impure-call": "time.*/random.* call inside a jitted function "
                       "(baked in at trace time)",
    "jit-tracer-branch": "Python control flow on a traced value inside a "
                         "jitted function",
}

# attribute reads that return STATIC Python values even on a tracer
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "weak_type"}
# builtins whose result is static (and which are safe on tracers)
_STATIC_CALLS = {"len", "isinstance", "type", "getattr", "hasattr", "id"}
# builtins that force a concrete host value out of their argument
_CONCRETIZING_CALLS = {"float", "int", "bool", "complex"}
# tracer methods that force a host sync
_SYNC_METHODS = {"item", "tolist", "block_until_ready", "__array__"}
# impure modules: calls through these inside a jitted body are trace-time
# constants (jax.random is fine — different module root)
_IMPURE_PREFIXES = ("time.", "random.", "numpy.random.")
_IMPURE_MODULES = {"time", "random"}

_JIT_TARGETS = {"jax.jit", "jax.api.jit"}
_PARTIAL_TARGETS = {"functools.partial", "partial"}


def _jit_static_argnames(call: ast.Call) -> set[str]:
    """static_argnames values from a jax.jit/partial(jax.jit, ...) call."""
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(node.value, str):
                    names.add(node.value)
    return names


def _collect_jit_functions(ctx: FileCtx, imports: ImportMap):
    """-> list of (FunctionDef, static_argnames) considered jit-compiled."""
    defs = func_defs_by_name(ctx.tree)
    jitted: dict[ast.FunctionDef, set[str]] = {}

    def is_jit(node: ast.AST) -> bool:
        target = imports.resolve_call_target(node)
        return target in _JIT_TARGETS or dotted_name(node) in _JIT_TARGETS

    for fn_list in defs.values():
        for fn in fn_list:
            for deco in fn.decorator_list:
                if is_jit(deco):
                    jitted.setdefault(fn, set())
                elif isinstance(deco, ast.Call):
                    target = imports.resolve_call_target(deco.func)
                    if target in _PARTIAL_TARGETS and deco.args and is_jit(deco.args[0]):
                        jitted.setdefault(fn, set()).update(_jit_static_argnames(deco))
                    elif is_jit(deco.func):
                        jitted.setdefault(fn, set()).update(_jit_static_argnames(deco))
    # call-site wrapping: jax.jit(fn), jax.jit(vmap(fn)), jit(shard_map(f,..))
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and is_jit(node.func) and node.args):
            continue
        static = _jit_static_argnames(node)
        for name_node in ast.walk(node.args[0]):
            if isinstance(name_node, ast.Name):
                for fn in defs.get(name_node.id, ()):
                    jitted.setdefault(fn, set()).update(static)
    return sorted(jitted.items(), key=lambda kv: kv[0].lineno)


def _param_names(args: ast.arguments) -> list[str]:
    params = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        params.append(args.vararg.arg)
    if args.kwarg:
        params.append(args.kwarg.arg)
    return params


class _JitBodyChecker:
    """Walk one jitted function's body with a taint set, emitting findings."""

    def __init__(self, ctx: FileCtx, imports: ImportMap, fn: ast.FunctionDef,
                 static_argnames: set[str]):
        self.ctx = ctx
        self.imports = imports
        self.fn = fn
        self.findings: list[Finding] = []
        self.tainted = {
            name for name in _param_names(fn.args) if name not in static_argnames
        }
        # nested defs are analyzed AFTER the enclosing body (their param
        # taint depends on how the body uses them — see _process_nested)
        self._nested: list[ast.FunctionDef] = []

    def run(self) -> None:
        self.walk_body(self.fn.body)
        self._process_nested(self.fn)

    def _process_nested(self, enclosing: ast.FunctionDef) -> None:
        """Analyze deferred nested defs.

        A nested function handed BY NAME into jax machinery (``lax.scan``,
        ``vmap``, ``pallas_call`` — any call argument position) runs under
        the trace with tracer parameters: taint them all. A helper that is
        only ever called directly gets per-parameter taint from its call
        sites (``pad_to(x, N, fill)`` with static ``N`` must not flag
        ``if x.shape[0] == n``).
        """
        pending, self._nested = self._nested, []
        for nested in pending:
            params = _param_names(nested.args)
            escapes = False
            site_taint: set[str] = set()
            for node in ast.walk(enclosing):
                if not isinstance(node, ast.Call):
                    continue
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if any(isinstance(n, ast.Name) and n.id == nested.name
                           for n in ast.walk(arg)):
                        escapes = True
                if (isinstance(node.func, ast.Name)
                        and node.func.id == nested.name):
                    for param, arg in zip(params, node.args):
                        if self.is_tainted(arg):
                            site_taint.add(param)
            outer = set(self.tainted)
            self.tainted = (outer - set(params)) | (
                set(params) if escapes else site_taint
            )
            self.walk_body(nested.body)
            self._process_nested(nested)
            self.tainted = outer

    def emit(self, node: ast.AST, rule: str, message: str) -> None:
        self.findings.append(Finding(
            self.ctx.path, node.lineno, node.col_offset, rule,
            f"{message} (in jitted `{self.fn.name}`)",
        ))

    # --- taint -----------------------------------------------------------

    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` compares identity, not value
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
        if isinstance(node, ast.Call):
            func_name = dotted_name(node.func)
            if func_name in _STATIC_CALLS:
                return False
            return any(self.is_tainted(c) for c in ast.iter_child_nodes(node))
        if isinstance(node, ast.Lambda):
            return False  # a lambda VALUE is not a tracer
        return any(self.is_tainted(c) for c in ast.iter_child_nodes(node))

    # --- expression hazards ---------------------------------------------

    def scan_expr(self, node: ast.AST) -> None:
        """Find host-sync / impure calls anywhere under an expression."""
        if isinstance(node, ast.Lambda):
            # lambda params are traced when the lambda feeds vmap/scan —
            # but only WITHIN the lambda body (a sort-key lambda must not
            # leak taint onto a same-named static name in the enclosing
            # scope)
            saved = set(self.tainted)
            self.tainted.update(_param_names(node.args))
            self.scan_expr(node.body)
            self.tainted = saved
            return
        if isinstance(node, ast.Call):
            self._check_call(node)
        for child in ast.iter_child_nodes(node):
            self.scan_expr(child)

    def _check_call(self, child: ast.Call) -> None:
        target = self.imports.resolve_call_target(child.func)
        plain = dotted_name(child.func)
        arg_tainted = any(
            self.is_tainted(a) for a in child.args
        ) or any(self.is_tainted(k.value) for k in child.keywords)
        if target is not None and (
            target.startswith(_IMPURE_PREFIXES)
            or target in _IMPURE_MODULES
        ):
            self.emit(child, "jit-impure-call",
                      f"`{plain}(...)` is impure under tracing: its result "
                      "is frozen into the compiled program")
        elif plain in _CONCRETIZING_CALLS and arg_tainted:
            self.emit(child, "jit-host-sync",
                      f"`{plain}()` on a traced value forces a host sync "
                      "(concretization error on abstract tracers)")
        elif (isinstance(child.func, ast.Attribute)
                and child.func.attr in _SYNC_METHODS
                and self.is_tainted(child.func.value)):
            self.emit(child, "jit-host-sync",
                      f"`.{child.func.attr}()` on a traced value forces a "
                      "host sync")
        elif (target is not None and target.startswith("numpy.")
                and not target.startswith("numpy.random.")
                and arg_tainted):
            self.emit(child, "jit-host-sync",
                      f"`{plain}(...)` is host numpy applied to a traced "
                      "value; use jnp inside jit")

    # --- statements ------------------------------------------------------

    def assign_targets(self, target: ast.AST, taint: bool) -> None:
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                if taint:
                    self.tainted.add(node.id)
                else:
                    self.tainted.discard(node.id)

    def walk_body(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self.walk_stmt(stmt)

    def walk_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._nested.append(stmt)  # analyzed by _process_nested
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            if value is not None:
                self.scan_expr(value)
                taint = self.is_tainted(value)
                targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                if isinstance(stmt, ast.AugAssign):
                    taint = taint or self.is_tainted(stmt.target)
                for target in targets:
                    self.assign_targets(target, taint)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self.scan_expr(stmt.test)
            if self.is_tainted(stmt.test):
                kind = "if" if isinstance(stmt, ast.If) else "while"
                self.emit(stmt, "jit-tracer-branch",
                          f"Python `{kind}` on a traced value; use jnp.where / "
                          "lax.cond / lax.while_loop")
            self.walk_body(stmt.body)
            self.walk_body(stmt.orelse)
            return
        if isinstance(stmt, ast.For):
            self.scan_expr(stmt.iter)
            if self.is_tainted(stmt.iter):
                self.emit(stmt, "jit-tracer-branch",
                          "Python `for` over a traced value; use lax.scan / "
                          "lax.fori_loop")
            self.assign_targets(stmt.target, taint=True)
            self.walk_body(stmt.body)
            self.walk_body(stmt.orelse)
            return
        if isinstance(stmt, ast.Assert):
            self.scan_expr(stmt.test)
            if self.is_tainted(stmt.test):
                self.emit(stmt, "jit-tracer-branch",
                          "`assert` on a traced value; use checkify or a "
                          "host_callback debug check")
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self.scan_expr(item.context_expr)
            self.walk_body(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self.walk_body(stmt.body)
            for handler in stmt.handlers:
                self.walk_body(handler.body)
            self.walk_body(stmt.orelse)
            self.walk_body(stmt.finalbody)
            return
        # Return / Expr / Raise / everything else: scan embedded expressions,
        # flagging ternaries on tracers along the way
        for node in ast.walk(stmt):
            if isinstance(node, ast.IfExp):
                if self.is_tainted(node.test):
                    self.emit(node, "jit-tracer-branch",
                              "ternary on a traced value; use jnp.where")
        self.scan_expr(stmt)


def check(project: Project) -> Iterator[Finding]:
    for ctx in project.files:
        imports = ImportMap(ctx.tree)
        # cheap skip: no jax import, no jitted functions
        if not any(m == "jax" or m.startswith("jax.")
                   for m in list(imports.modules.values())
                   + list(imports.from_imports.values())):
            continue
        seen: set[int] = set()
        for fn, static in _collect_jit_functions(ctx, imports):
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            checker = _JitBodyChecker(ctx, imports, fn, static)
            checker.run()
            yield from checker.findings
