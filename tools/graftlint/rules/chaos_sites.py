"""chaos-site cross-check: planted literals vs ``faults.KNOWN_SITES``.

The chaos registry fails fast on unknown sites when ARMING a plan, but a
typo in a *planted* ``faults.inject("...")`` literal is silent forever:
the site never matches any spec and the injection point is dead. The
inverse drift — a ``KNOWN_SITES`` entry whose plant was refactored away —
leaves chaos plans that "pass" without testing anything. Both directions
are cross-file properties, checked here:

- ``chaos-unknown-site``   — an ``inject``/``mutate_input``/``tear_write``
  site literal that is not in ``KNOWN_SITES``;
- ``chaos-unplanted-site`` — a ``KNOWN_SITES`` entry never planted in the
  scanned tree (reported at the entry's own line in faults.py).

``KNOWN_SITES`` is read from the scanned files themselves (the
``KNOWN_SITES = frozenset({...})`` assignment), so fixture trees exercise
the same path; with no definition in scope both checks no-op.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.core import FileCtx, Finding, Project

RULES = {
    "chaos-unknown-site": "faults.inject/mutate_input/tear_write site literal "
                          "not in faults.KNOWN_SITES (dead injection point)",
    "chaos-unplanted-site": "KNOWN_SITES entry not planted at any injection "
                            "point in the scanned tree",
}

_PLANT_FUNCS = {"inject", "mutate_input", "tear_write"}


def known_sites(project: Project) -> dict[str, tuple[str, int]]:
    """{site: (path, line)} from every ``KNOWN_SITES = frozenset(...)`` /
    set-literal assignment in the scanned files."""
    sites: dict[str, tuple[str, int]] = {}
    for ctx in project.files:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "KNOWN_SITES"
                for t in node.targets
            )):
                continue
            for const in ast.walk(node.value):
                if isinstance(const, ast.Constant) and isinstance(const.value, str):
                    sites[const.value] = (ctx.path, const.lineno)
    return sites


def _plant_calls(ctx: FileCtx) -> Iterator[tuple[ast.Call, str]]:
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None
        )
        if name not in _PLANT_FUNCS:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            yield node, first.value


def planted_sites(project: Project) -> dict[str, list[tuple[str, int]]]:
    """{site literal: [(path, line), ...]} for every plant call in scope."""
    plants: dict[str, list[tuple[str, int]]] = {}
    for ctx in project.files:
        for node, site in _plant_calls(ctx):
            plants.setdefault(site, []).append((ctx.path, node.lineno))
    return plants


def check(project: Project) -> Iterator[Finding]:
    known = known_sites(project)
    if not known:
        return  # no faults registry in the scanned set: nothing to check
    plants = planted_sites(project)
    for ctx in project.files:
        for node, site in _plant_calls(ctx):
            if site not in known:
                yield Finding(
                    ctx.path, node.lineno, node.col_offset, "chaos-unknown-site",
                    f"site {site!r} is not in faults.KNOWN_SITES — this "
                    "injection point can never fire (typo?)",
                )
    for site, (path, line) in sorted(known.items()):
        if site not in plants:
            yield Finding(
                path, line, 0, "chaos-unplanted-site",
                f"KNOWN_SITES entry {site!r} is planted nowhere in the "
                "scanned tree — chaos plans arming it test nothing",
            )
