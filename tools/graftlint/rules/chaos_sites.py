"""chaos-site AND chaos-kind cross-checks vs the ``faults`` registry.

The chaos registry fails fast on unknown sites/kinds when ARMING a plan,
but a typo in a *planted* ``faults.inject("...")`` literal — or in a
``kind`` literal inside a test's spec dict or a handler comparison — is
silent forever: the site/kind never matches and the injection point (or
the scenario arming it) is dead. The inverse drift — a ``KNOWN_SITES`` /
``KINDS`` entry nothing plants or arms anymore — leaves chaos plans that
"pass" without testing anything. All four directions are cross-file
properties, checked here:

- ``chaos-unknown-site``   — an ``inject``/``mutate_input``/``tear_write``
  /``corrupt_artifact`` site literal that is not in ``KNOWN_SITES``;
- ``chaos-unplanted-site`` — a ``KNOWN_SITES`` entry never planted in the
  scanned tree (reported at the entry's own line in faults.py);
- ``chaos-unknown-kind``   — a kind literal (a ``{"site": ..., "kind": X}``
  spec dict, a ``FaultSpec(kind=X)`` call, or a ``spec.kind == X`` /
  ``spec.kind in (...)`` handler comparison) not in ``KINDS``;
- ``chaos-unused-kind``    — a ``KINDS`` entry no spec literal in the
  scanned tree ever arms (reported at the KINDS tuple's line) — a fault
  family the chaos suite silently stopped exercising.

``KNOWN_SITES`` / ``KINDS`` are read from the scanned files themselves
(the ``KNOWN_SITES = frozenset({...})`` / ``KINDS = (...)`` assignments),
so fixture trees exercise the same path; with no definition in scope the
corresponding checks no-op.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.core import FileCtx, Finding, Project

RULES = {
    "chaos-unknown-site": "faults.inject/mutate_input/tear_write/"
                          "corrupt_artifact site literal not in "
                          "faults.KNOWN_SITES (dead injection point)",
    "chaos-unplanted-site": "KNOWN_SITES entry not planted at any injection "
                            "point in the scanned tree",
    "chaos-unknown-kind": "chaos kind literal (spec dict / FaultSpec kwarg / "
                          "handler comparison) not in faults.KINDS "
                          "(dead fault spec)",
    "chaos-unused-kind": "KINDS entry never armed by any spec literal in "
                         "the scanned tree (unexercised fault family)",
}

_PLANT_FUNCS = {"inject", "mutate_input", "tear_write", "corrupt_artifact"}


def known_sites(project: Project) -> dict[str, tuple[str, int]]:
    """{site: (path, line)} from every ``KNOWN_SITES = frozenset(...)`` /
    set-literal assignment in the scanned files."""
    sites: dict[str, tuple[str, int]] = {}
    for ctx in project.files:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "KNOWN_SITES"
                for t in node.targets
            )):
                continue
            for const in ast.walk(node.value):
                if isinstance(const, ast.Constant) and isinstance(const.value, str):
                    sites[const.value] = (ctx.path, const.lineno)
    return sites


def _plant_calls(ctx: FileCtx) -> Iterator[tuple[ast.Call, str]]:
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None
        )
        if name not in _PLANT_FUNCS:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            yield node, first.value


def planted_sites(project: Project) -> dict[str, list[tuple[str, int]]]:
    """{site literal: [(path, line), ...]} for every plant call in scope."""
    plants: dict[str, list[tuple[str, int]]] = {}
    for ctx in project.files:
        for node, site in _plant_calls(ctx):
            plants.setdefault(site, []).append((ctx.path, node.lineno))
    return plants


def known_kinds(project: Project) -> dict[str, tuple[str, int]]:
    """{kind: (path, line)} from every ``KINDS = (...)`` assignment in the
    scanned files (tuple/set/list of string constants)."""
    kinds: dict[str, tuple[str, int]] = {}
    for ctx in project.files:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "KINDS"
                for t in node.targets
            )):
                continue
            for const in ast.walk(node.value):
                if isinstance(const, ast.Constant) and isinstance(const.value, str):
                    kinds[const.value] = (ctx.path, const.lineno)
    return kinds


def _kind_literals(ctx: FileCtx) -> Iterator[tuple[ast.AST, str, bool]]:
    """(node, kind literal, is_spec) per kind usage in one file.

    ``is_spec`` usages ARM a fault (a ``{"site": ..., "kind": X}`` dict or
    a ``FaultSpec(kind=X)`` call) and count for the unused-kind direction;
    handler comparisons (``spec.kind == X`` / ``spec.kind in (...)``) are
    checked against KINDS but do not make a kind "used".
    """
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Dict):
            keys = [k.value for k in node.keys
                    if isinstance(k, ast.Constant)]
            if "kind" in keys and "site" in keys:
                for k, v in zip(node.keys, node.values):
                    if (isinstance(k, ast.Constant) and k.value == "kind"
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, str)):
                        yield v, v.value, True
        elif isinstance(node, ast.Call):
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else None
            )
            if name == "FaultSpec":
                for kw in node.keywords:
                    if (kw.arg == "kind" and isinstance(kw.value, ast.Constant)
                            and isinstance(kw.value.value, str)):
                        yield kw.value, kw.value.value, True
        elif isinstance(node, ast.Compare):
            left = node.left
            if not (isinstance(left, ast.Attribute) and left.attr == "kind"):
                continue
            for comp in node.comparators:
                consts = ([comp] if isinstance(comp, ast.Constant)
                          else list(getattr(comp, "elts", ())))
                for c in consts:
                    if isinstance(c, ast.Constant) and isinstance(c.value, str):
                        yield c, c.value, False


def check(project: Project) -> Iterator[Finding]:
    known = known_sites(project)
    if known:
        plants = planted_sites(project)
        for ctx in project.files:
            for node, site in _plant_calls(ctx):
                if site not in known:
                    yield Finding(
                        ctx.path, node.lineno, node.col_offset, "chaos-unknown-site",
                        f"site {site!r} is not in faults.KNOWN_SITES — this "
                        "injection point can never fire (typo?)",
                    )
        for site, (path, line) in sorted(known.items()):
            if site not in plants:
                yield Finding(
                    path, line, 0, "chaos-unplanted-site",
                    f"KNOWN_SITES entry {site!r} is planted nowhere in the "
                    "scanned tree — chaos plans arming it test nothing",
                )
    kinds = known_kinds(project)
    if kinds:
        used: set[str] = set()
        for ctx in project.files:
            for node, kind, is_spec in _kind_literals(ctx):
                if is_spec:
                    used.add(kind)
                if kind not in kinds:
                    yield Finding(
                        ctx.path, node.lineno, node.col_offset,
                        "chaos-unknown-kind",
                        f"kind {kind!r} is not in faults.KINDS — this "
                        "spec/handler can never fire (typo?)",
                    )
        for kind, (path, line) in sorted(kinds.items()):
            if kind not in used:
                yield Finding(
                    path, line, 0, "chaos-unused-kind",
                    f"KINDS entry {kind!r} is armed by no spec literal in "
                    "the scanned tree — this fault family is untested",
                )
