"""lock-discipline: shared registry state mutated outside its lock.

A lightweight static race detector for the shared-mutable layers the
telemetry / watchdog / overlap work built: the obs metrics registry is
fed from worker threads and the watchdog monitor thread, the watchdog's
entry table from every guarded stage, the overlap executor's counters
from pool workers — each guards its state with one lock, and a mutation
that skips it is a data race that only loses increments under load,
never in a unit test.

The ownership table is declarative: a ``LOCK_OWNERSHIP =
{"ClassName.attr": "lock_attr"}`` dict literal anywhere in the scanned
tree. The shipped tree consolidates every declaration into ONE registry
(ont_tcrconsensus_tpu/robustness/locks.py — also the universe graftrace's
lockset analysis proves over, and the lock set the runtime twin
``TCR_LOCKCHECK=1`` asserts on); fixture trees declare their own, and
with none in scope the rule no-ops — the same registry-in-the-scanned-set
discipline as the chaos/obs/graph site rules. The companion
``lock-registry`` sweep (lock_registry.py) keeps the table honest in
both directions.

Within a listed class, any *mutation* of ``self.<attr>`` — rebinding,
augmented assignment, subscript store/delete, or a mutating method call
(``.append``/``.update``/``.setdefault``/...) — must sit lexically
inside ``with self.<lock_attr>:``.  Reads are exempt (the registries
tolerate torn reads for display), as are ``__init__`` (no concurrent
access before construction completes) and methods named ``*_locked``
(the caller-holds-the-lock convention, e.g. IngestGuard._close_locked).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.core import FileCtx, Finding, Project

RULES = {
    "lock-discipline": "registry attribute mutated outside its declared "
                       "lock (LOCK_OWNERSHIP table) — a data race under "
                       "worker/monitor threads",
}

_TABLE_NAME = "LOCK_OWNERSHIP"
_MUTATING_METHODS = {
    "append", "add", "update", "setdefault", "pop", "popitem", "clear",
    "remove", "discard", "extend", "insert", "__setitem__",
}


def ownership(project: Project) -> dict[str, dict[str, str]]:
    """{class: {attr: lock_attr}} merged from every LOCK_OWNERSHIP dict
    literal in the scanned files."""
    table: dict[str, dict[str, str]] = {}
    for ctx in project.files:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == _TABLE_NAME
                for t in node.targets
            ) and isinstance(node.value, ast.Dict)):
                continue
            for k, v in zip(node.value.keys, node.value.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)
                        and "." in k.value):
                    continue
                cls, attr = k.value.rsplit(".", 1)
                table.setdefault(cls, {})[attr] = v.value
    return table


def _self_attr(node: ast.AST) -> str | None:
    """'attr' when ``node`` is ``self.attr`` (possibly under subscripts)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _MethodWalker:
    """Walk one method body tracking which self.<lock> blocks enclose."""

    def __init__(self, ctx: FileCtx, cls: str, method: str,
                 owned: dict[str, str]):
        self.ctx = ctx
        self.cls = cls
        self.method = method
        self.owned = owned
        self.held: set[str] = set()
        self.findings: list[Finding] = []

    def _flag(self, node: ast.AST, attr: str, how: str) -> None:
        lock = self.owned[attr]
        self.findings.append(Finding(
            self.ctx.path, node.lineno, node.col_offset, "lock-discipline",
            f"{self.cls}.{self.method} {how} self.{attr} outside "
            f"`with self.{lock}:` — worker/monitor threads race this "
            "registry",
        ))

    def _check_mutation(self, node: ast.AST, attr: str | None,
                        how: str) -> None:
        if attr is None or attr not in self.owned:
            return
        if self.owned[attr] not in self.held:
            self._flag(node, attr, how)

    def walk(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self.visit(stmt)

    def visit(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def may run on another thread later: it must take
            # the lock itself, so the held set does not flow in
            saved, self.held = self.held, set()
            self.walk(stmt.body)
            self.held = saved
            return
        if isinstance(stmt, ast.With):
            added = set()
            for item in stmt.items:
                lock = _self_attr(item.context_expr)
                if lock is not None and lock not in self.held:
                    self.held.add(lock)
                    added.add(lock)
            self.walk(stmt.body)
            self.held -= added
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for target in targets:
                self._check_mutation(stmt, _self_attr(target), "writes")
        if isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                self._check_mutation(stmt, _self_attr(target), "deletes")
        self._scan_calls(stmt)
        for field in ("body", "orelse", "finalbody"):
            for sub in getattr(stmt, field, ()):
                self.visit(sub)
        for handler in getattr(stmt, "handlers", ()):
            for sub in handler.body:
                self.visit(sub)

    def _scan_calls(self, node: ast.AST) -> None:
        """Mutating method calls in THIS statement's own expressions —
        nested statements are visited by visit() under their own held
        set, and a Lambda body runs later (possibly off-thread), so both
        are boundaries, not children."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.stmt, ast.excepthandler, ast.Lambda)):
                continue
            if (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr in _MUTATING_METHODS):
                self._check_mutation(
                    child, _self_attr(child.func.value),
                    f"calls .{child.func.attr}() on")
            self._scan_calls(child)


def check(project: Project) -> Iterator[Finding]:
    table = ownership(project)
    if not table:
        return
    for ctx in project.files:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.ClassDef) and node.name in table):
                continue
            owned = table[node.name]
            for method in node.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if (method.name == "__init__"
                        or method.name.endswith("_locked")):
                    continue
                walker = _MethodWalker(ctx, node.name, method.name, owned)
                walker.walk(method.body)
                yield from walker.findings
