"""recompile-hazard: data-dependent Python sizes reaching shape sinks.

The static twin of the runtime recompile audit (obs/device.py's
``jax.monitoring`` listener): that one *counts* XLA compiles after the
fact; this rule flags the source pattern that causes them.  A value
derived from ``len(...)`` is data-dependent — every distinct input size
that reaches a shape-determining argument compiles a fresh program, the
exact storm the bucketing helpers exist to prevent.

Taint: names assigned from expressions containing a ``len(...)`` call
(propagated through arithmetic, ``min``/``max``, f-strings — anything),
per scope, in statement order.  An expression is *sanitized* — clean no
matter what it contains — when it passes through a quantizer: a
``pow2_ceil(...)`` / ``bucket_width(...)`` call, or any reference to the
fixed ``DEFAULT_WIDTHS`` table (``next(w for w in DEFAULT_WIDTHS if
w >= need)`` is the sanctioned snap-to-bucket idiom).

Sinks: a tainted ``pad_to=`` keyword in any call (the repo's one shape
knob — ops/encode.pad_batch and friends), and a tainted shape argument
(first positional or ``shape=``) of a ``jax.numpy`` array constructor.
Host ``np.zeros`` stays exempt: host allocation is free to vary.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.astutil import ImportMap, dotted_name
from tools.graftlint.core import FileCtx, Finding, Project

RULES = {
    "recompile-hazard": "len()-derived Python size reaches a shape "
                        "argument (pad_to= / jnp constructor) without a "
                        "bucketing quantizer — one XLA compile per "
                        "distinct input size",
}

# calls whose result is quantized (safe to hand to a shape sink)
_QUANTIZERS = {"pow2_ceil", "bucket_width"}
# fixed bucket tables: expressions selecting from them are quantized
_QUANT_TABLES = {"DEFAULT_WIDTHS"}
# jax.numpy constructors whose leading/shape argument compiles the shape
_JNP_SHAPE_CALLS = {"zeros", "ones", "full", "empty", "arange"}


def _basename(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class _Taint:
    """Per-scope taint oracle over an evolving name set."""

    def __init__(self):
        self.names: set[str] = set()

    def sanitized(self, expr: ast.AST) -> bool:
        for node in ast.walk(expr):
            if (isinstance(node, ast.Call)
                    and _basename(node.func) in _QUANTIZERS):
                return True
            if _basename(node) in _QUANT_TABLES:
                return True
        return False

    def tainted(self, expr: ast.AST) -> bool:
        if self.sanitized(expr):
            return False
        for node in ast.walk(expr):
            if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                    and node.func.id == "len"):
                return True
            if (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                    and node.id in self.names):
                return True
        return False

    def assign(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            if value is None:
                return
            taint = self.tainted(value)
            if isinstance(stmt, ast.AugAssign):
                taint = taint or self.tainted(stmt.target)
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for target in targets:
                for node in ast.walk(target):
                    if isinstance(node, ast.Name):
                        if taint:
                            self.names.add(node.id)
                        else:
                            self.names.discard(node.id)


def _jnp_shape_call(node: ast.Call, imports: ImportMap) -> bool:
    target = (imports.resolve_call_target(node.func)
              or dotted_name(node.func) or "")
    return (target.startswith(("jax.numpy.", "jnp."))
            and _basename(node.func) in _JNP_SHAPE_CALLS)


def _scope_bodies(tree: ast.Module):
    yield tree.body
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.body


def _walk_scope(ctx: FileCtx, imports: ImportMap, body: list[ast.stmt],
                ) -> Iterator[Finding]:
    """Linear statement-order walk (loop bodies visited once, so
    loop-carried taint is conservatively missed)."""
    taint = _Taint()
    findings: list[Finding] = []

    def check_call(node: ast.Call) -> None:
        for kw in node.keywords:
            if kw.arg == "pad_to" and taint.tainted(kw.value):
                findings.append(Finding(
                    ctx.path, node.lineno, node.col_offset,
                    "recompile-hazard",
                    "pad_to= receives a len()-derived size; snap it "
                    "to a bucket first (pow2_ceil / bucket_width / "
                    "DEFAULT_WIDTHS) or every distinct input size "
                    "compiles a new program",
                ))
        if _jnp_shape_call(node, imports):
            shape_args = list(node.args[:1]) + [
                kw.value for kw in node.keywords if kw.arg == "shape"
            ]
            for arg in shape_args:
                if taint.tainted(arg):
                    findings.append(Finding(
                        ctx.path, node.lineno, node.col_offset,
                        "recompile-hazard",
                        f"jnp.{_basename(node.func)} shape is "
                        "len()-derived; bucket it or the constructor "
                        "recompiles per distinct size",
                    ))

    def scan_exprs(node: ast.AST) -> None:
        """Sink-check this statement's own expressions, stopping at
        nested statements / defs (visited in order by visit())."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.stmt, ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                check_call(child)
            scan_exprs(child)

    def visit(stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # own scope
        scan_exprs(stmt)
        taint.assign(stmt)
        branches = [list(getattr(stmt, field, ()))
                    for field in ("body", "orelse", "finalbody")]
        branches += [h.body for h in getattr(stmt, "handlers", ())]
        branches = [b for b in branches if b]
        if not branches:
            return
        # alternative branches (if/else, try/except) each run from the
        # pre-branch state; afterwards a name is tainted when ANY path
        # taints it (base included: a branch may not execute at all)
        base = set(taint.names)
        merged: set[str] = set()
        for branch in branches:
            taint.names = set(base)
            for sub in branch:
                visit(sub)
            merged |= taint.names
        taint.names = base | merged

    for stmt in body:
        visit(stmt)
    yield from findings


def check(project: Project) -> Iterator[Finding]:
    for ctx in project.files:
        if "pad_to" not in ctx.source and "jnp." not in ctx.source:
            continue  # cheap skip: no sinks possible
        imports = ImportMap(ctx.tree)
        for body in _scope_bodies(ctx.tree):
            yield from _walk_scope(ctx, imports, body)
