"""graftcheck CLI: build the production graph jax-free, analyze, report.

See :mod:`tools.graftcheck` for the contract and exit codes.  The
expected-findings comparison matches on ``(kind, subject, path)`` — not
message text — so wording edits don't churn the committed list while any
real finding added or removed does.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

DEFAULT_EXPECT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "expected_production.json")


def _build_spec(config_path: str | None):
    """(cfg, spec, problems): builder problems become findings dicts."""
    from ont_tcrconsensus_tpu.graph.ir import GraphValidationError
    from ont_tcrconsensus_tpu.graph.pipeline import build_library_graph
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig

    if config_path is not None:
        cfg = RunConfig.from_json(config_path)
    else:
        # Placeholder inputs: the graph shape only depends on flow-control
        # knobs, and nothing here stats the filesystem.
        cfg = RunConfig(reference_file="reference.fasta",
                        fastq_pass_dir="fastq_pass")
    try:
        return cfg, build_library_graph(cfg), []
    except GraphValidationError as exc:
        return cfg, None, list(exc.problems)


def _finding_key(d: dict) -> tuple:
    return (d["kind"], d["subject"], tuple(d.get("path", ())))


def _compare_expected(findings: list[dict], expect_path: str,
                      ) -> tuple[list[str], int]:
    """Human lines + exit contribution (1 on drift) for ``--expect``."""
    try:
        with open(expect_path, encoding="utf-8") as fh:
            expected = json.load(fh)
    except (OSError, ValueError) as exc:
        return [f"graftcheck: cannot read expected list {expect_path}: "
                f"{exc}"], 2
    want = {_finding_key(d) for d in expected.get("findings", [])}
    got = {_finding_key(d) for d in findings}
    lines = []
    for key in sorted(want - got):
        lines.append(
            f"graftcheck: expected finding no longer reported: {key} — "
            "fixed? update the expected list"
        )
    for key in sorted(got - want):
        lines.append(
            f"graftcheck: NEW finding not in the expected list: {key}"
        )
    return lines, (1 if lines else 0)


def _human(report_dict: dict, out) -> None:
    s = report_dict["summary"]
    print(f"graftcheck: graph {s['graph']!r}", file=out)
    print("  step  live-hbm  est-bytes  node", file=out)
    for row in report_dict["liveness"]:
        mark = " *" if row["node"] == s["hbm_high_water_node"] else ""
        print(f"  {row['step']:>4}  {len(row['live_hbm']):>8}  "
              f"{row['hbm_bytes_est']:>9}  {row['node']}{mark}", file=out)
    print(f"  hbm high-water ~{s['hbm_high_water_bytes_est']} bytes "
          f"at {s['hbm_high_water_node']}", file=out)
    don = report_dict["donation_eligible"]
    for node in sorted(don):
        print(f"  donation-eligible at {node}: {', '.join(don[node])}",
              file=out)
    for f in report_dict["findings"]:
        print(f"  {f['severity']}: {f['kind']}: {f['message']}", file=out)
    print(f"graftcheck: {s['verdict']} ({s['violations']} violation(s), "
          f"{s['advisories']} advisory(ies))", file=out)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftcheck",
        description="semantic analysis of the production stage graph "
                    "(see tools/graftcheck/__init__.py)",
    )
    ap.add_argument("--config", help="run-config JSON (default: a "
                                     "default-constructed production config)")
    ap.add_argument("--n-reads", type=int, default=10_000,
                    help="workload size feeding the byte model")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--expect", nargs="?", const=DEFAULT_EXPECT,
                    help="compare findings against an expected list "
                         "(default: the committed production list); "
                         "drift in either direction fails")
    ap.add_argument("--write-expect",
                    help="write the current findings as the expected list")
    try:
        args = ap.parse_args(argv)
    except SystemExit as exc:  # argparse exits 2 on usage errors
        return int(exc.code or 0)

    try:
        from ont_tcrconsensus_tpu.graph import check as check_mod

        if args.config is not None and not os.path.exists(args.config):
            print(f"graftcheck: no such config: {args.config}",
                  file=sys.stderr)
            return 2
        cfg, spec, problems = _build_spec(args.config)
        if spec is None:
            for p in problems:
                print(f"  violation: graph-invalid: {p}")
            print(f"graftcheck: violations ({len(problems)} violation(s), "
                  "0 advisory(ies))")
            return 1
        report = check_mod.analyze(
            spec, check_mod.production_byte_model(cfg, n_reads=args.n_reads))
        body = report.to_dict()

        rc = 1 if report.violations else 0
        expect_lines: list[str] = []
        if args.expect:
            expect_lines, expect_rc = _compare_expected(
                body["findings"], args.expect)
            rc = max(rc, expect_rc)
        if args.write_expect:
            with open(args.write_expect, "w", encoding="utf-8") as fh:
                json.dump({"graph": report.graph,
                           "findings": body["findings"]}, fh, indent=2)
                fh.write("\n")

        if args.as_json:
            body["expect"] = expect_lines
            body["exit_code"] = rc
            print(json.dumps(body, indent=2))
        else:
            _human(body, sys.stdout)
            for line in expect_lines:
                print(line, file=sys.stderr)
        return rc
    except Exception as exc:  # never-crash contract: no tracebacks
        print(f"graftcheck: internal error: {type(exc).__name__}: {exc}",
              file=sys.stderr)
        return 2
