"""graftcheck — semantic graph-contract analyzer (CLI).

The analysis core lives in :mod:`ont_tcrconsensus_tpu.graph.check` (in
the package, so ``tcr-consensus-tpu --validate`` ships it); this package
is the repo-side command-line front end:

    python -m tools.graftcheck [--config run.json] [--n-reads N]
                               [--json] [--expect FILE] [--write-expect FILE]

It builds the *production* GraphSpec (default config, or ``--config``)
entirely jax-free and prints the per-step live-hbm table, the donation
report, and every finding.  ``--expect`` compares the findings against a
committed expected list (tools/graftcheck/expected_production.json) and
fails on drift in either direction — the regression guard tier1.sh
stage 0 runs: a new implicit host round-trip fails CI, and so does
fixing one without updating the worklist.

Exit codes: 0 clean/advisories-as-expected, 1 violations or expected-
list drift, 2 usage or internal error (never a traceback).
"""

from tools.graftcheck.cli import main

__all__ = ["main"]
