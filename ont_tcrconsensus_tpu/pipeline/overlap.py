"""Off-critical-path stage execution with ordered artifact commits.

The round-5 on-chip bench spends 15.3 s of its 46.8 s wall (33%) computing
the error-profile QC artifact — a log nothing downstream consumes —
serially between stages (BENCH_r05.json).  This module runs such
side-artifact stages on bounded worker threads, overlapped with the
critical-path device stages (round-1 polish, round-2 clustering), while
keeping every artifact byte-identical to the serial run:

- COMPUTE happens on a worker thread.  The QC pass reads only immutable
  columnar blocks and dispatches its own jitted tiles; jax dispatch is
  thread-safe, so its device work simply interleaves into the stream
  between the critical path's dispatches (total device work is unchanged —
  the win is hiding each side's host gaps behind the other's compute).
- COMMIT (file writes + failure propagation) happens on the MAIN thread,
  in submission order, at a fixed point before the library's manifest is
  marked complete — so artifact content and completion semantics are
  exactly the serial run's (a crash before commit leaves the library
  incomplete and resume retries it, as before).
- In-flight work is bounded by a permit semaphore — the same bounded
  in-flight discipline as the fused-pass drive (assign.py:1053-1117) — so
  background stages cannot pile up unbounded sample buffers behind a fast
  producer.

StageTimer accounting is split the same way: the stage's own timer entry
records only the CRITICAL-PATH cost (the blocking wait at the commit
point; ~0 when the overlap worked), and the worker's wall clock is
recorded under ``<stage>_bg`` so the breakdown stays honest about where
the compute went (bench.py excludes ``_bg`` entries from the
critical-path sum). The worker clock is an :mod:`obs.trace` span opened
ON the worker thread — at ``telemetry: full`` the same measurement that
lands in the TSV's ``<stage>_bg`` row appears as that worker's own named
row on the trace timeline.
"""

from __future__ import annotations

import threading
import time

from ont_tcrconsensus_tpu.obs import metrics as obs_metrics
from ont_tcrconsensus_tpu.obs import trace
from ont_tcrconsensus_tpu.robustness import faults, jobscope, lockcheck, watchdog


class DeferredStage:
    """One background stage: compute on a worker, result at commit time."""

    def __init__(self, name: str, permits: threading.Semaphore,
                 units: int = 0, on_done=None):
        self.name = name
        self.units = units
        self._permits = permits
        self._on_done = on_done
        self._done = threading.Event()
        self._result = None
        self._exc: BaseException | None = None
        self._call: tuple | None = None  # (fn, args, kwargs) for rerun_sync
        self._scope: dict | None = None  # submitter's jobscope store
        self.worker_seconds = 0.0

    def _run(self, fn, args, kwargs) -> None:
        # a worker spawned by a scoped run (slice-packed serving) joins
        # its submitter's job scope, so its chaos plants, telemetry and
        # watchdog guards land in its OWN job's state, not a neighbor
        # tenant's; None (unscoped submitter) is a no-op
        jobscope.adopt(self._scope)
        # the worker's wall clock is a trace span on THIS thread: its one
        # exit-time measurement is both the `<name>_bg` TSV seconds (via
        # worker_seconds below) and the worker's row on the trace timeline
        sp = trace.span(f"{self.name}_bg", cat="overlap")
        try:
            with sp:
                # liveness: the worker registers its OWN watchdog scope (the
                # main thread's guards are per-thread), deadline-scaled by the
                # caller's workload hint — a stalled worker is cancelled with
                # a StageTimeout that surfaces at commit and takes the
                # existing recompute-synchronously path
                with watchdog.guard(f"overlap.{self.name}", units=self.units):
                    # chaos site: a worker thread dying mid-stage (the injected
                    # exception surfaces at commit, like any real worker failure)
                    faults.inject("overlap.worker")
                    watchdog.heartbeat("overlap.worker")
                    self._result = fn(*args, **kwargs)
        except BaseException as exc:  # re-raised on the main thread at commit
            self._exc = exc
        finally:
            self.worker_seconds = sp.dur_s
            if self._on_done is not None:
                self._on_done(self.worker_seconds)
            self._done.set()
            self._permits.release()

    def rerun_sync(self):
        """Re-execute the stage's callable on the CALLING thread.

        The retry path for a dead/failed worker: the inputs are immutable
        columnar blocks, so a synchronous re-run produces the identical
        artifact — only the overlap is lost. Raises whatever the callable
        raises; the caller owns classification and retry bounds.
        """
        fn, args, kwargs = self._call
        return fn(*args, **kwargs)

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self):
        """Block until the worker finishes; re-raise its failure here."""
        self._done.wait()
        if self._exc is not None:
            raise self._exc
        return self._result


class StageExecutor:
    """Bounded-worker scheduler for stages whose artifacts nothing on the
    critical path consumes.

    ``max_in_flight`` bounds concurrently-live background stages (permit
    acquired at submit, released when the worker finishes): each deferred
    stage pins its input buffers (e.g. a whole library's read store) until
    committed, so the bound is a memory bound, not just a thread bound.
    """

    def __init__(self, max_in_flight: int = 2):
        self._permits = threading.Semaphore(max_in_flight)
        self._pending: list[DeferredStage] = []
        self._slots = max_in_flight
        # pool efficiency accounting (telemetry's overlap busy/idle split):
        # window = first submit .. last worker completion, busy = summed
        # worker wall clocks, idle = window * slots - busy
        self._stats_lock = lockcheck.make_lock()
        self._t_first_submit: float | None = None
        self._t_last_done: float | None = None
        self._busy_s = 0.0
        self._pool_recorded = False

    # Lock ownership for the pool counters (-> _stats_lock) is declared
    # in the consolidated registry (robustness/locks.py) consumed by
    # graftlint's lock-discipline rule and graftrace; _pending is in
    # LOCK_EXEMPT there (main-thread only).

    def _note_done(self, worker_seconds: float) -> None:
        with self._stats_lock:
            self._busy_s += worker_seconds
            self._t_last_done = time.monotonic()

    def record_pool_metrics(self) -> None:
        """Roll this pool's busy/idle split into the armed telemetry
        registry (once; no-op when nothing was ever submitted). Call after
        the pool has drained — run.py does so per library."""
        with self._stats_lock:
            if self._pool_recorded or self._t_first_submit is None:
                return
            self._pool_recorded = True
            end = self._t_last_done or self._t_first_submit
            window = max(end - self._t_first_submit, 0.0)
            busy = self._busy_s
        obs_metrics.pool_add(
            "overlap.pool", busy_s=busy,
            idle_s=max(window * self._slots - busy, 0.0),
            window_s=window, slots=self._slots,
        )

    def submit(self, name: str, fn, /, *args, units: int = 0,
               **kwargs) -> DeferredStage:
        """Start ``fn(*args, **kwargs)`` on a worker thread; blocks only
        when ``max_in_flight`` stages are already live.

        ``units`` is the watchdog workload hint for the worker's deadline
        (``watchdog.scaled_timeout``): size it to the stage's work-item
        count so a big background pass is not falsely cancelled. Stages
        whose fn heartbeats internally can leave it 0 (base deadline)."""
        self._permits.acquire()
        with self._stats_lock:
            if self._t_first_submit is None:
                self._t_first_submit = time.monotonic()
        stage = DeferredStage(name, self._permits, units=units,
                              on_done=self._note_done)
        stage._call = (fn, args, kwargs)
        stage._scope = jobscope.current()
        threading.Thread(
            target=stage._run, args=(fn, args, kwargs),
            name=f"stage-{name}", daemon=True,
        ).start()
        self._pending.append(stage)
        return stage

    def commit(self, stage: DeferredStage, timer=None):
        """Block until ``stage`` finishes and return its result, re-raising
        any worker failure on this (the main) thread.

        With ``timer``, the blocking wait is recorded under the stage's own
        name (the critical-path cost) and the worker's full wall clock
        under ``<name>_bg`` (the overlapped cost).
        """
        try:
            if timer is not None:
                try:
                    with timer.stage(stage.name):
                        result = stage.wait()
                finally:
                    # record the worker's wall clock even when the stage
                    # FAILED — the timing table must not under-report
                    # exactly the runs someone is diagnosing
                    timer.add(stage.name + "_bg", stage.worker_seconds)
            else:
                result = stage.wait()
        finally:
            # a failed commit must still retire the stage, or wait_all()
            # on the failure path would re-report the same exception as a
            # second 'also failed' background stage
            if stage in self._pending:
                self._pending.remove(stage)
        return result

    def wait_all(self) -> list[tuple[str, BaseException]]:
        """Wait for every pending stage WITHOUT raising; returns the
        failures as (name, exception) pairs.  The failure-path cleanup hook:
        a library that died on the critical path must not leave workers
        racing ahead into the next library's run."""
        failures: list[tuple[str, BaseException]] = []
        for stage in list(self._pending):
            try:
                stage.wait()
            except BaseException as exc:
                failures.append((stage.name, exc))
            self._pending.remove(stage)
        return failures
