"""The end-to-end two-round UMI consensus pipeline.

Orchestrates the stage functions (:mod:`.stages`) per barcode library,
mirroring the reference flow (/root/reference/ont_tcr_consensus/
tcr_consensus.py:33-478):

  PHASE A (once):  reference self-homology -> region clusters + precision bar
  PHASE B (per library): fused device pass (primer trim -> EE filter ->
                   align -> UMI locate) -> split by region cluster
  round 1:         UMI cluster @0.93 -> subread select -> batched consensus
  round 2:         consensus align + blast-id filter -> split by region ->
                   UMI cluster @0.97 -> select(min=1) -> counts CSV

Unlike the reference (which refuses an existing output dir,
tcr_consensus.py:84-86), stages record completion in a per-library manifest
and ``resume=True`` skips completed libraries.

Multi-chip: ``mesh_shape`` (e.g. ``{"data": 8}``) builds a
:class:`jax.sharding.Mesh` and every fused-pass batch is sharded over the
``data`` axis — the TPU equivalent of the reference's per-library/per-region
Ray fan-out (tcr_consensus.py:141-167; SURVEY §2.3).
"""

from __future__ import annotations

import dataclasses
import faulthandler
import glob
import json
import os
import re
import shutil
import signal
import sys

import numpy as np

from ont_tcrconsensus_tpu.cluster import regions as regions_mod
from ont_tcrconsensus_tpu.graph import executor as graph_exec
from ont_tcrconsensus_tpu.io import bucketing, fastx, layout
from ont_tcrconsensus_tpu.io import validate as validate_mod
from ont_tcrconsensus_tpu.obs import device as obs_device
from ont_tcrconsensus_tpu.obs import history as obs_history
from ont_tcrconsensus_tpu.obs import live as obs_live
from ont_tcrconsensus_tpu.obs import metrics as obs_metrics
from ont_tcrconsensus_tpu.obs import report as obs_report
from ont_tcrconsensus_tpu.obs import trace as obs_trace
from ont_tcrconsensus_tpu.pipeline import overlap, stages
from ont_tcrconsensus_tpu.pipeline.config import RunConfig
from ont_tcrconsensus_tpu.qc import artifacts, umi_overlap
from ont_tcrconsensus_tpu.qc.timing import StageTimer
from ont_tcrconsensus_tpu.robustness import (
    contracts,
    faults,
    lockcheck,
    retry,
    shutdown,
    watchdog,
)

# fallback precision bar when no reference pair survives the homology filter
# (the reference would crash there; see cluster/regions.py docstring)
DEFAULT_BLAST_ID_BAR = 0.99


def _log(*parts):
    print(*parts, file=sys.stderr)


def enable_compilation_cache(cache_dir: str | None = None) -> dict:
    """Persist XLA executables across processes (first compile of the kernel
    set costs minutes; every later pipeline invocation then starts warm).
    Safe no-op when the backend rejects the cache.

    ``cache_dir`` is the ``compile_cache_dir`` config knob: None arms the
    default ``~/.cache`` path, ``"off"`` disables the persistent cache,
    anything else is the cache directory. Returns an ``{"armed", "dir"}``
    status dict (recorded into telemetry.json's analysis section)."""
    import jax

    if cache_dir == "off":
        try:
            jax.config.update("jax_compilation_cache_dir", None)
        except Exception:
            pass
        return {"armed": False, "dir": None}
    resolved = cache_dir or os.path.expanduser(
        "~/.cache/ont_tcrconsensus_tpu_xla")
    try:
        jax.config.update("jax_compilation_cache_dir", resolved)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as exc:  # unsupported backend/config: run cold
        _log(f"compilation cache unavailable: {exc!r}")
        return {"armed": False, "dir": resolved, "error": repr(exc)}
    return {"armed": True, "dir": resolved}


def run_pipeline(config_path: str, polisher=None,
                 live_port: int | None = None) -> dict[str, dict[str, int]]:
    """Run the full pipeline; returns {library: {region: count}}.

    ``live_port`` (the ``--live-port`` CLI flag) overrides the config's
    ``live_port`` knob — an operator can arm the live plane on a one-off
    run without editing the committed config."""
    cfg = RunConfig.from_json(config_path)
    if live_port is not None:
        cfg = dataclasses.replace(cfg, live_port=live_port)
        cfg.validate()
    return run_with_config(cfg, polisher=polisher)


def make_mesh_from_config(cfg: RunConfig):
    """Build the data mesh named by ``cfg.mesh_shape`` (None -> no mesh)."""
    if not cfg.mesh_shape:
        return None
    from ont_tcrconsensus_tpu.parallel import mesh as mesh_mod

    mesh = mesh_mod.make_mesh(dict(cfg.mesh_shape))
    if "data" not in mesh.axis_names:
        raise ValueError(f"mesh_shape {cfg.mesh_shape} needs a 'data' axis")
    n_data = dict(zip(mesh.axis_names, mesh.devices.shape))["data"]
    if cfg.read_batch_size is not None and cfg.read_batch_size % n_data:
        raise ValueError(
            f"read_batch_size={cfg.read_batch_size} must divide by the "
            f"data axis size {n_data}"
        )
    return mesh


def resolve_batching(cfg: RunConfig, num_refs: int, mesh=None):
    """(read_batch_size, BudgetModel) from the one HBM knob.

    The budgeter (parallel/budget.py) replaces the reference's hand-fit
    medaka memory model (medaka_polish.py:11-92); explicit config values
    override the derived sizes. With a mesh, the global batch must divide
    the data axis (each chip sees batch/n_data rows).
    """
    from ont_tcrconsensus_tpu.parallel import budget as budget_mod

    budget = budget_mod.BudgetModel(
        cfg.hbm_budget_gb if cfg.hbm_budget_gb is not None
        else budget_mod.detect_hbm_gb()
    )
    read_batch = cfg.read_batch_size or budget.read_batch(
        cfg.max_read_length, num_refs=max(num_refs, 1),
        band_width=cfg.sw_band_width,
    )
    if mesh is not None:
        n_data = dict(zip(mesh.axis_names, mesh.devices.shape))["data"]
        read_batch = max(read_batch - read_batch % n_data, n_data)
    return read_batch, budget


def run_with_config(cfg: RunConfig, polisher=None) -> dict[str, dict[str, int]]:
    """Run the pipeline; with ``profile_trace_dir`` set, the whole run is
    captured as a jax.profiler trace (per-kernel device time, HBM traffic,
    host gaps — view in TensorBoard/Perfetto), the device-level complement
    of ``logs/stage_timing.tsv``. The reference had no profiler at all; on
    TPU this is the tool that answers "which kernel is the bottleneck"."""
    if cfg.profile_trace_dir:
        import jax

        if cfg.distributed:
            # start_trace initializes the XLA backend, after which
            # jax.distributed.initialize refuses to run — bring the
            # process group up first (the inner call is a no-op then)
            from ont_tcrconsensus_tpu.parallel import distributed as dist

            dist.initialize(required=True)
        os.makedirs(cfg.profile_trace_dir, exist_ok=True)
        jax.profiler.start_trace(cfg.profile_trace_dir)
        try:
            return _run_with_config(cfg, polisher)
        finally:
            jax.profiler.stop_trace()
    return _run_with_config(cfg, polisher)


class _SigquitRunLog:
    """Per-run SIGQUIT -> run-log faulthandler registration.

    ``restore()`` reinstates the PRE-run state: the CLI's stderr dump if
    it was installed, otherwise the embedder's original SIGQUIT
    disposition — a library caller must not inherit a process-global
    handler from one run.
    """

    def __init__(self):
        self.fh = None
        self.had_stderr_dump = False

    def register(self, nano_dir: str, proc_id: int) -> None:
        if not hasattr(signal, "SIGQUIT"):
            return
        try:
            self.fh = open(
                os.path.join(nano_dir, f"stack_dumps_p{proc_id}.log"), "a"
            )
            # unregister first so our register saves the TRUE
            # pre-faulthandler handler as its restore point; the return
            # value remembers whether the CLI's stderr dump was installed
            self.had_stderr_dump = faulthandler.unregister(signal.SIGQUIT)
            # chain=False (default) on purpose: chain would fall through to
            # the handler that predates faulthandler's FIRST registration —
            # SIG_DFL, which TERMINATES the process. A diagnosis dump must
            # never kill the run it is diagnosing.
            faulthandler.register(signal.SIGQUIT, file=self.fh, all_threads=True)
        except (OSError, ValueError, AttributeError) as exc:
            _log(f"stack-dump registration unavailable: {exc!r}")
            if self.fh is not None:
                self.fh.close()
            self.fh = None
            if self.had_stderr_dump:
                try:
                    faulthandler.register(signal.SIGQUIT, all_threads=True)
                except (OSError, ValueError, AttributeError):
                    pass

    def restore(self) -> None:
        if self.fh is None:
            return
        try:
            faulthandler.unregister(signal.SIGQUIT)
            if self.had_stderr_dump:
                faulthandler.register(signal.SIGQUIT, all_threads=True)
        except (OSError, ValueError, AttributeError):
            pass
        self.fh.close()
        self.fh = None


def _run_with_config(cfg: RunConfig, polisher=None) -> dict[str, dict[str, int]]:
    # The watchdog and the per-run SIGQUIT registration are process-global
    # state: arm them HERE, around the whole body, so every exit path —
    # the only_run_reference_self_homology early return, a pre-loop
    # discovery error, a failed reference read — disarms the monitor and
    # restores the pre-run SIGQUIT disposition. An embedder's next
    # run_with_config call must never inherit this run's deadline monitor
    # or dump handler.
    # Runtime lockset twin (TCR_LOCKCHECK=1): must arm BEFORE any guarded
    # object is constructed — the watchdog below and the metrics/live
    # registries armed inside the try choose their lock type at __init__.
    lockcheck.arm_from_env()
    wd = None
    if cfg.stage_timeout_s:
        wd = watchdog.Watchdog(base_timeout_s=cfg.stage_timeout_s)
        watchdog.activate(wd)
        wd.start()
        _log(f"Watchdog armed: stage_timeout_s={cfg.stage_timeout_s} "
             f"(soft at {watchdog.SOFT_FRACTION:.0%}, auto-scaled by "
             "workload size)")
    # Telemetry (obs/) is process-global state like the watchdog: armed
    # INSIDE the try whose finally disarms it, so a failure anywhere —
    # including mid-arming (an exotic jax without the monitoring API, a
    # thread-creation failure in the sampler) — still disarms everything
    # and stops the watchdog; an embedder's next run never inherits this
    # run's registry or monitor. The body writes the artifacts
    # (telemetry.json / logs/trace.json) next to the robustness report
    # while still armed; at "off" the planted sites stay one
    # module-attribute check.
    sampler = None
    run_armed_live = False
    sigquit_log = _SigquitRunLog()
    live_usr1 = obs_live.Sigusr1Hook()
    try:
        if cfg.telemetry != "off":
            obs_metrics.arm()
            obs_device.install_compile_listener()
            if cfg.telemetry == "full":
                obs_trace.arm()
                sampler = obs_device.start_sampler()
        # The live plane arms independently of the telemetry level: its
        # flight ring is the post-mortem context for runs where the full
        # trace collector is NOT armed, and /metrics stays a valid (if
        # sparse) exposition even at telemetry=off. Under the warm-serving
        # daemon (serve/) the plane is DAEMON-owned — already armed before
        # this run started — so the run neither re-arms nor disarms it:
        # only a plane armed here is torn down here.
        if cfg.live_port is not None and obs_live.server() is None:
            srv = obs_live.arm(cfg.live_port)
            run_armed_live = True
            live_usr1.install()
            _log(f"Live observability plane on http://127.0.0.1:{srv.port} "
                 "(/healthz /metrics /progress; SIGUSR1 flushes the "
                 "flight recorder)")
        try:
            return _run_with_config_body(cfg, polisher, sigquit_log)
        except BaseException as exc:
            # the flight recorder's whole reason to exist: flush the last
            # N events while the process still can. Preempted (a SIGTERM/
            # SIGINT drain) and KeyboardInterrupt are BaseExceptions, so
            # Exception alone would miss exactly the deaths that matter.
            obs_live.flush_armed(
                "sigterm_drain" if isinstance(exc, shutdown.Preempted)
                else f"crash:{type(exc).__name__}"
            )
            raise
    finally:
        live_usr1.restore()
        if run_armed_live:
            obs_live.disarm()
        if sampler is not None:
            sampler.stop()
        obs_trace.disarm()
        obs_metrics.disarm()
        if wd is not None:
            watchdog.deactivate(wd)
            wd.stop()
        sigquit_log.restore()


def _run_with_config_body(
    cfg: RunConfig, polisher, sigquit_log: _SigquitRunLog,
) -> dict[str, dict[str, int]]:
    from ont_tcrconsensus_tpu.parallel import distributed as dist

    # arm (or explicitly disarm, "off") the persistent XLA executable cache
    # per the validated knob, and record the outcome in telemetry.json so a
    # cold-start regression is attributable to cache state, not guessed
    cache_state = enable_compilation_cache(cfg.compile_cache_dir)
    obs_metrics.analysis_set("compile_cache", cache_state)
    # fault-tolerant execution layer (robustness/): every run DECLARES its
    # chaos state — the config key wins over the TCR_CHAOS env var, and
    # with neither present any stale plan from a previous in-process run
    # is disarmed (a chaos soak must never bleed faults into a later
    # clean analysis run). Then install the config-derived retry policy
    # and reset the recorder behind robustness_report.json.
    if cfg.chaos:
        faults.arm(cfg.chaos, seed=cfg.chaos_seed)
    elif faults.arm_from_env() is None:
        faults.disarm()
    policy = retry.set_policy(retry.RetryPolicy(
        max_attempts=cfg.retry_max_attempts,
        base_delay_s=cfg.retry_base_delay_s,
    ))
    recorder = retry.recorder()
    recorder.reset()
    # stage-boundary conservation contracts: per-run mode + fresh counters
    contracts.set_mode(cfg.contracts)
    contracts.reset()
    if cfg.distributed:
        # no-op when already up (e.g. the CLI initialized pre-import);
        # required: a failed bring-up must abort, not degrade to N racing
        # single-process runs
        dist.initialize(required=True)
    n_proc, proc_id = dist.process_count(), dist.process_index()
    if polisher is None and cfg.polish_method == "rnn":
        from ont_tcrconsensus_tpu.models import polisher as polisher_mod

        params = polisher_mod.load_default_params()
        if params is not None:
            # only load (and pay pos_at retention for) the depth-2 pass
            # when selection can actually emit 2-member clusters — under
            # min_reads_per_cluster > 2 it is structurally dead
            low_params = (
                polisher_mod.load_low_depth_params()
                if cfg.low_depth_polish and cfg.min_reads_per_cluster <= 2
                else None
            )
            # bf16 serving only behind the per-backend exactness A/B gate
            # (identical consensus output certified on THIS backend class;
            # scripts/bf16_ab.py regenerates the artifact)
            use_bf16 = cfg.polish_bf16 and polisher_mod.bf16_serving_certified(
                min_polish_depth=cfg.min_polish_depth
            )
            if use_bf16:
                _log("polisher: bf16 serving enabled (exactness A/B certified)")
            polisher = polisher_mod.make_pipeline_polisher(
                params, min_polish_depth=cfg.min_polish_depth,
                low_depth_params=low_params, bf16=use_bf16,
            )
        else:
            _log("polish_method=rnn but no bundled weights; using vote consensus only")
    reference = fastx.read_fasta_dict(cfg.reference_file)
    nano_dir = os.path.join(cfg.fastq_pass_dir, "nano_tcr")
    # Every process runs the refusal check BEFORE any process creates the
    # dir (first barrier orders check vs mkdir), so a pre-existing dir
    # aborts all hosts consistently instead of parking peers in a barrier
    # behind a raising process 0.
    exists = os.path.exists(nano_dir)
    dist.barrier("nano_dir_check")
    if exists and not cfg.resume:
        raise FileExistsError(
            f"{nano_dir} exists; set resume=true to continue or remove it"
        )
    if proc_id == 0:
        os.makedirs(nano_dir, exist_ok=True)
    dist.barrier("nano_dir_init")  # dir visible before any other host proceeds
    # SIGQUIT -> all-thread stack dump into the run's own log (in addition
    # to the CLI's stderr registration): a wedged production run is always
    # diagnosable post-hoc from the output tree, even when stderr was lost.
    # The wrapper's finally restores the pre-run disposition on every exit.
    sigquit_log.register(nano_dir, proc_id)
    # crash/SIGUSR1 flight-recorder flushes land inside the output tree
    # (next to the watchdog/SIGQUIT logs); no-op when the plane is disarmed
    obs_live.set_flush_path(os.path.join(
        nano_dir, "logs",
        "flight_recorder.json" if n_proc == 1
        else f"flight_recorder_p{proc_id}.json",
    ))

    # PHASE A: reference self-homology (tcr_consensus.py:90-105)
    _log("Mapping reference self homology")
    homology = regions_mod.self_homology_map(reference, cfg.cluster_identity)
    if proc_id == 0:  # shared run-level artifacts: one writer across hosts
        with open(os.path.join(nano_dir, "region_cluster_dict.json"), "w") as fh:
            json.dump(homology.region_cluster, fh, indent=4)
        with open(os.path.join(nano_dir, "self_homology_stats.json"), "w") as fh:
            json.dump(homology.stats, fh, indent=4)
        # region -> [blast ids of its most-similar partners]; the analysis
        # layer's most-similar overlay input (ref analysis.py:697-716 reads
        # the same-named artifact of region_split.py:139-147)
        most_similar: dict[str, list[float]] = {}
        for qname, tname, bid in homology.most_similar:
            most_similar.setdefault(qname, []).append(bid)
            most_similar.setdefault(tname, []).append(bid)
        with open(os.path.join(
            nano_dir, "ref_homology_out_most_similar_region_dict.json"
        ), "w") as fh:
            json.dump(most_similar, fh, indent=4)
        artifacts.write_self_homology_log(
            homology.stats,
            os.path.join(nano_dir, "ref_homology_out_generate_region_split_dict.log"),
        )

    blast_id_threshold = cfg.blast_id_threshold
    overlap_consensus = cfg.minimal_region_overlap_consensus
    if blast_id_threshold is None:
        blast_id_threshold = (
            homology.max_blast_id if homology.max_blast_id is not None
            else DEFAULT_BLAST_ID_BAR
        )
    if overlap_consensus is None:
        overlap_consensus = (
            homology.max_blast_id if homology.max_blast_id is not None
            else cfg.minimal_region_overlap
        )
    if cfg.only_run_reference_self_homology:
        return {}

    panel = stages.ReferencePanel.build(reference, homology.region_cluster)
    mesh = make_mesh_from_config(cfg)
    if mesh is not None:
        _log("Sharding device batches over mesh:", dict(cfg.mesh_shape))
    read_batch, budget = resolve_batching(cfg, len(panel.names), mesh)
    _log(f"Device batching: read_batch={read_batch}, "
         f"hbm_budget={budget.hbm_gb:.1f} GB")
    engine = stages.AssignEngine(
        panel, cfg.umi_fwd, cfg.umi_rev,
        primers=cfg.primer_sequences(),
        primer_max_dist_frac=cfg.primer_max_dist_frac,
        a5=cfg.max_softclip_5_end, a3=cfg.max_softclip_3_end,
        trim_window=cfg.trim_window, band_width=cfg.sw_band_width, mesh=mesh,
        fast_denom=4 if cfg.round1_fast_assign else 0,
    )
    # round 2 aligns already-trimmed consensus sequences: no primer search
    engine_notrim = stages.AssignEngine(
        panel, cfg.umi_fwd, cfg.umi_rev, primers=[],
        a5=cfg.max_softclip_5_end, a3=cfg.max_softclip_3_end,
        band_width=cfg.sw_band_width, mesh=mesh,
    )

    fastq_list = sorted(glob.glob(os.path.join(cfg.fastq_pass_dir, "barcode*", "*fastq*")))
    if not fastq_list:
        fastq_list = sorted(
            p for p in glob.glob(os.path.join(cfg.fastq_pass_dir, "*.fastq*"))
        )
    if not fastq_list:
        raise FileNotFoundError(f"no fastq files under {cfg.fastq_pass_dir}")
    if n_proc > 1:
        # multi-host: each process owns a deterministic library shard over
        # DCN (parallel/distributed.py); chips within the host shard batches
        fastq_list = dist.shard_libraries(fastq_list)
        _log(f"Process {proc_id}/{n_proc} owns {len(fastq_list)} libraries")
    # /progress denominators + ETA priors: per-node seconds from the run's
    # own ledger and the cross-run one, filtered to this config fingerprint
    # (the ledger I/O only happens when the plane is armed)
    obs_live.progress_totals(len(fastq_list))
    obs_live.configure_eta_priors(
        [os.path.join(nano_dir, obs_history.HISTORY_BASENAME)]
        + ([cfg.history_ledger] if cfg.history_ledger else []),
        obs_history.config_fingerprint(cfg),
    )

    results: dict[str, dict[str, int]] = {}
    failed_libraries: list[tuple[str, str]] = []
    preempted: shutdown.Preempted | None = None
    # Preemption-safe shutdown: the first SIGTERM/SIGINT requests a stop,
    # the loop raises Preempted at the next stage-boundary checkpoint, the
    # per-library guard drains overlapped workers, and the process exits
    # with every committed checkpoint intact (resume=true continues).
    coord = shutdown.ShutdownCoordinator()
    coord.install()  # False off the main thread: cooperative stops only
    shutdown.activate(coord)
    try:
        for fastq in fastq_list:
            shutdown.checkpoint("run.library_start")
            obs_live.progress_library(layout.library_name_from_fastq(fastq))
            # The whole per-library unit is guarded (dir init and resume
            # reload included): a failed library degrades to a report
            # instead of aborting the run — and, multi-host, instead of
            # stranding the peers in the end-of-run collective below (they
            # cannot know this process would never arrive). Resume retries
            # it: no stage marked. Preempted derives from BaseException so
            # this guard can never swallow a shutdown into a skip.
            try:
                lay = layout.init_library_dir(fastq, nano_dir, resume=cfg.resume)
                watchdog.set_log_path(os.path.join(lay.logs, "watchdog.log"))
                if cfg.resume and lay.stage_done("counts"):
                    counts_csv = os.path.join(lay.counts, "umi_consensus_counts.csv")
                    # chaos site: disk corruption landing on a completed
                    # artifact between the original run and this resume
                    faults.corrupt_artifact("resume.verify", counts_csv)
                    if _verify_resume_stage(lay, "counts", cfg):
                        _log("Library already complete:", lay.library)
                        results[lay.library] = _read_counts_csv(counts_csv)
                        continue
                results[lay.library] = _run_library(
                    fastq, lay, cfg, panel, engine, engine_notrim,
                    blast_id_threshold, overlap_consensus, polisher,
                    read_batch, budget,
                )
            except Exception as exc:
                library = layout.library_name_from_fastq(fastq)
                failed_libraries.append((library, repr(exc)))
                _log(f"WARNING: library {library} failed and is skipped: {exc!r}")
            finally:
                # a failed library still advances /progress: the ETA is
                # about remaining work, not about success
                obs_live.progress_library_done()
    except shutdown.Preempted as p:
        preempted = p
        _log(f"PREEMPTED: {p}; every committed stage checkpoint is "
             "resume-safe — rerun with resume=true to continue")
    finally:
        coord.uninstall()
        shutdown.deactivate(coord)
        try:
            recorder.write(os.path.join(
                nano_dir,
                "robustness_report.json" if n_proc == 1
                else f"robustness_report_p{proc_id}.json",
            ), policy=policy, contracts=contracts.summary())
        except OSError as exc:  # report trouble must never mask the run's fate
            _log(f"WARNING: could not write robustness report: {exc!r}")
        if cfg.telemetry != "off":
            # telemetry roll-up next to the robustness report: one-shot
            # memory peaks (backend peak_bytes_in_use + ru_maxrss), then
            # telemetry.json (+ logs/trace.json at "full"). Failure and
            # preemption paths roll up too — a dying run's telemetry is
            # exactly the telemetry someone needs.
            try:
                obs_device.finalize_memory()
                obs_report.write_run_telemetry(
                    nano_dir, cfg.telemetry,
                    suffix="" if n_proc == 1 else f"_p{proc_id}",
                )
            except OSError as exc:
                _log(f"WARNING: could not write telemetry artifacts: {exc!r}")
            # cross-run ledger entry (obs/history.py): the run's summary
            # keyed by git sha / config fingerprint / backend, appended to
            # nano_tcr/history.jsonl (+ cfg.history_ledger when set) so
            # scripts/perf_gate.py has a baseline to gate against.
            # Never fails the run it records.
            obs_history.record_run(
                nano_dir, cfg, suffix="" if n_proc == 1 else f"_p{proc_id}",
            )
    if failed_libraries:
        with open(os.path.join(nano_dir, f"failed_libraries_p{proc_id}.log"), "w") as fh:
            for library, err in failed_libraries:
                fh.write(f"{library}\t{err}\n")
    if preempted is not None:
        # multi-host: peers receive the same preemption signal; skipping
        # the allgather here avoids parking a dying host in a collective
        raise preempted
    if n_proc > 1:
        # gather counts AND failure markers so every host sees the same
        # global picture — a failure on one shard must fail the whole run
        # on all hosts, not just the shard's owner
        merged: dict[str, dict[str, int]] = {}
        all_failed: list[tuple[str, str]] = []
        for part in dist.allgather_object(
            {"results": results, "failed": failed_libraries}
        ):
            merged.update(part["results"])
            all_failed.extend(tuple(f) for f in part["failed"])
        results, failed_libraries = merged, all_failed
    if failed_libraries:
        raise RuntimeError(
            f"{len(failed_libraries)} library(ies) failed: "
            f"{sorted(lib for lib, _ in failed_libraries)} — see "
            "failed_libraries_*.log; rerun with resume=true to retry"
        )
    _log("Done running all barcodes!")
    return results


# Resume verification lives with the graph executor now (the imperative
# path and the counts-level skip share the same gate); keep the local name
# for its two call sites below.
_verify_resume_stage = graph_exec.verify_resume_stage


def _run_library(fastq, lay, cfg, panel, engine, engine_notrim,
                 blast_id_threshold, overlap_consensus, polisher,
                 read_batch, budget) -> dict[str, int]:
    # Overlapped executor: off-critical-path stages run on worker threads
    # concurrently with polish / clustering, committing their
    # (byte-identical) log artifacts at fixed points before each round's
    # resume checkpoint (pipeline/overlap.py; under the graph executor the
    # set of overlapped stages is derived from edge consumption).
    qc_exec = overlap.StageExecutor() if cfg.overlap_qc else None
    try:
        if cfg.executor == "graph":
            return _run_library_graph(
                fastq, lay, cfg, panel, engine, engine_notrim,
                blast_id_threshold, overlap_consensus, polisher,
                read_batch, budget, qc_exec,
            )
        return _run_library_impl(
            fastq, lay, cfg, panel, engine, engine_notrim,
            blast_id_threshold, overlap_consensus, polisher,
            read_batch, budget, qc_exec,
        )
    except BaseException:
        # a critical-path failure must not leave overlapped QC workers
        # uncommitted (their failures would vanish and their buffers would
        # outlive the library) — drain them, then let the failure propagate
        if qc_exec is not None:
            for name, exc in qc_exec.wait_all():
                _log(f"WARNING: overlapped stage {name} also failed: {exc!r}")
        raise
    finally:
        # pool busy/idle split into telemetry (drained by now on every
        # path: commits on success, wait_all above on failure)
        if qc_exec is not None:
            qc_exec.record_pool_metrics()


def _make_remesh(ctx):
    """The degraded-mesh hook the graph executor calls when a
    ``device_lost`` escapes a node body (graph/executor.py
    ``_run_node_degradable``).

    Shrinks the world to the surviving slices and returns the degradation
    detail, or None when the data axis is already 1 (nothing left to
    degrade to — the executor re-raises and the run dies honestly):

    - both engines re-mesh onto the survivors (``AssignEngine.set_mesh``
      drops every shard_map program compiled against the dead device set);
    - the HBM budget rescales by the survival fraction
      (parallel/budget.py ``degraded_budget``) so every batch derived
      after the loss keeps the per-slice load constant;
    - ``read_batch`` re-quantizes to the new data-axis size, preserving
      the pad-to-multiple discipline for the re-dispatched node.
    """
    from ont_tcrconsensus_tpu.parallel import budget as budget_mod
    from ont_tcrconsensus_tpu.parallel import mesh as mesh_mod

    def _remesh(node_name, exc):
        old = ctx.engine.mesh
        degraded = mesh_mod.degrade_mesh(old)
        if degraded is None:
            return None
        old_n = mesh_mod.mesh_data_size(old)
        new_n = mesh_mod.mesh_data_size(degraded)
        for eng in (ctx.engine, ctx.engine_notrim):
            if eng is not None and getattr(eng, "mesh", None) is not None:
                eng.set_mesh(degraded)
        if ctx.budget is not None:
            ctx.budget = budget_mod.degraded_budget(ctx.budget, new_n, old_n)
        if ctx.read_batch:
            rb = ctx.read_batch
            ctx.read_batch = max(rb - rb % new_n, new_n)
        return {"data_from": old_n, "data_to": new_n}

    return _remesh


def _run_library_graph(fastq, lay, cfg, panel, engine, engine_notrim,
                       blast_id_threshold, overlap_consensus, polisher,
                       read_batch, budget, qc_exec) -> dict[str, int]:
    """Declare the library graph and hand it to the graph executor.

    Note what is NOT here: no overlap submissions, no commit points, no
    resume probes, no per-stage timers or watchdog guards — the executor
    derives all of that from the node/edge declarations
    (graph/pipeline.py). This function only supplies the per-library
    context the imperative path threaded positionally.
    """
    from ont_tcrconsensus_tpu.graph import pipeline as graph_pipeline

    ctx = graph_pipeline.LibraryContext(
        cfg=cfg, lay=lay, timer=StageTimer(), panel=panel, engine=engine,
        engine_notrim=engine_notrim, blast_id_threshold=blast_id_threshold,
        overlap_consensus=overlap_consensus, polisher=polisher,
        read_batch=read_batch, budget=budget,
    )
    if engine is not None and getattr(engine, "mesh", None) is not None:
        ctx.remesh = _make_remesh(ctx)
    spec = graph_pipeline.build_library_graph(cfg)
    try:
        # Static graftcheck verdict rides telemetry.json / the history
        # ledger, so analyzer findings are tracked per run alongside the
        # runtime numbers they predict. Never takes down a run.
        from ont_tcrconsensus_tpu.graph import check as graph_check
        from ont_tcrconsensus_tpu.obs import metrics as obs_metrics
        from ont_tcrconsensus_tpu.obs import transfers as obs_transfers

        report = graph_check.analyze(
            spec, graph_check.production_byte_model(cfg))
        obs_metrics.analysis_set("graftcheck", report.summary())
        # static per-node live-HBM into the registry NOW, so --report
        # --memory reconciles from the committed artifact alone (no
        # config, no jax) against the executor's boundary samples
        for step in report.liveness:
            obs_transfers.static_hbm(step["node"], step["hbm_bytes_est"])
    except Exception as exc:
        _log(f"WARNING: graftcheck analysis failed: {exc!r}")
    executor = graph_exec.GraphExecutor(spec, ctx, side_exec=qc_exec)
    results = executor.run({"library_fastq": fastq})
    return results["region_counts"]


def _commit_pending_qc(qc_exec, pending_qc, timer) -> None:
    """Commit overlapped QC stages (write logs, surface failures) in
    submission order on the main thread; clears the list.  Every commit
    point sits BEFORE the stage checkpoint that would let resume skip the
    producing round — a crash between compute and commit therefore leaves
    the round unmarked and resume regenerates the artifact, exactly like
    the serial run.

    A worker that died of a TRANSIENT fault (thread killed, device
    connection dropped) is recomputed synchronously on the main thread —
    the inputs are immutable columnar blocks, so the artifact is
    byte-identical and only the overlap is lost; deterministic failures
    propagate exactly as before."""
    if not pending_qc:
        return
    from ont_tcrconsensus_tpu.qc import error_profile

    for stage, log_path in pending_qc:
        try:
            counters = qc_exec.commit(stage, timer)
        except Exception as exc:
            cls = retry.classify(exc)
            rec = retry.recorder()
            if cls == "fatal":
                rec.record("overlap.worker", classification=cls,
                           outcome="fatal", error=repr(exc))
                raise
            rec.record("overlap.worker", classification=cls,
                       outcome="retried", error=repr(exc))
            _log(f"WARNING: overlapped stage {stage.name} hit a {cls} "
                 f"fault ({exc!r}); recomputing on the main thread")
            with timer.stage(stage.name):
                counters = stage.rerun_sync()
            rec.record("overlap.worker", classification=cls,
                       outcome="recovered", attempt=2)
        error_profile.write_error_profile_log(*counters, log_path)
        _log(f"qc: {stage.name} computed off the critical path "
             f"({stage.worker_seconds:.1f}s overlapped)")
    pending_qc.clear()


def _run_library_impl(fastq, lay, cfg, panel, engine, engine_notrim,
                      blast_id_threshold, overlap_consensus, polisher,
                      read_batch, budget, qc_exec) -> dict[str, int]:
    library = lay.library
    merged_path = os.path.join(lay.fasta, "merged_consensus.fasta")
    timer = StageTimer()

    # stage-level resume: a completed round 1 is reloaded from its artifact
    # — after integrity verification (verify_resume): a torn or bit-rotted
    # consensus fasta must re-run round 1, not silently seed round 2
    if cfg.resume and lay.stage_done("round1_consensus") and os.path.exists(merged_path):
        faults.corrupt_artifact("resume.verify", merged_path)
        if _verify_resume_stage(lay, "round1_consensus", cfg):
            _log("Resuming from round-1 consensus:", library)
            merged_consensus = [
                (rec.header, rec.sequence) for rec in fastx.read_fastx(merged_path)
            ]
            return _run_round2(lay, cfg, panel, engine_notrim, blast_id_threshold,
                               overlap_consensus, merged_consensus, timer,
                               read_batch, budget, round1_complete=True,
                               qc_exec=qc_exec)

    # PHASE B + round-1 assignment: ONE fused device pass per batch
    # (trim -> EE -> align -> UMI locate; preprocessing.py:7-159 +
    # minimap2_align.py:76-155 + region_split.py:219-333 + extract_umis.py)
    _log("Preprocessing, aligning and UMI-tagging nanopore reads:", library)
    # chaos site for file-level data faults: corrupt-input / truncate-file
    # swap in a seeded-mutated sibling copy of the input (the original is
    # never touched); with on_bad_record=quarantine the damage must land in
    # quarantine.fastq.gz while the clean subset flows through untouched
    fastq = faults.mutate_input("ingest.library_fastq", fastq)
    guard = None
    if cfg.on_bad_record != "fail":
        guard = validate_mod.IngestGuard(
            cfg.on_bad_record, source=os.fspath(fastq),
            quarantine_path=lay.quarantine_path,
        )
    try:
        # watchdog guard + per-batch heartbeats (assign.py drive loop): a
        # hung dispatch cancels into the same transient-retry wrapper
        with timer.stage("round1_fused_assign"), \
                watchdog.guard("round1_fused_assign"):
            # transient-retry wrap: the fused pass is idempotent (it
            # streams the fastq into a fresh store), so a dropped device
            # connection mid-library re-runs the whole pass instead of
            # skipping the library (robustness/retry.py classification).
            # The guard resets with it so a retry cannot double-count
            # quarantined records.
            store, astats = retry.call_with_retry(
                "assign.round1",
                lambda: stages.run_assign(
                    fastq, engine,
                    max_ee_rate=cfg.max_ee_rate_base,
                    min_len=cfg.minimal_length,
                    minimal_region_overlap=cfg.minimal_region_overlap,
                    max_softclip_5_end=cfg.max_softclip_5_end,
                    max_softclip_3_end=cfg.max_softclip_3_end,
                    batch_size=read_batch,
                    max_read_length=cfg.max_read_length,
                    subsample=cfg.dorado_trim_subsample_fastq,
                    guard=guard,
                ),
                reset=guard.reset if guard is not None else None,
            )
    finally:
        # finalize even when the library fails: the quarantine gzip must
        # gain its trailer (an open handle leaves a truncated artifact)
        # and the ingest events must reach the robustness report — they
        # are exactly the diagnostics a failed library needs
        if guard is not None:
            qsummary = guard.finalize(retry.recorder())
            if qsummary["n_bad"]:
                verb = ("quarantined" if guard.policy == "quarantine"
                        else "dropped")
                _log(f"ingest: {qsummary['n_bad']} bad record(s) in "
                     f"{library} {verb} ({qsummary['by_reason']})")
    with open(os.path.join(lay.logs, "ee_filter.log"), "w") as fh:
        fh.write(
            f"reads passing EE/length filter: {astats.n_total - astats.n_ee_fail}\n"
        )
        fh.write(f"reads with primer trim: {astats.n_trimmed}\n")
    _write_align_log(astats, os.path.join(lay.logs, f"{library}_region_cluster_split.log"))
    artifacts.write_fastq_stats_log(
        astats, os.path.join(lay.logs, f"{library}_fastq_stats.log")
    )
    artifacts.write_flagstat_log(
        astats, os.path.join(lay.logs, f"{library}_flagstat.log")
    )

    pending_qc: list[tuple[overlap.DeferredStage, str]] = []
    if cfg.error_profile_sample:
        from ont_tcrconsensus_tpu.qc import error_profile

        r1_log = os.path.join(lay.logs, f"{library}_align_error_profile.log")
        if qc_exec is not None:
            # off the critical path: computed while polish runs, committed
            # (log written, failures surfaced) before the round-1
            # checkpoint below
            pending_qc.append((
                qc_exec.submit(
                    "round1_error_profile", error_profile.profile_store,
                    store, panel, sample_size=cfg.error_profile_sample,
                    units=cfg.error_profile_sample,
                ),
                r1_log,
            ))
        else:
            with timer.stage("round1_error_profile"):
                counters = error_profile.profile_store(
                    store, panel, sample_size=cfg.error_profile_sample
                )
                error_profile.write_error_profile_log(*counters, r1_log)

    groups = stages.group_by_region_cluster(store, panel)
    if cfg.write_intermediate_fastas:
        with timer.stage("write_region_fastas"):
            stages.write_region_fastas(
                groups, store, lay.region_cluster_fasta, "region_cluster"
            )
    artifacts.write_region_split_log(
        astats, groups, store, panel.names,
        {n: len(s) for n, s in panel.seqs.items()},
        regions_mod.NEGATIVE_CONTROL_SUFFIXES,
        os.path.join(
            lay.logs, f"{library}_filter_and_split_reads_by_region_cluster.err"
        ),
    )

    # round 1: UMI records per region cluster, ONE library-wide batched
    # clustering pass over every group (stages.cluster_and_select_grouped —
    # per-group results, a handful of device dispatches instead of one per
    # group), then ONE library-wide batched consensus polish
    # (stages.polish_clusters_all). A poisoned group degrades gracefully: it
    # is skipped AND reported, the rest of the library completes (the
    # reference behaves the same way for failed medaka batches,
    # tcr_consensus.py:329-346) — if the BATCHED clustering pass itself
    # fails, every group retries individually so one bad group cannot
    # poison its peers.
    selected_by_group: list[tuple[str, list[stages.SelectedCluster]]] = []
    failed_groups: list[tuple[str, str]] = []
    records_by_group: list[tuple[str, list]] = []
    for cluster_key in sorted(groups):
        group_name = f"region_cluster{cluster_key}"
        try:
            with timer.stage("round1_umi_records"):
                umis = stages.build_umi_records(
                    store, groups[cluster_key], cfg.max_pattern_dist
                )
            if not umis:
                continue
            if cfg.write_intermediate_fastas:
                stages.write_umi_fasta(
                    umis, store,
                    os.path.join(lay.umi_fasta, f"{group_name}_detected_umis.fasta"),
                )
            records_by_group.append((group_name, umis))
        except Exception as exc:
            failed_groups.append((group_name, repr(exc)))
            _log(f"WARNING: {group_name} failed and is skipped: {exc!r}")

    grouped = None
    with timer.stage("round1_umi_cluster"):
        def _batched_r1():
            faults.inject("cluster.batched_round1")
            return stages.cluster_and_select_grouped(
                records_by_group,
                identity=cfg.vsearch_identity,
                min_umi_length=cfg.min_umi_length,
                max_umi_length=cfg.max_umi_length,
                min_reads_per_cluster=cfg.min_reads_per_cluster,
                max_reads_per_cluster=cfg.max_reads_per_cluster,
                balance_strands=cfg.balance_strands,
                mesh=engine.mesh,
            )

        try:
            # transients retry the batched pass; a deterministic failure
            # (or an exhausted policy) degrades to the per-group retry
            # loop below so one bad group cannot poison its peers. The
            # watchdog guard makes a HUNG pass a transient too: hard-
            # deadline cancel -> StageTimeout -> this same retry wrapper.
            with watchdog.guard(
                "round1_umi_cluster",
                units=sum(len(u) for _, u in records_by_group),
            ):
                grouped = retry.call_with_retry("cluster.batched_round1", _batched_r1)
        except Exception as exc:
            retry.recorder().record(
                "cluster.batched_round1", classification=retry.classify(exc),
                outcome="degraded", error=repr(exc),
            )
            _log(f"WARNING: batched UMI clustering failed ({exc!r}); "
                 "retrying each region cluster individually")
    for group_name, umis in records_by_group:
        try:
            if grouped is not None:
                selected, stat_rows = grouped[group_name]
            else:
                with timer.stage("round1_umi_cluster"):
                    selected, stat_rows = stages.cluster_and_select(
                        umis,
                        identity=cfg.vsearch_identity,
                        min_umi_length=cfg.min_umi_length,
                        max_umi_length=cfg.max_umi_length,
                        min_reads_per_cluster=cfg.min_reads_per_cluster,
                        max_reads_per_cluster=cfg.max_reads_per_cluster,
                        balance_strands=cfg.balance_strands,
                        mesh=engine.mesh,
                    )
            cdir = os.path.join(lay.clustering, group_name)
            os.makedirs(cdir, exist_ok=True)
            stages.write_cluster_stats_tsv(
                stat_rows, os.path.join(cdir, "vsearch_cluster_stats.tsv")
            )
            if selected:
                selected_by_group.append((group_name, selected))
        except Exception as exc:
            failed_groups.append((group_name, repr(exc)))
            _log(f"WARNING: {group_name} failed and is skipped: {exc!r}")
    n_clusters = sum(len(s) for _, s in selected_by_group)
    _log(f"Polishing clusters: {library} "
         f"({n_clusters} clusters over {len(selected_by_group)} region clusters)")
    # watchdog guard scaled by cluster count; the chunk loop heartbeats
    # per dispatch, so only a chunk that stops progressing can expire
    with timer.stage("round1_polish"), \
            watchdog.guard("round1_polish", units=n_clusters):
        by_group, polish_failed = stages.polish_clusters_all(
            selected_by_group, store,
            max_read_length=cfg.max_read_length,
            polisher=polisher,
            budget=budget,
            cluster_batch=cfg.cluster_batch_size,
            mesh=engine.mesh,
        )
    merged_consensus: list[tuple[str, str]] = []
    for group_name, selected in selected_by_group:
        if group_name in polish_failed:
            failed_groups.append((group_name, polish_failed[group_name]))
            _log(f"WARNING: {group_name} polish failed and is skipped: "
                 f"{polish_failed[group_name]}")
        else:
            # conservation: every selected cluster of a non-failed group
            # must have produced exactly one consensus record
            contracts.check_equal(
                "consensus", f"{group_name} consensus records",
                len(by_group[group_name]), "selected clusters", len(selected),
                detail={"library": library, "group": group_name},
            )
            merged_consensus.extend(by_group[group_name])
    if failed_groups:
        _log(
            "Not all umi cluster region fastas were successfully polished! "
            f"Incomplete: {[g for g, _ in failed_groups]}"
        )
        with open(os.path.join(lay.logs, "incomplete_region_clusters.log"), "w") as fh:
            for group_name, err in failed_groups:
                fh.write(f"{group_name}\t{err}\n")

    # round-1 QC must commit BEFORE the round1_consensus checkpoint below:
    # once that stage is marked, resume skips round 1 entirely, so a crash
    # later in round 2 would otherwise lose the round-1 log forever. The
    # overlap still spans the whole polish stage (the round's dominant
    # block); only round-2-spanning overlap is given up for the round-1
    # pass.
    _commit_pending_qc(qc_exec, pending_qc, timer)
    n_written = fastx.write_fasta(merged_path, merged_consensus)
    contracts.check_equal(
        "consensus", "merged_consensus.fasta records written", n_written,
        "in-memory consensus entries", len(merged_consensus),
        detail={"library": library},
    )
    if not failed_groups:
        # incomplete round 1 is NOT checkpointed: resume must retry the
        # failed groups instead of reusing a consensus missing them.
        # The artifact is checksummed into the v2 manifest so resume can
        # verify it before seeding round 2 from it.
        lay.mark_stage_done("round1_consensus", artifacts=[merged_path])
    # chaos site + preemption checkpoint at the round-1 commit: the
    # canonical mid-stage death — the manifest just committed, so a kill
    # here resumes into round 2 only, byte-identically
    faults.inject("run.round1_checkpoint")
    shutdown.checkpoint("run.round1_checkpoint")
    return _run_round2(lay, cfg, panel, engine_notrim, blast_id_threshold,
                       overlap_consensus, merged_consensus, timer,
                       read_batch, budget,
                       round1_complete=not failed_groups,
                       qc_exec=qc_exec, pending_qc=pending_qc)


_R2_HEADER = re.compile(r"^region_cluster(\d+)_cluster\d+_\d+$")


def _targeted_round2_dispatch(panel, engine, headers):
    """Build the round-2 targeted dispatcher (VERDICT r3 #6).

    Consensus headers carry their round-1 region cluster
    (``region_cluster<K>_cluster<id>_<n>``, stages.polish_clusters_all),
    so round 2 aligns each consensus only against cluster K's references
    instead of re-deriving candidates from the full panel. Returns
    ``(dispatch, None)``, or ``(None, reason)`` when the targeted pass is
    unavailable (header without provenance — e.g. a hand-fed fasta — or a
    pathological oversized cluster); the caller then keeps the full fused
    pass and logs the reason.
    """
    cluster_refs: dict[int, np.ndarray] = {}
    for k in np.unique(panel.cluster_of_region):
        cluster_refs[int(k)] = np.where(panel.cluster_of_region == k)[0].astype(
            np.int32
        )

    def cluster_of(name: str) -> int | None:
        m = _R2_HEADER.match(name.partition(" ")[0])
        if m is None:
            return None
        k = int(m.group(1))
        return k if k in cluster_refs else None

    seen: set[int] = set()
    for h in headers:
        k = cluster_of(h)
        if k is None:
            return None, f"header {h.partition(' ')[0]!r} lacks cluster provenance"
        seen.add(k)
    if not seen:
        return None, "no consensus sequences"
    # ONE static candidate width for the whole round (pow2 so at most a
    # handful of jit shapes ever exist), computed from the clusters that
    # actually occur. A pathological panel whose homology chaining built a
    # huge cluster is cheaper under the full fused pass (top-k=2 SW) than
    # under max_c unrolled SW passes — fall back.
    max_c = bucketing.pow2_ceil(max(len(cluster_refs[k]) for k in seen))
    if max_c > 8:
        return None, f"largest region cluster has >{8} refs (max_c={max_c})"

    def dispatch(batch, max_ee_rate, min_len):
        cand = np.full((len(batch.ids), max_c), -1, np.int32)
        for row, (nm, v) in enumerate(zip(batch.ids, batch.valid)):
            if v:
                refs = cluster_refs[cluster_of(nm)]
                cand[row, : len(refs)] = refs
        return engine.run_batch_targeted_async(batch, cand, min_len=min_len)

    return dispatch, None


def _run_round2(lay, cfg, panel, engine_notrim, blast_id_threshold,
                overlap_consensus, merged_consensus, timer,
                read_batch, budget, round1_complete: bool = True,
                qc_exec=None, pending_qc=()) -> dict[str, int]:
    pending_qc = list(pending_qc)
    library = lay.library

    # round 2: consensus align + blast-id filter + split by exact region
    _log("Aligning unique molecule consensus TCR sequences:", library)
    cons_records = [fastx.FastxRecord(h, "", s) for h, s in merged_consensus]
    qc_rows: list[dict] = []
    dispatch = None
    if cfg.round2_targeted_assign:
        dispatch, why_not = _targeted_round2_dispatch(
            panel, engine_notrim, (h for h, _ in merged_consensus)
        )
        if dispatch is None:
            _log(f"round 2: targeted assign unavailable ({why_not}); "
                 "falling back to the full fused assign")
    with timer.stage("round2_fused_assign"), \
            watchdog.guard("round2_fused_assign", units=len(cons_records)):
        # transient-retry wrap like round 1; qc_rows is cleared before
        # each retry so a half-consumed attempt cannot duplicate QC rows
        cons_store, cstats = retry.call_with_retry(
            "assign.round2",
            lambda: stages.run_assign(
                cons_records, engine_notrim,
                max_ee_rate=1.0,  # no quality data on consensus sequences
                min_len=1,
                minimal_region_overlap=overlap_consensus,
                max_softclip_5_end=cfg.max_softclip_5_end,
                max_softclip_3_end=cfg.max_softclip_3_end,
                batch_size=read_batch,
                max_read_length=cfg.max_read_length,
                blast_id_threshold=blast_id_threshold,
                collect_qc=qc_rows,
                dispatch=dispatch,
            ),
            reset=qc_rows.clear,
        )
    artifacts.write_consensus_filter_artifacts(
        qc_rows,
        {n: len(s) for n, s in panel.seqs.items()},
        lay.logs,
        "merged_consensus",
        blast_id_threshold=blast_id_threshold,
        minimal_region_overlap=overlap_consensus,
    )
    artifacts.write_flagstat_log(
        cstats, os.path.join(lay.logs, "merged_consensus_flagstat.log")
    )
    if cfg.error_profile_sample:
        from ont_tcrconsensus_tpu.qc import error_profile

        r2_log = os.path.join(lay.logs, "merged_consensus_align_error_profile.log")
        if qc_exec is not None:
            # overlapped with round-2 clustering below; committed with the
            # round-1 pass at the end of this function
            pending_qc.append((
                qc_exec.submit(
                    "round2_error_profile", error_profile.profile_store,
                    cons_store, panel, sample_size=cfg.error_profile_sample,
                    units=cfg.error_profile_sample,
                ),
                r2_log,
            ))
        else:
            with timer.stage("round2_error_profile"):
                counters = error_profile.profile_store(
                    cons_store, panel, sample_size=cfg.error_profile_sample
                )
                error_profile.write_error_profile_log(*counters, r2_log)
    region_groups = stages.group_by_region(cons_store, panel)
    if cfg.write_intermediate_fastas:
        stages.write_region_fastas(region_groups, cons_store, lay.region_fasta, "region_")

    # round 2: UMI dedup clustering at consensus identity — per-region
    # records, then ONE batched clustering pass over every region (hundreds
    # of tiny per-region calls collapse into a handful of dispatches).
    # Per-region failures degrade gracefully like round 1: skip, report,
    # continue; a failed batched pass retries per region.
    region_counts: dict[str, int] = {}
    region_cluster_umis: dict[str, list[str]] = {}
    failed_regions: list[tuple[str, str]] = []
    region_records: list[tuple[str, list]] = []
    for region, parts in sorted(region_groups.items()):
        try:
            with timer.stage("round2_umi_records"):
                umis = stages.build_umi_records(
                    cons_store, parts, cfg.max_pattern_dist
                )
            if not umis:
                continue
            if cfg.write_intermediate_fastas:
                stages.write_umi_fasta(
                    umis, cons_store,
                    os.path.join(
                        lay.consensus_umi_fasta,
                        f"region_{region}_detected_umis.fasta",
                    ),
                )
            region_records.append((region, umis))
        except Exception as exc:
            failed_regions.append((region, repr(exc)))
            _log(f"WARNING: round-2 region {region} failed and is skipped: {exc!r}")

    grouped2 = None
    with timer.stage("round2_umi_cluster"):
        def _batched_r2():
            faults.inject("cluster.batched_round2")
            return stages.cluster_and_select_grouped(
                region_records,
                identity=cfg.vsearch_identity_consensus,
                min_umi_length=cfg.min_umi_length,
                max_umi_length=cfg.max_umi_length,
                min_reads_per_cluster=1,
                max_reads_per_cluster=cfg.max_reads_per_cluster,
                balance_strands=False,
                mesh=engine_notrim.mesh,
            )

        try:
            # watchdog-guarded like round 1: a hung batched pass cancels
            # into this retry wrapper instead of wedging the run
            with watchdog.guard(
                "round2_umi_cluster",
                units=sum(len(u) for _, u in region_records),
            ):
                grouped2 = retry.call_with_retry("cluster.batched_round2", _batched_r2)
        except Exception as exc:
            retry.recorder().record(
                "cluster.batched_round2", classification=retry.classify(exc),
                outcome="degraded", error=repr(exc),
            )
            _log(f"WARNING: batched round-2 UMI clustering failed ({exc!r}); "
                 "retrying each region individually")
    for region, umis in region_records:
        try:
            if grouped2 is not None:
                selected, stat_rows = grouped2[region]
            else:
                with timer.stage("round2_umi_cluster"):
                    selected, stat_rows = stages.cluster_and_select(
                        umis,
                        identity=cfg.vsearch_identity_consensus,
                        min_umi_length=cfg.min_umi_length,
                        max_umi_length=cfg.max_umi_length,
                        min_reads_per_cluster=1,
                        max_reads_per_cluster=cfg.max_reads_per_cluster,
                        balance_strands=False,
                        mesh=engine_notrim.mesh,
                    )
            _finish_round2_region(region, selected, stat_rows, cons_store,
                                  lay, cfg, region_counts, region_cluster_umis)
        except Exception as exc:
            failed_regions.append((region, repr(exc)))
            _log(f"WARNING: round-2 region {region} failed and is skipped: {exc!r}")
    if failed_regions:
        with open(os.path.join(lay.logs, "incomplete_regions.log"), "w") as fh:
            for region, err in failed_regions:
                fh.write(f"{region}\t{err}\n")

    counts_csv = stages.write_counts_csv(region_counts, lay.counts)
    # counts conservation: the CSV on disk must read back exactly as the
    # in-memory per-region cluster totals it was written from
    contracts.check_equal(
        "counts", "counts CSV readback", _read_counts_csv(counts_csv),
        "in-memory region counts", region_counts,
        detail={"library": library},
    )
    if cfg.compare_umi_overlap_between_regions:
        _log("Testing for consensus umi matches between regions:", library)
        umi_overlap.count_overlapping_umis(
            region_cluster_umis, lay.logs, cfg.overlapping_umi_edit_threshold
        )
    # COMMIT point for overlapped round-2 QC: fixed position (always
    # before the stage-timing artifact and the counts manifest mark),
    # submission order, main thread — log bytes and failure/resume
    # semantics are exactly the serial run's, only the wall position
    # moved. (Round-1 QC committed before its own checkpoint in
    # _run_library_impl.)
    if qc_exec is not None:
        _commit_pending_qc(qc_exec, pending_qc, timer)
    timer.write_tsv(os.path.join(lay.logs, "stage_timing.tsv"))
    if round1_complete and not failed_regions:
        # incomplete counts are not checkpointed: resume must retry the
        # failed groups/regions instead of trusting a partial CSV. Only
        # the CSV is checksummed: the intermediates are regenerable (and
        # deleted under delete_tmp_files) — the counts CSV is the
        # library's contract with downstream analysis.
        lay.mark_stage_done("counts", artifacts=[counts_csv])

    if cfg.delete_tmp_files:
        for d in (lay.region_cluster_fasta, lay.clustering, lay.umi_fasta,
                  lay.fasta, lay.clustering_consensus, lay.region_fasta,
                  lay.consensus_umi_fasta):
            shutil.rmtree(d, ignore_errors=True)

    return region_counts


def _finish_round2_region(region, selected, stat_rows, cons_store, lay, cfg,
                          region_counts, region_cluster_umis) -> None:
    """Round-2 artifacts + counting for one exact region."""
    rdir = os.path.join(lay.clustering_consensus, f"region_{region}")
    os.makedirs(rdir, exist_ok=True)
    stages.write_cluster_stats_tsv(
        stat_rows, os.path.join(rdir, "vsearch_cluster_stats.tsv")
    )
    # smolecule parity: one entry per written member, named by cluster
    # (parse_umi_clusters.py:104-116)
    if cfg.write_intermediate_fastas:
        smolecule = os.path.join(rdir, "smolecule_clusters.fa")
        entries = [
            (str(cl.cluster_id),
             cons_store.blocks[m.block].decode_one(m.row))
            for cl in selected for m in cl.members
        ]
        fastx.write_fasta(smolecule, entries)
    # Count = round-2 CLUSTERS (unique molecules). Documented divergence:
    # the reference greps smolecule headers (count.py:9-20), i.e. written
    # members — identical whenever round 1 yields one cluster per
    # molecule, but it double-counts a molecule whose round-1 UMI split
    # produced two consensus even after its own round-2 dedup merged
    # them into one cluster. Counting clusters is the molecule-accurate
    # reading of "per-TCR UMI counts" (reference README.md:2).
    region_counts[region] = len(selected)
    region_cluster_umis[region] = [cl.members[0].combined for cl in selected]


def _write_align_log(stats: stages.AlignStats, path: str) -> None:
    with open(path, "w") as fh:
        fh.write(f"Total # primary alignments: {stats.n_aligned}\n")
        fh.write(f"n_total: {stats.n_total}\n")
        fh.write(f"n_ee_fail: {stats.n_ee_fail}\n")
        fh.write(f"n_trimmed: {stats.n_trimmed}\n")
        fh.write(f"n_short: {stats.n_short}\n")
        fh.write(f"n_long: {stats.n_long}\n")
        fh.write(f"n_pass: {stats.n_pass}\n")


def _read_counts_csv(path: str) -> dict[str, int]:
    out: dict[str, int] = {}
    if not os.path.exists(path):
        return out
    with open(path) as fh:
        next(fh, None)
        for line in fh:
            region, _, count = line.rstrip("\n").rpartition(",")
            if region:
                out[region] = int(count)
    return out
