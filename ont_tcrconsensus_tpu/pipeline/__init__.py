"""pipeline subpackage."""
