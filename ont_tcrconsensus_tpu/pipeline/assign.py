"""Fused per-batch device pass + columnar read store.

Round 1 of the reference pipeline runs five separate per-read CPU passes —
dorado primer trim, vsearch EE filter, minimap2 alignment, region split and
edlib UMI location (/root/reference/ont_tcr_consensus/tcr_consensus.py:141-222)
— each communicating through fastq/fasta files. Here all five are ONE jitted
device computation per padded read batch:

    trim -> EE mask -> k-mer sketch (both strands) -> top-k candidate
    banded SW -> UMI fuzzy-find in both adapter windows

and the read data stays on device as dense code arrays throughout; strings
are only materialized at artifact boundaries (:func:`..ops.encode.decode_batch`).
Survivors land in a :class:`ReadStore` of per-width columnar blocks that
downstream stages (grouping, UMI clustering, polish) index by (block, row) —
no per-read Python objects on the hot path.

Multi-chip: the fused pass is embarrassingly parallel over the batch axis, so
when a :class:`jax.sharding.Mesh` is supplied every input batch is sharded on
its leading axis over the ``data`` axis and XLA runs the same program per
chip with zero collectives (the reference's Ray fan-out, tcr_consensus.py:
141-167, mapped onto ICI).
"""

from __future__ import annotations

import dataclasses
import functools
import os
from collections import defaultdict

import jax
import jax.numpy as jnp
import numpy as np

from ont_tcrconsensus_tpu.io import bucketing, fastx
from ont_tcrconsensus_tpu.obs import device as obs_device
from ont_tcrconsensus_tpu.obs import metrics as obs_metrics
from ont_tcrconsensus_tpu.ops import ee_filter, encode, fuzzy_match, sketch, sw_pallas
from ont_tcrconsensus_tpu.robustness import faults as robustness_faults
from ont_tcrconsensus_tpu.robustness import watchdog

MIN_SCORE = 100  # SW score gate for a "primary alignment" equivalent
BIG_DIST = 1 << 20  # sentinel distance for "no qualifying primer hit"

# --- round-1 SW fast path (VERDICT r4 #4; DIVERGENCES #12) -----------------
# Round 1's filters need three things from the SW stage: a junk gate
# (score >= MIN_SCORE), the aligned reference span for the overlap filter
# (region_split.py:261-269 semantics), and the region pick among the top-k
# sketch candidates.  For sketch-confident reads all three are decided
# without base-level alignment: the region pick already follows sketch
# candidate 1 (the margin-pruned second pass only ever re-checks the
# low-margin quarter), the junk gate maps onto a cosine floor with a wide
# measured separation (simulated ONT reads bottom out near cos1 ~ 0.5;
# uniform-random junk tops out near ~0.2 — see tests/test_fast_assign.py),
# and a clean amplicon read's aligned span is its trimmed length capped at
# the region length.  So the fused pass runs SW only on the B/denom rows
# that NEED it — junk-suspects (cos1 below the floor), reads whose
# estimated span sits within a band-slack of the overlap boundary, and the
# lowest sketch margins — and synthesizes the three outputs for the rest.
# blast-id is NOT synthesized (NaN + sw_done=False); round 1 never filters
# on it and the error profiler samples only sw_done rows.
#
# Measured calibration (simulator R10.4-like error model, hashed k=8
# dim=4096 profiles): real reads cos1 >= 0.84 (min over 550+ reads at 6-
# and 48-region panels); uniform-random junk cos1 <= 0.34 (max over 120
# junk reads, growing ~0.01 per 8x panel size). 0.45 keeps a >=0.1 junk
# margin at 1000-ref panels and a ~0.4 real margin.
SW_COS_CONFIDENT = 0.45  # aligned-gate cosine floor for non-SW'd rows
# The synthesized span equals the true SW span up to net indel drift —
# ~0.5-1% of the region for R10.4-class error — so only reads within a
# proportional band of the overlap bound can be mis-filtered; those are
# forced into the SW subset. The band is 2% of the region length (2-4x
# the drift), NOT an absolute: a fixed +-64 nt would mark EVERY clean
# read marginal on refs <= 1280 nt (0.05*rl <= 64 at overlap 0.95) and
# silently overflow the subset capacity (code-review r5 finding #1),
# while 2% vs the 5% overlap margin stays capacity-healthy at any rl.
SW_LEN_SLACK_FRAC = 0.02
SW_LEN_SLACK_MIN = 16    # nt floor for very short panels
_NEED_BIG = 1.0e3        # flag weights dominating the margin term


# ---------------------------------------------------------------------------
# reference panel (device-resident)


@dataclasses.dataclass
class ReferencePanel:
    """Encoded reference regions + sketch profiles, built once per run."""

    names: list[str]
    seqs: dict[str, str]
    codes: np.ndarray          # (R, Wr) uint8
    lens: np.ndarray           # (R,) int32
    profiles: np.ndarray       # (R, dim) float32
    region_cluster: dict[str, int]
    cluster_of_region: np.ndarray  # (R,) int32 — region idx -> cluster id

    # device copies
    d_codes: jax.Array = dataclasses.field(repr=False, default=None)
    d_lens: jax.Array = dataclasses.field(repr=False, default=None)
    d_profiles: jax.Array = dataclasses.field(repr=False, default=None)

    @classmethod
    def build(cls, reference: dict[str, str], region_cluster: dict[str, int],
              pad_multiple: int = 128) -> "ReferencePanel":
        names = list(reference)
        max_len = max(len(s) for s in reference.values())
        codes, lens = encode.encode_batch([reference[n] for n in names], pad_to=max_len,
                                          multiple=pad_multiple)
        profiles = np.asarray(sketch.kmer_profile(codes, lens))
        cluster_of_region = np.array(
            [region_cluster[n] for n in names], dtype=np.int32
        )
        return cls(
            names=names, seqs=dict(reference), codes=codes, lens=lens,
            profiles=profiles, region_cluster=dict(region_cluster),
            cluster_of_region=cluster_of_region,
            d_codes=jnp.asarray(codes), d_lens=jnp.asarray(lens),
            d_profiles=jnp.asarray(profiles),
        )

    def region_len(self, idx: int) -> int:
        return int(self.lens[idx])


# ---------------------------------------------------------------------------
# the fused device pass


def _umi_windows(codes, lens_t, t_start, umi_masks, umi_mask_lens,
                 *, a5: int, a3: int) -> dict:
    """Fwd/rev UMI pattern search in both adapter windows — ONE dispatch.

    Window budgets are FIXED in the physical read frame, strand-independent:
    the reference re-derives the sequencer-orientation read for minus-strand
    alignments (``get_forward_sequence()``, region_split.py:493-500) and
    then always slices ``seq[:a5]`` / ``seq[-a3:]`` on it
    (extract_umis.py:120-121) — so a minus read's physical 5' window gets
    the a5 budget even though it carries the molecule's 3' structure. An
    earlier revision swapped the budgets per strand (molecule-frame
    reasoning); ADVICE r4 flagged that as a real divergence — with
    asymmetric budgets it moves the window edge 5 nt on minus reads —
    so this follows the reference exactly (tests/test_assign_band.py
    pins the a5 != a3 strand case). The mutually-revcomp UMI patterns
    keep the pattern search itself strand-agnostic.
    """
    B, W = codes.shape
    aw = max(a5, a3)
    pos_w = jnp.arange(aw, dtype=jnp.int32)[None, :]
    idx5 = jnp.clip(t_start[:, None] + pos_w, 0, W - 1)
    w5 = jnp.take(jnp.asarray(encode.CODE_TO_MASK),
                  jnp.take_along_axis(codes, idx5, axis=1).astype(jnp.int32))
    w5 = jnp.where(pos_w < a5, w5, jnp.uint8(0))
    l5 = jnp.minimum(lens_t, a5)
    start3 = jnp.maximum(lens_t - a3, 0)  # trimmed-frame coords (downstream)
    idx3 = jnp.clip((t_start + start3)[:, None] + pos_w, 0, W - 1)
    w3 = jnp.take(jnp.asarray(encode.CODE_TO_MASK),
                  jnp.take_along_axis(codes, idx3, axis=1).astype(jnp.int32))
    w3 = jnp.where(pos_w < a3, w3, jnp.uint8(0))
    l3 = jnp.minimum(lens_t, a3)
    ud, us, ue = fuzzy_match.fuzzy_find_multi(
        umi_masks, umi_mask_lens,
        jnp.concatenate([w5, w3], axis=0),
        jnp.concatenate([l5, l3], axis=0),
    )  # each (2, 2B)
    return {
        "d5": ud[0, :B], "s5": us[0, :B], "e5": ue[0, :B],
        "d3": ud[1, B:], "s3": us[1, B:], "e3": ue[1, B:],
        "start3": start3,
    }


@functools.partial(
    jax.jit, static_argnames=("band_width", "a5", "a3", "max_c")
)
def _targeted_pass(
    codes, lens, cand_idx,
    ref_codes, ref_lens,
    umi_masks, umi_mask_lens,
    min_len,
    *,
    band_width: int, a5: int, a3: int, max_c: int,
):
    """Round-2 device pass: align each consensus ONLY against its known
    region cluster's references (VERDICT r3 #6).

    Round 1 already binned every molecule into a region cluster, and the
    consensus drafts are molecule-(+)-oriented by construction
    (stages.py polish path orients subreads before the vote), so the full
    sketch -> both-strand top-k -> SW re-derivation of the fused pass is
    pure waste here: no primer trim (consensus carries only flank+UMI
    ends that local SW soft-clips), no EE data, no strand search, and the
    candidate set is the <=max_c refs of the read's own cluster
    (``cand_idx`` (B, max_c) int32, -1 padded). The reference re-aligns
    the full library (ref:tcr_consensus.py:356-372) because minimap2 has
    no notion of provenance; blast-id filter semantics downstream are
    IDENTICAL (same consume path).

    Returns the same out-dict contract as :func:`_fused_pass`.
    """
    B, W = codes.shape
    lens = lens.astype(jnp.int32)
    t_start = jnp.zeros((B,), jnp.int32)
    lens_t = lens
    ee_ok = lens_t >= min_len
    is_rev = jnp.zeros((B,), bool)

    def sw_one(ridx):
        valid_c = ridx >= 0
        r = jnp.where(valid_c, ridx, 0)
        rl = jnp.take(ref_lens, r)
        # two-sided margin split: consensus flank+UMI margins are small
        # and symmetric (no one-sided-trim case exists here)
        m5 = (lens_t - rl) // 2
        res = sw_pallas.align_banded_auto(
            codes, lens_t, jnp.take(ref_codes, r, axis=0), rl,
            (-m5).astype(jnp.int32), band_width=band_width,
        )
        return {
            "ridx": r.astype(jnp.int32),
            "score": jnp.where(valid_c, res.score, jnp.int32(-1)),
            "n_match": res.n_match, "n_cols": res.n_cols,
            "ref_start": res.ref_start, "ref_end": res.ref_end,
            "read_start": res.read_start, "read_end": res.read_end,
        }

    best = sw_one(cand_idx[:, 0])
    for c in range(1, max_c):
        cur = sw_one(cand_idx[:, c])
        better = cur["score"] > best["score"]  # ties keep the earlier ref
        best = {k: jnp.where(better, cur[k], best[k]) for k in best}

    umi_out = _umi_windows(
        codes, lens_t, t_start, umi_masks, umi_mask_lens, a5=a5, a3=a3
    )
    blast_id = best["n_match"] / jnp.maximum(best["n_cols"], 1)
    return {
        "lens": lens_t, "t_start": t_start,
        "ee_ok": ee_ok, "is_rev": is_rev,
        "ridx": best["ridx"], "score": best["score"],
        "blast_id": blast_id.astype(jnp.float32),
        "ref_start": best["ref_start"], "ref_end": best["ref_end"],
        "read_start": best["read_start"], "read_end": best["read_end"],
        "sw_done": jnp.ones_like(best["ridx"], dtype=bool),
        **umi_out,
    }


@functools.partial(
    jax.jit,
    static_argnames=(
        "top_k", "band_width", "a5", "a3", "trim_window", "has_quals",
        "primer_shapes", "sw_subset_denom",
    ),
)
def _fused_pass(
    codes, quals, lens,
    ref_codes, ref_lens, ref_profiles,
    umi_masks, umi_mask_lens,
    primer_stack, primer_stack_lens, primer_max_dists,
    max_ee_rate, min_len, overlap_frac,
    *,
    top_k: int, band_width: int, a5: int, a3: int,
    trim_window: int, has_quals: bool, primer_shapes: tuple,
    sw_subset_denom: int = 0,
):
    """One device dispatch: trim + filter + assign + UMI-locate a batch.

    All inputs are padded device arrays; every output is a (B,)-shaped array
    except the trimmed codes/quals. ``primer_stack`` is (2P, m) — P forward
    primers then their P reverse complements, zero-padded (static count via
    ``primer_shapes``); ``umi_masks`` is (2, m_umi) — fwd then rev pattern.
    Pattern searches run as single multi-pattern dispatches over the
    concatenated 5'/3' windows: the DP scan is latency-bound, so stacked
    patterns/windows are ~free while per-pattern calls are not.
    """
    B, W = codes.shape
    lens = lens.astype(jnp.int32)

    # --- primer trim (dorado trim analogue, preprocessing.py:7-59) ---
    t_start = jnp.zeros((B,), jnp.int32)
    t_end = lens
    if primer_shapes:
        P = len(primer_shapes)
        tw = min(trim_window, W)
        pos = jnp.arange(tw, dtype=jnp.int32)[None, :]
        # 5' window (forward primers) + 3' window (RC primers), one dispatch
        w5 = jnp.take(jnp.asarray(encode.CODE_TO_MASK), codes[:, :tw].astype(jnp.int32))
        start3w = jnp.maximum(lens - tw, 0)
        idx3 = jnp.clip(start3w[:, None] + pos, 0, W - 1)
        w3 = jnp.take(jnp.asarray(encode.CODE_TO_MASK),
                      jnp.take_along_axis(codes, idx3, axis=1).astype(jnp.int32))
        wlen = jnp.minimum(lens, tw)
        wins = jnp.concatenate([w5, w3], axis=0)          # (2B, tw)
        wlens = jnp.concatenate([wlen, wlen], axis=0)
        d, s, e = fuzzy_match.fuzzy_find_multi(
            primer_stack, primer_stack_lens, wins, wlens
        )  # each (2P, 2B)
        pmax = primer_max_dists[:, None]
        # loop-equivalent selection: among qualifying primers the smallest
        # distance wins, ties to the earliest primer (argmin is first-min)
        d5p = jnp.where(d[:P, :B] <= pmax, d[:P, :B], jnp.int32(BIG_DIST))
        p5 = jnp.argmin(d5p, axis=0)
        hit5 = jnp.take_along_axis(d5p, p5[None, :], axis=0)[0] < BIG_DIST
        best_e5 = jnp.take_along_axis(e[:P, :B], p5[None, :], axis=0)[0]
        d3p = jnp.where(d[P:, B:] <= pmax, d[P:, B:], jnp.int32(BIG_DIST))
        p3 = jnp.argmin(d3p, axis=0)
        hit3 = jnp.take_along_axis(d3p, p3[None, :], axis=0)[0] < BIG_DIST
        best_s3 = jnp.take_along_axis(s[P:, B:], p3[None, :], axis=0)[0]
        t_start = jnp.where(hit5, best_e5, 0)
        t_end = jnp.where(hit3, start3w + best_s3, lens)
        t_end = jnp.maximum(t_end, t_start)

    # The trim is VIRTUAL: reads stay unshifted on device, only the
    # [t_start, t_end) span bounds move. No (B, W) shift gathers, and —
    # decisive over a tunneled TPU — no (B, W) codes/quals readback: the
    # host already holds the unshifted batch and compacts survivors itself.
    lens_t = (t_end - t_start).astype(jnp.int32)

    # --- EE / length filter (vsearch --fastq_filter, preprocessing.py:104-159)
    if has_quals:
        ee_ok = ee_filter.ee_rate_mask_span(quals, t_start, t_end, max_ee_rate, min_len)
    else:
        ee_ok = lens_t >= min_len

    # --- sketch candidates + strand (minimap2 seeding analogue) ---
    # computed on the untrimmed read: the <=150 nt adapter/primer margin is
    # uniform noise against a ~2 kb signal and local SW soft-clips it
    cand_idx, cand_scores, is_rev = sketch.candidates_both_strands(
        codes, lens, ref_profiles, top_k=top_k
    )
    oriented = jnp.where(is_rev[:, None], sketch.revcomp_batch(codes, lens), codes)
    # trimmed-span start in the oriented frame (revcomp flips the span)
    t_start_o = jnp.where(is_rev, lens - t_end, t_start)

    # Band-centering bias for one-sided primer trims: when only one primer
    # hit, the missed side keeps its adapter/primer junk inside the span, so
    # splitting the read-vs-ref length margin evenly mis-centers the band by
    # ~junk/2 (~35-75 nt) — real headroom at band 128 (+/-64). Anchor the
    # trusted side instead: its margin is just flank+UMI (~56 nt), capped at
    # that side's configured softclip budget (a5/a3, ADVICE r3: a config
    # with a longer flank+UMI region raises the cap with it) so the
    # two-sided case (margin//2 < cap) is untouched. Flags follow the span
    # into the oriented frame (revcomp swaps the ends).
    if primer_shapes:
        b5, b3 = hit5 & ~hit3, hit3 & ~hit5
        anchor5 = jnp.where(is_rev, b3, b5)
        anchor3 = jnp.where(is_rev, b5, b3)
    else:
        anchor5 = anchor3 = jnp.zeros((B,), bool)

    # Adapter/primer bases outside the virtual-trim span are masked to the
    # pad sentinel before SW: they then never match (local alignment
    # soft-clips them), so score/blast_id/ref spans cover only the trimmed
    # read — the error-profile stage later aligns the trimmed read against
    # the stored ref span and would otherwise count adapter-aligned
    # reference bases as deletions (ADVICE r2).
    pos_full = jnp.arange(W, dtype=jnp.int32)[None, :]
    in_span = (pos_full >= t_start_o[:, None]) & (
        pos_full < (t_start_o + lens_t)[:, None]
    )
    oriented_sw = jnp.where(in_span, oriented, jnp.uint8(sw_pallas.PAD_SENTINEL))

    # --- banded SW vs each candidate; keep the best score ---
    def sw_pass(codes_in, lens_in, lens_t_in, t_start_in, a5_in, a3_in, ridx):
        rl = jnp.take(ref_lens, ridx)
        margin = lens_t_in - rl
        half = margin // 2
        cap5 = jnp.minimum(half, a5)
        cap3 = jnp.minimum(half, a3)
        m5 = jnp.where(a5_in, cap5, jnp.where(a3_in, margin - cap3, half))
        offs = (-t_start_in - m5).astype(jnp.int32)
        res = sw_pallas.align_banded_auto(
            codes_in, lens_in, jnp.take(ref_codes, ridx, axis=0), rl, offs,
            band_width=band_width,
        )
        return {
            "score": res.score, "ridx": ridx,
            "ref_start": res.ref_start, "ref_end": res.ref_end,
            "read_start": res.read_start, "read_end": res.read_end,
            "n_match": res.n_match, "n_cols": res.n_cols,
        }

    if sw_subset_denom > 0 and top_k == 2:
        # fast path (see module constants): SW only the needy subset,
        # synthesize filter-sufficient outputs for the confident rest.
        k_sw = min(B, max(B // sw_subset_denom, 8))
        cos1 = cand_scores[:, 0]
        margin = cand_scores[:, 0] - cand_scores[:, 1]
        rl1 = jnp.take(ref_lens, cand_idx[:, 0])
        est_start = jnp.clip((rl1 - lens_t) // 2, 0, rl1)
        est_end = jnp.minimum(est_start + lens_t, rl1)
        est_span = (est_end - est_start).astype(jnp.float32)
        min_span = rl1.astype(jnp.float32) * overlap_frac
        slack = jnp.maximum(
            rl1.astype(jnp.float32) * jnp.float32(SW_LEN_SLACK_FRAC),
            jnp.float32(SW_LEN_SLACK_MIN),
        )
        length_marginal = jnp.abs(est_span - min_span) <= slack
        junk_suspect = cos1 < jnp.float32(SW_COS_CONFIDENT)
        need = (
            -margin
            + jnp.where(length_marginal, jnp.float32(_NEED_BIG), 0.0)
            + jnp.where(junk_suspect, jnp.float32(2.0 * _NEED_BIG), 0.0)
        )
        # padding rows (len 0) and EE/length-gate failures are rejected by
        # the host regardless of SW — don't let them displace real needy
        # rows from the SW subset (code-review r5 finding #3)
        need = jnp.where(ee_ok & (lens_t > 0), need,
                         jnp.float32(-3.0 * _NEED_BIG))
        _, sw_rows = jax.lax.top_k(need, k_sw)

        def take(x):
            return jnp.take(x, sw_rows, axis=0)

        sub_args = (take(oriented_sw), take(lens), take(lens_t),
                    take(t_start_o), take(anchor5), take(anchor3))
        sub_best = sw_pass(*sub_args, take(cand_idx[:, 0]))
        sub_cur = sw_pass(*sub_args, take(cand_idx[:, 1]))
        better = sub_cur["score"] > sub_best["score"]
        sub_best = {
            k: jnp.where(better, sub_cur[k], sub_best[k]) for k in sub_best
        }

        # synthesized outputs for confident rows (filter-sufficient only)
        best = {
            "score": jnp.where(cos1 >= jnp.float32(SW_COS_CONFIDENT),
                               jnp.int32(MIN_SCORE), jnp.int32(-1)),
            "ridx": cand_idx[:, 0],
            "ref_start": est_start.astype(jnp.int32),
            "ref_end": est_end.astype(jnp.int32),
            "read_start": jnp.zeros((B,), jnp.int32),
            "read_end": lens_t,
            "n_match": jnp.zeros((B,), jnp.int32),
            "n_cols": jnp.zeros((B,), jnp.int32),
        }
        best = {
            k: best[k].at[sw_rows].set(sub_best[k].astype(best[k].dtype))
            for k in best
        }
        sw_done = jnp.zeros((B,), bool).at[sw_rows].set(True)
    else:
        best = sw_pass(oriented_sw, lens, lens_t, t_start_o, anchor5,
                       anchor3, cand_idx[:, 0])
        if top_k == 2 and B >= 8:
            # Margin-pruned second pass: the full second SW pass nearly
            # doubled the fused pass's dominant cost, but the sketch margin
            # is decisive for most reads — only homologous region pairs
            # (~1% divergence) score close. Run candidate 2 ONLY for the
            # quarter of the batch with the smallest cosine margin (static
            # B/4 sub-batch keeps shapes compile-stable); everyone else
            # keeps candidate 1. The bench's assignment-accuracy check
            # guards this capacity.
            k2 = B // 4
            margin = cand_scores[:, 0] - cand_scores[:, 1]
            _, amb = jax.lax.top_k(-margin, k2)
            cur = sw_pass(
                jnp.take(oriented_sw, amb, axis=0), jnp.take(lens, amb),
                jnp.take(lens_t, amb), jnp.take(t_start_o, amb),
                jnp.take(anchor5, amb), jnp.take(anchor3, amb),
                jnp.take(cand_idx[:, 1], amb),
            )
            better = cur["score"] > jnp.take(best["score"], amb)
            best = {
                k: best[k].at[amb].set(
                    jnp.where(better, cur[k], jnp.take(best[k], amb))
                )
                for k in best
            }
        else:
            for c in range(1, top_k):
                cur = sw_pass(oriented_sw, lens, lens_t, t_start_o, anchor5,
                              anchor3, cand_idx[:, c])
                better = cur["score"] > best["score"]
                best = {k: jnp.where(better, cur[k], best[k]) for k in best}
        sw_done = jnp.ones((B,), bool)

    # --- UMI fuzzy location in both adapter windows (extract_umis.py:19-126)
    umi_out = _umi_windows(
        codes, lens_t, t_start, umi_masks, umi_mask_lens, a5=a5, a3=a3
    )

    # synthesized rows carry NaN (no alignment columns exist for them)
    blast_id = jnp.where(
        sw_done,
        best["n_match"] / jnp.maximum(best["n_cols"], 1),
        jnp.float32(jnp.nan),
    )
    return {
        "lens": lens_t, "t_start": t_start,
        "ee_ok": ee_ok, "is_rev": is_rev,
        "ridx": best["ridx"], "score": best["score"],
        "blast_id": blast_id.astype(jnp.float32),
        "ref_start": best["ref_start"], "ref_end": best["ref_end"],
        "read_start": best["read_start"], "read_end": best["read_end"],
        "sw_done": sw_done,
        **umi_out,
    }


# ---------------------------------------------------------------------------
# columnar survivors


@dataclasses.dataclass
class ReadBlock:
    """Columnar arrays for the survivors of one width bucket."""

    width: int
    codes: np.ndarray        # (n, W) uint8 (trimmed, original orientation)
    lens: np.ndarray         # (n,) int32
    names: list[str]
    is_rev: np.ndarray       # (n,) bool
    region_idx: np.ndarray   # (n,) int32
    blast_id: np.ndarray     # (n,) float32
    ref_start: np.ndarray    # (n,) int32 — aligned reference span (exclusive end)
    ref_end: np.ndarray
    umi: dict[str, np.ndarray]  # d5,s5,e5,d3,s3,e3,start3 — (n,) int32 each
    # (n, W) uint8 phred, trimmed in the same frame as codes; None for
    # FASTA input. Kept for the polisher's v4 quality channels — quals are
    # uint8 like codes, so the store's survivor footprint doubles, still
    # far under the streamed-ingest ceiling (STREAMING_INGEST.md).
    quals: np.ndarray | None = None
    # (n,) bool — True where blast_id/ref spans come from an actual SW
    # (False: SW fast-path synthesized estimates; the error profiler
    # samples only sw_done rows). None == all exact (legacy blocks).
    sw_done: np.ndarray | None = None

    @property
    def num_reads(self) -> int:
        return len(self.lens)

    def decode(self, rows: np.ndarray) -> list[str]:
        return encode.decode_batch(self.codes[rows], self.lens[rows])

    def decode_one(self, row: int) -> str:
        return encode.decode_batch(
            self.codes[row : row + 1], self.lens[row : row + 1]
        )[0]


@dataclasses.dataclass
class ReadStore:
    """All surviving reads of one library, as per-width columnar blocks."""

    blocks: list[ReadBlock]

    @property
    def num_reads(self) -> int:
        return sum(b.num_reads for b in self.blocks)

    def group_rows_by(self, key_of_region: np.ndarray) -> dict[int, list[tuple[int, np.ndarray]]]:
        """Group reads by ``key_of_region[region_idx]``.

        Returns {key: [(block_index, row_indices), ...]}.
        """
        groups: dict[int, list[tuple[int, np.ndarray]]] = defaultdict(list)
        for bi, blk in enumerate(self.blocks):
            keys = key_of_region[blk.region_idx]
            for key in np.unique(keys):
                groups[int(key)].append((bi, np.where(keys == key)[0]))
        return dict(groups)


@dataclasses.dataclass
class LengthStats:
    """seqkit-stat-style aggregates (ref preprocessing.py:82-99 artifact)."""

    n: int = 0
    sum_len: int = 0
    min_len: int = 0
    max_len: int = 0
    sum_qual: float = 0.0   # mean-Phred sum over reads (0 when no quals)

    def update(self, lens: np.ndarray, mean_quals: np.ndarray | None = None):
        if lens.size == 0:
            return
        self.n += int(lens.size)
        self.sum_len += int(lens.sum())
        mn = int(lens.min())
        self.min_len = mn if self.min_len == 0 else min(self.min_len, mn)
        self.max_len = max(self.max_len, int(lens.max()))
        if mean_quals is not None and mean_quals.size:
            self.sum_qual += float(mean_quals.sum())

    @property
    def avg_len(self) -> float:
        return self.sum_len / self.n if self.n else 0.0

    @property
    def avg_qual(self) -> float:
        return self.sum_qual / self.n if self.n else 0.0


@dataclasses.dataclass
class AlignStats:
    n_total: int = 0
    n_ee_fail: int = 0
    n_trimmed: int = 0     # reads with at least one primer cut
    n_aligned: int = 0     # score >= MIN_SCORE among EE survivors
    n_unaligned: int = 0   # EE survivors below the score gate
    n_short: int = 0
    n_long: int = 0
    n_low_blast: int = 0
    n_pass: int = 0
    # ingest accounting (conservation contracts, robustness/contracts.py)
    n_ingested: int = 0        # records drawn from the parser
    n_bucket_short: int = 0    # dropped below the batcher min_len gate
    n_bucket_long: int = 0     # dropped above the largest width bucket
    pre_filter: LengthStats = dataclasses.field(default_factory=LengthStats)
    post_filter: LengthStats = dataclasses.field(default_factory=LengthStats)


# ---------------------------------------------------------------------------
# host engine


class AssignEngine:
    """Holds device constants + jit/shard_map caches for the fused pass.

    ``mesh`` (optional) shards every batch's leading axis over the mesh's
    ``data`` axis; batch sizes must divide the data-axis size (run.py pads
    batches to a fixed power-of-two size, so this holds by construction).
    """

    def __init__(
        self,
        panel: ReferencePanel,
        umi_fwd: str,
        umi_rev: str,
        primers: list[str] | None = None,
        primer_max_dist_frac: float = 0.15,
        top_k: int = 2,
        band_width: int = 128,
        a5: int = 81,
        a3: int = 76,
        trim_window: int = 150,
        mesh=None,
        fast_denom: int = 4,
    ):
        self.panel = panel
        self.top_k = top_k
        self.band_width = band_width
        self.a5 = a5
        self.a3 = a3
        self.trim_window = trim_window
        self.mesh = mesh
        # SW fast-path subset denominator (0 disables); active only when a
        # dispatch supplies overlap_frac (round 1) — see _fused_pass
        self.fast_denom = fast_denom

        def stack_masks(masks: list[np.ndarray]) -> tuple[jax.Array, jax.Array]:
            stacked, lens_ = encode.pad_batch(masks, pad_value=0, multiple=1)
            return jnp.asarray(stacked), jnp.asarray(lens_)

        self.umi_masks, self.umi_mask_lens = stack_masks(
            [encode.encode_mask(umi_fwd), encode.encode_mask(umi_rev)]
        )
        primers = primers or []
        if primers:
            self.primer_stack, self.primer_stack_lens = stack_masks(
                [encode.encode_mask(p) for p in primers]
                + [encode.encode_mask(encode.revcomp_str(p)) for p in primers]
            )
        else:
            self.primer_stack = jnp.zeros((0, 1), jnp.uint8)
            self.primer_stack_lens = jnp.zeros((0,), jnp.int32)
        self.primer_max_dists = jnp.asarray(
            np.array(
                [max(1, int(len(p) * primer_max_dist_frac)) for p in primers],
                np.int32,
            )
        )
        self.primer_shapes = tuple(len(p) for p in primers)
        self._sharded_cache: dict[bool, object] = {}

    def set_mesh(self, mesh) -> None:
        """Swap the engine onto a different mesh mid-run (the degraded-mesh
        re-execution path): every cached shard_map program was compiled
        against the OLD mesh's device set, so the cache is dropped — the
        next dispatch recompiles against the survivors."""
        self.mesh = mesh
        self._sharded_cache.clear()

    def _static_kwargs(self, has_quals: bool, fast: bool) -> dict:
        return dict(
            top_k=self.top_k, band_width=self.band_width,
            a5=self.a5, a3=self.a3, trim_window=self.trim_window,
            has_quals=has_quals, primer_shapes=self.primer_shapes,
            sw_subset_denom=self.fast_denom if fast else 0,
        )

    def _sharded_fn(self, has_quals: bool, fast: bool):
        """shard_map-wrapped fused pass: batch axis over the mesh's data axis.

        shard_map (not jit auto-partitioning) so the per-shard program is the
        exact single-chip program — the Pallas kernel included.
        """
        key = (has_quals, fast)
        if key in self._sharded_cache:
            return self._sharded_cache[key]
        from ont_tcrconsensus_tpu.parallel.mesh import shard_map_compat as shard_map
        from jax.sharding import PartitionSpec as P

        kwstat = self._static_kwargs(has_quals, fast)

        def base(codes, quals, lens, *rest):
            return _fused_pass(codes, quals, lens, *rest, **kwstat)

        d1, d2 = P("data"), P("data", None)
        rep = P()
        in_specs = (
            d2, d2 if has_quals else rep, d1,
            rep, rep, rep, rep, rep,
            rep, rep, rep,
            rep, rep, rep,
        )
        out_specs = {
            k: d1
            for k in ("lens", "t_start", "ee_ok", "is_rev", "ridx", "score",
                      "blast_id", "ref_start", "ref_end", "read_start",
                      "read_end", "sw_done",
                      "d5", "s5", "e5", "d3", "s3", "e3", "start3")
        }
        fn = jax.jit(shard_map(
            base, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        ))
        self._sharded_cache[key] = fn
        return fn

    def run_batch_async(self, batch: bucketing.ReadBatch, max_ee_rate: float,
                        min_len: int,
                        overlap_frac: float | None = None,
                        ) -> dict[str, jax.Array]:
        """Dispatch the fused pass; returns DEVICE arrays (jax async
        dispatch means this does not block on the computation).

        ``overlap_frac`` (the round-1 overlap filter fraction) arms the SW
        fast path: the device pass needs the overlap bound to route
        length-marginal reads into the SW subset. ``None`` (round-2 /
        standalone callers) keeps the exact full-batch SW.
        """
        has_quals = batch.quals is not None
        fast = (overlap_frac is not None and self.fast_denom > 0
                and self.top_k == 2)
        args = (
            jnp.asarray(batch.codes),
            jnp.asarray(batch.quals) if has_quals else jnp.zeros((1, 1), jnp.uint8),
            jnp.asarray(batch.lengths),
            self.panel.d_codes, self.panel.d_lens, self.panel.d_profiles,
            self.umi_masks, self.umi_mask_lens,
            self.primer_stack, self.primer_stack_lens, self.primer_max_dists,
            jnp.float32(max_ee_rate), jnp.int32(min_len),
            jnp.float32(overlap_frac if overlap_frac is not None else 0.0),
        )
        if self.mesh is not None:
            robustness_faults.inject("mesh.dispatch")
            return self._sharded_fn(has_quals, fast)(*args)
        return _fused_pass(*args, **self._static_kwargs(has_quals, fast))

    def _sharded_targeted_fn(self, max_c: int):
        """shard_map-wrapped targeted pass (same pattern as _sharded_fn)."""
        key = ("targeted", max_c)
        if key in self._sharded_cache:
            return self._sharded_cache[key]
        from ont_tcrconsensus_tpu.parallel.mesh import shard_map_compat as shard_map
        from jax.sharding import PartitionSpec as P

        kwstat = dict(band_width=self.band_width, a5=self.a5, a3=self.a3,
                      max_c=max_c)

        def base(codes, lens, cand, *rest):
            return _targeted_pass(codes, lens, cand, *rest, **kwstat)

        d1, d2, rep = P("data"), P("data", None), P()
        in_specs = (d2, d1, d2, rep, rep, rep, rep, rep)
        out_specs = {
            k: d1
            for k in ("lens", "t_start", "ee_ok", "is_rev", "ridx", "score",
                      "blast_id", "ref_start", "ref_end", "read_start",
                      "read_end", "sw_done",
                      "d5", "s5", "e5", "d3", "s3", "e3", "start3")
        }
        fn = jax.jit(shard_map(
            base, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        ))
        self._sharded_cache[key] = fn
        return fn

    def run_batch_targeted_async(
        self, batch: bucketing.ReadBatch, cand_idx: np.ndarray, min_len: int,
    ) -> dict[str, jax.Array]:
        """Round-2 dispatch: align each read only against its candidate
        refs (``cand_idx`` (B, max_c) int32, -1 padded); see
        :func:`_targeted_pass`."""
        max_c = int(cand_idx.shape[1])
        args = (
            jnp.asarray(batch.codes), jnp.asarray(batch.lengths),
            jnp.asarray(cand_idx),
            self.panel.d_codes, self.panel.d_lens,
            self.umi_masks, self.umi_mask_lens,
            jnp.int32(min_len),
        )
        if self.mesh is not None:
            robustness_faults.inject("mesh.dispatch")
            return self._sharded_targeted_fn(max_c)(*args)
        return _targeted_pass(
            *args, band_width=self.band_width, a5=self.a5, a3=self.a3,
            max_c=max_c,
        )

    def run_batch(self, batch: bucketing.ReadBatch, max_ee_rate: float,
                  min_len: int,
                  overlap_frac: float | None = None) -> dict[str, np.ndarray]:
        # ONE batched device->host transfer: per-array readback pays a flat
        # per-transfer latency (dramatic over a tunneled TPU: ~20 arrays of
        # round-trips per batch), device_get coalesces them
        return jax.device_get(
            self.run_batch_async(batch, max_ee_rate, min_len, overlap_frac)
        )


_PREFETCH_DONE = object()


def _prefetch(iterator, depth: int = 2):
    """Run an iterator in a worker thread, ``depth`` items ahead.

    Host-side batch building (parse + encode + pad) overlaps device
    execution: the consumer blocks in device readback (GIL released) while
    the worker prepares the next padded batch (SURVEY §7 hard-part 5).
    Abandoning the generator early (break / exception in the consumer)
    stops the worker too: every blocking put is a timed wait on a stop
    event the generator's ``finally`` sets, so no thread is left pinned on
    a full queue holding padded batches.
    """
    import queue
    import threading

    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def put_until_stop(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in iterator:
                if not put_until_stop(item) or stop.is_set():
                    return
            put_until_stop(_PREFETCH_DONE)
        except BaseException as exc:  # propagate into the consumer
            put_until_stop(exc)

    thread = threading.Thread(target=worker, daemon=True)
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _PREFETCH_DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        # JOIN, not just signal: the worker may be mid-pull (parsing a
        # chunk, routing bad records through an IngestGuard) — a retrying
        # caller resets that guard right after this generator unwinds, so
        # a stale worker touching it after teardown would double-count
        # quarantined records. Bounded: the worker exits at its next
        # put/stop check (<= 0.5 s) once the current pull completes.
        thread.join()


def _batches_from_source(source, batch_size, widths, subsample,
                         counters=None, guard=None):
    """Batch iterator from a file path (native C++ parser when available,
    pure-Python fallback) or any FastxRecord iterable.

    ``guard`` (an :class:`..io.validate.IngestGuard`) switches a path
    source to the TOLERANT parsers: malformed records/regions are routed to
    the guard (quarantine/drop per its policy) instead of raising, and
    parsing resynchronizes at the next record. Without a guard the legacy
    fail-fast behavior is unchanged.
    """
    if isinstance(source, bucketing.EncodedRecords):
        # device-resident hand-off: round-1 consensus codes feed round 2
        # without a decode->string->re-encode detour (bijective on the
        # 0..4 alphabet, so batches are byte-identical to the string
        # path; pinned by the graph-vs-imperative identity test).
        # subsample never applies here — consensus records are not raw
        # reads and the imperative path never subsamples them either.
        return bucketing.batch_encoded(
            source, batch_size=batch_size, widths=widths, min_len=1,
            counters=counters,
        )
    if isinstance(source, (str, os.PathLike)):
        from ont_tcrconsensus_tpu.io import native

        tolerant = guard is not None
        # STREAMED ingest: O(chunk) host memory, so a 100+ GB lane never
        # materializes (SURVEY §7 hard-part 5; VERDICT r3 #5). Batch shapes
        # are identical to a whole-file parse. The FIRST chunk is pulled
        # eagerly so early malformed input / native failures surface here
        # (falling back to the pure-Python parser before anything was
        # consumed); a failure DEEPER in the file necessarily raises
        # mid-stream — the price of not materializing the whole file.
        chunk_iter = None
        first_cell: list = []
        try:
            if native.available():
                chunk_iter = native.parse_chunks(source, tolerant=tolerant)
                first = next(chunk_iter, None)
                if first is not None:
                    first_cell.append(first)
                del first
        except ValueError:
            raise
        except Exception:
            chunk_iter = None
        if chunk_iter is not None:
            def chunks():
                def consume_bad(parsed):
                    if guard is not None and parsed.bad:
                        guard.handle_native(parsed.bad)
                    return parsed

                while first_cell:
                    # pop so the eager first chunk frees after consumption
                    # instead of staying pinned for the whole ingest
                    yield consume_bad(first_cell.pop())
                for parsed in chunk_iter:
                    yield consume_bad(parsed)

            return bucketing.batch_parsed_chunks(
                chunks(),
                batch_size=batch_size, widths=widths, min_len=1,
                subsample=subsample, counters=counters,
            )
        if tolerant:
            from ont_tcrconsensus_tpu.io import validate as validate_mod

            source = validate_mod.iter_records_tolerant(source, guard)
        else:
            source = fastx.read_fastx(source)

    records = iter(source)

    def limited():
        taken = 0
        for rec in records:
            if subsample is not None and taken >= subsample:
                return
            taken += 1
            yield rec

    return bucketing.batch_reads(
        limited(), batch_size=batch_size, widths=widths, min_len=1,
        counters=counters,
    )


def run_assign(
    source,
    engine: AssignEngine,
    max_ee_rate: float,
    min_len: int,
    minimal_region_overlap: float,
    max_softclip_5_end: int,
    max_softclip_3_end: int,
    batch_size: int = 1024,
    max_read_length: int = 4096,
    blast_id_threshold: float | None = None,
    collect_qc: list | None = None,
    subsample: int | None = None,
    prefetch_depth: int = 2,
    dispatch=None,
    guard=None,
) -> tuple[ReadStore, AlignStats]:
    """Stream a fastx file or record iterable through the fused pass.

    Filters mirror region_split.py:261-269 (ref-overlap + read-length window)
    plus — when ``blast_id_threshold`` is set (round 2) — the consensus
    blast-id gate of minimap2_align.py:209-245. ``subsample`` mirrors
    ``dorado trim --max-reads`` head-subsampling (preprocessing.py:41-57).
    ``dispatch`` overrides the per-batch device call (default: the engine's
    fused pass) — round 2 passes the targeted-candidate dispatcher; every
    downstream filter/consume step is shared, so filter semantics cannot
    drift between the two paths.

    A path source uses the native C++ parser when the extension builds
    (io/native), falling back to the pure-Python parser; batch building is
    prefetched on a worker thread so ingest overlaps device compute.
    ``guard`` (io/validate.IngestGuard) routes malformed records to
    quarantine/drop instead of failing the file (data-plane hardening).
    """
    panel = engine.panel
    stats = AlignStats()
    counters = bucketing.IngestCounters()
    acc: dict[int, list[dict]] = defaultdict(list)
    acc_names: dict[int, list[list[str]]] = defaultdict(list)

    widths = tuple(w for w in bucketing.DEFAULT_WIDTHS if w <= max_read_length)

    def consume(batch, out):
        valid = batch.valid
        nv = int(valid.sum())
        stats.n_total += nv

        lens = out["lens"]
        ee_ok = out["ee_ok"] & valid
        stats.n_ee_fail += int(nv - (ee_ok & valid).sum())
        stats.n_trimmed += int(((out["t_start"] > 0) & valid).sum())
        mean_quals = None
        if batch.quals is not None:
            pos = np.arange(batch.quals.shape[1])[None, :]
            in_span = (pos >= out["t_start"][:, None]) & (
                pos < (out["t_start"] + lens)[:, None]
            )
            qsum = np.where(in_span, batch.quals, 0).sum(axis=1)
            mean_quals = qsum / np.maximum(lens, 1)
        stats.pre_filter.update(
            lens[valid], mean_quals[valid] if mean_quals is not None else None
        )
        aligned = ee_ok & (out["score"] >= MIN_SCORE)
        stats.n_aligned += int(aligned.sum())
        stats.n_unaligned += int((ee_ok & ~aligned).sum())

        rlens = panel.lens[out["ridx"]]
        ref_span = out["ref_end"] - out["ref_start"]
        min_span = rlens * minimal_region_overlap
        max_len = rlens * (2 - minimal_region_overlap) + (
            max_softclip_5_end + max_softclip_3_end
        )
        short = aligned & (ref_span < min_span)
        long_ = aligned & ~short & (lens > max_len)
        stats.n_short += int(short.sum())
        stats.n_long += int(long_.sum())
        ok = aligned & ~short & ~long_
        if blast_id_threshold is not None:
            low = ok & ~(out["blast_id"] > blast_id_threshold)
            stats.n_low_blast += int(low.sum())
            ok = ok & ~low
        stats.n_pass += int(ok.sum())
        stats.post_filter.update(
            lens[ok], mean_quals[ok] if mean_quals is not None else None
        )

        if collect_qc is not None:
            status = np.full(len(valid), "", dtype=object)
            status[np.asarray(short)] = "short"
            status[np.asarray(long_)] = "long"
            if blast_id_threshold is not None:
                status[np.asarray(low)] = "low_blast_id"
            status[np.asarray(ok)] = "pass"
            for i in np.where(aligned)[0]:
                qc = {
                    "name": batch.ids[i].partition(" ")[0],
                    "region": panel.names[int(out["ridx"][i])],
                    "ref_span": int(ref_span[i]),
                    "read_len": int(lens[i]),
                    "region_len": int(rlens[i]),
                    "blast_id": float(out["blast_id"][i]),
                    "status": str(status[i]),
                }
                if status[i] == "short":
                    qc["nt_short"] = float(min_span[i] - ref_span[i])
                elif status[i] == "long":
                    qc["nt_long"] = float(lens[i] - max_len[i])
                collect_qc.append(qc)

        rows = np.where(ok)[0]
        if len(rows) == 0:
            return
        # trimmed survivor codes, rebuilt host-side from the unshifted batch
        # (the device pass trims virtually; see _fused_pass)
        Wb = batch.codes.shape[1]
        shift_idx = np.clip(
            out["t_start"][rows][:, None] + np.arange(Wb)[None, :], 0, Wb - 1
        )
        shifted = np.take_along_axis(batch.codes[rows], shift_idx, axis=1)
        in_new = np.arange(Wb)[None, :] < lens[rows][:, None]
        trimmed_codes = np.where(in_new, shifted, encode.PAD_CODE).astype(np.uint8)
        trimmed_quals = None
        if batch.quals is not None:
            q_shift = np.take_along_axis(batch.quals[rows], shift_idx, axis=1)
            trimmed_quals = np.where(in_new, q_shift, 0).astype(np.uint8)
        acc[batch.width].append({
            "codes": trimmed_codes,
            "quals": trimmed_quals,
            "lens": lens[rows],
            "is_rev": out["is_rev"][rows],
            "region_idx": out["ridx"][rows].astype(np.int32),
            "blast_id": out["blast_id"][rows].astype(np.float32),
            "ref_start": out["ref_start"][rows].astype(np.int32),
            "ref_end": out["ref_end"][rows].astype(np.int32),
            "sw_done": (out["sw_done"][rows].astype(bool)
                        if "sw_done" in out
                        else np.ones(len(rows), bool)),
            **{k: out[k][rows].astype(np.int32)
               for k in ("d5", "s5", "e5", "d3", "s3", "e3", "start3")},
        })
        acc_names[batch.width].append(
            [batch.ids[i].partition(" ")[0] for i in rows]
        )

    # Pipelined drive: a prefetch thread builds padded batches, the main
    # thread only dispatches to the device, and a consumer thread does the
    # readback + stats + survivor compaction — [parse/pad] | [device] |
    # [consume] run concurrently. A 2-permit semaphore acquired BEFORE each
    # dispatch and released AFTER each consume bounds live device outputs
    # at two batches — exactly the old double-buffer loop's HBM footprint.
    # On a multi-core TPU VM the dispatch loop therefore never stalls on
    # host-side compaction (VERDICT r2 #1: host work off the critical
    # path); consume order is preserved by the single consumer thread.
    import queue
    import threading

    inflight: queue.Queue = queue.Queue()
    permits = threading.Semaphore(2)
    consumer_err: list[BaseException] = []

    def consumer_loop():
        while True:
            item = inflight.get()
            if item is _PREFETCH_DONE:
                return
            batch, out_dev = item
            try:
                # the blocked-on-device wait lands under assign.dispatch
                # (this thread holds no dispatch frame, so the get records
                # under its own site) — the device half of the dispatch tax
                consume(batch, obs_device.timed_get("assign.dispatch", out_dev))
            except BaseException as exc:
                consumer_err.append(exc)
                return
            finally:
                permits.release()

    def acquire_permit() -> bool:
        """Timed acquire so a dead consumer cannot deadlock the drive."""
        while not permits.acquire(timeout=1.0):
            if consumer_err or not consumer.is_alive():
                return False
        return True

    consumer = threading.Thread(target=consumer_loop, daemon=True)
    consumer.start()
    # held in a name so the finally can CLOSE it: an exception flying out
    # of the loop leaves a for-statement generator open until GC, and its
    # prefetch worker would keep feeding the guard while the retry wrapper
    # is already resetting it
    prefetch_gen = _prefetch(
        _batches_from_source(source, batch_size, widths, subsample,
                             counters=counters, guard=guard),
        depth=prefetch_depth,
    )
    try:
        for batch in prefetch_gen:
            # liveness: one heartbeat per ingest batch — a wedged parser,
            # prefetch worker, or device dispatch stops these, and the
            # stage watchdog (pipeline-level guard) cancels into the
            # transient retry of the whole idempotent pass
            watchdog.heartbeat("assign.batch")
            if not acquire_permit():
                break
            # chaos site: a transient device fault on the fused-pass
            # dispatch (raises out of run_assign; run.py retries the whole
            # idempotent pass under the transient policy)
            robustness_faults.inject("assign.dispatch")
            obs_metrics.counter_add("assign.batches")
            # host-gap half of the dispatch tax: time spent STAGING and
            # dispatching (the async call returns before the device runs);
            # the consumer thread's device_get above owns the blocked half
            with obs_device.dispatch("assign.dispatch", bucket=batch.width):
                if dispatch is not None:
                    # gate params flow from THIS call site for both paths,
                    # so the EE/length filter cannot drift between them
                    out_dev = dispatch(batch, max_ee_rate, min_len)
                else:
                    # overlap_frac arms the SW fast path ONLY when no
                    # blast-id gate runs (round 1): round 2's gate needs
                    # true blast-ids for every read, so it keeps the exact
                    # full-batch SW
                    out_dev = engine.run_batch_async(
                        batch, max_ee_rate, min_len,
                        overlap_frac=(minimal_region_overlap
                                      if blast_id_threshold is None else None),
                    )
            inflight.put((batch, out_dev))
    finally:
        prefetch_gen.close()  # runs _prefetch's finally: stop + join worker
        inflight.put(_PREFETCH_DONE)
        consumer.join()
    if consumer_err:
        raise consumer_err[0]

    blocks = []
    for width in sorted(acc):
        parts = acc[width]
        umi = {
            k: np.concatenate([p[k] for p in parts])
            for k in ("d5", "s5", "e5", "d3", "s3", "e3", "start3")
        }
        blocks.append(ReadBlock(
            width=width,
            codes=np.concatenate([p["codes"] for p in parts]),
            lens=np.concatenate([p["lens"] for p in parts]),
            names=[n for ns in acc_names[width] for n in ns],
            is_rev=np.concatenate([p["is_rev"] for p in parts]),
            region_idx=np.concatenate([p["region_idx"] for p in parts]),
            blast_id=np.concatenate([p["blast_id"] for p in parts]),
            ref_start=np.concatenate([p["ref_start"] for p in parts]),
            ref_end=np.concatenate([p["ref_end"] for p in parts]),
            umi=umi,
            quals=(np.concatenate([p["quals"] for p in parts])
                   if all(p["quals"] is not None for p in parts) else None),
            sw_done=np.concatenate([p["sw_done"] for p in parts]),
        ))
    stats.n_ingested = counters.n_records
    stats.n_bucket_short = counters.n_dropped_short
    stats.n_bucket_long = counters.n_dropped_long
    store = ReadStore(blocks=blocks)
    # stage-boundary conservation contracts (robustness/contracts.py):
    # quarantined records never reach the batcher, so the parsed records
    # minus the bucket drops must be exactly what the device pass counted,
    # the filter categories must partition that total, and the columnar
    # store must hold exactly the passing reads.
    from ont_tcrconsensus_tpu.robustness import contracts

    src_desc = str(source)[:200] if isinstance(source, (str, os.PathLike)) else "<records>"
    contracts.check_equal(
        "ingest", "records parsed minus bucket drops",
        counters.n_records - counters.n_dropped_short - counters.n_dropped_long,
        "reads entering the device pass", stats.n_total,
        detail={"source": src_desc, "ingested": counters.n_records,
                "bucket_short": counters.n_dropped_short,
                "bucket_long": counters.n_dropped_long},
    )
    contracts.check_equal(
        "assign_partition", "filter category sum",
        stats.n_ee_fail + stats.n_unaligned + stats.n_short + stats.n_long
        + stats.n_low_blast + stats.n_pass,
        "batch total", stats.n_total, detail={"source": src_desc},
    )
    contracts.check_equal(
        "assign_store", "columnar store rows", store.num_reads,
        "passing reads", stats.n_pass, detail={"source": src_desc},
    )
    return store, stats
