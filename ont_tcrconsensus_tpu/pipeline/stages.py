"""Pipeline stages: device-batched equivalents of the reference's Ray tasks.

Each function is one stage of the 14-stage reference pipeline
(/root/reference/ont_tcr_consensus/tcr_consensus.py:33-478), operating on
padded device batches instead of "Ray task -> subprocess -> files". Stage
contracts (inputs, filters, artifact layouts) mirror the reference; the
compute underneath is the kernel library (:mod:`..ops`).
"""

from __future__ import annotations

import dataclasses
import os
from collections import defaultdict
from collections.abc import Iterable, Iterator

import numpy as np

from ont_tcrconsensus_tpu.cluster import umi as umi_mod
from ont_tcrconsensus_tpu.io import bucketing, fastx
from ont_tcrconsensus_tpu.ops import consensus as consensus_mod
from ont_tcrconsensus_tpu.ops import ee_filter, encode, fuzzy_match, sketch, sw_pallas

# ---------------------------------------------------------------------------
# reference panel


@dataclasses.dataclass
class ReferencePanel:
    """Encoded reference regions + sketch profiles, built once per run."""

    names: list[str]
    seqs: dict[str, str]
    codes: np.ndarray          # (R, W) uint8
    lens: np.ndarray           # (R,) int32
    profiles: np.ndarray       # (R, dim) float32
    region_cluster: dict[str, int]

    @classmethod
    def build(cls, reference: dict[str, str], region_cluster: dict[str, int],
              pad_multiple: int = 128) -> "ReferencePanel":
        names = list(reference)
        max_len = max(len(s) for s in reference.values())
        codes, lens = encode.encode_batch([reference[n] for n in names], pad_to=max_len,
                                          multiple=pad_multiple)
        profiles = np.asarray(sketch.kmer_profile(codes, lens))
        return cls(names=names, seqs=dict(reference), codes=codes, lens=lens,
                   profiles=profiles, region_cluster=dict(region_cluster))

    def region_len(self, idx: int) -> int:
        return int(self.lens[idx])


# ---------------------------------------------------------------------------
# stage: expected-error filtering (vsearch --fastq_filter equivalent,
# preprocessing.py:104-159)


def ee_filter_stage(
    records: Iterable[fastx.FastxRecord],
    max_ee_rate: float,
    min_len: int,
    batch_size: int = 2048,
    max_read_length: int = 4096,
    subsample: int | None = None,
) -> Iterator[fastx.FastxRecord]:
    """Stream records through the device EE filter; yields survivors.

    ``subsample`` mirrors ``dorado trim --max-reads`` head-subsampling
    (preprocessing.py:41-57): only the first N records are considered.
    """
    taken = 0

    def limited():
        nonlocal taken
        for rec in records:
            if subsample is not None and taken >= subsample:
                return
            taken += 1
            yield rec

    for batch in bucketing.batch_reads(
        limited(), batch_size=batch_size,
        widths=tuple(w for w in bucketing.DEFAULT_WIDTHS if w <= max_read_length),
        min_len=1,
    ):
        keep = np.asarray(
            ee_filter.ee_rate_mask(batch.quals, batch.lengths, max_ee_rate, min_len)
        ).copy()
        keep &= batch.valid
        kept_ids = set(np.where(keep)[0].tolist())
        for i in sorted(kept_ids):
            name, _, comment = batch.ids[i].partition(" ")
            seq = encode.decode_seq(batch.codes[i], int(batch.lengths[i]))
            qual = "".join(chr(33 + q) for q in batch.quals[i, : batch.lengths[i]])
            yield fastx.FastxRecord(name, comment, seq, qual)


# ---------------------------------------------------------------------------
# stage: alignment + region assignment (minimap2_ont_align +
# filter_and_split_reads_by_region_cluster, minimap2_align.py:76-155 +
# region_split.py:219-333)


@dataclasses.dataclass
class AlignedRead:
    name: str
    seq: str               # original orientation, as sequenced
    strand: str            # '+' or '-'
    region_idx: int
    blast_id: float
    ref_start: int
    ref_end: int
    read_start: int        # in aligned (oriented) coordinates
    read_end: int
    score: int


@dataclasses.dataclass
class AlignStats:
    n_total: int = 0
    n_aligned: int = 0     # primary-mapped equivalents
    n_short: int = 0
    n_long: int = 0
    n_pass: int = 0


def assign_reads(
    records: Iterable[fastx.FastxRecord],
    panel: ReferencePanel,
    minimal_region_overlap: float,
    max_softclip_5_end: int,
    max_softclip_3_end: int,
    batch_size: int = 1024,
    top_k: int = 2,
    band_width: int = 256,
    min_score: int = 100,
    max_read_length: int = 4096,
    blast_id_threshold: float | None = None,
    collect_qc: list | None = None,
) -> tuple[list[AlignedRead], AlignStats]:
    """Align every read to its best reference region; apply region filters.

    A read's "primary alignment" is the best banded-SW score over the
    ``top_k`` sketch candidates on the detected strand. Filters mirror
    region_split.py:261-269 (ref overlap, read-length window) and — when
    ``blast_id_threshold`` is given (round 2) — minimap2_align.py:209-245.
    """
    stats = AlignStats()
    out: list[AlignedRead] = []
    widths = tuple(w for w in bucketing.DEFAULT_WIDTHS if w <= max_read_length)
    for batch in bucketing.batch_reads(
        records, batch_size=batch_size, widths=widths, with_quals=False, min_len=1
    ):
        nv = batch.num_valid
        stats.n_total += nv
        codes = batch.codes[:nv]
        lens = batch.lengths[:nv]
        cand_idx, _, is_rev = sketch.candidates_both_strands(
            codes, lens, panel.profiles, top_k=top_k
        )
        cand_idx = np.asarray(cand_idx)
        is_rev = np.asarray(is_rev)
        # orient reads for alignment
        oriented = np.asarray(sketch.revcomp_batch(codes, lens))
        oriented = np.where(is_rev[:, None], oriented, codes)
        # align against each candidate; keep the best score
        best = None
        for c in range(top_k):
            ridx = cand_idx[:, c]
            offs = sketch.diag_offset(lens, panel.lens[ridx]).astype(np.int32)
            res = sw_pallas.align_banded_auto(
                oriented, lens, panel.codes[ridx], panel.lens[ridx], offs,
                band_width=band_width,
            )
            res_np = {
                "score": np.asarray(res.score), "ridx": ridx,
                "ref_start": np.asarray(res.ref_start), "ref_end": np.asarray(res.ref_end),
                "read_start": np.asarray(res.read_start), "read_end": np.asarray(res.read_end),
                "blast_id": np.asarray(res.blast_id),
            }
            if best is None:
                best = res_np
            else:
                better = res_np["score"] > best["score"]
                for k in best:
                    best[k] = np.where(better, res_np[k], best[k])
        for i in range(nv):
            if best["score"][i] < min_score:
                continue
            stats.n_aligned += 1
            ridx = int(best["ridx"][i])
            rlen = panel.region_len(ridx)
            ref_span = int(best["ref_end"][i]) - int(best["ref_start"][i])
            qc = {
                "name": batch.ids[i].partition(" ")[0],
                "region": panel.names[ridx],
                "ref_span": ref_span,
                "read_len": int(lens[i]),
                "region_len": rlen,
                "blast_id": float(best["blast_id"][i]),
            }
            if ref_span < rlen * minimal_region_overlap:
                stats.n_short += 1
                if collect_qc is not None:
                    qc["status"] = "short"
                    qc["nt_short"] = rlen * minimal_region_overlap - ref_span
                    collect_qc.append(qc)
                continue
            max_len = rlen * (2 - minimal_region_overlap) + (
                max_softclip_5_end + max_softclip_3_end
            )
            if int(lens[i]) > max_len:
                stats.n_long += 1
                if collect_qc is not None:
                    qc["status"] = "long"
                    qc["nt_long"] = int(lens[i]) - max_len
                    collect_qc.append(qc)
                continue
            if blast_id_threshold is not None and not (
                float(best["blast_id"][i]) > blast_id_threshold
            ):
                if collect_qc is not None:
                    qc["status"] = "low_blast_id"
                    collect_qc.append(qc)
                continue
            stats.n_pass += 1
            if collect_qc is not None:
                qc["status"] = "pass"
                collect_qc.append(qc)
            name, _, _ = batch.ids[i].partition(" ")
            out.append(AlignedRead(
                name=name,
                seq=encode.decode_seq(codes[i], int(lens[i])),
                strand="-" if is_rev[i] else "+",
                region_idx=ridx,
                blast_id=float(best["blast_id"][i]),
                ref_start=int(best["ref_start"][i]),
                ref_end=int(best["ref_end"][i]),
                read_start=int(best["read_start"][i]),
                read_end=int(best["read_end"][i]),
                score=int(best["score"][i]),
            ))
    return out, stats


def split_by_region_cluster(
    aligned: list[AlignedRead], panel: ReferencePanel
) -> dict[int, list[AlignedRead]]:
    """Round-1 grouping: reads binned per region *cluster*
    (region_split.py:271-280)."""
    groups: dict[int, list[AlignedRead]] = defaultdict(list)
    for r in aligned:
        cluster = panel.region_cluster[panel.names[r.region_idx]]
        groups[cluster].append(r)
    return dict(groups)


def split_by_region(
    aligned: list[AlignedRead], panel: ReferencePanel
) -> dict[str, list[AlignedRead]]:
    """Round-2 grouping: per exact region (region_split.py:336-435)."""
    groups: dict[str, list[AlignedRead]] = defaultdict(list)
    for r in aligned:
        groups[panel.names[r.region_idx]].append(r)
    return dict(groups)


def write_region_fastas(
    groups: dict, out_dir: str, prefix: str
) -> dict[str, str]:
    """Write per-group fastas in the reference's format: original-orientation
    sequence, header ``<name>;strand=<+/->`` (region_split.py:273-280)."""
    paths = {}
    for key, reads in sorted(groups.items(), key=lambda kv: str(kv[0])):
        fname = f"{prefix}{key}.fasta"
        path = os.path.join(out_dir, fname)
        fastx.write_fasta(
            path, ((f"{r.name};strand={r.strand}", r.seq) for r in reads)
        )
        paths[str(key)] = path
    return paths


# ---------------------------------------------------------------------------
# stage: UMI extraction (extract_umis.py:189-267)


@dataclasses.dataclass
class UmiRecord:
    name: str
    strand: str
    umi_fwd_dist: int
    umi_rev_dist: int
    umi_fwd_seq: str
    umi_rev_seq: str
    combined: str          # canonical (molecule) orientation
    seq: str               # full read, original orientation

    def header(self) -> str:
        """7-field header parity (extract_umis.py:174-181)."""
        return (
            f"{self.name};strand={self.strand};umi_fwd_dist={self.umi_fwd_dist};"
            f"umi_rev_dist={self.umi_rev_dist};umi_fwd_seq={self.umi_fwd_seq};"
            f"umi_rev_seq={self.umi_rev_seq};seq={self.seq}"
        )


def extract_umis_stage(
    reads: list[tuple[str, str, str]],
    umi_fwd: str,
    umi_rev: str,
    max_pattern_dist: int,
    adapter_length_5_end: int,
    adapter_length_3_end: int,
    batch_size: int = 4096,
) -> list[UmiRecord]:
    """Find both degenerate UMIs in each read's adapter windows.

    Args:
      reads: (name, seq_original_orientation, strand) triples.

    The 5' window is searched with ``umi_fwd`` and the 3' window with
    ``umi_rev`` regardless of strand — the two patterns are reverse
    complements of each other, so '-' reads match symmetrically
    (extract_umis.py:221-245). The combined UMI is canonicalized:
    '+' -> fwd+rev, '-' -> revcomp(rev)+revcomp(fwd)
    (combine_umis_fasta, extract_umis.py:140-151).
    """
    fwd_mask = encode.encode_mask(umi_fwd)
    rev_mask = encode.encode_mask(umi_rev)
    out: list[UmiRecord] = []
    win_pad = max(adapter_length_5_end, adapter_length_3_end)

    for start in range(0, len(reads), batch_size):
        chunk = reads[start : start + batch_size]
        win5 = [seq[:adapter_length_5_end] for _, seq, _ in chunk]
        win3 = [seq[-adapter_length_3_end:] for _, seq, _ in chunk]
        # pad the final chunk to the full batch size (static shapes)
        n_pad = batch_size - len(chunk)
        if n_pad:
            win5 += [""] * n_pad
            win3 += [""] * n_pad
        w5, l5 = encode.encode_mask_batch(win5, pad_to=win_pad)
        w3, l3 = encode.encode_mask_batch(win3, pad_to=win_pad)
        d5, s5, e5 = (np.asarray(x) for x in fuzzy_match.fuzzy_find(fwd_mask, w5, l5))
        d3, s3, e3 = (np.asarray(x) for x in fuzzy_match.fuzzy_find(rev_mask, w3, l3))
        for i, (name, seq, strand) in enumerate(chunk):
            if d5[i] > max_pattern_dist or d3[i] > max_pattern_dist:
                continue
            u5 = win5[i][s5[i] : e5[i]]
            u3 = win3[i][s3[i] : e3[i]]
            if not u5 or not u3:
                continue
            if strand == "+":
                combined = u5 + u3
            else:
                combined = encode.revcomp_str(u3) + encode.revcomp_str(u5)
            out.append(UmiRecord(
                name=name, strand=strand,
                umi_fwd_dist=int(d5[i]), umi_rev_dist=int(d3[i]),
                umi_fwd_seq=u5, umi_rev_seq=u3,
                combined=combined, seq=seq,
            ))
    return out


def write_umi_fasta(records: list[UmiRecord], path: str) -> int:
    """The 'UMI fasta': combined UMI as sequence, full read smuggled in the
    header (extract_umis.py:154-186)."""
    return fastx.write_fasta(path, ((r.header(), r.combined) for r in records))


# ---------------------------------------------------------------------------
# stage: UMI clustering + subread selection (vsearch_umi_cluster.py +
# parse_umi_clusters.py)


@dataclasses.dataclass
class SelectedCluster:
    cluster_id: int
    members: list[UmiRecord]       # the selected subreads (<= max)
    n_fwd: int
    n_rev: int
    written_fwd: int
    written_rev: int
    n_found: int


def cluster_and_select(
    umi_records: list[UmiRecord],
    identity: float,
    min_umi_length: int,
    max_umi_length: int,
    min_reads_per_cluster: int,
    max_reads_per_cluster: int,
    balance_strands: bool,
) -> tuple[list[SelectedCluster], list[dict]]:
    """Cluster combined UMIs, then select subreads per cluster.

    Length bounds replicate vsearch --minseqlength/--maxseqlength (records
    outside are dropped before clustering, vsearch_umi_cluster.py:29-33).
    Selection replicates polish_cluster's strand math exactly
    (parse_umi_clusters.py:67-116): first-come member order, minority strand
    capped at max/2, optional balancing.

    Returns (selected clusters, per-cluster stats rows — including skipped
    clusters, for the stats TSV parity).
    """
    eligible = [r for r in umi_records if min_umi_length <= len(r.combined) <= max_umi_length]
    if not eligible:
        return [], []
    clusters = umi_mod.cluster_umis([r.combined for r in eligible], identity)
    members: dict[int, list[UmiRecord]] = defaultdict(list)
    for rec, lab in zip(eligible, clusters.labels):
        members[int(lab)].append(rec)

    selected: list[SelectedCluster] = []
    stat_rows: list[dict] = []
    for cid in sorted(members):
        mem = members[cid]
        fwd = [m for m in mem if m.strand == "+"]
        rev = [m for m in mem if m.strand == "-"]
        n_fwd, n_rev = len(fwd), len(rev)
        if balance_strands:
            min_fwd = min_rev = min_reads_per_cluster // 2
            max_after = min(n_fwd * 2, n_rev * 2, max_reads_per_cluster)
            max_fwd = max_rev = max_after // 2
        else:
            min_fwd = min_rev = 0
            if n_fwd > n_rev:
                max_rev = min(n_rev, max_reads_per_cluster // 2)
                max_fwd = min(max_reads_per_cluster - max_rev, n_fwd)
            else:
                max_fwd = min(n_fwd, max_reads_per_cluster // 2)
                max_rev = min(max_reads_per_cluster - max_fwd, n_rev)
        n_reads = max_fwd + max_rev
        take = (
            n_fwd >= min_fwd and n_rev >= min_rev and n_reads >= min_reads_per_cluster
        )
        chosen = (fwd[:max_fwd] + rev[:max_rev])[:max_reads_per_cluster] if take else []
        row = {
            "id_cluster": f"cluster{cid}",
            "n_fwd": n_fwd, "n_rev": n_rev,
            "written_fwd": len([m for m in chosen if m.strand == "+"]),
            "written_rev": len([m for m in chosen if m.strand == "-"]),
            "n": len(mem), "written": len(chosen),
            "cluster_written": int(bool(chosen)),
        }
        stat_rows.append(row)
        if chosen:
            selected.append(SelectedCluster(
                cluster_id=cid, members=chosen,
                n_fwd=n_fwd, n_rev=n_rev,
                written_fwd=row["written_fwd"], written_rev=row["written_rev"],
                n_found=len(mem),
            ))
    return selected, stat_rows


def write_cluster_stats_tsv(stat_rows: list[dict], path: str) -> None:
    """vsearch_cluster_stats.tsv parity (parse_umi_clusters.py:183-195)."""
    cols = ["id_cluster", "n_fwd", "n_rev", "written_fwd", "written_rev",
            "n", "written", "cluster_written"]
    with open(path, "w") as fh:
        fh.write("\t".join(cols) + "\n")
        for row in stat_rows:
            fh.write("\t".join(str(row[c]) for c in cols) + "\n")


# ---------------------------------------------------------------------------
# stage: consensus polishing (medaka smolecule replacement)


def polish_clusters_stage(
    selected: list[SelectedCluster],
    group_name: str,
    max_read_length: int = 4096,
    rounds: int = 4,
    band_width: int = 128,
    polisher=None,
    cluster_batch: int = 16,
) -> list[tuple[str, str]]:
    """Consensus per selected cluster; returns (header, sequence) pairs.

    Headers follow the reference's rewrite
    ``<group>_<clusterN>_<n_subreads>`` (medaka_polish.py:146-180).
    Subreads enter in canonical (+) orientation — strand is known from
    alignment, so no internal re-orientation pass is needed.

    Static-shape discipline: clusters are grouped by (subread-count bucket,
    width bucket) and processed in batches of ``cluster_batch`` through one
    device dispatch per round (``consensus_clusters_batch``), so XLA
    compiles one kernel per shape bucket instead of one per cluster.
    Padding rows have length 0: they score 0 and cast no votes.
    """
    prepared: dict[tuple[int, int], list[tuple[SelectedCluster, np.ndarray, np.ndarray]]] = (
        defaultdict(list)
    )
    for cl in selected:
        seqs = [
            m.seq if m.strand == "+" else encode.revcomp_str(m.seq)
            for m in cl.members
        ]
        # one lane-width of growth slack above the longest subread
        need = max(len(s) for s in seqs) + 128
        width = min(
            max_read_length,
            next((w for w in bucketing.DEFAULT_WIDTHS if w >= need), max_read_length),
        )
        codes, lens = encode.encode_batch(seqs, pad_to=width, multiple=128)
        s_bucket = 1
        while s_bucket < len(seqs):
            s_bucket *= 2
        if s_bucket > len(seqs):
            pad_rows = s_bucket - len(seqs)
            codes = np.concatenate(
                [codes, np.full((pad_rows, codes.shape[1]), encode.PAD_CODE, np.uint8)]
            )
            lens = np.concatenate([lens, np.zeros(pad_rows, lens.dtype)])
        prepared[(s_bucket, codes.shape[1])].append((cl, codes, lens))

    out: list[tuple[str, str]] = []
    for (s_bucket, width), items in sorted(prepared.items()):
        for start in range(0, len(items), cluster_batch):
            chunk = items[start : start + cluster_batch]
            C = len(chunk)
            sub = np.stack([codes for _, codes, _ in chunk])
            lens = np.stack([ln for _, _, ln in chunk])
            if C < cluster_batch:  # pad the cluster axis: stable compile shapes
                pad = cluster_batch - C
                sub = np.concatenate(
                    [sub, np.full((pad, s_bucket, width), encode.PAD_CODE, np.uint8)]
                )
                lens = np.concatenate([lens, np.zeros((pad, s_bucket), lens.dtype)])
            drafts, dlens = consensus_mod.consensus_clusters_batch(
                sub, lens, rounds=rounds, band_width=band_width
            )
            for c in range(C):
                cl = chunk[c][0]
                cons, clen = drafts[c], int(dlens[c])
                if polisher is not None:
                    cons, clen = polisher(sub[c], lens[c], cons, clen)
                seq = encode.decode_seq(cons, clen)
                out.append(
                    (f"{group_name}_cluster{cl.cluster_id}_{len(cl.members)}", seq)
                )
    out.sort(key=lambda kv: int(kv[0].rsplit("_cluster", 1)[1].split("_")[0]))
    return out


# ---------------------------------------------------------------------------
# stage: counting (count.py)


def write_counts_csv(region_counts: dict[str, int], counts_dir: str,
                     region_name: str = "TCR") -> str:
    """counts/umi_consensus_counts.csv parity (count.py:39-51)."""
    path = os.path.join(counts_dir, "umi_consensus_counts.csv")
    with open(path, "w") as fh:
        fh.write(f"{region_name},Count\n")
        for region, count in region_counts.items():
            fh.write(f"{region},{count}\n")
    return path
