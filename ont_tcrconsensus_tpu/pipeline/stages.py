"""Pipeline stages over the columnar read store.

Each function is one stage of the 14-stage reference pipeline
(/root/reference/ont_tcr_consensus/tcr_consensus.py:33-478). The read-level
hot path (trim/filter/align/UMI-locate) is the fused device pass in
:mod:`.assign`; this module holds the host-side stages that operate on its
columnar survivors: grouping, UMI record assembly, clustering + subread
selection, batched consensus polish, and counting. Strings materialize only
at artifact boundaries.
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import defaultdict
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ont_tcrconsensus_tpu.cluster import umi as umi_mod
from ont_tcrconsensus_tpu.io import bucketing, fastx
from ont_tcrconsensus_tpu.obs import device as obs_device
from ont_tcrconsensus_tpu.obs import metrics as obs_metrics
from ont_tcrconsensus_tpu.ops import consensus as consensus_mod
from ont_tcrconsensus_tpu.ops import encode
from ont_tcrconsensus_tpu.robustness import contracts, faults, retry, watchdog
from ont_tcrconsensus_tpu.pipeline.assign import (  # noqa: F401  (re-exported)
    AlignStats,
    AssignEngine,
    ReadStore,
    ReferencePanel,
    run_assign,
)

# ---------------------------------------------------------------------------
# stage: UMI record assembly (extract_umis.py:189-267)


@dataclasses.dataclass
class UmiRecord:
    """One read's extracted UMI pair + a (block, row) handle into the store."""

    name: str
    strand: str
    umi_fwd_dist: int
    umi_rev_dist: int
    umi_fwd_seq: str
    umi_rev_seq: str
    combined: str          # canonical (molecule) orientation
    block: int
    row: int

    def header(self, store: ReadStore) -> str:
        """7-field header parity (extract_umis.py:174-181); the full read is
        smuggled in ``seq=`` exactly like the reference's UMI fasta."""
        seq = store.blocks[self.block].decode_one(self.row)
        return (
            f"{self.name};strand={self.strand};umi_fwd_dist={self.umi_fwd_dist};"
            f"umi_rev_dist={self.umi_rev_dist};umi_fwd_seq={self.umi_fwd_seq};"
            f"umi_rev_seq={self.umi_rev_seq};seq={seq}"
        )


def build_umi_records(
    store: ReadStore,
    parts: list[tuple[int, np.ndarray]],
    max_pattern_dist: int,
) -> list[UmiRecord]:
    """Assemble UMI records for one read group from the fused-pass fields.

    The 5' window was searched with ``umi_fwd`` and the 3' window with
    ``umi_rev`` regardless of strand — the patterns are reverse complements,
    so '-' reads match symmetrically (extract_umis.py:221-245). Combined UMI
    canonicalization: '+' -> fwd+rev, '-' -> revcomp(rev)+revcomp(fwd)
    (extract_umis.py:140-151). Reads where either pattern exceeds
    ``max_pattern_dist`` are dropped, mirroring the edlib k gate.
    """
    out: list[UmiRecord] = []
    for bi, rows in parts:
        blk = store.blocks[bi]
        u = blk.umi
        ok = (u["d5"][rows] <= max_pattern_dist) & (u["d3"][rows] <= max_pattern_dist)
        ok &= (u["e5"][rows] > u["s5"][rows]) & (u["e3"][rows] > u["s3"][rows])
        ascii_rows = encode._DECODE_ASCII[blk.codes[rows]]
        for k, r in enumerate(rows):
            if not ok[k]:
                continue
            s5, e5 = int(u["s5"][r]), int(u["e5"][r])
            a3 = int(u["start3"][r])
            s3, e3 = a3 + int(u["s3"][r]), a3 + int(u["e3"][r])
            u5 = ascii_rows[k, s5:e5].tobytes().decode("ascii")
            u3 = ascii_rows[k, s3:e3].tobytes().decode("ascii")
            strand = "-" if blk.is_rev[r] else "+"
            if strand == "+":
                combined = u5 + u3
            else:
                combined = encode.revcomp_str(u3) + encode.revcomp_str(u5)
            out.append(UmiRecord(
                name=blk.names[r], strand=strand,
                umi_fwd_dist=int(u["d5"][r]), umi_rev_dist=int(u["d3"][r]),
                umi_fwd_seq=u5, umi_rev_seq=u3,
                combined=combined, block=bi, row=int(r),
            ))
    return out


def write_umi_fasta(records: list[UmiRecord], store: ReadStore, path: str) -> int:
    """The 'UMI fasta': combined UMI as sequence, full read smuggled in the
    header (extract_umis.py:154-186)."""
    return fastx.write_fasta(
        path, ((r.header(store), r.combined) for r in records)
    )


# ---------------------------------------------------------------------------
# stage: region grouping + per-group fasta artifacts (region_split.py)


def group_by_region_cluster(store: ReadStore, panel: ReferencePanel):
    """Round-1 grouping: reads binned per region *cluster*
    (region_split.py:271-280). Returns {cluster_id: [(block, rows)]}."""
    return store.group_rows_by(panel.cluster_of_region)


def group_by_region(store: ReadStore, panel: ReferencePanel):
    """Round-2 grouping: per exact region (region_split.py:336-435).
    Returns {region_name: [(block, rows)]}."""
    idx_groups = store.group_rows_by(np.arange(len(panel.names), dtype=np.int32))
    return {panel.names[k]: v for k, v in idx_groups.items()}


def write_region_fastas(
    groups: dict, store: ReadStore, out_dir: str, prefix: str
) -> dict[str, str]:
    """Per-group fastas in the reference's format: original-orientation
    sequence, header ``<name>;strand=<+/->`` (region_split.py:273-280)."""
    paths = {}
    for key, parts in sorted(groups.items(), key=lambda kv: str(kv[0])):
        path = os.path.join(out_dir, f"{prefix}{key}.fasta")

        def rows_iter(parts=parts):
            for bi, rows in parts:
                blk = store.blocks[bi]
                seqs = blk.decode(rows)
                for k, r in enumerate(rows):
                    strand = "-" if blk.is_rev[r] else "+"
                    yield f"{blk.names[r]};strand={strand}", seqs[k]

        fastx.write_fasta(path, rows_iter())
        paths[str(key)] = path
    return paths


# ---------------------------------------------------------------------------
# stage: UMI clustering + subread selection (vsearch_umi_cluster.py +
# parse_umi_clusters.py)


@dataclasses.dataclass
class SelectedCluster:
    cluster_id: int
    members: list[UmiRecord]       # the selected subreads (<= max)
    n_fwd: int
    n_rev: int
    written_fwd: int
    written_rev: int
    n_found: int


def cluster_and_select(
    umi_records: list[UmiRecord],
    identity: float,
    min_umi_length: int,
    max_umi_length: int,
    min_reads_per_cluster: int,
    max_reads_per_cluster: int,
    balance_strands: bool,
    mesh=None,
) -> tuple[list[SelectedCluster], list[dict]]:
    """Cluster combined UMIs, then select subreads per cluster.

    Length bounds replicate vsearch --minseqlength/--maxseqlength (records
    outside are dropped before clustering, vsearch_umi_cluster.py:29-33).
    Selection replicates polish_cluster's strand math exactly
    (parse_umi_clusters.py:67-116): first-come member order, minority strand
    capped at max/2, optional balancing.

    Returns (selected clusters, per-cluster stats rows — including skipped
    clusters, for the stats TSV parity).
    """
    eligible = [r for r in umi_records if min_umi_length <= len(r.combined) <= max_umi_length]
    if not eligible:
        return [], []
    clusters = umi_mod.cluster_umis(
        [r.combined for r in eligible], identity, mesh=mesh
    )
    selected, stat_rows = _select_from_clusters(
        eligible, clusters,
        min_reads_per_cluster=min_reads_per_cluster,
        max_reads_per_cluster=max_reads_per_cluster,
        balance_strands=balance_strands,
        identity=identity, mesh=mesh,
    )
    # UMI conservation across the r5 rescue merge: the post-rescue cluster
    # stats must still partition the eligible records exactly
    contracts.check_equal(
        "umi", "cluster-stats member total", sum(r["n"] for r in stat_rows),
        "eligible UMI records", len(eligible),
    )
    return selected, stat_rows


def cluster_and_select_grouped(
    named_records: list[tuple[str, list[UmiRecord]]],
    identity: float,
    min_umi_length: int,
    max_umi_length: int,
    min_reads_per_cluster: int,
    max_reads_per_cluster: int,
    balance_strands: bool,
    mesh=None,
) -> dict[str, tuple[list[SelectedCluster], list[dict]]]:
    """:func:`cluster_and_select` over MANY groups with batched dispatches.

    The reference runs vsearch once per region cluster / region
    (vsearch_umi_cluster.py called per group); clustering here instead
    batches every group through ONE global device pass
    (:func:`..cluster.umi.cluster_umis_grouped` — cross-group identities
    masked, so results are per-group exact) and runs the subread selection
    host-side per group. Returns {group_name: (selected, stat_rows)}.
    """
    eligibles = [
        (name, [
            r for r in records
            if min_umi_length <= len(r.combined) <= max_umi_length
        ])
        for name, records in named_records
    ]
    groups = [[r.combined for r in recs] for _, recs in eligibles]
    watchdog.heartbeat("cluster.batched_dispatch")
    obs_metrics.counter_add("cluster.batched")
    # one dispatch scope around the whole batched clustering pass: its
    # device waits (the distance-matrix gets inside cluster/umi.py) are
    # credited here, the remainder is the pass's host gap
    with obs_device.dispatch("cluster.batched_dispatch"):
        clusters_list = umi_mod.cluster_umis_grouped(groups, identity, mesh=mesh)
    out: dict[str, tuple[list[SelectedCluster], list[dict]]] = {}
    # first selection pass (host-only), collecting the rescue work so the
    # second-chance device half runs ONCE across all groups (code-review
    # r5: per-group rescue dispatches would reintroduce the latency tax
    # this grouped driver exists to remove)
    rescue_work: list[tuple] = []
    first_pass: dict[str, tuple] = {}
    for (name, recs), clusters in zip(eligibles, clusters_list):
        if not recs:
            out[name] = ([], [])
            continue
        members = _group_members(recs, clusters.labels)
        selected, stat_rows, taken = _run_selection(
            members, min_reads_per_cluster, max_reads_per_cluster,
            balance_strands,
        )
        first_pass[name] = (recs, clusters, selected, stat_rows)
        if min_reads_per_cluster > 1:
            rescue_work.append((name, recs, clusters, members, taken))
    roots_by = (
        _rescue_grouped(rescue_work, identity, mesh=mesh)
        if rescue_work else {}
    )
    for name, (recs, clusters, selected, stat_rows) in first_pass.items():
        watchdog.heartbeat("cluster.group_select")
        roots = roots_by.get(name)
        if roots is not None:
            selected, stat_rows, _ = _run_selection(
                _group_members(recs, clusters.labels, roots),
                min_reads_per_cluster, max_reads_per_cluster,
                balance_strands,
            )
        # UMI conservation across the batched r5 rescue merge (contracts):
        # rescue relabels clusters but must never create or lose members
        contracts.check_equal(
            "umi", "cluster-stats member total",
            sum(r["n"] for r in stat_rows),
            "eligible UMI records", len(recs), detail={"group": name},
        )
        out[name] = (selected, stat_rows)
    return out


#: relaxed dovetail free-end budget for the second-chance UMI pass — one
#: notch above the clustering default (ops/edit_distance k_end=8): enough to
#: forgive deeper extraction-boundary erosion, far too small to bridge
#: distinct molecules (~0.6 identity on random 64 nt UMIs).
RESCUE_K_END = 16


def _rescue_identities(codes, lens, sub_global, gid, rescue_k_end, mesh=None):
    """(n_sub, K+1) candidate centroid indices + relaxed-end identities.

    Shared device half of the rescue pass: k-mer shortlist over ALL
    centroid rows, then exact dovetail distances with ``rescue_k_end``
    free ends on the flattened pair list (pow2-padded for stable compile
    shapes). Self entries — and, when ``gid`` is given, cross-group
    entries — are forced to identity -1 so they never form edges. The
    shortlist needs no group-awareness: same-molecule variants always
    outrank random cross-group UMIs in k-mer dot product (the same
    argument as cluster_umis_grouped).
    """
    from ont_tcrconsensus_tpu.ops import edit_distance, sketch

    # pow2-pad BOTH axes so the jitted profile/top_k kernels compile once
    # per size class, not once per centroid count (code-review r5 — the
    # same discipline as cluster.umi._neighbor_identities); padded target
    # rows are zero-length (ident forced -1 below via longest==0), padded
    # query rows repeat row 0 and are sliced off before the merge.
    n_all = codes.shape[0]
    n_pad = bucketing.pow2_ceil(n_all, 16)
    if n_pad > n_all:
        codes = np.concatenate(
            [codes, np.zeros((n_pad - n_all, codes.shape[1]), codes.dtype)]
        )
        lens = np.concatenate([lens, np.zeros(n_pad - n_all, lens.dtype)])
    n_sub = len(sub_global)
    q_pad = bucketing.pow2_ceil(n_sub, 16)
    sub_q = np.concatenate(
        [sub_global, np.zeros(q_pad - n_sub, np.int32)]
    ) if q_pad > n_sub else np.asarray(sub_global, np.int32)
    # k=4 exact (dim=None) profiles: the UMI-scale shortlist the clustering
    # pass uses — the read-scale hashed default is the wrong instrument for
    # 64 nt UMIs
    profiles = np.asarray(sketch.kmer_profile(codes, lens, k=4, dim=None))
    K = min(8, n_all - 1)
    cand = np.asarray(
        sketch.top_candidates(profiles[sub_q], profiles, K + 1)
    )[:n_sub]  # (n_sub, K+1) — may include self / padded rows
    qi = np.repeat(sub_global, K + 1)
    ti = cand.reshape(-1).astype(np.int32)
    n_pairs = len(qi)
    n_padded = bucketing.pow2_ceil(n_pairs)
    if n_padded > n_pairs:
        pad = n_padded - n_pairs
        qi = np.concatenate([qi, np.zeros(pad, np.int32)])
        ti = np.concatenate([ti, np.zeros(pad, np.int32)])
    d = np.asarray(edit_distance.pairwise_dovetail_auto(
        codes[qi], lens[qi], codes[ti], lens[ti],
        k_end=rescue_k_end, mesh=mesh,
    )).astype(np.float32)[:n_pairs]
    longest = np.maximum(lens[qi[:n_pairs]], lens[ti[:n_pairs]]).astype(np.float32)
    ident = np.where(longest > 0, 1.0 - d / np.maximum(longest, 1.0), -1.0)
    ident = ident.reshape(len(sub_global), K + 1)
    ident[cand == np.asarray(sub_global)[:, None]] = -1.0  # never self-merge
    padded_target = cand >= n_all  # zero-profile padding rows: never edges
    ident[padded_target] = -1.0
    if gid is not None:
        safe_cand = np.where(padded_target, 0, cand)
        ident[gid[safe_cand] != gid[sub_global][:, None]] = -1.0
        ident[padded_target] = -1.0
    return cand, ident


def _rescue_merge_roots(subs, n_c, cand_local, ident, identity, taken):
    """Host half: single-best-edge union-find over one group's clusters.

    ``cand_local`` rows are group-local centroid indices aligned with
    ``subs``; entries with ident -1 (self/cross-group/padding) never win.
    Each merged component is labeled by its SURVIVING cluster's id when one
    exists (fragments joining survivor 5 emit as cluster 5 — a surviving
    cluster's header/stat-row id must never churn because a fragment
    rescued into it; code-review r5), else by its smallest fragment id.
    Returns {cluster_id: root_id} or None when nothing merged.
    """
    parent = np.arange(n_c)

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    merged = False
    for row, cid in enumerate(subs):
        ok = ident[row] >= identity
        if not ok.any():
            continue
        # single best edge: highest identity, ties -> smaller cluster id
        best_ident = ident[row][ok].max()
        best = int(cand_local[row][ok & (ident[row] >= best_ident)].min())
        a, b = find(cid), find(best)
        if a != b:
            parent[max(a, b)] = min(a, b)
            merged = True
    if not merged:
        return None
    # component label: the survivor if present (at most one — survivors
    # carry no out-edges, so two can never connect), else min fragment id
    comp_members: dict[int, list[int]] = defaultdict(list)
    for c in range(n_c):
        comp_members[find(c)].append(c)
    label: dict[int, int] = {}
    for root, cs in comp_members.items():
        surv = [c for c in cs if c in taken]
        label[root] = surv[0] if surv else min(cs)
    return {c: label[find(c)] for c in range(n_c)}


def _rescue_subs(members: dict, taken: set) -> list[int]:
    return [cid for cid in sorted(members) if cid not in taken and members[cid]]


def _rescue_subthreshold(
    eligible: list[UmiRecord],
    clusters,
    members: dict[int, list[UmiRecord]],
    taken: set[int],
    identity: float,
    rescue_k_end: int = RESCUE_K_END,
    mesh=None,
) -> dict[int, int] | None:
    """Second-chance pass for clusters that failed min_reads_per_cluster.

    The lane-scale loss chain (LANE_SCALE_R4.md): a molecule's reads can
    split 2+1+1 across UMI clusters when extraction-boundary erosion
    exceeds the dovetail free-end budget, and every fragment then falls
    below ``min_reads_per_cluster`` — the molecule vanishes (undercount).
    vsearch has no such pass; the reference simply loses these molecules
    too, but the counts contract here is bit-exactness against ground
    truth, so the split is healed deterministically (DIVERGENCES.md #11):

    - each sub-threshold cluster's CENTROID UMI is re-scored against the
      other cluster centroids (k-mer shortlist, then exact dovetail with
      ``rescue_k_end`` free ends — one notch above the clustering pass);
    - it merges into its single best match at >= the SAME identity
      threshold (ties: smaller cluster id). One out-edge per sub-threshold
      cluster means two surviving clusters can never become connected, so
      well-formed molecules are never joined; fragments can chain into
      each other or into a survivor, exactly healing the 2+1+1 case.

    This is the SINGLE-GROUP path (cluster_and_select); the grouped
    driver batches the device half across all groups instead
    (:func:`_rescue_grouped`, code-review r5: per-group dispatches would
    reintroduce the latency tax the grouped UMI stage exists to remove).

    Returns {cluster_id: root_id} for every cluster, or None when nothing
    merged.
    """
    subs = _rescue_subs(members, taken)
    n_c = clusters.num_clusters
    if not subs or n_c < 2:
        return None
    cent_strs = [
        eligible[int(clusters.centroid_of[c])].combined for c in range(n_c)
    ]
    codes, lens = encode.encode_batch(cent_strs, pad_to=128)
    cand, ident = _rescue_identities(
        codes, lens, np.asarray(subs, np.int32), None, rescue_k_end, mesh=mesh
    )
    return _rescue_merge_roots(subs, n_c, cand, ident, identity, taken)


def _rescue_grouped(
    work: list[tuple],
    identity: float,
    rescue_k_end: int = RESCUE_K_END,
    mesh=None,
) -> dict:
    """Batched :func:`_rescue_subthreshold` over many groups.

    ``work``: [(key, eligible, clusters, members, taken), ...]. ONE
    k-mer-profile + shortlist + dovetail dispatch covers every group's
    centroids (cross-group identities masked to -1 before any edge is
    formed), then the union-find runs host-side per group — the same
    batching shape as cluster_umis_grouped. Returns {key: roots|None}.
    """
    per_group = []
    cent_all: list[str] = []
    offsets = [0]
    gids: list[int] = []
    subs_global: list[int] = []
    for g, (key, eligible, clusters, members, taken) in enumerate(work):
        subs = _rescue_subs(members, taken)
        n_c = clusters.num_clusters
        s = offsets[-1]
        if not subs or n_c < 2:
            per_group.append((key, None, None, s, taken))
            continue
        cent_all.extend(
            eligible[int(clusters.centroid_of[c])].combined
            for c in range(n_c)
        )
        gids.extend([g] * n_c)
        subs_global.extend(s + c for c in subs)
        offsets.append(s + n_c)
        per_group.append((key, subs, n_c, s, taken))
    out = {key: None for key, *_ in per_group}
    if not subs_global or len(cent_all) < 2:
        return out
    codes, lens = encode.encode_batch(cent_all, pad_to=128)
    cand, ident = _rescue_identities(
        codes, lens, np.asarray(subs_global, np.int32),
        np.asarray(gids, np.int32), rescue_k_end, mesh=mesh,
    )
    row = 0
    for key, subs, n_c, s, taken in per_group:
        if subs is None:
            continue
        rows = slice(row, row + len(subs))
        row += len(subs)
        cand_local = cand[rows] - s
        ident_g = ident[rows].copy()
        oob = (cand_local < 0) | (cand_local >= n_c)
        cand_local = np.where(oob, 0, cand_local)
        ident_g[oob] = -1.0  # already -1 via gid mask; belt and braces
        out[key] = _rescue_merge_roots(subs, n_c, cand_local, ident_g,
                                       identity, taken)
    return out


def _group_members(eligible, labels, roots=None) -> dict[int, list[UmiRecord]]:
    """Cluster-id -> members, in eligible (first-come) order; ``roots``
    remaps ids through rescue merges so merged clusters read exactly as if
    vsearch had joined them."""
    members: dict[int, list[UmiRecord]] = defaultdict(list)
    for rec, lab in zip(eligible, labels):
        cid = int(lab)
        members[roots[cid] if roots else cid].append(rec)
    return members


def _run_selection(
    members: dict[int, list[UmiRecord]],
    min_reads_per_cluster: int,
    max_reads_per_cluster: int,
    balance_strands: bool,
) -> tuple[list[SelectedCluster], list[dict], set[int]]:
    """The polish_cluster strand math (parse_umi_clusters.py:67-116) over
    one group's member map; returns (selected, stats rows, taken ids)."""
    selected: list[SelectedCluster] = []
    stat_rows: list[dict] = []
    taken: set[int] = set()
    for cid in sorted(members):
        mem = members[cid]
        fwd = [m for m in mem if m.strand == "+"]
        rev = [m for m in mem if m.strand == "-"]
        n_fwd, n_rev = len(fwd), len(rev)
        if balance_strands:
            min_fwd = min_rev = min_reads_per_cluster // 2
            max_after = min(n_fwd * 2, n_rev * 2, max_reads_per_cluster)
            max_fwd = max_rev = max_after // 2
        else:
            min_fwd = min_rev = 0
            if n_fwd > n_rev:
                max_rev = min(n_rev, max_reads_per_cluster // 2)
                max_fwd = min(max_reads_per_cluster - max_rev, n_fwd)
            else:
                max_fwd = min(n_fwd, max_reads_per_cluster // 2)
                max_rev = min(max_reads_per_cluster - max_fwd, n_rev)
        n_reads = max_fwd + max_rev
        take = (
            n_fwd >= min_fwd and n_rev >= min_rev and n_reads >= min_reads_per_cluster
        )
        chosen = (fwd[:max_fwd] + rev[:max_rev])[:max_reads_per_cluster] if take else []
        row = {
            "id_cluster": f"cluster{cid}",
            "n_fwd": n_fwd, "n_rev": n_rev,
            "written_fwd": len([m for m in chosen if m.strand == "+"]),
            "written_rev": len([m for m in chosen if m.strand == "-"]),
            "n": len(mem), "written": len(chosen),
            "cluster_written": int(bool(chosen)),
        }
        stat_rows.append(row)
        if chosen:
            taken.add(cid)
            selected.append(SelectedCluster(
                cluster_id=cid, members=chosen,
                n_fwd=n_fwd, n_rev=n_rev,
                written_fwd=row["written_fwd"], written_rev=row["written_rev"],
                n_found=len(mem),
            ))
    return selected, stat_rows, taken


def _select_from_clusters(
    eligible: list[UmiRecord],
    clusters,
    min_reads_per_cluster: int,
    max_reads_per_cluster: int,
    balance_strands: bool,
    identity: float | None = None,
    rescue: bool = True,
    mesh=None,
) -> tuple[list[SelectedCluster], list[dict]]:
    """Subread selection + stats rows for one group's cluster labels."""
    members = _group_members(eligible, clusters.labels)
    selected, stat_rows, taken = _run_selection(
        members, min_reads_per_cluster, max_reads_per_cluster, balance_strands
    )
    if rescue and identity is not None and min_reads_per_cluster > 1:
        roots = _rescue_subthreshold(
            eligible, clusters, members, taken, identity, mesh=mesh
        )
        if roots is not None:
            selected, stat_rows, _ = _run_selection(
                _group_members(eligible, clusters.labels, roots),
                min_reads_per_cluster, max_reads_per_cluster, balance_strands,
            )
    return selected, stat_rows


def write_cluster_stats_tsv(stat_rows: list[dict], path: str) -> None:
    """vsearch_cluster_stats.tsv parity (parse_umi_clusters.py:183-195)."""
    cols = ["id_cluster", "n_fwd", "n_rev", "written_fwd", "written_rev",
            "n", "written", "cluster_written"]
    with open(path, "w") as fh:
        fh.write("\t".join(cols) + "\n")
        for row in stat_rows:
            fh.write("\t".join(str(row[c]) for c in cols) + "\n")


# ---------------------------------------------------------------------------
# stage: consensus polishing (medaka smolecule replacement)


def polish_clusters_all(
    selected_by_group: list[tuple[str, list[SelectedCluster]]],
    store: ReadStore,
    max_read_length: int = 4096,
    rounds: int = 4,
    band_width: int = consensus_mod.POLISH_BAND_WIDTH,
    polisher=None,
    cluster_batch: int | None = None,
    budget=None,
    mesh=None,
    keep_codes: bool = False,
    donate: bool = False,
) -> tuple[dict[str, list[tuple[str, str]]], dict[str, str]]:
    """Consensus for every selected cluster of every group, batched together.

    The reference polishes per region-cluster task (medaka_polish.py:95-144);
    a TPU chip wants the opposite — ONE large device batch per tile shape
    across the whole library, so the (C, S, W) pileup kernels run with a full
    cluster axis instead of dozens of per-group slivers (and compile once per
    shape, not once per group). Headers follow the reference's rewrite
    ``<group>_<clusterN>_<n_subreads>`` (medaka_polish.py:146-180).

    Subreads are gathered from the columnar store and flipped to canonical
    (+) orientation (strand is known from alignment — unlike medaka, no
    internal re-orientation pass).

    Static-shape discipline: clusters are grouped by (subread-count bucket,
    width bucket) and processed in batches of ``cluster_batch`` through one
    device dispatch per round (``consensus_clusters_batch``); the optional
    ``polisher`` is called ONCE per chunk on the whole (C, S, W) tile.
    Padding rows have length 0: they score 0 and cast no votes.

    ``mesh`` shards every polish dispatch's cluster/lane axis over the
    mesh's ``data`` axis (chunk sizes are padded to its multiple), putting
    the library's dominant stage on every chip instead of one — the TPU
    reading of the reference's node-wide medaka task fan-out
    (medaka_polish.py:95-144; VERDICT r2 #3).

    ``keep_codes=True`` returns each consensus as its 1-d uint8 code
    vector (the device representation) instead of an ACGT string — the
    device-resident hand-off: the downstream consumer re-batches codes
    directly and only artifact boundaries decode (decode∘encode is a
    bijection on codes 0..4, so both modes name identical sequences).
    ``donate`` forwards the graph-executor donation discipline to the
    per-round device uploads (see ``consensus_clusters_batch``).

    Host/device overlap: each chunk's gather/stack/pad (the host half of
    the dispatch tax) is packed for chunk N+1 on a one-slot background
    worker while chunk N's device rounds run, so the measured
    ``polish.dispatch`` host gap covers only true dispatch glue.

    Returns ``(consensus_by_group, failed_groups)``: per-group (header, seq)
    lists in cluster-id order, and {group: error} for groups hit by a failed
    device chunk (the per-task degradation of tcr_consensus.py:329-346).
    Chunks are independent, so other chunks still complete and their results
    accumulate in ``consensus_by_group`` — but note the pipeline driver
    (run.py) discards a group's ENTIRE output when the group appears in
    ``failed_groups``, successful same-group chunks included: a partial
    group would silently under-count its molecules, so the whole group is
    reported failed and retried on resume (the reference drops failed
    medaka batches the same way).
    """
    prepared: dict[tuple[int, int], list[tuple[str, SelectedCluster, np.ndarray, np.ndarray]]] = (
        defaultdict(list)
    )
    by_group: dict[str, list[tuple[str, str]]] = {g: [] for g, _ in selected_by_group}
    failed: dict[str, str] = {}
    for group_name, selected in selected_by_group:
        # the gather phase degrades per group like the device chunks below: a
        # poisoned cluster (oversized member, corrupt handle) fails only its
        # own group (ref tcr_consensus.py:329-346 semantics)
        try:
            group_prepared = []
            for cl in selected:
                rows_codes = []
                rows_quals: list | None = []
                rows_rev = []
                max_len = 0
                for m in cl.members:
                    blk = store.blocks[m.block]
                    ln = int(blk.lens[m.row])
                    c = blk.codes[m.row, :ln]
                    q = blk.quals[m.row, :ln] if blk.quals is not None else None
                    if m.strand == "-":
                        c = encode.revcomp_codes(c)
                        # quals REVERSE (no complement) alongside the revcomp
                        # so q[i] stays the phred of the base now at i
                        q = q[::-1] if q is not None else None
                    rows_codes.append(c)
                    if q is None:
                        rows_quals = None
                    elif rows_quals is not None:
                        rows_quals.append(q)
                    rows_rev.append(m.strand == "-")
                    max_len = max(max_len, ln)
                # one lane-width of growth slack above the longest subread
                need = max_len + 128
                width = min(
                    max_read_length,
                    next((w for w in bucketing.DEFAULT_WIDTHS if w >= need), max_read_length),
                )
                codes, lens = encode.pad_batch(rows_codes, pad_to=width, multiple=128)
                s_bucket = bucketing.pow2_ceil(len(rows_codes))
                quals = None
                if rows_quals is not None:
                    quals = np.zeros((s_bucket, codes.shape[1]), np.uint8)
                    for i, q in enumerate(rows_quals):
                        quals[i, : len(q)] = q
                strands = np.zeros(s_bucket, bool)
                strands[: len(rows_rev)] = rows_rev
                if s_bucket > len(rows_codes):
                    pad_rows = s_bucket - len(rows_codes)
                    codes = np.concatenate(
                        [codes, np.full((pad_rows, codes.shape[1]), encode.PAD_CODE, np.uint8)]
                    )
                    lens = np.concatenate([lens, np.zeros(pad_rows, lens.dtype)])
                group_prepared.append(
                    (s_bucket, codes.shape[1], cl, codes, lens, quals, strands)
                )
        except Exception as exc:
            failed[group_name] = repr(exc)
            continue
        for s_bucket, width, cl, codes, lens, quals, strands in group_prepared:
            prepared[(s_bucket, width)].append(
                (group_name, cl, codes, lens, quals, strands)
            )
    n_data = None
    if mesh is not None:
        # the cluster axis shards over 'data': chunks must divide it
        from ont_tcrconsensus_tpu.parallel.mesh import mesh_data_size

        n_data = mesh_data_size(mesh)
    # one-slot pack prefetch: a single background worker stacks/pads the
    # NEXT chunk's (C, S, W) tile while the current chunk's device rounds
    # run; heartbeats, metrics, and every dispatch stay on this thread
    packer = ThreadPoolExecutor(max_workers=1,
                                thread_name_prefix="polish-pack")
    try:
        _polish_bucket_loop(
            prepared, by_group, failed, packer,
            rounds=rounds, band_width=band_width, polisher=polisher,
            cluster_batch=cluster_batch, budget=budget, mesh=mesh,
            n_data=n_data, keep_codes=keep_codes, donate=donate,
        )
    finally:
        packer.shutdown(wait=True)
    for entries in by_group.values():
        entries.sort(key=lambda kv: int(kv[0].rsplit("_cluster", 1)[1].split("_")[0]))
    return by_group, failed


def _polish_bucket_loop(prepared, by_group, failed, packer, *, rounds,
                        band_width, polisher, cluster_batch, budget, mesh,
                        n_data, keep_codes, donate) -> None:
    """Shape-bucketed chunk drive of :func:`polish_clusters_all` (split
    out so the pack-prefetch executor's lifetime wraps it cleanly)."""
    for (s_bucket, width), items in sorted(prepared.items()):
        # Band scales with the width bucket: +/-32 is >4 sigma of same-
        # molecule drift up to ~2 kb, but cumulative indel drift grows with
        # length (~11 nt sigma at 4 kb), so long-amplicon buckets double the
        # band instead of silently clipping tail subreads off it (ADVICE r2).
        eff_band = band_width if width <= 2048 else max(band_width, 128)
        # cluster-tile batch from the HBM budget (the medaka memory-model
        # analogue, parallel/budget.py) unless explicitly overridden
        keep_pos = bool(getattr(polisher, "wants_v4", False))
        if cluster_batch is not None:
            cb = cluster_batch
        elif budget is not None:
            cb = budget.cluster_batch(s_bucket, width, eff_band,
                                      keep_final_pileup=polisher is not None,
                                      keep_pos=keep_pos)
        else:
            cb = 16
        # never pad the cluster axis past the work available (a small
        # library padded to the full HBM tile wastes most of the dispatch);
        # power-of-two so compile shapes stay bounded
        cb = min(cb, bucketing.pow2_ceil(len(items)))
        if n_data is not None:
            cb = max(cb, n_data)
        # Fault-tolerant chunk drive (robustness/): transient device faults
        # retry the SAME shape under the bounded-backoff policy; a
        # RESOURCE_EXHAUSTED re-enters the HBM budget with a halved
        # allowance and requeues the chunk at the smaller cluster batch
        # (degrade, don't skip — the work still completes); anything else
        # is a deterministic bug and falls through to the existing
        # skip-and-report path. With no fault firing this walks the exact
        # chunk sequence of the plain loop, so outputs are byte-identical.
        worklist: list[tuple[list, int, int]] = [(items, cb, 0)]
        while worklist:
            run_items, cb_run, shrink = worklist.pop(0)
            requeued = False
            starts = list(range(0, len(run_items), cb_run))

            def _pack_at(i: int):
                return _pack_polish_chunk(
                    run_items[starts[i]: starts[i] + cb_run],
                    cb_run, s_bucket, width,
                )

            next_packed = None
            for si, start in enumerate(starts):
                chunk = run_items[start : start + cb_run]
                this_packed, next_packed = next_packed, (
                    packer.submit(_pack_at, si + 1)
                    if si + 1 < len(starts) else None
                )
                seqs = None
                attempt = 1
                while True:
                    try:
                        # liveness: each chunk dispatch is one heartbeat —
                        # the watchdog only fires when a DISPATCH stops
                        # progressing, never from many fast chunks
                        watchdog.heartbeat("polish.chunk")
                        faults.inject("polish.dispatch")
                        if mesh is not None:
                            # mesh-only faults: a slice dying mid-node
                            # (escalates to the executor's degraded-mesh
                            # re-execution) and a per-slice OOM (rides the
                            # ordinary shrink-and-requeue path below)
                            faults.inject("mesh.device_lost")
                            faults.inject("mesh.slice_oom")
                        # double-buffered pack: chunk N's tile was stacked
                        # by the background worker while chunk N-1 ran on
                        # device (futures cache their result, so a retry
                        # reuses the packed arrays — the pack is pure);
                        # the first chunk of a worklist entry packs inline
                        packed = (this_packed.result()
                                  if this_packed is not None
                                  else _pack_at(si))
                        # dispatch-tax attribution for the dominant stage:
                        # the device_gets inside ops/consensus and the
                        # polisher credit their blocked seconds to this
                        # frame; what remains is round1_polish's host gap
                        # (the pack above deliberately sits OUTSIDE it)
                        with obs_device.dispatch(
                            "polish.dispatch", bucket=f"{s_bucket}x{width}",
                        ):
                            seqs = _dispatch_polish_packed(
                                packed, len(chunk), rounds=rounds,
                                eff_band=eff_band, keep_pos=keep_pos,
                                polisher=polisher, mesh=mesh,
                                keep_codes=keep_codes, donate=donate,
                            )
                    except Exception as exc:
                        pol, rec = retry.policy(), retry.recorder()
                        cls = retry.classify(exc)
                        if cls == "device_lost":
                            # a dead slice can't be retried OR shrunk
                            # around from here: escalate to the graph
                            # executor, which shrinks the mesh's data axis
                            # to the survivors and re-runs the whole node
                            rec.record("polish.dispatch", classification=cls,
                                       outcome="escalated", attempt=attempt,
                                       error=repr(exc))
                            raise
                        if cls == "transient" and attempt < pol.max_attempts:
                            rec.record("polish.dispatch", classification=cls,
                                       outcome="retried", attempt=attempt,
                                       error=repr(exc))
                            time.sleep(pol.delay(attempt))
                            attempt += 1
                            continue
                        if cls == "oom":
                            new_cb = _shrunken_cluster_batch(
                                budget, shrink, s_bucket, width, eff_band,
                                keep_final=polisher is not None,
                                keep_pos=keep_pos, cb_run=cb_run,
                            )
                            if n_data is not None:
                                new_cb = max(new_cb, n_data)
                            if new_cb < cb_run:
                                rec.record(
                                    "polish.dispatch", classification="oom",
                                    outcome="oom_shrink", attempt=attempt,
                                    error=repr(exc),
                                    detail={"cluster_batch_from": cb_run,
                                            "cluster_batch_to": new_cb,
                                            "shrink_level": shrink + 1},
                                )
                                # requeue the failing chunk AND the untried
                                # remainder at the smaller batch: HBM is
                                # exhausted, so every further dispatch at
                                # cb_run is a guaranteed repeat OOM (final
                                # per-group sort keeps output order exact)
                                worklist.append(
                                    (run_items[start:], new_cb, shrink + 1)
                                )
                                requeued = True
                                break
                        rec.record("polish.dispatch", classification=cls,
                                   outcome="degraded", attempt=attempt,
                                   error=repr(exc))
                        for group_name, *_ in chunk:
                            failed.setdefault(group_name, repr(exc))
                        break
                    else:
                        if attempt > 1 or shrink:
                            retry.recorder().record(
                                "polish.dispatch",
                                classification="oom" if shrink else "transient",
                                outcome="recovered", attempt=attempt,
                                detail=({"shrink_level": shrink}
                                        if shrink else None),
                            )
                        break
                if requeued:
                    if next_packed is not None:
                        next_packed.cancel()
                    break
                # chunk counted at RESOLUTION (success or final failure),
                # after the retry loop and the requeue branch: transient
                # retries count once, and an OOM-requeued chunk's clusters
                # count only in the smaller chunks that finally settle
                # them — so polish.chunk_clusters always sums to the
                # eligible cluster total, even on degraded runs
                obs_metrics.counter_add("polish.chunks")
                obs_metrics.observe("polish.chunk_clusters", len(chunk))
                if seqs is None:
                    continue
                for c, seq in enumerate(seqs):
                    group_name, cl = chunk[c][0], chunk[c][1]
                    by_group[group_name].append(
                        (f"{group_name}_cluster{cl.cluster_id}_{len(cl.members)}", seq)
                    )


def _pack_polish_chunk(chunk, cb, s_bucket, width):
    """Host-side gather of one chunk into its padded (cb, S, W) tile:
    stack the per-cluster code/len/qual/strand arrays and pad the
    cluster axis to ``cb`` for stable compile shapes. Pure numpy on
    already-prepared arrays — safe to run on the prefetch worker while
    the previous chunk occupies the device."""
    C = len(chunk)
    sub = np.stack([codes for _, _, codes, _, _, _ in chunk])
    lens = np.stack([ln for _, _, _, ln, _, _ in chunk])
    have_quals = all(q is not None for _, _, _, _, q, _ in chunk)
    quals = (np.stack([q for _, _, _, _, q, _ in chunk])
             if have_quals else None)
    strands = np.stack([s for _, _, _, _, _, s in chunk])
    if C < cb:  # pad the cluster axis: stable compile shapes
        pad = cb - C
        sub = np.concatenate(
            [sub, np.full((pad, s_bucket, width), encode.PAD_CODE, np.uint8)]
        )
        lens = np.concatenate([lens, np.zeros((pad, s_bucket), lens.dtype)])
        if quals is not None:
            quals = np.concatenate(
                [quals, np.zeros((pad, s_bucket, width), np.uint8)]
            )
        strands = np.concatenate(
            [strands, np.zeros((pad, s_bucket), bool)]
        )
    return sub, lens, quals, strands


def _dispatch_polish_packed(packed, C, *, rounds, eff_band, keep_pos,
                            polisher, mesh, keep_codes=False,
                            donate=False) -> list:
    """One (C, S, W) consensus+polish device dispatch over a packed tile;
    returns the C consensus sequences in chunk order (strings, or 1-d
    uint8 code vectors under ``keep_codes``). Pure function of its
    inputs — safe to retry verbatim after a transient fault or to re-run
    at a smaller cluster batch after an OOM."""
    sub, lens, quals, strands = packed
    drafts, dlens, *rest = consensus_mod.consensus_clusters_batch(
        sub, lens, rounds=rounds, band_width=eff_band,
        keep_final_pileup=polisher is not None,
        keep_pos=keep_pos, mesh=mesh, donate=donate,
    )
    if polisher is not None:
        # donate is forwarded only when on: custom polishers predating
        # the donation discipline keep their exact signature
        pol_kwargs = {"donate": True} if donate else {}
        drafts, dlens = polisher(
            sub, lens, drafts, dlens, pileup=rest[0],
            band_width=eff_band, mesh=mesh,
            quals=quals, strands=strands, **pol_kwargs,
        )
    if keep_codes:
        drafts = np.asarray(drafts)
        dlens = np.asarray(dlens)
        return [drafts[c, : int(dlens[c])].astype(np.uint8, copy=True)
                for c in range(C)]
    return encode.decode_batch(drafts[:C], dlens[:C])


def _shrunken_cluster_batch(budget, shrink, s_bucket, width, eff_band, *,
                            keep_final, keep_pos, cb_run) -> int:
    """Next cluster batch after the ``shrink``-th OOM at ``cb_run``:
    re-derive from the budget model with a halved HBM allowance (the
    medaka memory model run in reverse), clamped strictly below ``cb_run``
    with a floor of 1 so the shrink sequence always terminates."""
    if budget is not None:
        shrunk = dataclasses.replace(
            budget, hbm_gb=budget.hbm_gb / (2.0 ** (shrink + 1))
        )
        new_cb = shrunk.cluster_batch(s_bucket, width, eff_band,
                                      keep_final_pileup=keep_final,
                                      keep_pos=keep_pos)
    else:
        new_cb = cb_run // 2
    return max(1, min(new_cb, cb_run // 2))


def polish_clusters_stage(
    selected: list[SelectedCluster],
    group_name: str,
    store: ReadStore,
    **kwargs,
) -> list[tuple[str, str]]:
    """Single-group convenience wrapper over :func:`polish_clusters_all`."""
    by_group, failed = polish_clusters_all(
        [(group_name, selected)], store, **kwargs
    )
    if failed:
        raise RuntimeError(f"polish failed for {group_name}: {failed[group_name]}")
    return by_group[group_name]


# ---------------------------------------------------------------------------
# stage: counting (count.py)


def write_counts_csv(region_counts: dict[str, int], counts_dir: str,
                     region_name: str = "TCR") -> str:
    """counts/umi_consensus_counts.csv parity (count.py:39-51)."""
    path = os.path.join(counts_dir, "umi_consensus_counts.csv")
    with open(path, "w") as fh:
        fh.write(f"{region_name},Count\n")
        for region, count in region_counts.items():
            fh.write(f"{region},{count}\n")
    return path
