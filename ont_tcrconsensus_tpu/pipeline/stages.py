"""Pipeline stages over the columnar read store.

Each function is one stage of the 14-stage reference pipeline
(/root/reference/ont_tcr_consensus/tcr_consensus.py:33-478). The read-level
hot path (trim/filter/align/UMI-locate) is the fused device pass in
:mod:`.assign`; this module holds the host-side stages that operate on its
columnar survivors: grouping, UMI record assembly, clustering + subread
selection, batched consensus polish, and counting. Strings materialize only
at artifact boundaries.
"""

from __future__ import annotations

import dataclasses
import os
from collections import defaultdict

import numpy as np

from ont_tcrconsensus_tpu.cluster import umi as umi_mod
from ont_tcrconsensus_tpu.io import bucketing, fastx
from ont_tcrconsensus_tpu.ops import consensus as consensus_mod
from ont_tcrconsensus_tpu.ops import encode
from ont_tcrconsensus_tpu.pipeline.assign import (  # noqa: F401  (re-exported)
    AlignStats,
    AssignEngine,
    ReadStore,
    ReferencePanel,
    run_assign,
)

# ---------------------------------------------------------------------------
# stage: UMI record assembly (extract_umis.py:189-267)


@dataclasses.dataclass
class UmiRecord:
    """One read's extracted UMI pair + a (block, row) handle into the store."""

    name: str
    strand: str
    umi_fwd_dist: int
    umi_rev_dist: int
    umi_fwd_seq: str
    umi_rev_seq: str
    combined: str          # canonical (molecule) orientation
    block: int
    row: int

    def header(self, store: ReadStore) -> str:
        """7-field header parity (extract_umis.py:174-181); the full read is
        smuggled in ``seq=`` exactly like the reference's UMI fasta."""
        seq = store.blocks[self.block].decode_one(self.row)
        return (
            f"{self.name};strand={self.strand};umi_fwd_dist={self.umi_fwd_dist};"
            f"umi_rev_dist={self.umi_rev_dist};umi_fwd_seq={self.umi_fwd_seq};"
            f"umi_rev_seq={self.umi_rev_seq};seq={seq}"
        )


def build_umi_records(
    store: ReadStore,
    parts: list[tuple[int, np.ndarray]],
    max_pattern_dist: int,
) -> list[UmiRecord]:
    """Assemble UMI records for one read group from the fused-pass fields.

    The 5' window was searched with ``umi_fwd`` and the 3' window with
    ``umi_rev`` regardless of strand — the patterns are reverse complements,
    so '-' reads match symmetrically (extract_umis.py:221-245). Combined UMI
    canonicalization: '+' -> fwd+rev, '-' -> revcomp(rev)+revcomp(fwd)
    (extract_umis.py:140-151). Reads where either pattern exceeds
    ``max_pattern_dist`` are dropped, mirroring the edlib k gate.
    """
    out: list[UmiRecord] = []
    for bi, rows in parts:
        blk = store.blocks[bi]
        u = blk.umi
        ok = (u["d5"][rows] <= max_pattern_dist) & (u["d3"][rows] <= max_pattern_dist)
        ok &= (u["e5"][rows] > u["s5"][rows]) & (u["e3"][rows] > u["s3"][rows])
        ascii_rows = encode._DECODE_ASCII[blk.codes[rows]]
        for k, r in enumerate(rows):
            if not ok[k]:
                continue
            s5, e5 = int(u["s5"][r]), int(u["e5"][r])
            a3 = int(u["start3"][r])
            s3, e3 = a3 + int(u["s3"][r]), a3 + int(u["e3"][r])
            u5 = ascii_rows[k, s5:e5].tobytes().decode("ascii")
            u3 = ascii_rows[k, s3:e3].tobytes().decode("ascii")
            strand = "-" if blk.is_rev[r] else "+"
            if strand == "+":
                combined = u5 + u3
            else:
                combined = encode.revcomp_str(u3) + encode.revcomp_str(u5)
            out.append(UmiRecord(
                name=blk.names[r], strand=strand,
                umi_fwd_dist=int(u["d5"][r]), umi_rev_dist=int(u["d3"][r]),
                umi_fwd_seq=u5, umi_rev_seq=u3,
                combined=combined, block=bi, row=int(r),
            ))
    return out


def write_umi_fasta(records: list[UmiRecord], store: ReadStore, path: str) -> int:
    """The 'UMI fasta': combined UMI as sequence, full read smuggled in the
    header (extract_umis.py:154-186)."""
    return fastx.write_fasta(
        path, ((r.header(store), r.combined) for r in records)
    )


# ---------------------------------------------------------------------------
# stage: region grouping + per-group fasta artifacts (region_split.py)


def group_by_region_cluster(store: ReadStore, panel: ReferencePanel):
    """Round-1 grouping: reads binned per region *cluster*
    (region_split.py:271-280). Returns {cluster_id: [(block, rows)]}."""
    return store.group_rows_by(panel.cluster_of_region)


def group_by_region(store: ReadStore, panel: ReferencePanel):
    """Round-2 grouping: per exact region (region_split.py:336-435).
    Returns {region_name: [(block, rows)]}."""
    idx_groups = store.group_rows_by(np.arange(len(panel.names), dtype=np.int32))
    return {panel.names[k]: v for k, v in idx_groups.items()}


def write_region_fastas(
    groups: dict, store: ReadStore, out_dir: str, prefix: str
) -> dict[str, str]:
    """Per-group fastas in the reference's format: original-orientation
    sequence, header ``<name>;strand=<+/->`` (region_split.py:273-280)."""
    paths = {}
    for key, parts in sorted(groups.items(), key=lambda kv: str(kv[0])):
        path = os.path.join(out_dir, f"{prefix}{key}.fasta")

        def rows_iter(parts=parts):
            for bi, rows in parts:
                blk = store.blocks[bi]
                seqs = blk.decode(rows)
                for k, r in enumerate(rows):
                    strand = "-" if blk.is_rev[r] else "+"
                    yield f"{blk.names[r]};strand={strand}", seqs[k]

        fastx.write_fasta(path, rows_iter())
        paths[str(key)] = path
    return paths


# ---------------------------------------------------------------------------
# stage: UMI clustering + subread selection (vsearch_umi_cluster.py +
# parse_umi_clusters.py)


@dataclasses.dataclass
class SelectedCluster:
    cluster_id: int
    members: list[UmiRecord]       # the selected subreads (<= max)
    n_fwd: int
    n_rev: int
    written_fwd: int
    written_rev: int
    n_found: int


def cluster_and_select(
    umi_records: list[UmiRecord],
    identity: float,
    min_umi_length: int,
    max_umi_length: int,
    min_reads_per_cluster: int,
    max_reads_per_cluster: int,
    balance_strands: bool,
    mesh=None,
) -> tuple[list[SelectedCluster], list[dict]]:
    """Cluster combined UMIs, then select subreads per cluster.

    Length bounds replicate vsearch --minseqlength/--maxseqlength (records
    outside are dropped before clustering, vsearch_umi_cluster.py:29-33).
    Selection replicates polish_cluster's strand math exactly
    (parse_umi_clusters.py:67-116): first-come member order, minority strand
    capped at max/2, optional balancing.

    Returns (selected clusters, per-cluster stats rows — including skipped
    clusters, for the stats TSV parity).
    """
    eligible = [r for r in umi_records if min_umi_length <= len(r.combined) <= max_umi_length]
    if not eligible:
        return [], []
    clusters = umi_mod.cluster_umis(
        [r.combined for r in eligible], identity, mesh=mesh
    )
    return _select_from_clusters(
        eligible, clusters,
        min_reads_per_cluster=min_reads_per_cluster,
        max_reads_per_cluster=max_reads_per_cluster,
        balance_strands=balance_strands,
    )


def cluster_and_select_grouped(
    named_records: list[tuple[str, list[UmiRecord]]],
    identity: float,
    min_umi_length: int,
    max_umi_length: int,
    min_reads_per_cluster: int,
    max_reads_per_cluster: int,
    balance_strands: bool,
    mesh=None,
) -> dict[str, tuple[list[SelectedCluster], list[dict]]]:
    """:func:`cluster_and_select` over MANY groups with batched dispatches.

    The reference runs vsearch once per region cluster / region
    (vsearch_umi_cluster.py called per group); clustering here instead
    batches every group through ONE global device pass
    (:func:`..cluster.umi.cluster_umis_grouped` — cross-group identities
    masked, so results are per-group exact) and runs the subread selection
    host-side per group. Returns {group_name: (selected, stat_rows)}.
    """
    eligibles = [
        (name, [
            r for r in records
            if min_umi_length <= len(r.combined) <= max_umi_length
        ])
        for name, records in named_records
    ]
    groups = [[r.combined for r in recs] for _, recs in eligibles]
    clusters_list = umi_mod.cluster_umis_grouped(groups, identity, mesh=mesh)
    out: dict[str, tuple[list[SelectedCluster], list[dict]]] = {}
    for (name, recs), clusters in zip(eligibles, clusters_list):
        if not recs:
            out[name] = ([], [])
            continue
        out[name] = _select_from_clusters(
            recs, clusters,
            min_reads_per_cluster=min_reads_per_cluster,
            max_reads_per_cluster=max_reads_per_cluster,
            balance_strands=balance_strands,
        )
    return out


def _select_from_clusters(
    eligible: list[UmiRecord],
    clusters,
    min_reads_per_cluster: int,
    max_reads_per_cluster: int,
    balance_strands: bool,
) -> tuple[list[SelectedCluster], list[dict]]:
    """Subread selection + stats rows for one group's cluster labels."""
    members: dict[int, list[UmiRecord]] = defaultdict(list)
    for rec, lab in zip(eligible, clusters.labels):
        members[int(lab)].append(rec)

    selected: list[SelectedCluster] = []
    stat_rows: list[dict] = []
    for cid in sorted(members):
        mem = members[cid]
        fwd = [m for m in mem if m.strand == "+"]
        rev = [m for m in mem if m.strand == "-"]
        n_fwd, n_rev = len(fwd), len(rev)
        if balance_strands:
            min_fwd = min_rev = min_reads_per_cluster // 2
            max_after = min(n_fwd * 2, n_rev * 2, max_reads_per_cluster)
            max_fwd = max_rev = max_after // 2
        else:
            min_fwd = min_rev = 0
            if n_fwd > n_rev:
                max_rev = min(n_rev, max_reads_per_cluster // 2)
                max_fwd = min(max_reads_per_cluster - max_rev, n_fwd)
            else:
                max_fwd = min(n_fwd, max_reads_per_cluster // 2)
                max_rev = min(max_reads_per_cluster - max_fwd, n_rev)
        n_reads = max_fwd + max_rev
        take = (
            n_fwd >= min_fwd and n_rev >= min_rev and n_reads >= min_reads_per_cluster
        )
        chosen = (fwd[:max_fwd] + rev[:max_rev])[:max_reads_per_cluster] if take else []
        row = {
            "id_cluster": f"cluster{cid}",
            "n_fwd": n_fwd, "n_rev": n_rev,
            "written_fwd": len([m for m in chosen if m.strand == "+"]),
            "written_rev": len([m for m in chosen if m.strand == "-"]),
            "n": len(mem), "written": len(chosen),
            "cluster_written": int(bool(chosen)),
        }
        stat_rows.append(row)
        if chosen:
            selected.append(SelectedCluster(
                cluster_id=cid, members=chosen,
                n_fwd=n_fwd, n_rev=n_rev,
                written_fwd=row["written_fwd"], written_rev=row["written_rev"],
                n_found=len(mem),
            ))
    return selected, stat_rows


def write_cluster_stats_tsv(stat_rows: list[dict], path: str) -> None:
    """vsearch_cluster_stats.tsv parity (parse_umi_clusters.py:183-195)."""
    cols = ["id_cluster", "n_fwd", "n_rev", "written_fwd", "written_rev",
            "n", "written", "cluster_written"]
    with open(path, "w") as fh:
        fh.write("\t".join(cols) + "\n")
        for row in stat_rows:
            fh.write("\t".join(str(row[c]) for c in cols) + "\n")


# ---------------------------------------------------------------------------
# stage: consensus polishing (medaka smolecule replacement)


def polish_clusters_all(
    selected_by_group: list[tuple[str, list[SelectedCluster]]],
    store: ReadStore,
    max_read_length: int = 4096,
    rounds: int = 4,
    band_width: int = consensus_mod.POLISH_BAND_WIDTH,
    polisher=None,
    cluster_batch: int | None = None,
    budget=None,
    mesh=None,
) -> tuple[dict[str, list[tuple[str, str]]], dict[str, str]]:
    """Consensus for every selected cluster of every group, batched together.

    The reference polishes per region-cluster task (medaka_polish.py:95-144);
    a TPU chip wants the opposite — ONE large device batch per tile shape
    across the whole library, so the (C, S, W) pileup kernels run with a full
    cluster axis instead of dozens of per-group slivers (and compile once per
    shape, not once per group). Headers follow the reference's rewrite
    ``<group>_<clusterN>_<n_subreads>`` (medaka_polish.py:146-180).

    Subreads are gathered from the columnar store and flipped to canonical
    (+) orientation (strand is known from alignment — unlike medaka, no
    internal re-orientation pass).

    Static-shape discipline: clusters are grouped by (subread-count bucket,
    width bucket) and processed in batches of ``cluster_batch`` through one
    device dispatch per round (``consensus_clusters_batch``); the optional
    ``polisher`` is called ONCE per chunk on the whole (C, S, W) tile.
    Padding rows have length 0: they score 0 and cast no votes.

    ``mesh`` shards every polish dispatch's cluster/lane axis over the
    mesh's ``data`` axis (chunk sizes are padded to its multiple), putting
    the library's dominant stage on every chip instead of one — the TPU
    reading of the reference's node-wide medaka task fan-out
    (medaka_polish.py:95-144; VERDICT r2 #3).

    Returns ``(consensus_by_group, failed_groups)``: per-group (header, seq)
    lists in cluster-id order, and {group: error} for groups hit by a failed
    device chunk (the per-task degradation of tcr_consensus.py:329-346).
    Chunks are independent, so other chunks still complete and their results
    accumulate in ``consensus_by_group`` — but note the pipeline driver
    (run.py) discards a group's ENTIRE output when the group appears in
    ``failed_groups``, successful same-group chunks included: a partial
    group would silently under-count its molecules, so the whole group is
    reported failed and retried on resume (the reference drops failed
    medaka batches the same way).
    """
    prepared: dict[tuple[int, int], list[tuple[str, SelectedCluster, np.ndarray, np.ndarray]]] = (
        defaultdict(list)
    )
    by_group: dict[str, list[tuple[str, str]]] = {g: [] for g, _ in selected_by_group}
    failed: dict[str, str] = {}
    for group_name, selected in selected_by_group:
        # the gather phase degrades per group like the device chunks below: a
        # poisoned cluster (oversized member, corrupt handle) fails only its
        # own group (ref tcr_consensus.py:329-346 semantics)
        try:
            group_prepared = []
            for cl in selected:
                rows_codes = []
                max_len = 0
                for m in cl.members:
                    blk = store.blocks[m.block]
                    ln = int(blk.lens[m.row])
                    c = blk.codes[m.row, :ln]
                    if m.strand == "-":
                        c = encode.revcomp_codes(c)
                    rows_codes.append(c)
                    max_len = max(max_len, ln)
                # one lane-width of growth slack above the longest subread
                need = max_len + 128
                width = min(
                    max_read_length,
                    next((w for w in bucketing.DEFAULT_WIDTHS if w >= need), max_read_length),
                )
                codes, lens = encode.pad_batch(rows_codes, pad_to=width, multiple=128)
                s_bucket = bucketing.pow2_ceil(len(rows_codes))
                if s_bucket > len(rows_codes):
                    pad_rows = s_bucket - len(rows_codes)
                    codes = np.concatenate(
                        [codes, np.full((pad_rows, codes.shape[1]), encode.PAD_CODE, np.uint8)]
                    )
                    lens = np.concatenate([lens, np.zeros(pad_rows, lens.dtype)])
                group_prepared.append((s_bucket, codes.shape[1], cl, codes, lens))
        except Exception as exc:
            failed[group_name] = repr(exc)
            continue
        for s_bucket, width, cl, codes, lens in group_prepared:
            prepared[(s_bucket, width)].append((group_name, cl, codes, lens))
    for (s_bucket, width), items in sorted(prepared.items()):
        # Band scales with the width bucket: +/-32 is >4 sigma of same-
        # molecule drift up to ~2 kb, but cumulative indel drift grows with
        # length (~11 nt sigma at 4 kb), so long-amplicon buckets double the
        # band instead of silently clipping tail subreads off it (ADVICE r2).
        eff_band = band_width if width <= 2048 else max(band_width, 128)
        # cluster-tile batch from the HBM budget (the medaka memory-model
        # analogue, parallel/budget.py) unless explicitly overridden
        if cluster_batch is not None:
            cb = cluster_batch
        elif budget is not None:
            cb = budget.cluster_batch(s_bucket, width, eff_band,
                                      keep_final_pileup=polisher is not None)
        else:
            cb = 16
        # never pad the cluster axis past the work available (a small
        # library padded to the full HBM tile wastes most of the dispatch);
        # power-of-two so compile shapes stay bounded
        cb = min(cb, bucketing.pow2_ceil(len(items)))
        if mesh is not None:
            # the cluster axis shards over 'data': chunks must divide it
            from ont_tcrconsensus_tpu.parallel.mesh import mesh_data_size

            n_data = mesh_data_size(mesh)
            cb = max(cb, n_data)
        for start in range(0, len(items), cb):
            chunk = items[start : start + cb]
            C = len(chunk)
            try:
                sub = np.stack([codes for _, _, codes, _ in chunk])
                lens = np.stack([ln for _, _, _, ln in chunk])
                if C < cb:  # pad the cluster axis: stable compile shapes
                    pad = cb - C
                    sub = np.concatenate(
                        [sub, np.full((pad, s_bucket, width), encode.PAD_CODE, np.uint8)]
                    )
                    lens = np.concatenate([lens, np.zeros((pad, s_bucket), lens.dtype)])
                drafts, dlens, *rest = consensus_mod.consensus_clusters_batch(
                    sub, lens, rounds=rounds, band_width=eff_band,
                    keep_final_pileup=polisher is not None, mesh=mesh,
                )
                if polisher is not None:
                    drafts, dlens = polisher(
                        sub, lens, drafts, dlens, pileup=rest[0],
                        band_width=eff_band, mesh=mesh,
                    )
                seqs = encode.decode_batch(drafts[:C], dlens[:C])
            except Exception as exc:
                for group_name, _, _, _ in chunk:
                    failed.setdefault(group_name, repr(exc))
                continue
            for c in range(C):
                group_name, cl = chunk[c][0], chunk[c][1]
                by_group[group_name].append(
                    (f"{group_name}_cluster{cl.cluster_id}_{len(cl.members)}", seqs[c])
                )
    for entries in by_group.values():
        entries.sort(key=lambda kv: int(kv[0].rsplit("_cluster", 1)[1].split("_")[0]))
    return by_group, failed


def polish_clusters_stage(
    selected: list[SelectedCluster],
    group_name: str,
    store: ReadStore,
    **kwargs,
) -> list[tuple[str, str]]:
    """Single-group convenience wrapper over :func:`polish_clusters_all`."""
    by_group, failed = polish_clusters_all(
        [(group_name, selected)], store, **kwargs
    )
    if failed:
        raise RuntimeError(f"polish failed for {group_name}: {failed[group_name]}")
    return by_group[group_name]


# ---------------------------------------------------------------------------
# stage: counting (count.py)


def write_counts_csv(region_counts: dict[str, int], counts_dir: str,
                     region_name: str = "TCR") -> str:
    """counts/umi_consensus_counts.csv parity (count.py:39-51)."""
    path = os.path.join(counts_dir, "umi_consensus_counts.csv")
    with open(path, "w") as fh:
        fh.write(f"{region_name},Count\n")
        for region, count in region_counts.items():
            fh.write(f"{region},{count}\n")
    return path
