"""CLI entry point: ``tcr-consensus-tpu <run_config.json>``.

Mirrors the reference console script ``tcr_consensus``
(/root/reference/pyproject.toml:46-47, tcr_consensus.py:33-36).
"""

from __future__ import annotations

import argparse
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="Count unique TCR molecule nanopore consensus reads (TPU-native)."
    )
    parser.add_argument("json_config_file", help="Path to analysis run JSON config file")
    args = parser.parse_args(argv)

    from ont_tcrconsensus_tpu.pipeline.run import run_pipeline

    run_pipeline(args.json_config_file)
    return 0


if __name__ == "__main__":
    sys.exit(main())
