"""CLI entry point: ``tcr-consensus-tpu <run_config.json>``.

Mirrors the reference console script ``tcr_consensus``
(/root/reference/pyproject.toml:46-47, tcr_consensus.py:33-36). On a
multi-host TPU pod slice, set ``TCR_CONSENSUS_DISTRIBUTED=1`` (the launcher
script does this when the TPU runtime reports multiple workers) and run the
same command on every host: ``jax.distributed`` discovers the pod topology
and ``mesh_shape`` then spans the global device set — the multi-host
shard-by-barcode configuration of SURVEY §2.3. DCN carries only XLA
collectives; bulk reads stay host-local, mirroring the reference's
filesystem data plane.
"""

from __future__ import annotations

import argparse
import faulthandler
import os
import signal
import sys


def _install_stack_dump_signal() -> None:
    """SIGQUIT (Ctrl-\\ / ``kill -QUIT``) -> all-thread stack dump.

    The post-hoc diagnosis hook for a wedged production run: even with the
    watchdog disarmed, an operator can always extract every thread's stack
    without killing the process. The pipeline additionally re-registers
    the dump into ``<nano_tcr>/stack_dumps_p<proc>.log`` once the output
    tree exists (pipeline/run.py), and the watchdog writes its own dumps
    to the per-library log on every stall it detects.
    """
    if not hasattr(signal, "SIGQUIT"):
        return  # non-POSIX platform: diagnosis via the watchdog log only
    try:
        faulthandler.register(signal.SIGQUIT, all_threads=True)
    except (ValueError, OSError, AttributeError):
        pass  # exotic runtime without signal support: never fatal


def main(argv: list[str] | None = None) -> int:
    _install_stack_dump_signal()
    if argv is None:
        argv = sys.argv[1:]
    # subcommand dispatch ahead of the one-shot parser: `serve` turns the
    # CLI into the long-lived warm daemon (serve/daemon.py) with its own
    # argument surface; everything else keeps the legacy single-positional
    # form untouched
    if argv and argv[0] == "serve":
        from ont_tcrconsensus_tpu.serve.daemon import serve_main

        return serve_main(argv[1:])
    parser = argparse.ArgumentParser(
        description="Count unique TCR molecule nanopore consensus reads (TPU-native)."
    )
    parser.add_argument(
        "json_config_file",
        help="Path to analysis run JSON config file (with --report: a "
        "completed run's workdir — the fastq_pass dir, its nano_tcr "
        "subdir, or the run config JSON)",
    )
    parser.add_argument(
        "--cpu", action="store_true",
        help="Force the CPU backend. The TPU plugin registers itself over "
        "JAX_PLATFORMS, so when the device tunnel is wedged any jax init "
        "hangs; the config API is the only reliable override.",
    )
    parser.add_argument(
        "--report", action="store_true",
        help="Render a human-readable run summary from a completed run's "
        "committed telemetry/robustness artifacts (telemetry.json, "
        "robustness_report.json, stage_timing.tsv, logs/trace.json) — "
        "reads files only, never imports jax, safe on hosts with a "
        "wedged device tunnel. --validate checks a run's INPUTS before "
        "it starts; --report explains a run AFTER it ran.",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="With --report or --validate: emit the machine-readable dump "
        "instead of the human rendering (same artifact resolution rules "
        "and exit codes).",
    )
    parser.add_argument(
        "--critical-path", action="store_true",
        help="With --report: analyze the executed stage graph recorded in "
        "telemetry.json (joined with trace spans when present) — critical "
        "path through the node DAG, per-node slack, what-if savings, "
        "per-node dispatch tax, overlap-pool efficiency.",
    )
    parser.add_argument(
        "--memory", action="store_true",
        help="With --report: reconcile graftcheck's static per-node HBM "
        "liveness against the measured node-boundary samples in "
        "telemetry.json's transfers section — per-node static vs "
        "measured bytes, donation verdicts, host round-trip bytes; "
        "divergence beyond threshold is a named problem.",
    )
    parser.add_argument(
        "--live-port", type=int, default=None, metavar="PORT",
        help="Arm the live observability plane for this run (overrides the "
        "live_port config knob): read-only /healthz, /metrics (Prometheus "
        "text) and /progress (JSON with ETA) on 127.0.0.1:PORT, plus the "
        "crash flight recorder (nano_tcr/logs/flight_recorder.json; "
        "flushed on crash, SIGTERM drain, watchdog hard expiry, or "
        "SIGUSR1). 0 binds an ephemeral port.",
    )
    parser.add_argument(
        "--validate", action="store_true",
        help="Dry-run input validation: parse the config, scan every input "
        "file (record counts/sizes via the tolerant parser — no device "
        "work, no jax import), run the graftcheck semantic analysis over "
        "the declared stage graph (liveness/donation/placement/sharding "
        "— violations are problems, known host round-trips are "
        "advisories), audit any existing workdir's stage manifests "
        "(torn/v1 manifests, full sha256 over completed artifacts), "
        "print a validation report, and exit non-zero on any problem.",
    )
    args = parser.parse_args(argv)

    if args.json and not (args.report or args.validate):
        parser.error("--json is a --report/--validate option")
    if args.critical_path and not args.report:
        parser.error("--critical-path is a --report option")
    if args.memory and not args.report:
        parser.error("--memory is a --report option")
    if args.live_port is not None and (args.report or args.validate):
        parser.error("--live-port is a run option (it arms a live endpoint "
                     "for the run's duration; --report/--validate exit "
                     "immediately)")

    if args.report:
        # never touches jax: safe on hosts with a wedged device tunnel
        from ont_tcrconsensus_tpu.obs import report as report_mod

        return report_mod.report_main(
            args.json_config_file, as_json=args.json,
            critical_path=args.critical_path, memory=args.memory,
        )

    if args.validate:
        # never touches jax: safe on hosts with a wedged device tunnel
        from ont_tcrconsensus_tpu.io import validate as validate_mod

        return validate_mod.validate_inputs(args.json_config_file,
                                            as_json=args.json)

    if args.cpu or os.environ.get("TCR_CONSENSUS_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")

    if os.environ.get("TCR_CONSENSUS_DISTRIBUTED"):
        import jax

        # TPU pod runtime provides coordinator/process env; this is a no-op
        # single-host and makes jax.devices() global across hosts otherwise
        jax.distributed.initialize()

    from ont_tcrconsensus_tpu.pipeline.run import run_pipeline
    from ont_tcrconsensus_tpu.robustness import shutdown

    try:
        run_pipeline(args.json_config_file, live_port=args.live_port)
    except shutdown.Preempted as p:
        # preemption-safe exit: committed checkpoints are intact; 143 is
        # the conventional SIGTERM status so orchestrators reschedule
        print(f"preempted: {p}; rerun with resume=true to continue",
              file=sys.stderr)
        return 143
    return 0


if __name__ == "__main__":
    sys.exit(main())
