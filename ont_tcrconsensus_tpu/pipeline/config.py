"""Validated run configuration.

The reference reads one flat JSON eagerly into locals with no defaults and no
validation (/root/reference/ont_tcr_consensus/tcr_consensus.py:38-71;
configs/run_config.json:1-32 — every key required, KeyError if absent). Here
the same knobs live on a typed dataclass with defaults, type/range checks and
a clear error message per key, plus TPU-specific keys (device batch sizes,
mesh shape). Unknown keys are rejected so typos fail fast.

Derived values mirror the reference exactly:
- ``cluster_identity = 1 - max_ee_rate_base`` (tcr_consensus.py:68)
- ``blast_id_threshold`` / ``minimal_region_overlap_consensus`` default to the
  measured max reference self-homology (tcr_consensus.py:99-102), resolved at
  pipeline time, not config-load time.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

# Keys accepted for compatibility with the reference config but unused here
# (they configure external binaries this framework replaces).
_COMPAT_IGNORED = {
    "dorado_excutable",  # sic — reference's own spelling (run_config.json:30)
    "dorado_executable",
    "medaka_model",
    "medaka_memory_gb_per_umi_cluster",
    "medaka_memory_gb_task_overhead",
    "max_cap_medaka_memory_gb",
}

# packaged primer set (dorado trim analogue input; the reference ships the
# same four GSP/UVP primers at ont_tcr_consensus/primers/primers.fasta)
DEFAULT_PRIMERS_FASTA = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "primers", "primers.fasta",
)


@dataclasses.dataclass
class RunConfig:
    """All pipeline knobs. Field names match the reference JSON keys."""

    # --- inputs ---
    reference_file: str
    fastq_pass_dir: str

    # --- flow control ---
    only_run_reference_self_homology: bool = False
    delete_tmp_files: bool = True

    # --- read preprocessing (trim + EE filter; preprocessing.py:7-159) ---
    trim_primers: bool = True
    nanopore_tcr_seq_primers_fasta: str | None = None  # None -> packaged set
    primer_max_dist_frac: float = 0.15   # edits allowed per primer length
    #   (0.15 separates true primer hits, ~0-3 edits at ONT error rates,
    #   from adapter-remnant-anchored partial matches at ~10+ edits)
    trim_window: int = 150               # nt searched at each read end
    dorado_trim_subsample_fastq: int | None = None
    minimal_length: int = 1470
    max_ee_rate_base: float = 0.07

    # --- alignment / region split (minimap2_align.py, region_split.py) ---
    minimal_region_overlap: float = 0.95
    max_softclip_5_end: int = 81
    max_softclip_3_end: int = 76
    sw_band_width: int = 128
    #   banded-SW lanes around the length-centered diagonal. Same-read drift
    #   is a random indel walk: std ≈ sqrt(L * indel_rate) ≈ 11 nt over 2 kb
    #   at ONT rates, so ±64 is >5 sigma; halving from 256 halves the
    #   dominant fused-pass kernel's per-row work (bench exactness and
    #   assignment accuracy are the guard)

    # --- UMI extraction (extract_umis.py:19-107) ---
    umi_fwd: str = "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT"
    umi_rev: str = "AAABBBBAABBBBAABBBBAABBBBAABBAAA"
    max_pattern_dist: int = 3
    min_umi_length: int = 58
    max_umi_length: int = 68

    # --- UMI clustering round 1 (vsearch_umi_cluster.py:21-54) ---
    vsearch_identity: float = 0.93
    min_reads_per_cluster: int = 4
    max_reads_per_cluster: int = 60
    balance_strands: bool = False

    # --- UMI cross-region audit (extract_umis.py:345-369) ---
    compare_umi_overlap_between_regions: bool = False
    overlapping_umi_edit_threshold: int = 1

    # --- consensus round 2 (tcr_consensus.py:356-444) ---
    minimal_region_overlap_consensus: float | None = None
    blast_id_threshold: float | None = None
    vsearch_identity_consensus: float = 0.97

    # --- polishing ---
    # "poa" = draft consensus only; "rnn" = draft + Flax polisher pass.
    # Default is "rnn", matching the reference's medaka precision stage.
    # The v3 polisher trains on a randomized family of systematic ONT
    # error regimes and is evaluated on HELD-OUT regimes so the eval can
    # fail off-distribution (models/weights/polisher_v3_eval.json,
    # n=250/depth/regime on 1.6 kb templates): in-family 8.4%->33% exact
    # at depth 4, 43%->79% at 6, 84%->90% at 10; on the held-out
    # homopolymer-shifted regime 31%->78% at depth 10 where voting
    # collapses; at iid depth 10, where voting is already optimal, the
    # gate fires 0%. At SERVED depths (>= min_polish_depth) broke <= 9/250
    # in every regime; the eval's depth-3 rows (measured at eval gate 3,
    # see the JSON's _meta) are NET-NEGATIVE on held-out regimes (up to
    # 20/250 broke on iid) — that is the evidence for keeping the serving
    # gate at 4. Regenerate via
    # `python -m ont_tcrconsensus_tpu.models.train --v3`.
    polish_method: str = "rnn"
    min_polish_depth: int = 4  # clusters with fewer subreads keep the vote
    #   consensus; the per-regime depth-3 tradeoff (fixed vs broke) is
    #   measured in models/weights/polisher_v3_eval.json — lower to 3 when
    #   the bundled weights' eval shows fixed >> broke there
    # Depth-2 polish pass below the gate: exactly-2-subread clusters' vote
    # consensus fails the round-2 blast-id bar ~99% of the time and the
    # v4-family weights recover a measured fraction (evidence:
    # models/weights/polisher_depth_gate_blastid.json); cannot touch any
    # other cluster. Structurally inert unless min_reads_per_cluster <= 2
    # (selection never emits 2-member clusters otherwise), and run.py only
    # pays its costs when it can actually fire.
    low_depth_polish: bool = True

    # --- TPU execution (new; no reference analogue) ---
    hbm_budget_gb: float | None = None  # None -> detect chip HBM (the one
    #   scheduler knob; batch sizes derive from it — parallel/budget.py,
    #   replacing the reference's medaka memory model)
    read_batch_size: int | None = None  # None -> derived from hbm_budget_gb
    cluster_batch_size: int | None = None  # None -> derived per tile shape
    umi_batch_size: int = 4096        # UMIs per distance-matrix tile
    max_read_length: int = 4096       # padded read width cap
    round2_targeted_assign: bool = True  # align consensus only against its
    #   round-1 region cluster's refs (skip sketch/strand re-derivation);
    #   False restores the full fused pass for round 2
    round1_fast_assign: bool = True   # SW only the needy quarter of each
    #   round-1 batch (sketch-confident reads synthesize their filter
    #   inputs — assign.py fast path, DIVERGENCES #12); False restores
    #   full-batch SW in round 1
    mesh_shape: dict[str, int] | None = None  # e.g. {"data": 8}
    distributed: bool = False         # multi-host: jax.distributed init +
    #   shard-by-barcode across processes (parallel/distributed.py)
    resume: bool = False              # stage-level resume from manifest
    write_intermediate_fastas: bool = True  # per-stage fasta artifacts
    profile_trace_dir: str | None = None
    #   when set, the whole run is wrapped in a jax.profiler trace written
    #   there (one subdir per process) — open with TensorBoard/Perfetto to
    #   see per-kernel device time, HBM traffic and host gaps; the
    #   device-level complement of logs/stage_timing.tsv
    telemetry: str = "on"  # unified telemetry layer (obs/): "off" disarms
    #   everything (planted sites are one module-attr check); "on"
    #   (default) arms the cheap counters, the per-dispatch-site host-gap/
    #   block split, the XLA recompile audit and the memory high-water
    #   one-shot, rolled up into a per-run nano_tcr/telemetry.json; "full"
    #   additionally records the Chrome-trace timeline (logs/trace.json —
    #   stage spans per thread + instant events for every robustness
    #   occurrence) and runs the periodic HBM/RSS sampler. Render with
    #   `tcr-consensus-tpu --report <workdir>`
    live_port: int | None = None  # live observability plane (obs/live.py):
    #   when set, the run serves read-only GET endpoints on
    #   127.0.0.1:<live_port> for its duration — /healthz (liveness +
    #   watchdog heartbeat-staleness verdict), /metrics (Prometheus text
    #   exposition of the armed registry + live per-stage heartbeat ages)
    #   and /progress (current library/node, nodes done/total, ETA from
    #   history-ledger priors) — and arms the crash flight recorder (a
    #   bounded span/robustness/heartbeat ring flushed atomically to
    #   nano_tcr/logs/flight_recorder.json on crash, SIGTERM drain,
    #   watchdog hard expiry, or SIGUSR1). 0 binds an OS-chosen ephemeral
    #   port (tests). null (default) disarms the whole plane: the planted
    #   sites are one module-attr check and nothing ever listens. Binds
    #   loopback only and serves no mutating route; excluded from the
    #   config fingerprint (observation, not workload)
    compile_cache_dir: str | None = None  # persistent XLA compilation cache
    #   (jax_compilation_cache_dir): null (default) uses
    #   ~/.cache/ont_tcrconsensus_tpu_xla, any other string is used as the
    #   cache directory, and "off" disables the persistent cache entirely.
    #   A warm-serving daemon (serve/) points this at durable storage so a
    #   restarted daemon reloads executables instead of recompiling.
    #   Excluded from the config fingerprint (an executable cache location,
    #   not a workload knob)
    serve_queue_max: int = 8  # daemon mode only (serve/queue.py): bounded
    #   tenant job queue depth; a submit beyond this is rejected with
    #   reason "queue_full" instead of queued unboundedly. Ignored by
    #   one-shot runs; excluded from the config fingerprint
    serve_workers: int = 1  # daemon mode only (serve/daemon.py +
    #   serve/slices.py): runner-pool width. 1 (default) keeps the serial
    #   one-job-at-a-time loop; >1 packs up to this many concurrent tenant
    #   jobs onto disjoint pow2 device slices, each under its own mesh and
    #   fault-isolation scope. Ignored by one-shot runs; excluded from the
    #   config fingerprint
    serve_prewarm: bool = True  # daemon mode only (serve/prewarm.py): AOT
    #   lower+compile the fused-assign (and polisher, when weights are
    #   bundled) entry points for the declared width buckets at daemon
    #   start, so the first job pays no compile latency. False skips the
    #   prewarm (first job compiles lazily). Ignored by one-shot runs;
    #   excluded from the config fingerprint
    history_ledger: str | None = None  # opt-in CROSS-run ledger path (e.g.
    #   a repo-level BENCH_HISTORY.jsonl): every telemetry-armed run
    #   appends its history entry there in addition to the per-run
    #   nano_tcr/history.jsonl (obs/history.py) — the baseline pool
    #   scripts/perf_gate.py gates new runs against. Excluded from the
    #   config fingerprint (it is a location, not a workload knob)
    error_profile_sample: int = 512  # reads/library profiled for the cs-tag
    #   error artifact (qc/error_profile.py); 0 disables. 512 resolves any
    #   motif above ~1% of reads in the top-40 dump; raise for deeper audits
    overlap_qc: bool = True  # run the error-profile passes on worker
    #   threads overlapped with round-1 polish / round-2 clustering
    #   (pipeline/overlap.py); artifacts stay byte-identical — False
    #   restores the fully serial stage order. Under executor="graph" this
    #   only gates the worker pool: WHICH stages overlap is derived from
    #   edge consumption in the stage graph (graph/pipeline.py)
    executor: str = "graph"  # per-library scheduler: "graph" (default)
    #   declares the round1→round2 pipeline as a typed dataflow graph
    #   (graph/) and topologically executes it — placement-aware edges,
    #   derived overlap, per-node watchdog/chaos/obs/resume attachment;
    #   "imperative" keeps the hand-sequenced run.py path (kept one PR
    #   for A/B; artifacts are byte-identical between the two)
    # --- robustness (robustness/; new, no reference analogue) ---
    retry_max_attempts: int = 3  # total attempts per dispatch site for
    #   TRANSIENT-classified failures (device/transport faults): 3 = one
    #   dispatch + two backoff retries. Deterministic bugs never retry;
    #   HBM OOM instead re-derives a shrunken batch from parallel/budget.py
    #   and requeues (stages.polish_clusters_all)
    retry_base_delay_s: float = 0.1  # first backoff delay; doubles per
    #   attempt (jittered, capped at 5 s — robustness/retry.RetryPolicy)
    chaos: list | None = None  # fault-injection plan: list of spec dicts
    #   ({"site": ..., "kind": ..., "skip": ..., "times": ...};
    #   robustness/faults.py) armed at run start. The TCR_CHAOS env var
    #   arms the same way when this key is null. None/[] = chaos off
    #   (injection points are a single global check)
    chaos_seed: int = 0  # seed for probabilistic ("p") chaos specs
    on_bad_record: str = "fail"  # data-fault policy for malformed input
    #   records (io/validate.py): "fail" keeps the legacy first-bad-record-
    #   raises behavior; "quarantine" resynchronizes at the next record and
    #   lands the bad bytes in a per-library quarantine.fastq.gz with
    #   machine-readable reasons in robustness_report.json; "drop" counts +
    #   reports without keeping the bytes. Truncated gzip and truncated
    #   final records become quarantine events instead of tracebacks.
    stage_timeout_s: float | None = None  # liveness watchdog
    #   (robustness/watchdog.py): base HARD deadline per pipeline stage,
    #   measured from the stage's last heartbeat and auto-scaled by
    #   workload size (base covers 1000 work units; larger workloads scale
    #   linearly — watchdog.scaled_timeout). At half the hard deadline a
    #   stall event + all-thread stack dump land in the robustness report /
    #   library log; at the hard deadline the stalled stage is cancelled
    #   with a StageTimeout, which retries as a transient fault. None
    #   (default) disarms the watchdog entirely (heartbeats are one global
    #   check). Size for the SLOWEST legitimate single dispatch including
    #   cold compiles — e.g. 600 for production lanes
    verify_resume: str = "fast"  # resume integrity checking against the
    #   v2 stage manifest's recorded artifact checksums (io/layout.py):
    #   "off" trusts the manifest mark alone (legacy blind-trust), "fast"
    #   (default) checks artifact byte sizes (catches truncation/missing
    #   files, ~free), "full" re-hashes sha256 (catches any bit rot). A
    #   failed/unverifiable stage (v1 manifest) warns and re-runs instead
    #   of resuming from garbage
    contracts: str = "warn"  # stage-boundary conservation contracts
    #   (robustness/contracts.py): "off" skips the checks, "warn" (default)
    #   logs + records violations in robustness_report.json, "strict"
    #   additionally fails the run on the first violation
    polish_bf16: bool = True  # allow bf16 polisher serving WHEN the
    #   per-backend exactness A/B artifact certifies identical consensus
    #   output (models/polisher.py bf16_serving_certified; generate with
    #   scripts/bf16_ab.py). Without a certifying artifact — or on the CPU
    #   backend, where XLA emulates bf16 slower than fp32 — serving stays
    #   fp32 regardless of this flag; False forces fp32 everywhere

    @property
    def cluster_identity(self) -> float:
        """Region-cluster threshold; reference tcr_consensus.py:68."""
        return 1.0 - self.max_ee_rate_base

    def primer_sequences(self) -> list[str]:
        """Primer set for the trim stage; [] when trimming is disabled."""
        if not self.trim_primers:
            return []
        from ont_tcrconsensus_tpu.io import fastx

        path = self.nanopore_tcr_seq_primers_fasta or DEFAULT_PRIMERS_FASTA
        return [rec.sequence for rec in fastx.read_fastx(path)]

    def validate(self) -> None:
        if not self.reference_file:
            raise ValueError("reference_file is required")
        if not self.fastq_pass_dir:
            raise ValueError("fastq_pass_dir is required")
        for name, lo, hi in (
            ("max_ee_rate_base", 0.0, 1.0),
            ("minimal_region_overlap", 0.0, 1.0),
            ("vsearch_identity", 0.0, 1.0),
            ("vsearch_identity_consensus", 0.0, 1.0),
            ("blast_id_threshold", 0.0, 1.0),                # nullable
            ("minimal_region_overlap_consensus", 0.0, 1.0),  # nullable
        ):
            v = getattr(self, name)
            if v is not None and not (lo <= v <= hi):
                raise ValueError(f"{name}={v} outside [{lo}, {hi}]")
        for name in ("dorado_trim_subsample_fastq",):  # nullable int
            v = getattr(self, name)
            if v is not None and (not isinstance(v, int) or v <= 0):
                raise ValueError(f"{name}={v!r} must be a positive int or null")
        if not isinstance(self.overlapping_umi_edit_threshold, int) or (
            self.overlapping_umi_edit_threshold < 0
        ):
            raise ValueError("overlapping_umi_edit_threshold must be a non-negative int")
        for name in (
            "minimal_length", "max_pattern_dist", "min_umi_length",
            "max_umi_length", "min_reads_per_cluster", "max_reads_per_cluster",
            "min_polish_depth",
            "umi_batch_size", "max_read_length",
            "max_softclip_5_end", "max_softclip_3_end",
        ):
            v = getattr(self, name)
            if not isinstance(v, int) or v < 0:
                raise ValueError(f"{name}={v!r} must be a non-negative int")
        if not isinstance(self.error_profile_sample, int) or self.error_profile_sample < 0:
            raise ValueError(
                f"error_profile_sample={self.error_profile_sample!r} must be a "
                "non-negative int"
            )
        for name in ("read_batch_size", "cluster_batch_size"):  # nullable int
            v = getattr(self, name)
            if v is not None and (not isinstance(v, int) or v <= 0):
                raise ValueError(f"{name}={v!r} must be a positive int or null")
        if self.hbm_budget_gb is not None and not (
            isinstance(self.hbm_budget_gb, (int, float)) and self.hbm_budget_gb > 0
        ):
            raise ValueError(
                f"hbm_budget_gb={self.hbm_budget_gb!r} must be a positive number or null"
            )
        if not (0.0 <= self.primer_max_dist_frac <= 1.0):
            raise ValueError(
                f"primer_max_dist_frac={self.primer_max_dist_frac} outside [0, 1]"
            )
        if not isinstance(self.trim_window, int) or self.trim_window <= 0:
            raise ValueError(f"trim_window={self.trim_window!r} must be a positive int")
        if (not isinstance(self.sw_band_width, int) or self.sw_band_width <= 0
                or self.sw_band_width % 128):
            raise ValueError(
                f"sw_band_width={self.sw_band_width!r} must be a positive "
                "multiple of 128 (TPU lane tiles)"
            )
        if self.trim_primers and self.nanopore_tcr_seq_primers_fasta:
            if not os.path.exists(self.nanopore_tcr_seq_primers_fasta):
                raise ValueError(
                    f"primers fasta not found: {self.nanopore_tcr_seq_primers_fasta}"
                )
        if self.min_umi_length > self.max_umi_length:
            raise ValueError("min_umi_length > max_umi_length")
        if self.min_reads_per_cluster > self.max_reads_per_cluster:
            raise ValueError("min_reads_per_cluster > max_reads_per_cluster")
        if not isinstance(self.retry_max_attempts, int) or self.retry_max_attempts < 1:
            raise ValueError(
                f"retry_max_attempts={self.retry_max_attempts!r} must be a "
                "positive int (1 = no retries)"
            )
        if not isinstance(self.retry_base_delay_s, (int, float)) or (
            self.retry_base_delay_s < 0
        ):
            raise ValueError(
                f"retry_base_delay_s={self.retry_base_delay_s!r} must be a "
                "non-negative number"
            )
        if self.chaos is not None:
            if not isinstance(self.chaos, list) or not all(
                isinstance(s, dict) for s in self.chaos
            ):
                raise ValueError("chaos must be null or a list of fault-spec dicts")
            from ont_tcrconsensus_tpu.robustness import faults as faults_mod

            for s in self.chaos:  # validates site/kind; typos fail fast
                faults_mod.FaultSpec(**s)
        if self.polish_method not in ("poa", "rnn"):
            raise ValueError(f"polish_method={self.polish_method!r} not in ('poa', 'rnn')")
        if self.on_bad_record not in ("fail", "quarantine", "drop"):
            raise ValueError(
                f"on_bad_record={self.on_bad_record!r} not in "
                "('fail', 'quarantine', 'drop')"
            )
        if self.contracts not in ("off", "warn", "strict"):
            raise ValueError(
                f"contracts={self.contracts!r} not in ('off', 'warn', 'strict')"
            )
        if self.stage_timeout_s is not None and not (
            isinstance(self.stage_timeout_s, (int, float))
            and self.stage_timeout_s > 0
        ):
            raise ValueError(
                f"stage_timeout_s={self.stage_timeout_s!r} must be a "
                "positive number or null (null = watchdog disarmed)"
            )
        if self.verify_resume not in ("off", "fast", "full"):
            raise ValueError(
                f"verify_resume={self.verify_resume!r} not in "
                "('off', 'fast', 'full')"
            )
        if self.executor not in ("graph", "imperative"):
            raise ValueError(
                f"executor={self.executor!r} not in ('graph', 'imperative')"
            )
        if self.telemetry not in ("off", "on", "full"):
            raise ValueError(
                f"telemetry={self.telemetry!r} not in ('off', 'on', 'full')"
            )
        if self.live_port is not None and (
            not isinstance(self.live_port, int)
            or isinstance(self.live_port, bool)
            or not (0 <= self.live_port <= 65535)
        ):
            raise ValueError(
                f"live_port={self.live_port!r} must be an int in [0, 65535] "
                "(0 = ephemeral) or null (null = live plane disarmed)"
            )
        if self.history_ledger is not None and (
            not isinstance(self.history_ledger, str) or not self.history_ledger
        ):
            raise ValueError(
                f"history_ledger={self.history_ledger!r} must be a non-empty "
                "path string or null"
            )
        if self.compile_cache_dir is not None and (
            not isinstance(self.compile_cache_dir, str)
            or not self.compile_cache_dir
        ):
            raise ValueError(
                f"compile_cache_dir={self.compile_cache_dir!r} must be a "
                "non-empty path string, \"off\" (cache disabled) or null "
                "(null = the default ~/.cache path)"
            )
        if not isinstance(self.serve_queue_max, int) or (
            isinstance(self.serve_queue_max, bool) or self.serve_queue_max < 1
        ):
            raise ValueError(
                f"serve_queue_max={self.serve_queue_max!r} must be a "
                "positive int"
            )
        if not isinstance(self.serve_workers, int) or (
            isinstance(self.serve_workers, bool) or self.serve_workers < 1
        ):
            raise ValueError(
                f"serve_workers={self.serve_workers!r} must be a "
                "positive int"
            )
        for pat_name in ("umi_fwd", "umi_rev"):
            pat = getattr(self, pat_name)
            if not pat or any(c not in "ACGTUNRYSWKMBDHV" for c in pat.upper()):
                raise ValueError(f"{pat_name}={pat!r} contains non-IUPAC characters")

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "RunConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        clean: dict[str, Any] = {}
        for k, v in d.items():
            if k in _COMPAT_IGNORED:
                continue
            if k not in known:
                raise ValueError(f"unknown config key: {k!r}")
            clean[k] = v
        cfg = cls(**clean)
        cfg.validate()
        return cfg

    @classmethod
    def from_json(cls, path: str | os.PathLike[str]) -> "RunConfig":
        with open(path) as fh:
            return cls.from_dict(json.load(fh))

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)
