"""cluster subpackage."""
