"""Reference self-homology mapping and region clustering.

TPU-native rebuild of the reference's PAF-driven region clustering
(/root/reference/ont_tcr_consensus/region_split.py:61-216 fed by
minimap2_align.py:40-73): reads are consensus-polished within groups of
indistinguishable reference regions, and the final blast-id filter defaults
to the *highest inter-reference similarity* so surviving consensus maps
uniquely (the pipeline's precision contract, SURVEY §3.2).

Pipeline here: hashed k-mer profile cosine matrix on the MXU (prefilter,
replaces minimap2 seeding) -> banded SW on the shortlisted pairs
(:mod:`..ops.sw_align`) -> the reference's own filters and greedy clustering,
replicated exactly:

- pairs kept iff alignment block length > 0.99 * min(len_a, len_b)
  (region_split.py:114-117),
- symmetric pairs deduplicated (:121-129),
- per query the most-similar partner by blast identity (:132-137),
- greedy clustering over tuples sorted by similarity desc (:61-82).

Divergence (documented): if NO pair survives the 0.99-overlap filter the
reference crashes on ``np.max([])`` (region_split.py:216); here the returned
``max_blast_id`` is None and the caller falls back to a configured default.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ont_tcrconsensus_tpu.ops import encode, sketch, sw_pallas

NEGATIVE_CONTROL_SUFFIXES = ("_v_n", "cdr3j_n", "full_n")  # region_split.py:305


@dataclasses.dataclass
class HomologyResult:
    region_cluster: dict[str, int]        # region name -> cluster index
    most_similar: list[tuple[str, str, float]]  # (query, partner, blast_id)
    max_blast_id: float | None            # the dynamic precision bar
    stats: dict[str, float]               # QC log values (SURVEY §5)


def greedy_most_similar_clustering(
    tuples: list[tuple[str, str, float]], similarity_threshold: float
) -> list[set[str]]:
    """Exact replica of the reference's greedy single-link pass
    (region_split.py:61-82), including its quirks: sub-threshold pairs of
    two unseen regions are skipped without marking them seen, and a pair
    touching an existing cluster joins the *first* cluster containing
    either region."""
    sorted_data = sorted(tuples, key=lambda x: x[2], reverse=True)
    clusters: list[set[str]] = []
    seen: set[str] = set()
    for a, b, sim in sorted_data:
        if a not in seen and b not in seen:
            if sim >= similarity_threshold:
                clusters.append({a, b})
                seen.update([a, b])
        elif a in seen or b in seen:
            for cluster in clusters:
                if a in cluster or b in cluster:
                    if sim >= similarity_threshold:
                        cluster.update([a, b])
                        seen.update([a, b])
                    break
    return clusters


def self_homology_map(
    reference: dict[str, str],
    cluster_threshold: float,
    prefilter_cosine: float = 0.12,
    band_width: int = 512,
    sketch_k: int = 8,
    sketch_dim: int = 4096,
    pair_batch: int = 256,
) -> HomologyResult:
    """All-vs-all reference homology -> region clusters + precision bar.

    Args:
      reference: {region name: sequence}.
      cluster_threshold: blast-id above which regions share a cluster
        (the reference passes 1 - max_ee_rate_base, tcr_consensus.py:68).
    """
    names = list(reference)
    seqs = [reference[n] for n in names]
    R = len(names)
    if R == 0:
        return HomologyResult({}, [], None, {"num_pairs_prefilter": 0})
    max_len = max(len(s) for s in seqs)
    codes, lens = encode.encode_batch(seqs, pad_to=max_len)
    profiles = sketch.kmer_profile(codes, lens, k=sketch_k, dim=sketch_dim)
    sim = np.asarray(sketch.similarity_matrix(profiles, profiles))

    ii, jj = np.where(np.triu(sim, k=1) >= prefilter_cosine)
    tuples: list[tuple[str, str, float]] = []
    if len(ii):
        # banded SW on the shortlist, batched
        blast_ids = np.zeros(len(ii), dtype=np.float64)
        block_lens = np.zeros(len(ii), dtype=np.int64)
        offs = sketch.diag_offset(lens[ii], lens[jj]).astype(np.int32)
        for s in range(0, len(ii), pair_batch):
            sl = slice(s, min(s + pair_batch, len(ii)))
            res = sw_pallas.align_banded_auto(
                codes[ii[sl]], lens[ii[sl]], codes[jj[sl]], lens[jj[sl]],
                offs[sl], band_width=band_width,
            )
            blast_ids[sl] = np.asarray(res.blast_id)
            block_lens[sl] = np.asarray(res.n_cols)
        # reference filter: alignment block > 0.99 * min length
        min_len = np.minimum(lens[ii], lens[jj])
        keep = block_lens > 0.99 * min_len
        # per query (smaller index plays minimap2's query role) keep the
        # most-similar partner (region_split.py:132-137)
        best: dict[int, tuple[int, float]] = {}
        for qi, ti, bid in zip(ii[keep], jj[keep], blast_ids[keep]):
            cur = best.get(qi)
            if cur is None or bid > cur[1]:
                best[qi] = (ti, bid)
        tuples = [(names[q], names[t], float(b)) for q, (t, b) in sorted(best.items())]

    clusters = greedy_most_similar_clustering(tuples, cluster_threshold)
    region_cluster: dict[str, int] = {}
    idx = 0
    for cl in clusters:
        for region in cl:
            region_cluster[region] = idx
        idx += 1
    for region in names:  # singletons, in reference order
        if region not in region_cluster:
            region_cluster[region] = idx
            idx += 1

    bids = [t[2] for t in tuples]
    stats = {
        "num_pairs_prefilter": int(len(ii)),
        "num_most_similar_pairs": len(tuples),
        "num_region_clusters": idx,
    }
    if bids:
        stats.update({
            "median_blast_id": float(np.median(bids)),
            "q925_blast_id": float(np.quantile(bids, 0.925)),
            "q950_blast_id": float(np.quantile(bids, 0.950)),
            "q975_blast_id": float(np.quantile(bids, 0.975)),
            "q990_blast_id": float(np.quantile(bids, 0.990)),
            "max_blast_id": float(np.max(bids)),
        })
    return HomologyResult(
        region_cluster=region_cluster,
        most_similar=tuples,
        max_blast_id=float(np.max(bids)) if bids else None,
        stats=stats,
    )


def region_length_dict(reference: dict[str, str]) -> dict[str, int]:
    """region_split.py:52-58 analogue."""
    return {name: len(seq) for name, seq in reference.items()}


def countable_regions(reference: dict[str, str]) -> set[str]:
    """Regions that count toward detection stats — negative controls
    excluded (region_split.py:302-309)."""
    return {n for n in reference if not n.endswith(NEGATIVE_CONTROL_SUFFIXES)}
