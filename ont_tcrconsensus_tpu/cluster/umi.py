"""Greedy centroid UMI clustering driven by device distance batches.

TPU-native replacement for ``vsearch --cluster_fast`` on combined UMIs
(/root/reference/ont_tcr_consensus/vsearch_umi_cluster.py:21-54 round 1 at
id 0.93, :59-97 round 2 at id 0.97). vsearch's exact behavior is
input-order- and heuristic-dependent (kmer-ranked candidate scan,
maxaccepts/maxrejects); SURVEY §7 "hard parts" #1 allows an equivalent,
*deterministic* policy with equivalence asserted at the UMI-counts level:

1. exact-duplicate UMIs collapse first (hash map, host);
2. unique UMIs get k-mer count profiles; a tiled MXU matmul ranks the
   ``shortlist_k`` nearest uniques per unique (replaces vsearch's kmer
   prefilter);
3. batched budgeted-dovetail edit distances (:mod:`..ops.edit_distance`
   ``pairwise_dovetail`` — terminal gaps free up to 8 nt, matching
   vsearch's free end gaps so UMI-extraction boundary fuzz never splits a
   molecule) refine the shortlist into an identity graph;
4. clusters = connected components of the >=identity graph, numbered by
   their best-ranked member in vsearch's processing order (length desc,
   then first-occurrence asc — cluster_fast's length sort), which also
   names the component's centroid.

Identity = 1 - d_dovetail/max(len_a, len_b) (documented divergences from
vsearch: free terminal gaps up to 8 nt — see edit_distance module
docstring — and transitive closure instead of vsearch's centroid-star
assignment). Components are the robust reading of the 0.93 contract: a
centroid-star splits a molecule whose longest (centroid) read is
error-rich even though every member pair clears the threshold, silently
dropping thin molecules below min_reads_per_cluster; with inter-molecule
UMI identities far below threshold (~0.6 on 64 nt random UMIs, audited by
the cross-region UMI overlap check), transitive merging cannot join
distinct molecules but always heals star fragmentation.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ont_tcrconsensus_tpu.obs import device as obs_device
from ont_tcrconsensus_tpu.ops import edit_distance, encode, sketch


@dataclasses.dataclass
class UmiClusters:
    labels: np.ndarray            # (N,) int32 cluster id per input sequence
    num_clusters: int
    centroid_of: np.ndarray       # (num_clusters,) input index of each centroid

    def members(self, cluster_id: int) -> np.ndarray:
        return np.where(self.labels == cluster_id)[0]


def _dedup(umis: list[str]) -> tuple[list[str], np.ndarray]:
    """Collapse exact duplicates; returns (uniques, inverse)."""
    first_idx: dict[str, int] = {}
    uniq: list[str] = []
    inverse = np.zeros(len(umis), dtype=np.int32)
    for i, u in enumerate(umis):
        j = first_idx.get(u)
        if j is None:
            j = len(uniq)
            first_idx[u] = j
            uniq.append(u)
        inverse[i] = j
    return uniq, inverse


def _finish(ulabels, centroids, inverse, N: int) -> UmiClusters:
    """Map unique-level labels/centroids back to input indices."""
    labels = ulabels[inverse]
    U = int(inverse.max()) + 1 if N else 0
    uniq_to_input = np.full(U, -1, dtype=np.int32)
    for i in range(N):
        j = inverse[i]
        if uniq_to_input[j] < 0:
            uniq_to_input[j] = i
    return UmiClusters(
        labels=labels.astype(np.int32),
        num_clusters=int(labels.max()) + 1 if N else 0,
        centroid_of=uniq_to_input[centroids],
    )


def cluster_umis(
    umis: list[str],
    identity_threshold: float,
    shortlist_k: int = 32,
    kmer_k: int = 4,
    pair_batch: int = 65536,
    pad_width: int = 128,
    mesh=None,
) -> UmiClusters:
    """Cluster combined-UMI strings; returns per-input labels.

    Deterministic for a fixed input list. Centroid ids are dense, ordered by
    creation (vsearch writes clusters in the same creation order).
    """
    N = len(umis)
    if N == 0:
        return UmiClusters(np.zeros(0, np.int32), 0, np.zeros(0, np.int32))

    uniq, inverse = _dedup(umis)
    U = len(uniq)

    codes, lens = encode.encode_batch(uniq, pad_to=pad_width)
    order = sorted(range(U), key=lambda u: (-len(uniq[u]), u))

    if U == 1:
        ulabels = np.zeros(1, np.int32)
        centroids = np.array([0], np.int32)
    elif U <= _FULL_MATRIX_MAX:
        # small sets (the per-region round-2 dedup case): ONE device dispatch
        # computes the full identity matrix — exact (no shortlist, so no
        # merge-repair pass) and ~6x fewer dispatches, which dominates cost
        # at this size
        neigh_idx, neigh_ident = _full_identities(codes, lens, mesh=mesh)
        ulabels, centroids = _greedy_assign(order, neigh_idx, neigh_ident, identity_threshold)
    else:
        neigh_idx, neigh_ident = _neighbor_identities(
            codes, lens, shortlist_k=shortlist_k, kmer_k=kmer_k,
            pair_batch=pair_batch, mesh=mesh,
        )
        ulabels, centroids = _greedy_assign(order, neigh_idx, neigh_ident, identity_threshold)
        ulabels, centroids = _merge_close_centroids(
            ulabels, centroids, codes, lens, identity_threshold,
            shortlist_k=shortlist_k, kmer_k=kmer_k, pair_batch=pair_batch,
            mesh=mesh,
        )

    return _finish(ulabels, centroids, inverse, N)


def cluster_umis_grouped(
    umi_groups: list[list[str]],
    identity_threshold: float,
    shortlist_k: int = 32,
    kmer_k: int = 4,
    pair_batch: int = 65536,
    pad_width: int = 128,
    mesh=None,
) -> list[UmiClusters]:
    """Cluster MANY independent UMI sets with a handful of device dispatches.

    The pipeline clusters UMIs once per region cluster (round 1) and once
    per region (round 2) — dozens to hundreds of small independent calls,
    each paying dispatch latency (decisive over a tunneled TPU). This
    batches them: one global unique set, ONE shortlist + exact-distance
    pass over all groups together, then per-group host-side component
    assignment. Cross-group identities are masked to -1 before any edge is
    formed, so results are exactly per-group. The shortlist needs no
    group-awareness: same-molecule variants (the >=0.93 pairs) always
    outrank random UMIs in k-mer dot product, whichever group those random
    UMIs come from.

    Returns one :class:`UmiClusters` per input group, identical to calling
    :func:`cluster_umis` per group whenever the per-group shortlist would
    have found the same >=threshold neighbors (asserted by tests).
    """
    n_groups = len(umi_groups)
    results: list[UmiClusters | None] = [None] * n_groups

    # dedup per group, concatenate uniques
    g_uniq: list[list[str]] = []
    g_inv: list[np.ndarray] = []
    offsets = [0]
    for umis in umi_groups:
        uniq, inverse = _dedup(umis)
        g_uniq.append(uniq)
        g_inv.append(inverse)
        offsets.append(offsets[-1] + len(uniq))
    U_all = offsets[-1]
    if U_all == 0:
        return [
            UmiClusters(np.zeros(0, np.int32), 0, np.zeros(0, np.int32))
            for _ in umi_groups
        ]
    all_uniq = [u for uniq in g_uniq for u in uniq]
    gid = np.zeros(U_all, np.int32)
    for g in range(n_groups):
        gid[offsets[g]:offsets[g + 1]] = g
    codes, lens = encode.encode_batch(all_uniq, pad_to=pad_width)

    def masked_neighbors(codes, lens, gid):
        """Global neighbor lists with cross-group identities forced to -1."""
        U = codes.shape[0]
        if U == 1:
            return np.zeros((1, 0), np.int32), np.zeros((1, 0), np.float32)
        if U <= _FULL_MATRIX_MAX:
            neigh, ident = _full_identities(codes, lens, mesh=mesh)
        else:
            neigh, ident = _neighbor_identities(
                codes, lens, shortlist_k=shortlist_k, kmer_k=kmer_k,
                pair_batch=pair_batch, mesh=mesh,
            )
        ident = np.where(gid[neigh] == gid[:, None], ident, -1.0)
        return neigh, ident

    neigh, ident = masked_neighbors(codes, lens, gid)
    used_shortlist = U_all > _FULL_MATRIX_MAX

    def local_rows(neigh, ident, s, e):
        """Remap global neighbor rows [s:e) to group-local indices (cross-
        group entries point at local 0 with ident already -1)."""
        nl = neigh[s:e] - s
        il = ident[s:e]
        out_of_group = (nl < 0) | (nl >= e - s)
        nl = np.where(out_of_group, 0, nl).astype(np.int32)
        il = np.where(out_of_group, -1.0, il)
        return nl, il

    # per-group greedy assignment (host only)
    per_group: list[tuple[np.ndarray, np.ndarray]] = []
    for g in range(n_groups):
        s, e = offsets[g], offsets[g + 1]
        Ug = e - s
        if Ug == 0:
            per_group.append((np.zeros(0, np.int32), np.zeros(0, np.int32)))
            continue
        if Ug == 1:
            per_group.append((np.zeros(1, np.int32), np.array([0], np.int32)))
            continue
        nl, il = local_rows(neigh, ident, s, e)
        order = sorted(range(Ug), key=lambda u: (-len(g_uniq[g][u]), u))
        labels_g, cents_g = _greedy_assign(order, nl, il, identity_threshold)
        per_group.append((labels_g, cents_g))

    if used_shortlist:
        # batched merge-repair: ONE neighbor pass over all groups' centroids
        cent_global = np.concatenate([
            per_group[g][1] + offsets[g] for g in range(n_groups)
        ]).astype(np.int32)
        c_offsets = [0]
        for g in range(n_groups):
            c_offsets.append(c_offsets[-1] + len(per_group[g][1]))
        c_gid = gid[cent_global]
        c_neigh, c_ident = masked_neighbors(
            codes[cent_global], lens[cent_global], c_gid
        )
        for g in range(n_groups):
            s, e = c_offsets[g], c_offsets[g + 1]
            if e - s <= 1:
                continue
            nl, il = local_rows(c_neigh, c_ident, s, e)
            labels_g, cents_g = per_group[g]
            labels_g, cents_g = _merge_from_ident(
                labels_g, cents_g, nl, il, identity_threshold
            )
            per_group[g] = (labels_g, cents_g)

    for g in range(n_groups):
        labels_g, cents_g = per_group[g]
        results[g] = _finish(labels_g, cents_g, g_inv[g], len(umi_groups[g]))
    return results


_PAIR_CHUNK = 8192  # fixed device-dispatch shape for the exact-distance pass
# Below this, ONE full-matrix dispatch beats the shortlist path's ~7 device
# round-trips: at U_pad=256 the (U_pad, U_pad) dovetail DP is 65k parallel
# lanes x 128 scan steps — milliseconds of well-shaped TPU work, vs hundreds
# of ms of dispatch latency for profile+topk+pairs+merge. Typical per-group
# UMI sets (round 1: ~one unique UMI per read in the group; round 2: one per
# molecule) sit well under this.
_FULL_MATRIX_MAX = 256


def _full_identities(codes, lens, mesh=None):
    """All-vs-all identities in one device dispatch (U <= _FULL_MATRIX_MAX).

    Returns (neigh (U, U-1), ident (U, U-1)): every other unique as a
    "neighbor", so :func:`_greedy_assign` sees the complete identity graph.
    U is padded to a power of two (16..256), bounding the kernel at five
    compile classes.
    """
    U = codes.shape[0]
    U_pad = _pow2_ceil(U)
    if U_pad > U:
        codes = np.concatenate(
            [codes, np.zeros((U_pad - U, codes.shape[1]), codes.dtype)]
        )
        lens = np.concatenate([lens, np.zeros(U_pad - U, lens.dtype)])
    # the blocking readback is the stage's device wait: time it under the
    # umi.distance site (credits the enclosing cluster.batched_dispatch
    # frame when the batched pass drives this)
    d = np.asarray(obs_device.timed_get(
        "umi.distance",
        edit_distance.many_vs_many_dovetail_auto(codes, lens, codes, lens,
                                                 mesh=mesh),
    )).astype(np.float32)[:U, :U]
    longest = np.maximum(lens[:U, None], lens[None, :U]).astype(np.float32)
    ident = 1.0 - d / np.maximum(longest, 1.0)
    cols = np.arange(U - 1)[None, :]
    rows = np.arange(U)[:, None]
    neigh = (cols + (cols >= rows)).astype(np.int32)  # skip the diagonal
    return neigh, np.take_along_axis(ident, neigh, axis=1)


def _pow2_ceil(n: int, lo: int = 16) -> int:
    from ont_tcrconsensus_tpu.io.bucketing import pow2_ceil

    return pow2_ceil(n, lo)


def _neighbor_identities(codes, lens, shortlist_k, kmer_k, pair_batch, mesh=None):
    """(U, K) nearest-unique shortlist + exact identities, device-computed.

    Every device call runs on power-of-two padded shapes (U padded with
    zero-length rows, the pair list padded to ``_PAIR_CHUNK`` multiples), so
    the jitted kernels compile once per size class instead of once per
    region/group cardinality — the UMI stage is called hundreds of times per
    library with different U. Padded rows are harmless by construction:
    zero profiles score 0 in the dot-product ranking (``lax.top_k`` ties
    prefer lower = real indices), and their identities are forced to -1
    below so they never produce graph edges.
    """
    U = codes.shape[0]
    U_pad = _pow2_ceil(U)
    K = min(shortlist_k, U_pad - 1)
    if U_pad > U:
        codes = np.concatenate(
            [codes, np.zeros((U_pad - U, codes.shape[1]), codes.dtype)]
        )
        lens = np.concatenate([lens, np.zeros(U_pad - U, lens.dtype)])
    profiles = np.asarray(sketch.kmer_profile(codes, lens, k=kmer_k, dim=None))
    # tiled top-(K+1) against all uniques; drop the self column vectorized:
    # each row holds at most one self hit, so skipping its position (or the
    # trailing extra column when absent) leaves exactly K entries
    neigh = np.zeros((U_pad, K), dtype=np.int32)
    tile = max(1, min(4096, U_pad))
    for s in range(0, U_pad, tile):
        e = min(s + tile, U_pad)
        idx = np.asarray(sketch.top_candidates(profiles[s:e], profiles, K + 1))
        rows = np.arange(s, e)[:, None]
        is_self = idx == rows
        self_pos = np.where(is_self.any(axis=1), is_self.argmax(axis=1), K)[:, None]
        cols = np.arange(K)[None, :]
        cols = cols + (cols >= self_pos)
        neigh[s:e] = np.take_along_axis(idx, cols, axis=1)
    neigh = neigh[:U]
    # exact distances on the (U * K) pair list, padded to full chunks
    qi = np.repeat(np.arange(U, dtype=np.int32), K)
    ti = neigh.reshape(-1)
    n_pairs = len(qi)
    chunk = min(_PAIR_CHUNK, pair_batch)
    n_padded = ((n_pairs + chunk - 1) // chunk) * chunk
    if n_padded > n_pairs:
        qi = np.concatenate([qi, np.zeros(n_padded - n_pairs, np.int32)])
        ti = np.concatenate([ti, np.zeros(n_padded - n_pairs, np.int32)])
    ident = np.zeros(n_padded, dtype=np.float32)
    for s in range(0, n_padded, chunk):
        sl = slice(s, s + chunk)
        d = np.asarray(obs_device.timed_get(
            "umi.distance",
            edit_distance.pairwise_dovetail_auto(
                codes[qi[sl]], lens[qi[sl]], codes[ti[sl]], lens[ti[sl]],
                mesh=mesh,
            ),
        )).astype(np.float32)
        longest = np.maximum(lens[qi[sl]], lens[ti[sl]]).astype(np.float32)
        ident[sl] = np.where(longest > 0, 1.0 - d / np.maximum(longest, 1.0), 0.0)
    ident = ident[:n_pairs].reshape(U, K)
    ident[neigh == np.arange(U)[:, None]] = -1.0  # safety: never self-join
    ident[neigh >= U] = -1.0  # padded rows never produce edges
    return neigh, ident


def _merge_close_centroids(labels, centroids, codes, lens, threshold,
                           shortlist_k, kmer_k, pair_batch, mesh=None):
    """Repair shortlist misses: no centroid may sit within the identity
    threshold of an earlier-created one.

    Under the full (shortlist-free) greedy policy that property holds by
    construction; a per-UMI shortlist of k nearest uniques can miss the true
    centroid and found a spurious cluster (VERDICT r1 weak #10). Verifying
    centroid-vs-centroid — a far smaller set, so its own shortlist is far
    denser — and union-merging any violating pair toward the earlier
    centroid restores the documented policy wherever the miss occurred.
    Labels are re-compacted in creation order of the surviving centroids.
    """
    C = len(centroids)
    if C <= 1:
        return labels, centroids
    ccodes, clens = codes[centroids], lens[centroids]
    if C <= _FULL_MATRIX_MAX:
        neigh, ident = _full_identities(ccodes, clens, mesh=mesh)
    else:
        neigh, ident = _neighbor_identities(
            ccodes, clens, shortlist_k=shortlist_k, kmer_k=kmer_k,
            pair_batch=pair_batch, mesh=mesh,
        )
    return _merge_from_ident(labels, centroids, neigh, ident, threshold)


def _merge_from_ident(labels, centroids, neigh, ident, threshold):
    """Union-merge centroids whose precomputed identities cross the
    threshold (the host half of :func:`_merge_close_centroids`; ``neigh``
    rows index into the centroid list)."""
    C = len(centroids)
    parent = np.arange(C)

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for j in range(C):
        over = ident[j] >= threshold
        if not over.any():
            continue
        i = int(neigh[j][over].min())  # earliest-created close centroid
        a, b = find(j), find(i)
        if a != b:
            parent[max(a, b)] = min(a, b)
    roots = np.array([find(j) for j in range(C)])
    if (roots == np.arange(C)).all():
        return labels, centroids
    # dense new ids in creation order of surviving roots
    surviving = np.unique(roots)
    new_id = np.full(C, -1, np.int32)
    new_id[surviving] = np.arange(len(surviving), dtype=np.int32)
    return new_id[roots[labels]], centroids[surviving]


def _greedy_assign(order, neigh_idx, neigh_ident, threshold):
    """Connected components of the >=threshold identity graph.

    Components (scipy C union-find) instead of a centroid-star scan; see
    the module docstring for why. Component ids are dense, ordered by each
    component's best-ranked member under ``order``; that member is also the
    component's centroid (vsearch names clusters after their longest
    member the same way)."""
    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import connected_components

    U, K = neigh_ident.shape
    src = np.repeat(np.arange(U, dtype=np.int32), K)
    dst = neigh_idx.reshape(-1)
    keep = neigh_ident.reshape(-1) >= threshold
    src, dst = src[keep], dst[keep]
    adj = coo_matrix(
        (np.ones(len(src), np.int8), (src, dst)), shape=(U, U)
    )
    _, comp = connected_components(adj, directed=True, connection="weak")

    labels = np.full(U, -1, dtype=np.int32)
    comp_id: dict[int, int] = {}
    centroids: list[int] = []
    for u in order:
        c = int(comp[u])
        cid = comp_id.get(c)
        if cid is None:
            cid = len(centroids)
            comp_id[c] = cid
            centroids.append(u)
        labels[u] = cid
    return labels, np.array(centroids, dtype=np.int32)
