"""Flax bidirectional-GRU consensus polisher (the medaka-RNN replacement).

The reference's precision stage is medaka's pileup-counts bi-GRU
(/root/reference/ont_tcr_consensus/medaka_polish.py:113-134, model
``r1041_e82_400bps_sup_v5.0.0``). medaka's pretrained weights target its own
feature encoding and basecaller error profile; this framework instead trains
the same architecture family *in-repo* on the simulator's error model
(:mod:`..io.simulator`) — documented divergence: weights are not ports, the
architecture (counts features -> stacked bi-GRU -> per-position class head)
is the medaka design.

Two heads per draft position (medaka's insert-column capability, folded
into one output):

- class head (5): 0-3 = true base A/C/G/T, 4 = deletion (the draft
  position is absent from the true sequence);
- insertion head (5): 0 = nothing inserted after this position,
  1-4 = a base (A/C/G/T) the draft MISSED after this position.

The insertion head is what makes the stage able to fix ONT's dominant
error — homopolymer run shrinkage — which no substitute/delete-only
polisher can repair (every subread under-calls the same run, so the vote
draft is short and the missing base must be re-inserted).

All shapes static: (batch, length, features) -> (batch, length, 10).
"""

from __future__ import annotations

import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ont_tcrconsensus_tpu.obs import device as obs_device
from ont_tcrconsensus_tpu.parallel.mesh import mesh_data_size

NUM_CLASSES = 5
NUM_INS_CLASSES = 5   # none / +A / +C / +G / +T
TOTAL_LOGITS = NUM_CLASSES + NUM_INS_CLASSES
FEATURE_DIM = 15     # see ops.consensus.pileup_features (v1-v3 weights)
# single source of truth lives next to the feature builder — the serving
# dispatch keys on it, so two drifting copies would silently mis-route
from ont_tcrconsensus_tpu.ops.consensus import FEATURE_DIM_V4  # noqa: E402


def params_feature_dim(params: dict) -> int:
    """The feature dim a params tree was trained for (embed kernel fan-in) —
    how serving picks the matching feature encoding per weights generation."""
    return int(np.asarray(params["embed"]["kernel"]).shape[0])


class BiGRU(nn.Module):
    """One bidirectional GRU layer; concatenates both directions."""

    hidden: int

    @nn.compact
    def __call__(self, x):
        fwd = nn.RNN(nn.GRUCell(self.hidden), name="fwd")(x)
        bwd = nn.RNN(nn.GRUCell(self.hidden), reverse=True, keep_order=True, name="bwd")(x)
        return jnp.concatenate([fwd, bwd], axis=-1)


class ConsensusPolisher(nn.Module):
    """medaka-class polisher: Dense -> 2x bi-GRU -> class + insertion heads."""

    hidden: int = 96
    num_layers: int = 2

    @nn.compact
    def __call__(self, feats):
        x = nn.Dense(self.hidden, name="embed")(feats)
        x = nn.gelu(x)
        for i in range(self.num_layers):
            x = BiGRU(self.hidden, name=f"bigru{i}")(x)
        return nn.Dense(TOTAL_LOGITS, name="head")(x)


def init_params(rng_seed: int = 0, length: int = 128,
                feature_dim: int = FEATURE_DIM) -> dict:
    model = ConsensusPolisher()
    rng = jax.random.PRNGKey(rng_seed)
    return model.init(rng, jnp.zeros((1, length, feature_dim)))["params"]


def _cast_bf16(tree):
    """Float leaves -> bf16 (ints/bools untouched)."""
    return jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
        tree,
    )


def apply_logits(params, feats: jax.Array, bf16: bool = False) -> jax.Array:
    """(B, L, F) -> (B, L, 10) logits: [:5] class head, [5:] insertion head.

    ``bf16`` runs the whole network (params + activations) in bfloat16 and
    casts the logits back to fp32 — the MXU serves bf16 matmuls at ~2x the
    fp32 rate on TPU. Serving uses it ONLY behind the exactness A/B gate
    (:func:`bf16_serving_certified`): the polisher's decisions are
    argmax/0.9-confidence thresholds, so bf16 logit noise only matters if
    it flips a decision, and the gate certifies on-backend that it does
    not (identical consensus output) before the fast path is allowed.
    """
    if bf16:
        logits = ConsensusPolisher().apply(
            {"params": _cast_bf16(params)},
            jnp.asarray(feats).astype(jnp.bfloat16),
        )
        return logits.astype(jnp.float32)
    return ConsensusPolisher().apply({"params": params}, feats)


def polish_draft(
    params, feats: np.ndarray, draft: np.ndarray, draft_len: int,
    depth: np.ndarray | None = None,
    min_confidence: float = 0.9,
) -> tuple[np.ndarray, int]:
    """Apply the polisher to one draft: subs applied, deletions cut,
    confident insertions spliced in.

    Args:
      feats: (L, F) pileup features (ops.consensus.pileup_features).
      draft: (L,) dense codes; draft_len: true length.
      depth: (L,) pileup depth; positions with no coverage keep the draft
        base verbatim (the model has no evidence there).
      min_confidence: the model only overrides the draft where its softmax
        probability exceeds this — a polisher must never be worse than doing
        nothing, so low-confidence disagreements defer to the vote consensus
        (medaka imposes the same property through sheer training scale).

    Returns (polished codes padded to 2*L, new length).
    """
    from ont_tcrconsensus_tpu.ops.encode import PAD_CODE

    logits = np.asarray(apply_logits(params, jnp.asarray(feats)[None, :, :]))[0]
    cls, ins = logits[:, :NUM_CLASSES], logits[:, NUM_CLASSES:]

    def softmax_conf(lg):
        p = np.exp(lg - lg.max(axis=-1, keepdims=True))
        p /= p.sum(axis=-1, keepdims=True)
        return lg.argmax(axis=-1).astype(np.uint8), p.max(axis=-1)

    pred, conf = softmax_conf(cls)
    ins_pred, ins_conf = softmax_conf(ins)
    L = draft.shape[0]
    in_draft = np.arange(L) < int(draft_len)
    covered = in_draft if depth is None else (in_draft & (np.asarray(depth) > 0))
    apply = covered & (conf >= min_confidence)
    base = np.where(apply, pred, draft)
    keep = in_draft & ~(apply & (pred == 4))
    do_ins = covered & (ins_conf >= min_confidence) & (ins_pred > 0)
    slot_base = np.stack(
        [base, np.where(do_ins, ins_pred - 1, 0)], axis=1
    ).reshape(-1)
    slot_keep = np.stack([keep, do_ins], axis=1).reshape(-1)
    kept = slot_base[slot_keep].astype(np.uint8)
    out = np.full((2 * L,), PAD_CODE, np.uint8)
    out[: kept.size] = kept
    return out, int(kept.size)


def _logits_to_preds(params, feats, base_at, bf16=False):
    from ont_tcrconsensus_tpu.ops import pileup as pileup_mod

    logits = apply_logits(params, feats, bf16=bf16)  # (C, W, 10)
    cls, ins = logits[..., :NUM_CLASSES], logits[..., NUM_CLASSES:]
    probs = jax.nn.softmax(cls, axis=-1)
    pred = jnp.argmax(cls, axis=-1).astype(jnp.uint8)
    conf = jnp.max(probs, axis=-1)
    ins_probs = jax.nn.softmax(ins, axis=-1)
    ins_pred = jnp.argmax(ins, axis=-1).astype(jnp.uint8)
    ins_conf = jnp.max(ins_probs, axis=-1)
    depth = jnp.sum(base_at != pileup_mod.UNCOVERED, axis=1)
    return pred, conf, depth, ins_pred, ins_conf


def _polish_from_pileup(params, base_at, ins_cnt, ins_base, drafts,
                        bf16=False):
    """(C,S,W) pileup columns -> (pred, conf, depth, ins_pred, ins_conf)."""
    from ont_tcrconsensus_tpu.ops import consensus as consensus_mod

    feats = jax.vmap(consensus_mod.pileup_features)(
        base_at, ins_cnt, ins_base, drafts
    )
    return _logits_to_preds(params, feats, base_at, bf16=bf16)


def _polish_from_pileup_v4(params, base_at, ins_cnt, ins_base, pos_at,
                           drafts, quals, is_rev, bf16=False):
    """v4 twin of :func:`_polish_from_pileup`: strand + quality features.

    Extra args: ``pos_at`` (C,S,W) from the traceback, ``quals`` (C,S,W)
    uint8 phred in canonical orientation, ``is_rev`` (C,S) bool.
    """
    from ont_tcrconsensus_tpu.ops import consensus as consensus_mod

    feats = jax.vmap(consensus_mod.pileup_features_v4)(
        base_at, ins_cnt, ins_base, drafts, pos_at, quals, is_rev
    )
    return _logits_to_preds(params, feats, base_at, bf16=bf16)


def _device_polish_batch(params, sub, lens, drafts, dlens, band_width,
                         mesh=None, quals=None, is_rev=None, bf16=False):
    """(C,S,W) cluster tile -> (pred (C,W), confidence (C,W), depth (C,W)).

    One pileup + one RNN dispatch for the whole tile — the batched medaka
    pass (medaka_polish.py:95-144 analogue, without the per-cluster
    subprocess fan-out the reference schedules around). ``mesh`` shards the
    pileup lanes and the RNN's cluster axis over its ``data`` axis.
    ``quals``/``is_rev`` non-None routes the v4 feature encoding.
    """
    from ont_tcrconsensus_tpu.ops import pileup as pileup_mod

    base_at, ins_cnt, ins_base, pos_at, _ = pileup_mod.pileup_columns_batch_auto(
        sub, lens, drafts, dlens, band_width=band_width,
        out_len=drafts.shape[1], mesh=mesh,
    )
    if quals is not None:
        if mesh is not None:
            return _sharded_polish_from_pileup_v4(mesh, bf16)(
                params, base_at, ins_cnt, ins_base, pos_at, drafts,
                quals, is_rev,
            )
        return _polish_from_pileup_v4_jit(
            params, base_at, ins_cnt, ins_base, pos_at, drafts, quals,
            is_rev, bf16=bf16,
        )
    if mesh is not None:
        return _sharded_polish_from_pileup(mesh, bf16)(
            params, base_at, ins_cnt, ins_base, drafts
        )
    return _polish_from_pileup_jit(
        params, base_at, ins_cnt, ins_base, drafts, bf16=bf16
    )


_device_polish_batch_jit = jax.jit(
    _device_polish_batch, static_argnames=("band_width", "bf16")
)
_polish_from_pileup_jit = jax.jit(
    _polish_from_pileup, static_argnames=("bf16",)
)
_polish_from_pileup_v4_jit = jax.jit(
    _polish_from_pileup_v4, static_argnames=("bf16",)
)


import functools as _functools  # noqa: E402


@_functools.lru_cache(maxsize=None)
def _sharded_polish_from_pileup(mesh, bf16=False, donate=False):
    """Cluster-axis-sharded RNN serving (params replicated; no collectives).

    ``donate`` hands the drafts upload (arg 4) to XLA: ``pred`` shares
    its (C, W) uint8 shape, so the serving output reuses the input
    buffer's HBM in place. Callers donate only fresh per-call uploads.
    """
    from ont_tcrconsensus_tpu.parallel.mesh import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P

    d = P("data")
    kw = {"donate_argnums": (4,)} if donate else {}
    return jax.jit(shard_map(
        _functools.partial(_polish_from_pileup, bf16=bf16), mesh=mesh,
        in_specs=(P(), d, d, d, d), out_specs=(d,) * 5,
        check_vma=False,
    ), **kw)


@_functools.lru_cache(maxsize=None)
def _sharded_polish_from_pileup_v4(mesh, bf16=False, donate=False):
    """v4 twin of :func:`_sharded_polish_from_pileup` (drafts is arg 5)."""
    from ont_tcrconsensus_tpu.parallel.mesh import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P

    d = P("data")
    kw = {"donate_argnums": (5,)} if donate else {}
    return jax.jit(shard_map(
        _functools.partial(_polish_from_pileup_v4, bf16=bf16), mesh=mesh,
        in_specs=(P(), d, d, d, d, d, d, d), out_specs=(d,) * 5,
        check_vma=False,
    ), **kw)


@_functools.lru_cache(maxsize=None)
def _donating_polish_from_pileup(bf16=False):
    """Unsharded serving with the drafts upload donated (arg 4 aliases
    the uint8 prediction plane)."""
    return jax.jit(_functools.partial(_polish_from_pileup, bf16=bf16),
                   donate_argnums=(4,))


@_functools.lru_cache(maxsize=None)
def _donating_polish_from_pileup_v4(bf16=False):
    """v4 twin of :func:`_donating_polish_from_pileup` (drafts is arg 5)."""
    return jax.jit(_functools.partial(_polish_from_pileup_v4, bf16=bf16),
                   donate_argnums=(5,))


def make_pipeline_polisher(params, band_width: int | None = None,
                           min_confidence: float = 0.9,
                           min_polish_depth: int = 4,
                           iterations: int = 1,
                           low_depth_params=None,
                           low_depth: int = 2,
                           bf16: bool = False):
    """Adapter for ``stages.polish_clusters_all(polisher=...)``.

    Returns f(sub (C,S,W), lens (C,S), drafts (C,W), dlens (C,),
    pileup=None) -> (polished (C,W), polished_lens (C,)): one device
    dispatch per cluster tile; the tiny splice of predicted deletions
    happens host-side. When the consensus stage hands over its final-round
    device pileup (the converged round's columns ARE the final draft's
    pileup), the polisher skips recomputing it — the single most expensive
    kernel in the polish path.

    ``min_polish_depth``: clusters with fewer live subreads keep their vote
    consensus untouched. The held-out precision-at-depth eval
    (models/weights/polisher_v3_eval.json) shows strong gains at depth >= 4
    in every regime (e.g. in-family 8.4% -> 33% exact at depth 4,
    43% -> 79% at 6) but a net-NEGATIVE depth-3 tradeoff off-distribution
    (its _meta records the eval gate) — the pileup carries too little
    evidence for a 0.9 gate there; medaka's own accuracy collapses in
    that regime too.

    ``iterations``: >1 re-piles the subreads against the POLISHED draft
    and applies the model again. Measured with the v3 weights (150
    clusters x depths 4/6/10 on hp_shift + in_family): the second pass
    moves exactness within noise (deltas <= +-0.03) at the cost of a
    full pileup recompute — the model converges in one pass, so the
    default stays 1. The knob remains for future model generations whose
    confident fixes might compound.

    ``low_depth_params``: optional weights for the depth-2 pass (the
    v4-family strand+quality encoding; in production the bundled v4
    generation serves here — a dedicated depth-2-only-trained specialist
    tied it within noise, see LOW_DEPTH_WEIGHTS).
    Clusters with EXACTLY ``low_depth`` live subreads — below the main
    gate, where vote consensus fails the round-2 blast-id bar ~99% of the
    time (weights/polisher_depth_gate_blastid.json) — get this model's
    predictions instead of keeping the raw vote; all other clusters are
    untouched. Both models share one pileup; the specialist costs one
    extra RNN dispatch per tile only when such clusters exist.

    ``bf16``: serve every RNN dispatch (main + specialist) in bfloat16.
    Callers must gate this on :func:`bf16_serving_certified` — the
    per-backend exactness A/B artifact that shows identical consensus
    output (run.py does; scripts/bf16_ab.py generates the artifact).
    """
    from ont_tcrconsensus_tpu.ops.consensus import POLISH_BAND_WIDTH, QUAL_FILL
    from ont_tcrconsensus_tpu.ops.encode import PAD_CODE

    default_band = POLISH_BAND_WIDTH if band_width is None else band_width
    # the weights generation decides the feature encoding: 25-dim params
    # serve pileup_features_v4 (strand + qual channels), 15-dim the v1 set
    wants_v4 = params_feature_dim(params) == FEATURE_DIM_V4
    low_v4 = (low_depth_params is not None
              and params_feature_dim(low_depth_params) == FEATURE_DIM_V4)
    need_v4 = wants_v4 or low_v4

    def polish(sub, lens, drafts, dlens, pileup=None, band_width=None,
               mesh=None, quals=None, strands=None, donate=False):
        for _ in range(max(int(iterations), 1)):
            drafts, dlens = _polish_once(
                sub, lens, drafts, dlens, pileup=pileup,
                band_width=band_width, mesh=mesh,
                quals=quals, strands=strands, donate=donate,
            )
            pileup = None  # later passes re-pile vs the new draft
        return drafts, dlens

    def _serve_from_pileup(p, v4, base_at, ins_cnt, ins_base, pos_at,
                           drafts_d, quals, strands, mesh, donate=False):
        if v4:
            if mesh is None:
                if donate:
                    return _donating_polish_from_pileup_v4(bf16)(
                        p, base_at, ins_cnt, ins_base, pos_at, drafts_d,
                        jnp.asarray(quals), jnp.asarray(strands),
                    )
                return _polish_from_pileup_v4_jit(
                    p, base_at, ins_cnt, ins_base, pos_at, drafts_d,
                    jnp.asarray(quals), jnp.asarray(strands), bf16=bf16,
                )
            return _sharded_polish_from_pileup_v4(mesh, bf16, donate)(
                p, base_at, ins_cnt, ins_base, pos_at, drafts_d,
                jnp.asarray(quals), jnp.asarray(strands),
            )
        if mesh is None:
            if donate:
                return _donating_polish_from_pileup(bf16)(
                    p, base_at, ins_cnt, ins_base, drafts_d
                )
            return _polish_from_pileup_jit(
                p, base_at, ins_cnt, ins_base, drafts_d, bf16=bf16
            )
        return _sharded_polish_from_pileup(mesh, bf16, donate)(
            p, base_at, ins_cnt, ins_base, drafts_d
        )

    def _polish_once(sub, lens, drafts, dlens, pileup=None, band_width=None,
                     mesh=None, quals=None, strands=None, donate=False):
        """``band_width`` is forwarded by the polish stage so recomputed
        pileups use the SAME band the consensus rounds (and any reused
        pileup) did — two knobs drifting apart would mix feature scales
        within one run. ``mesh`` shards the serving dispatches on the
        cluster axis (ignored when C doesn't divide its data axis).
        ``quals`` (C,S,W) phred / ``strands`` (C,S) bool-is-rev feed the
        v4 feature channels; with v4 weights but no quals (FASTA input)
        the QUAL_FILL constant stands in — the same fill a fraction of
        training examples used, so it stays in-distribution.
        ``donate`` (the graph-executor donation discipline) donates each
        serving dispatch's fresh drafts upload into its prediction
        output — every serve below does its own ``jnp.asarray(drafts)``
        from the numpy master, so main and low-depth serves each own the
        buffer they donate. Ignored on CPU (XLA:CPU doesn't honor
        donation and would warn per compile)."""
        donate = donate and jax.default_backend() != "cpu"
        if donate:
            # the donation safety argument requires a HOST master: each
            # serve's jnp.asarray(drafts) must be a fresh upload owning
            # its buffer. A device-resident drafts would alias one buffer
            # across both serves (and the np.asarray readback below).
            drafts = np.asarray(drafts)
        if mesh is not None and np.asarray(drafts).shape[0] % mesh_data_size(mesh):
            mesh = None
        live = (np.asarray(lens) > 0).sum(axis=1)
        low_mask = (
            (live == low_depth) if low_depth_params is not None
            else np.zeros(live.shape, bool)
        )
        if need_v4:
            if quals is None:
                quals = np.full(np.asarray(sub).shape, QUAL_FILL, np.uint8)
            if strands is None:
                strands = np.zeros(np.asarray(lens).shape, bool)
        if pileup is not None and need_v4 and pileup[3] is None:
            # the consensus stage kept the pileup without its pos_at plane
            # (keep_pos=False); v4's quality channels need it -> recompute
            pileup = None
        use_low = bool(low_mask.any())
        if pileup is None and use_low:
            # two models share ONE pileup: compute it unfused (the fused
            # pileup+RNN dispatch below can only serve one params tree)
            from ont_tcrconsensus_tpu.ops import pileup as pileup_mod

            ba, ic, ib, pa, _ = pileup_mod.pileup_columns_batch_auto(
                jnp.asarray(sub), jnp.asarray(lens), jnp.asarray(drafts),
                jnp.asarray(dlens),
                band_width=default_band if band_width is None else band_width,
                out_len=np.asarray(drafts).shape[1], mesh=mesh,
            )
            pileup = (ba, ic, ib, pa)
        if pileup is not None:
            base_at, ins_cnt, ins_base, pos_at = pileup
            out = _serve_from_pileup(
                params, wants_v4, base_at, ins_cnt, ins_base, pos_at,
                jnp.asarray(drafts), quals, strands, mesh, donate,
            )
            if use_low:
                out_low = _serve_from_pileup(
                    low_depth_params, low_v4, base_at, ins_cnt, ins_base,
                    pos_at, jnp.asarray(drafts), quals, strands, mesh,
                    donate,
                )
        elif mesh is not None:
            out = _device_polish_batch(
                params, jnp.asarray(sub), jnp.asarray(lens),
                jnp.asarray(drafts), jnp.asarray(dlens),
                default_band if band_width is None else band_width,
                mesh=mesh,
                quals=jnp.asarray(quals) if wants_v4 else None,
                is_rev=jnp.asarray(strands) if wants_v4 else None,
                bf16=bf16,
            )
        else:
            out = _device_polish_batch_jit(
                params, jnp.asarray(sub), jnp.asarray(lens),
                jnp.asarray(drafts), jnp.asarray(dlens),
                default_band if band_width is None else band_width,
                quals=jnp.asarray(quals) if wants_v4 else None,
                is_rev=jnp.asarray(strands) if wants_v4 else None,
                bf16=bf16,
            )
        # blocked seconds credit the enclosing polish.dispatch frame
        pred, conf, depth, ins_pred, ins_conf = obs_device.timed_get(
            "polisher.get", out
        )
        if use_low:
            # the depth-2 specialist's predictions replace the main
            # model's ONLY on exactly-low_depth clusters (blast-id
            # evidence: weights/polisher_depth_gate_blastid.json — vote
            # fails the 0.99 bar ~99% there, the v4-family specialist
            # recovers a real fraction; depth>=3 vote already passes, so
            # the pass cannot touch any other cluster)
            (pred_l, conf_l, _depth_l, ins_pred_l,
             ins_conf_l) = obs_device.timed_get("polisher.get", out_low)
            m = low_mask[:, None]
            pred = np.where(m, pred_l, pred)
            conf = np.where(m, conf_l, conf)
            ins_pred = np.where(m, ins_pred_l, ins_pred)
            ins_conf = np.where(m, ins_conf_l, ins_conf)
        drafts = np.asarray(drafts)
        dlens = np.asarray(dlens)
        C, W = drafts.shape
        pos = np.arange(W)
        out = np.full_like(drafts, PAD_CODE)
        out_lens = np.zeros_like(dlens)
        in_draft = pos[None, :] < dlens[:, None]
        deep_enough = (live >= min_polish_depth)[:, None] | low_mask[:, None]
        covered = in_draft & (depth > 0) & deep_enough
        apply = covered & (conf >= min_confidence)
        base = np.where(apply, pred, drafts)
        keep = in_draft & ~(apply & (pred == 4))
        do_ins = covered & (ins_conf >= min_confidence) & (ins_pred > 0)
        # interleave kept bases with confident insertions (slot 2j = draft
        # position j, slot 2j+1 = insertion after j), then compact. The
        # width is fixed: clusters that would overflow W keep their tail
        # un-inserted (the pileup band already bounds drift well below W).
        slot_base = np.stack(
            [base, np.where(do_ins, ins_pred - 1, 0)], axis=2
        ).reshape(C, 2 * W)
        slot_keep = np.stack([keep, do_ins], axis=2).reshape(C, 2 * W)
        for c in range(C):
            if dlens[c] == 0:
                continue
            kept = slot_base[c][slot_keep[c]].astype(np.uint8)[:W]
            out[c, : kept.size] = kept
            out_lens[c] = kept.size
        return out, out_lens

    # the polish stage keys keep_pos (whether the consensus rounds retain
    # the pos_at plane for the v4 quality channels) off this attribute;
    # the low-depth specialist is v4-family, so it needs pos_at too
    polish.wants_v4 = need_v4
    return polish


# ---------------------------------------------------------------------------
# training (in-repo, on the simulator's error model)


def cross_entropy_loss(params, feats, labels, ins_labels, mask):
    """Two-head loss: class (base/del) + insertion, both masked the same."""
    logits = apply_logits(params, feats)
    cls, ins = logits[..., :NUM_CLASSES], logits[..., NUM_CLASSES:]

    def ce(lg, lab):
        logp = jax.nn.log_softmax(lg)
        ll = jnp.take_along_axis(
            logp, lab[..., None].astype(jnp.int32), axis=-1
        )[..., 0]
        return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)

    return ce(cls, labels) + ce(ins, ins_labels)


def make_train_step(optimizer):
    """Returns a jittable (params, opt_state, batch) -> (params, opt_state, loss)."""

    def train_step(params, opt_state, feats, labels, ins_labels, mask):
        loss, grads = jax.value_and_grad(cross_entropy_loss)(
            params, feats, labels, ins_labels, mask
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    return train_step


def save_params(params, path: str) -> None:
    import flax.serialization

    with open(path, "wb") as fh:
        fh.write(flax.serialization.to_bytes(params))


def load_params(path: str) -> dict:
    import flax.serialization

    # msgpack_restore needs no shape template, so one loader serves every
    # weights generation (15-dim v1-v3 and 25-dim v4 alike); the embed
    # kernel's fan-in then tells serving which feature encoding to build
    # (params_feature_dim)
    with open(path, "rb") as fh:
        return flax.serialization.msgpack_restore(fh.read())


_WEIGHTS_DIR = os.path.join(os.path.dirname(__file__), "weights")
DEFAULT_WEIGHTS = os.path.join(_WEIGHTS_DIR, "polisher_v2.msgpack")
# Newest bundled generation wins — but only generations that EARNED it:
# v4 (strand+qual features, VERDICT r4 #6) measured EQUAL-or-worse to v3
# under the round-5 eval protocol (same oriented-read simulation for
# both): depth-4 held-out exactness within noise, depth-3/6 worse (it
# fires ~3x more, fixed AND broke both up; raising its confidence gate to
# 0.95 tames breaks but loses the fixes — weights/polisher_v4_eval*.json
# vs polisher_v3_eval_r5protocol.json). So v4 ships as a recorded
# experiment, NOT in the serving order; v3 (held-out-regime training,
# VERDICT r3 #3) remains the served generation.
_WEIGHT_PREFERENCE = (
    os.path.join(_WEIGHTS_DIR, "polisher_v3.msgpack"),
    DEFAULT_WEIGHTS,
)


def serving_weights_path() -> str:
    """The weights file the pipeline actually serves (newest existing
    generation; DEFAULT_WEIGHTS when none exists yet). train._main targets
    this by default so retraining can never silently write a file the
    pipeline ignores.

    Evidence gate: a v3+ generation is served only once its sibling
    ``*_eval.json`` exists — the training CLI writes weights first and the
    held-out eval afterwards, so a mid-training (or mid-session,
    unevaluated) weights file must not silently flip the whole pipeline's
    polisher. v2 predates the eval artifact and stays the ungated floor."""
    for path in _WEIGHT_PREFERENCE:
        if not os.path.exists(path):
            continue
        if path != DEFAULT_WEIGHTS:
            eval_json = os.path.splitext(path)[0] + "_eval.json"
            if not os.path.exists(eval_json):
                continue
        return path
    return DEFAULT_WEIGHTS


def load_default_params() -> dict | None:
    """Bundled in-repo weights (newest generation first), or None."""
    path = serving_weights_path()
    if os.path.exists(path):
        return load_params(path)
    return None


# The low-depth (exactly-2-subread) pass serves the v4 generation: its
# strand+quality channels are the right instrument precisely where two
# disagreeing reads leave quality as the only arbiter (it lost the MAIN
# serving slot on held-out exactness at depth>=4, but at depth 2 it cuts
# the vote's ~99% blast-id-bar failure rate to ~80-86%; a dedicated
# depth-2-only-trained specialist ties it within noise — both measured in
# the evidence artifact below).
LOW_DEPTH_WEIGHTS = os.path.join(_WEIGHTS_DIR, "polisher_v4.msgpack")
LOW_DEPTH_EVIDENCE = os.path.join(
    _WEIGHTS_DIR, "polisher_depth_gate_blastid.json"
)


def load_low_depth_params() -> dict | None:
    """Weights for the exactly-depth-2 polish pass, or None.

    Same evidence-gate discipline as the main generations: served only
    when the blast-id evidence artifact exists alongside the weights."""
    if os.path.exists(LOW_DEPTH_WEIGHTS) and os.path.exists(LOW_DEPTH_EVIDENCE):
        return load_params(LOW_DEPTH_WEIGHTS)
    return None


# ---------------------------------------------------------------------------
# bf16 serving gate (the same evidence-artifact discipline as the weights
# generations): the fast path is allowed only when an on-backend exactness
# A/B shows byte-identical consensus output.


def bf16_ab_artifact_path(backend: str) -> str:
    return os.path.join(_WEIGHTS_DIR, f"polisher_bf16_ab_{backend}.json")


def _current_low_depth_basename() -> str | None:
    """Basename of the low-depth specialist that would serve right now, or
    None — the A/B writer and the gate must agree on this so a specialist
    appearing (or changing) after certification invalidates the cert."""
    if os.path.exists(LOW_DEPTH_WEIGHTS) and os.path.exists(LOW_DEPTH_EVIDENCE):
        return os.path.basename(LOW_DEPTH_WEIGHTS)
    return None


def bf16_serving_certified(backend: str | None = None,
                           device_kind: str | None = None,
                           min_polish_depth: int = 4) -> bool:
    """True when bf16 RNN serving is allowed in the current environment
    (default: the live jax backend + device kind).

    Requires the backend's A/B artifact (:func:`run_bf16_exactness_ab`) to
    exist, certify ``identical: true``, and to have been produced against
    (a) the currently-served weights generation, (b) the currently-active
    low-depth specialist (including its absence — the specialist's RNN
    dispatch is part of the A/B only when it was live at capture time),
    (c) the same accelerator generation (``device_kind``) — bf16 rounding
    through a different MXU/compiler generation can flip a 0.9-confidence
    decision a v5e cert never exercised — and (d) the same serving gate
    config (``min_polish_depth``): a lowered depth gate serves the main
    RNN in depth regimes the A/B routed elsewhere. A retrain, a
    specialist change, a hardware change, or a gate-config change all
    force a re-certify. CPU is always False: XLA emulates bf16 there
    slower than fp32, so the fast path has nothing to win even when
    exact.
    """
    import json

    if backend is None:
        import jax

        backend = jax.default_backend()
        if device_kind is None:
            device_kind = jax.devices()[0].device_kind
    if backend == "cpu":
        return False
    path = bf16_ab_artifact_path(backend)
    if not os.path.exists(path):
        return False
    try:
        with open(path) as fh:
            rec = json.load(fh)
    except (OSError, ValueError):
        return False
    return (
        bool(rec.get("identical"))
        and rec.get("weights") == os.path.basename(serving_weights_path())
        and rec.get("low_depth_weights") == _current_low_depth_basename()
        and rec.get("min_polish_depth") == min_polish_depth
        and (device_kind is None or rec.get("device_kind") == device_kind)
    )


def run_bf16_exactness_ab(
    n_clusters: int = 96,
    depths: tuple[int, ...] = (2, 4, 6, 10),
    template_len: int = 1300,
    seed: int = 17,
    out_path: str | None = None,
    write: bool = True,
    min_polish_depth: int = 4,
) -> dict:
    """Exactness A/B: fp32 vs bf16 pipeline polisher on simulated clusters.

    Builds ``n_clusters`` clusters cycling over ``depths`` with the
    systematic ONT error model (the bench/eval regime), runs the FULL
    pipeline polisher (vote consensus -> RNN polish, low-depth specialist
    included when bundled) once in fp32 and once in bf16, and compares the
    polished (codes, lengths) byte-exactly.  Writes the per-backend gate
    artifact consumed by :func:`bf16_serving_certified` and returns it.

    The comparison is decision-level by construction: both runs share the
    identical vote consensus and pileup, so any divergence is exactly a
    bf16-flipped polisher decision — which is what the gate must exclude.
    """
    import json
    import time

    import jax

    from ont_tcrconsensus_tpu.io import simulator
    from ont_tcrconsensus_tpu.models import train
    from ont_tcrconsensus_tpu.ops import consensus, encode

    rng = np.random.default_rng(seed)
    err = (0.01, 0.004, 0.004)
    model = train.DEFAULT_ERROR_MODEL
    width = train._auto_width(template_len)
    s_max = max(depths)

    main_params = load_params(serving_weights_path())
    low_params = load_low_depth_params()

    def make_polisher(bf16):
        return make_pipeline_polisher(
            main_params, min_polish_depth=min_polish_depth,
            low_depth_params=low_params, low_depth=2, bf16=bf16,
        )

    codes = np.full((n_clusters, s_max, width), encode.PAD_CODE, np.uint8)
    lens = np.zeros((n_clusters, s_max), np.int32)
    quals = np.zeros((n_clusters, s_max, width), np.uint8)
    strands = np.zeros((n_clusters, s_max), bool)
    for c in range(n_clusters):
        depth = depths[c % len(depths)]
        template = simulator._rand_seq(rng, template_len)
        template_rc = simulator.revcomp(template)
        for i in range(depth):
            r, q, is_rev = train._simulate_oriented_read(
                rng, template, template_rc, err, model
            )
            codes[c, i, : len(r)] = r
            quals[c, i, : len(q)] = q
            lens[c, i] = len(r)
            strands[c, i] = is_rev
    drafts, dlens = consensus.consensus_clusters_batch(
        codes, lens, rounds=4, band_width=consensus.POLISH_BAND_WIDTH
    )
    drafts, dlens = np.asarray(drafts), np.asarray(dlens)

    out32, len32 = make_polisher(False)(
        codes, lens, drafts.copy(), dlens.copy(), quals=quals, strands=strands
    )
    out16, len16 = make_polisher(True)(
        codes, lens, drafts.copy(), dlens.copy(), quals=quals, strands=strands
    )
    mismatch = int(np.sum(
        (np.asarray(len32) != np.asarray(len16))
        | (np.asarray(out32) != np.asarray(out16)).any(axis=1)
    ))
    backend = jax.default_backend()
    rec = {
        "backend": backend,
        "device_kind": jax.devices()[0].device_kind,
        "weights": os.path.basename(serving_weights_path()),
        "low_depth_weights": _current_low_depth_basename(),
        "min_polish_depth": min_polish_depth,
        "identical": mismatch == 0,
        "n_clusters": n_clusters,
        "mismatched_clusters": mismatch,
        "depths": list(depths),
        "template_len": template_len,
        "seed": seed,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if write:
        path = out_path or bf16_ab_artifact_path(backend)
        with open(path, "w") as fh:
            json.dump(rec, fh, indent=1)
    return rec
