"""In-repo polisher training on the simulator's ONT error model.

The reference ships medaka's externally-trained weights; here training is
first-party (SURVEY §7 M3 adapted): examples are real pipeline states —
a low-depth vote consensus (which still carries residual errors) plus its
pileup features, labeled by aligning the true template to that draft. The
RNN learns exactly the residual error distribution the vote stage leaves.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np
import optax

from ont_tcrconsensus_tpu.io import simulator
from ont_tcrconsensus_tpu.models import polisher
from ont_tcrconsensus_tpu.ops import consensus, encode, pileup


@dataclasses.dataclass
class ExampleBatch:
    feats: np.ndarray   # (N, W, F)
    labels: np.ndarray  # (N, W) int32: 0-3 base, 4 deletion
    mask: np.ndarray    # (N, W) float32: 1 where supervised


def _auto_width(template_len: int) -> int:
    """Smallest power of two fitting the template plus indel growth and a
    vote-splice margin (>= template_len + 256)."""
    return 1 << (int(template_len) + 255).bit_length()


def make_examples(
    seed: int,
    n_examples: int,
    template_len: int = 256,
    depth_range: tuple[int, int] = (3, 6),
    err: tuple[float, float, float] = (0.03, 0.015, 0.015),
    width: int | None = None,
    band_width: int = consensus.POLISH_BAND_WIDTH,
) -> ExampleBatch:
    """Build supervised examples from simulated low-depth clusters.

    Labels: per draft position the true base (0-3) or 4 when the position is
    an erroneous insertion in the draft (true deletion). Positions the truth
    alignment does not cover are masked out.
    """
    if width is None:
        width = _auto_width(template_len)
    rng = np.random.default_rng(seed)
    feats_l, labels_l, mask_l = [], [], []
    for _ in range(n_examples):
        template = simulator._rand_seq(rng, template_len)
        depth = int(rng.integers(depth_range[0], depth_range[1] + 1))
        reads = []
        for _ in range(depth):
            s, _ = simulator.mutate(rng, template, *err)
            reads.append(encode.encode_seq(s))
        codes = np.full((depth, width), encode.PAD_CODE, np.uint8)
        lens = np.zeros(depth, np.int32)
        for i, r in enumerate(reads):
            codes[i, : len(r)] = r
            lens[i] = len(r)
        draft, draft_len = consensus.consensus_cluster(
            codes, lens, rounds=1, band_width=band_width, pad_to=width
        )
        if draft_len == 0:
            continue
        base_at, ins_cnt, _, _ = pileup.pileup_columns(
            codes, lens, jnp.asarray(draft), jnp.int32(draft_len),
            np.zeros(depth, np.int32), band_width=band_width, out_len=width,
        )
        feats = np.asarray(consensus.pileup_features(base_at, ins_cnt, draft))

        # label by aligning the truth to the draft
        truth = encode.encode_seq(template)
        tcodes = np.full((1, width), encode.PAD_CODE, np.uint8)
        tcodes[0, : len(truth)] = truth
        t_base, _, _, t_span = pileup.pileup_columns(
            tcodes, np.array([len(truth)], np.int32),
            jnp.asarray(draft), jnp.int32(draft_len),
            np.zeros(1, np.int32), band_width=band_width, out_len=width,
        )
        t_base = np.asarray(t_base)[0]
        labels = np.where(t_base == pileup.UNCOVERED, 0, t_base).astype(np.int32)
        mask = ((t_base != pileup.UNCOVERED) & (np.arange(width) < draft_len)).astype(np.float32)
        feats_l.append(feats)
        labels_l.append(labels)
        mask_l.append(mask)
    return ExampleBatch(
        feats=np.stack(feats_l), labels=np.stack(labels_l), mask=np.stack(mask_l)
    )


def train(
    steps: int = 300,
    batch_size: int = 16,
    lr: float = 1e-3,
    seed: int = 0,
    pool_examples: int = 192,
    template_len: int = 256,
    params=None,
    log_every: int = 50,
) -> tuple[dict, list[float]]:
    """Train the polisher; returns (params, loss trace)."""
    pool = make_examples(seed, pool_examples, template_len=template_len)
    if params is None:
        params = polisher.init_params(seed)
    optimizer = optax.adam(lr)
    opt_state = optimizer.init(params)
    step_fn = polisher.make_train_step(optimizer)
    import jax

    step_fn = jax.jit(step_fn)
    rng = np.random.default_rng(seed + 1)
    losses = []
    for s in range(steps):
        idx = rng.integers(0, pool.feats.shape[0], size=batch_size)
        params, opt_state, loss = step_fn(
            params, opt_state,
            jnp.asarray(pool.feats[idx]), jnp.asarray(pool.labels[idx]),
            jnp.asarray(pool.mask[idx]),
        )
        losses.append(float(loss))
        if log_every and s % log_every == 0:
            print(f"step {s}: loss {float(loss):.4f}")
    return params, losses


def evaluate_consensus_gain(
    params,
    seed: int = 101,
    n_clusters: int = 24,
    template_len: int = 1600,
    depths: tuple[int, ...] = (2, 3, 4, 6, 10),
    err: tuple[float, float, float] = (0.01, 0.004, 0.004),
    band_width: int = consensus.POLISH_BAND_WIDTH,
    min_confidence: float = 0.9,
) -> dict[int, dict[str, float]]:
    """Precision-at-depth, vote-only vs +RNN (VERDICT r1 item 10).

    For each subread depth: the fraction of simulated clusters whose
    consensus is bit-exact to the true template, (a) after the vote stage
    alone and (b) after the confidence-gated RNN pass — the same comparison
    the reference's estimate_precision_at_num_subreads tool makes from
    pipeline artifacts (minimap2_align.py:362-435), measured directly.
    """
    from ont_tcrconsensus_tpu.models.polisher import make_pipeline_polisher

    rng = np.random.default_rng(seed)
    width = _auto_width(template_len)
    polish = make_pipeline_polisher(params, band_width=band_width,
                                    min_confidence=min_confidence)
    out: dict[int, dict[str, float]] = {}
    for depth in depths:
        vote_ok = rnn_ok = 0
        for _ in range(n_clusters):
            template = simulator._rand_seq(rng, template_len)
            truth = encode.encode_seq(template)
            codes = np.full((1, depth, width), encode.PAD_CODE, np.uint8)
            lens = np.zeros((1, depth), np.int32)
            for i in range(depth):
                s, _ = simulator.mutate(rng, template, *err)
                r = encode.encode_seq(s)
                codes[0, i, : len(r)] = r
                lens[0, i] = len(r)
            drafts, dlens = consensus.consensus_clusters_batch(
                codes, lens, rounds=4, band_width=band_width
            )
            drafts, dlens = np.asarray(drafts), np.asarray(dlens)
            if dlens[0] == len(truth) and (drafts[0, : dlens[0]] == truth).all():
                vote_ok += 1
            pol, plens = polish(codes, lens, drafts, dlens)
            if plens[0] == len(truth) and (pol[0, : plens[0]] == truth).all():
                rnn_ok += 1
        out[depth] = {
            "n": n_clusters,
            "vote_exact": vote_ok / n_clusters,
            "rnn_exact": rnn_ok / n_clusters,
        }
    return out


def evaluate_accuracy(params, seed: int = 99, n_examples: int = 32) -> dict[str, float]:
    """Per-position accuracy of the polisher vs the raw draft on held-out data."""
    ex = make_examples(seed, n_examples)
    logits = np.asarray(polisher.apply_logits(params, jnp.asarray(ex.feats)))
    pred = logits.argmax(axis=-1)
    m = ex.mask > 0
    model_acc = float((pred[m] == ex.labels[m]).mean())
    # baseline: the draft itself (class = draft base, never deletion);
    # feats[..., 7:11] is the draft one-hot
    draft_base = ex.feats[..., 7:11].argmax(axis=-1)
    draft_is_base = ex.feats[..., 7:11].sum(axis=-1) > 0
    base_acc = float(
        ((draft_base[m] == ex.labels[m]) & draft_is_base[m]).mean()
    )
    return {"model_acc": model_acc, "draft_acc": base_acc}


def _main(argv=None) -> int:
    """``python -m ont_tcrconsensus_tpu.models.train``: retrain + evaluate.

    Trains at pipeline-realistic template lengths (the bundled v1 weights
    were trained at 256 nt; real TCR amplicons are 1.4-2.3 kb), writes the
    weights, and prints the vote-vs-RNN precision-at-depth table that
    justifies (or demotes) polish_method="rnn" as the default.
    """
    import argparse
    import json

    from ont_tcrconsensus_tpu.models.polisher import DEFAULT_WEIGHTS, save_params

    parser = argparse.ArgumentParser(description="Train the consensus polisher.")
    parser.add_argument("--steps", type=int, default=600)
    parser.add_argument("--template-len", type=int, default=1600)
    parser.add_argument("--pool-examples", type=int, default=128)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--out", default=DEFAULT_WEIGHTS)
    parser.add_argument("--eval-only", action="store_true")
    parser.add_argument("--eval-clusters", type=int, default=24)
    args = parser.parse_args(argv)

    if args.eval_only:
        from ont_tcrconsensus_tpu.models.polisher import load_params

        params = load_params(args.out)
    else:
        params, losses = train(
            steps=args.steps, batch_size=args.batch_size, seed=args.seed,
            pool_examples=args.pool_examples, template_len=args.template_len,
        )
        save_params(params, args.out)
        print(f"saved {args.out} (final loss {losses[-1]:.4f})")
    gain = evaluate_consensus_gain(
        params, template_len=args.template_len, n_clusters=args.eval_clusters
    )
    print(json.dumps(gain, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
