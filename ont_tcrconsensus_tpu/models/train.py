"""In-repo polisher training on the simulator's ONT error models.

The reference ships medaka's externally-trained weights; here training is
first-party (SURVEY §7 M3 adapted): examples are real pipeline states —
a low-depth vote consensus (which still carries residual errors) plus its
pileup features, labeled by aligning the true template to that draft. The
RNN learns exactly the residual error distribution the vote stage leaves.

Round-3 honesty fix (VERDICT r2 weak #3 / next #4): the round-2 eval
trained AND judged on the iid error model — the regime where majority
voting is already near-optimal, so "zero RNN gain" was circular. Training
and evaluation now default to the SYSTEMATIC :class:`..io.simulator.
OntErrorModel` (homopolymer-length-dependent indels, context-biased
substitutions, strand asymmetry — the errors medaka exists to fix), the
eval is n>=500 templates/depth, and it reports gate-fire rates (how many
positions the RNN actually changed) alongside exactness.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np
import optax

from ont_tcrconsensus_tpu.io import simulator
from ont_tcrconsensus_tpu.models import polisher
from ont_tcrconsensus_tpu.ops import consensus, encode, pileup

# training/eval default: the systematic error model at ONT-sup-like rates
DEFAULT_ERROR_MODEL = simulator.OntErrorModel()

# --- v3 de-circularization (VERDICT r3 #3) -------------------------------
# v2 trained AND evaluated on the same generative family (different seeds
# only), so the eval could not fail off-distribution. v3 trains on a
# RANDOMIZED family of parameterizations and evaluates on held-out regimes
# whose parameters (homopolymer slope/cap, context family, transition
# fraction) were never seen in training — plus the iid model, which shares
# NO structure with the training family.
TRAIN_REGIMES: tuple[simulator.OntErrorModel, ...] = (
    simulator.OntErrorModel(),
    simulator.OntErrorModel(sub_rate=0.009, ins_rate=0.003, del_rate=0.006),
    simulator.OntErrorModel(hp_slope=0.6, hp_cap=6.0),
    simulator.OntErrorModel(
        motif_sub_boost=(("GA", 2.0), ("CT", 3.5), ("TC", 1.5)),
        transition_frac=0.75,
    ),
    # v3.1: widen the homopolymer axis upward (held-out hp_shift at 1.6
    # exposed it as the weakest direction — every subread shrinks the same
    # run, the one error family voting cannot touch). 1.3 stays short of
    # the held-out 1.6 so the eval remains out-of-range on that axis.
    simulator.OntErrorModel(hp_slope=1.3, hp_cap=12.0, del_rate=0.005),
)

# held out: parameters OUTSIDE the training family's ranges/context sets
HELDOUT_REGIMES: dict[str, simulator.OntErrorModel | None] = {
    # stronger homopolymer shrinkage than any training regime
    "hp_shift": simulator.OntErrorModel(
        hp_slope=1.6, hp_cap=14.0, del_rate=0.006
    ),
    # a context-bias family disjoint from the training one, lower
    # transition fraction than any training regime
    "ctx_shift": simulator.OntErrorModel(
        motif_sub_boost=(("AG", 3.0), ("TG", 2.5), ("CA", 2.0)),
        transition_frac=0.4,
    ),
    # no systematic structure at all (legacy iid rates)
    "iid": None,
    # the v2 regime, kept for continuity with polisher_v2_eval.json
    "in_family": simulator.OntErrorModel(),
}


@dataclasses.dataclass
class ExampleBatch:
    feats: np.ndarray       # (N, W, F) — F=15 (v1) or 25 (v4 strand+qual)
    labels: np.ndarray      # (N, W) int32: 0-3 base, 4 deletion
    ins_labels: np.ndarray  # (N, W) int32: 0 none, 1-4 insert A/C/G/T after
    mask: np.ndarray        # (N, W) float32: 1 where supervised


def _auto_width(template_len: int) -> int:
    """Smallest power of two fitting the template plus indel growth and a
    vote-splice margin (>= template_len + 256)."""
    return 1 << (int(template_len) + 255).bit_length()


def _simulate_oriented_read(rng, template: str, template_rc: str, err,
                            error_model):
    """One subread the way the pipeline actually sees it: sequenced in a
    random orientation (systematic errors hit the SEQUENCED strand, like
    simulate_library), then flipped back to canonical (+) with its quals
    reversed — plus the (quals, is_rev) metadata the v4 features consume.

    Returns (codes uint8, quals uint8 phred, is_rev bool).
    """
    is_rev = bool(rng.random() < 0.5)
    src = template_rc if is_rev else template
    if error_model is not None:
        s, q = simulator.mutate_ont(rng, src, error_model)
    else:
        s, q = simulator.mutate(rng, src, *err)
    codes = encode.encode_seq(s)
    quals = (np.frombuffer(q.encode("ascii"), np.uint8).astype(np.int32) - 33)
    quals = np.clip(quals, 0, 255).astype(np.uint8)
    if is_rev:
        codes = encode.revcomp_codes(codes)
        quals = quals[::-1]
    return codes, quals, is_rev


def sample_depth(rng, depth_range: tuple[int, int],
                 depth_dist: str = "uniform") -> int:
    """``lowdepth`` concentrates 70% of examples at depth 2-4 — the regime
    where the lane-scale counts contract is lost (VERDICT r4 #2: the
    depth-2/3 molecule loss; medaka itself runs at --depth 2, ref
    medaka_polish.py:119-134) — with the rest uniform up to the max so
    deep clusters stay in-distribution."""
    lo, hi = depth_range
    low_band = [d for d in (2, 3, 4) if lo <= d <= hi]
    if depth_dist == "lowdepth" and low_band and hi >= 5:
        if rng.random() < 0.7:
            return int(rng.choice(low_band))
        return int(rng.integers(5, hi + 1))
    return int(rng.integers(lo, hi + 1))


def make_examples(
    seed: int,
    n_examples: int,
    template_len: int = 256,
    depth_range: tuple[int, int] = (2, 8),
    err: tuple[float, float, float] = (0.03, 0.015, 0.015),
    width: int | None = None,
    band_width: int = consensus.POLISH_BAND_WIDTH,
    error_model: simulator.OntErrorModel | None = DEFAULT_ERROR_MODEL,
    rounds: int = 4,
    err_weight: float = 50.0,
    error_models: tuple | None = None,
    features: str = "v1",
    qual_dropout: float = 0.15,
    depth_dist: str = "uniform",
) -> ExampleBatch:
    """Build supervised examples from simulated low-depth clusters.

    Labels: per draft position the true base (0-3), 4 when the position is
    an erroneous insertion in the draft (true deletion), and — from the
    truth alignment's insertion columns — the base the draft MISSED after
    each position (``ins_labels``). Positions the truth alignment does not
    cover are masked out. ``error_model=None`` falls back to the iid
    ``err`` rates (legacy mode, kept for ablations).

    Two round-3 honesty fixes (the v2.0 weights never fired their gate):

    - drafts come from CONVERGED vote consensus (``rounds=4``, what the
      pipeline serves the polisher), not round-1 drafts — the residual
      errors after convergence are the distribution the model must fix;
    - ``mask`` carries LOSS WEIGHTS, not just 0/1: positions where the
      draft disagrees with the truth (or misses an insertion) are ~1% of
      the mass, so an unweighted model learns to copy the draft with high
      confidence and the serving gate never fires. ``err_weight`` rebalances
      exactly those positions.

    v4 additions: subreads are sequenced in random orientation (systematic
    errors hit the sequenced strand) and ``features="v4"`` builds the
    25-channel strand+quality encoding; ``qual_dropout`` replaces a
    fraction of examples' quals with the QUAL_FILL constant so serving on
    FASTA input (no quals) stays in-distribution; ``depth_dist="lowdepth"``
    concentrates training at depth 2-4 (see :func:`sample_depth`).
    """
    if width is None:
        width = _auto_width(template_len)
    rng = np.random.default_rng(seed)
    feats_l, labels_l, ins_l, mask_l = [], [], [], []
    for n in range(n_examples):
        template = simulator._rand_seq(rng, template_len)
        template_rc = simulator.revcomp(template)
        depth = sample_depth(rng, depth_range, depth_dist)
        # v3 domain randomization: cycle the regime per example
        em = error_models[n % len(error_models)] if error_models else error_model
        codes = np.full((depth, width), encode.PAD_CODE, np.uint8)
        lens = np.zeros(depth, np.int32)
        quals = np.zeros((depth, width), np.uint8)
        strands = np.zeros(depth, bool)
        for i in range(depth):
            r, q, is_rev = _simulate_oriented_read(
                rng, template, template_rc, err, em
            )
            codes[i, : len(r)] = r
            quals[i, : len(q)] = q
            lens[i] = len(r)
            strands[i] = is_rev
        if rng.random() < qual_dropout:
            # the no-quals serving regime: constant fill on the real rows
            pos = np.arange(width)[None, :]
            quals = np.where(
                pos < lens[:, None], consensus.QUAL_FILL, 0
            ).astype(np.uint8)
        draft, draft_len = consensus.consensus_cluster(
            codes, lens, rounds=rounds, band_width=band_width, pad_to=width
        )
        if draft_len == 0:
            continue
        base_at, ins_cnt, ins_base, pos_at, _ = pileup.pileup_columns(
            codes, lens, jnp.asarray(draft), jnp.int32(draft_len),
            np.zeros(depth, np.int32), band_width=band_width, out_len=width,
        )
        if features == "v4":
            feats = np.asarray(consensus.pileup_features_v4(
                base_at, ins_cnt, ins_base, draft, pos_at,
                jnp.asarray(quals), jnp.asarray(strands),
            ))
        else:
            feats = np.asarray(
                consensus.pileup_features(base_at, ins_cnt, ins_base, draft)
            )

        # label by aligning the truth to the draft
        truth = encode.encode_seq(template)
        tcodes = np.full((1, width), encode.PAD_CODE, np.uint8)
        tcodes[0, : len(truth)] = truth
        t_base, t_ins_cnt, t_ins_base, _, _ = pileup.pileup_columns(
            tcodes, np.array([len(truth)], np.int32),
            jnp.asarray(draft), jnp.int32(draft_len),
            np.zeros(1, np.int32), band_width=band_width, out_len=width,
        )
        t_base = np.asarray(t_base)[0]
        t_ins_cnt = np.asarray(t_ins_cnt)[0]
        t_ins_base = np.asarray(t_ins_base)[0]
        labels = np.where(t_base == pileup.UNCOVERED, 0, t_base).astype(np.int32)
        ins_labels = np.where(
            (t_base != pileup.UNCOVERED) & (t_ins_cnt > 0),
            t_ins_base.astype(np.int32) + 1, 0,
        ).astype(np.int32)
        supervised = (t_base != pileup.UNCOVERED) & (np.arange(width) < draft_len)
        disagree = supervised & (
            (labels != draft[:width].astype(np.int32)) | (ins_labels > 0)
        )
        mask = np.where(
            disagree, float(err_weight), 1.0
        ).astype(np.float32) * supervised.astype(np.float32)
        feats_l.append(feats)
        labels_l.append(labels)
        ins_l.append(ins_labels)
        mask_l.append(mask)
    return ExampleBatch(
        feats=np.stack(feats_l), labels=np.stack(labels_l),
        ins_labels=np.stack(ins_l), mask=np.stack(mask_l),
    )


def train(
    steps: int = 300,
    batch_size: int = 16,
    lr: float = 1e-3,
    seed: int = 0,
    pool_examples: int = 192,
    template_len: int = 256,
    params=None,
    log_every: int = 50,
    error_model: simulator.OntErrorModel | None = DEFAULT_ERROR_MODEL,
    error_models: tuple | None = None,
    depth_range: tuple[int, int] = (2, 8),
    features: str = "v1",
    depth_dist: str = "uniform",
) -> tuple[dict, list[float]]:
    """Train the polisher; returns (params, loss trace)."""
    pool = make_examples(
        seed, pool_examples, template_len=template_len,
        error_model=error_model, error_models=error_models,
        depth_range=depth_range, features=features, depth_dist=depth_dist,
    )
    if params is None:
        params = polisher.init_params(
            seed, feature_dim=pool.feats.shape[-1]
        )
    optimizer = optax.adam(lr)
    opt_state = optimizer.init(params)
    step_fn = polisher.make_train_step(optimizer)
    import jax

    step_fn = jax.jit(step_fn)
    rng = np.random.default_rng(seed + 1)
    losses = []
    for s in range(steps):
        idx = rng.integers(0, pool.feats.shape[0], size=batch_size)
        params, opt_state, loss = step_fn(
            params, opt_state,
            jnp.asarray(pool.feats[idx]), jnp.asarray(pool.labels[idx]),
            jnp.asarray(pool.ins_labels[idx]), jnp.asarray(pool.mask[idx]),
        )
        losses.append(float(loss))
        if log_every and s % log_every == 0:
            print(f"step {s}: loss {float(loss):.4f}")
    return params, losses


def evaluate_consensus_gain(
    params,
    seed: int = 101,
    n_clusters: int = 500,
    template_len: int = 1600,
    depths: tuple[int, ...] = (2, 3, 4, 6, 10),
    err: tuple[float, float, float] = (0.01, 0.004, 0.004),
    band_width: int = consensus.POLISH_BAND_WIDTH,
    min_confidence: float = 0.9,
    error_model: simulator.OntErrorModel | None = DEFAULT_ERROR_MODEL,
    cluster_batch: int = 16,
    min_polish_depth: int = 4,
    polish_iterations: int = 1,
) -> dict[int, dict[str, float]]:
    """Precision-at-depth, vote-only vs +RNN, with gate-fire accounting.

    For each subread depth: the fraction of simulated clusters whose
    consensus is bit-exact to the true template, (a) after the vote stage
    alone and (b) after the confidence-gated RNN pass — the same comparison
    the reference's estimate_precision_at_num_subreads tool makes from
    pipeline artifacts (minimap2_align.py:362-435), measured directly.
    Also reported per depth (VERDICT r2 next #4 — the round-2 eval could
    not distinguish "the RNN is useless" from "the gate never fires"):

    - ``changed_frac``: clusters where the RNN changed >=1 position;
    - ``edits_per_cluster``: mean positions changed (sub+del+ins);
    - ``fixed``/``broke``: clusters the RNN moved exact->inexact and back.
    """
    from ont_tcrconsensus_tpu.models.polisher import make_pipeline_polisher

    rng = np.random.default_rng(seed)
    width = _auto_width(template_len)
    # default matches the SERVING gate (4) so a plain eval is comparable
    # with the bundled v2 tables; evaluate_regimes passes 3 explicitly to
    # MEASURE the depth-3 tradeoff and records it in _meta
    polish = make_pipeline_polisher(params, band_width=band_width,
                                    min_confidence=min_confidence,
                                    min_polish_depth=min_polish_depth,
                                    iterations=polish_iterations)
    out: dict[int, dict[str, float]] = {}
    for depth in depths:
        vote_ok = rnn_ok = changed = fixed = broke = 0
        edits = 0
        done = 0
        while done < n_clusters:
            cb = min(cluster_batch, n_clusters - done)
            truths = []
            codes = np.full((cb, depth, width), encode.PAD_CODE, np.uint8)
            lens = np.zeros((cb, depth), np.int32)
            quals = np.zeros((cb, depth, width), np.uint8)
            strands = np.zeros((cb, depth), bool)
            for c in range(cb):
                template = simulator._rand_seq(rng, template_len)
                template_rc = simulator.revcomp(template)
                truths.append(encode.encode_seq(template))
                for i in range(depth):
                    r, q, is_rev = _simulate_oriented_read(
                        rng, template, template_rc, err, error_model
                    )
                    codes[c, i, : len(r)] = r
                    quals[c, i, : len(q)] = q
                    lens[c, i] = len(r)
                    strands[c, i] = is_rev
            drafts, dlens = consensus.consensus_clusters_batch(
                codes, lens, rounds=4, band_width=band_width
            )
            drafts, dlens = np.asarray(drafts), np.asarray(dlens)
            pol, plens = polish(codes, lens, drafts, dlens,
                                quals=quals, strands=strands)
            for c in range(cb):
                truth = truths[c]
                v_ok = dlens[c] == len(truth) and (
                    drafts[c, : dlens[c]] == truth
                ).all()
                r_ok = plens[c] == len(truth) and (
                    pol[c, : plens[c]] == truth
                ).all()
                vote_ok += v_ok
                rnn_ok += r_ok
                same = plens[c] == dlens[c] and (
                    pol[c, : plens[c]] == drafts[c, : dlens[c]]
                ).all()
                if not same:
                    changed += 1
                    # rough edit count: length delta + mismatches on overlap
                    ov = min(int(plens[c]), int(dlens[c]))
                    edits += abs(int(plens[c]) - int(dlens[c])) + int(
                        (pol[c, :ov] != drafts[c, :ov]).sum()
                    )
                fixed += (not v_ok) and r_ok
                broke += v_ok and (not r_ok)
            done += cb
        out[depth] = {
            "n": int(n_clusters),
            "vote_exact": float(vote_ok / n_clusters),
            "rnn_exact": float(rnn_ok / n_clusters),
            "changed_frac": float(changed / n_clusters),
            "edits_per_cluster": float(edits / n_clusters),
            "fixed": int(fixed),
            "broke": int(broke),
        }
    return out


def evaluate_regimes(
    params,
    regimes: dict[str, simulator.OntErrorModel | None] = None,
    seed: int = 101,
    n_clusters: int = 250,
    template_len: int = 1600,
    depths: tuple[int, ...] = (2, 3, 4, 6, 10),
    min_confidence: float = 0.9,
    min_polish_depth: int = 3,
) -> dict:
    """Per-regime precision-at-depth tables on HELD-OUT error regimes.

    The v3 honesty contract (VERDICT r3 #3): the eval can fail — the
    regimes' parameters were never seen in training (hp_shift / ctx_shift)
    or share no structure with it at all (iid). Seeds differ per regime so
    templates are independent draws too. ``min_polish_depth`` defaults one
    BELOW the serving gate so the depth-3 rows measure the gate tradeoff;
    the gate used is recorded in the returned ``_meta``.
    """
    if regimes is None:
        regimes = HELDOUT_REGIMES
    # the gate parameters are part of the result's meaning: a v2-vs-v3
    # depth-3 comparison without this metadata would attribute the gate
    # delta to the weights (code-review r4)
    out: dict = {"_meta": {
        "min_polish_depth": min_polish_depth,
        "min_confidence": min_confidence,
        "n_clusters": n_clusters, "template_len": template_len,
        "note": "depth rows below the serving min_polish_depth (4) are "
                f"measured with the eval gate ({min_polish_depth}); "
                "serving keeps vote consensus there unless the config "
                "lowers the gate",
    }}
    for i, (name, model) in enumerate(sorted(regimes.items())):
        out[name] = evaluate_consensus_gain(
            params, seed=seed + 31 * i, n_clusters=n_clusters,
            template_len=template_len, depths=depths,
            error_model=model, min_confidence=min_confidence,
            min_polish_depth=min_polish_depth,
        )
    return out


def evaluate_accuracy(params, seed: int = 99, n_examples: int = 32) -> dict[str, float]:
    """Per-position accuracy of the polisher vs the raw draft on held-out data."""
    fdim = polisher.params_feature_dim(params)
    ex = make_examples(
        seed, n_examples,
        features="v4" if fdim == polisher.FEATURE_DIM_V4 else "v1",
    )
    logits = np.asarray(polisher.apply_logits(params, jnp.asarray(ex.feats)))
    pred = logits[..., : polisher.NUM_CLASSES].argmax(axis=-1)
    m = ex.mask > 0
    model_acc = float((pred[m] == ex.labels[m]).mean())
    # baseline: the draft itself (class = draft base, never deletion); the
    # draft one-hot is the LAST 4 feature channels in both encodings
    draft_base = ex.feats[..., -4:].argmax(axis=-1)
    draft_is_base = ex.feats[..., -4:].sum(axis=-1) > 0
    base_acc = float(
        ((draft_base[m] == ex.labels[m]) & draft_is_base[m]).mean()
    )
    ins_pred = logits[..., polisher.NUM_CLASSES:].argmax(axis=-1)
    ins_acc = float((ins_pred[m] == ex.ins_labels[m]).mean())
    return {"model_acc": model_acc, "draft_acc": base_acc, "ins_acc": ins_acc}


def _main(argv=None) -> int:
    """``python -m ont_tcrconsensus_tpu.models.train``: retrain + evaluate.

    Trains at pipeline-realistic template lengths on the systematic ONT
    error model, writes the weights, and prints the vote-vs-RNN
    precision-at-depth table (with gate-fire rates) that justifies (or
    demotes) polish_method="rnn" as the default.
    """
    import argparse
    import json
    import os
    import sys

    from ont_tcrconsensus_tpu.models.polisher import DEFAULT_WEIGHTS, save_params

    parser = argparse.ArgumentParser(description="Train the consensus polisher.")
    parser.add_argument("--steps", type=int, default=600)
    parser.add_argument("--template-len", type=int, default=1600)
    parser.add_argument("--pool-examples", type=int, default=128)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--out", default=None,
                        help="weights path (default: the file the pipeline "
                             "serves — polisher.serving_weights_path())")
    parser.add_argument("--eval-only", action="store_true")
    parser.add_argument("--eval-clusters", type=int, default=500)
    parser.add_argument("--iid", action="store_true",
                        help="legacy iid error model (ablation only)")
    parser.add_argument("--v3", action="store_true",
                        help="v3 flow: train on the randomized regime "
                             "family, evaluate on held-out regimes, write "
                             "polisher_v3.msgpack + polisher_v3_eval.json")
    parser.add_argument("--v4", action="store_true",
                        help="v4 flow: the v3 regime family PLUS the "
                             "25-channel strand+quality features and a "
                             "low-depth-dominant (2-4) example mix; writes "
                             "polisher_v4.msgpack + polisher_v4_eval.json")
    parser.add_argument("--eval-json", default=None,
                        help="also write the eval table to this path")
    parser.add_argument("--depth-max", type=int, default=8,
                        help="max subread depth in training examples")
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (the axon TPU plugin "
                             "overrides JAX_PLATFORMS and a wedged tunnel "
                             "hangs backend init — same escape hatch as "
                             "the CLI --cpu / bench BENCH_FORCE_CPU)")
    parser.add_argument("--resume", action="store_true",
                        help="warm-start from the existing --out weights")
    args = parser.parse_args(argv)

    if args.cpu or os.environ.get("TCR_CONSENSUS_FORCE_CPU"):
        import jax

        from ont_tcrconsensus_tpu.pipeline.run import enable_compilation_cache

        jax.config.update("jax_platforms", "cpu")
        enable_compilation_cache()

    if (args.v3 or args.v4) and args.iid:
        parser.error("--v3/--v4 train on the regime family; --iid is the "
                     "single-regime ablation — pick one")
    if args.v3 and args.v4:
        parser.error("pick ONE of --v3 / --v4")
    weights_dir = os.path.dirname(DEFAULT_WEIGHTS)
    if args.out is None:
        if args.v4:
            args.out = os.path.join(weights_dir, "polisher_v4.msgpack")
        elif args.v3:
            args.out = os.path.join(weights_dir, "polisher_v3.msgpack")
        else:
            # target what the pipeline SERVES so a default retrain can
            # never write a file load_default_params ignores
            from ont_tcrconsensus_tpu.models.polisher import serving_weights_path

            args.out = serving_weights_path()
            base = os.path.basename(args.out)
            if base not in ("polisher_v2.msgpack",):
                # ADVICE r4: a plain retrain resolving to a v3/v4 file
                # would overwrite regime-family weights with single-regime
                # ones AND leave the sibling _eval.json describing weights
                # that no longer exist — refuse instead of diverging
                parser.error(
                    f"default --out resolves to the served weights "
                    f"{base}, which were trained with the "
                    f"{'--v4' if 'v4' in base else '--v3'} flow; pass "
                    f"that flag to retrain them, or an explicit --out "
                    f"for a single-regime experiment"
                )
    if (args.v3 or args.v4) and args.eval_json is None:
        # derive from --out so a custom-out experiment can never clobber
        # the bundled evidence file the config/docs cite (code-review r4)
        args.eval_json = os.path.splitext(args.out)[0] + "_eval.json"

    error_model = None if args.iid else DEFAULT_ERROR_MODEL
    if args.eval_only:
        from ont_tcrconsensus_tpu.models.polisher import load_params

        params = load_params(args.out)
    else:
        init = None
        if args.resume:
            if not os.path.exists(args.out):
                parser.error(f"--resume: no weights at {args.out}")
            from ont_tcrconsensus_tpu.models.polisher import load_params

            init = load_params(args.out)
            print(f"warm-starting from {args.out}")
            if args.seed == 0:
                print("WARNING: --resume with the default --seed replays "
                      "the IDENTICAL example pool and batch order as the "
                      "original run — pass a new --seed to continue on "
                      "fresh data", file=sys.stderr)
        params, losses = train(
            steps=args.steps, batch_size=args.batch_size, seed=args.seed,
            pool_examples=args.pool_examples, template_len=args.template_len,
            params=init,
            error_model=error_model,
            error_models=TRAIN_REGIMES if (args.v3 or args.v4) else None,
            depth_range=(2, args.depth_max),
            features="v4" if args.v4 else "v1",
            depth_dist="lowdepth" if args.v4 else "uniform",
        )
        save_params(params, args.out)
        print(f"saved {args.out} (final loss {losses[-1]:.4f})")
    if args.v3 or args.v4:
        gain = evaluate_regimes(
            params, template_len=args.template_len,
            n_clusters=args.eval_clusters,
        )
    else:
        gain = evaluate_consensus_gain(
            params, template_len=args.template_len,
            n_clusters=args.eval_clusters, error_model=error_model,
        )
    print(json.dumps(gain, indent=2))
    if args.eval_json:
        with open(args.eval_json, "w") as fh:
            json.dump(gain, fh, indent=2)
        print(f"wrote {args.eval_json}")
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
