"""models subpackage."""
