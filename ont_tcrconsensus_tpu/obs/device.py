"""Device instrumentation: dispatch-tax split, recompile audit, memory HWM.

**Dispatch tax** (ROADMAP 1): the round1_polish wall clock is dominated by
host-side gaps between device dispatches, but ``stage_timing.tsv`` cannot
say which site pays them. :func:`dispatch` wraps a dispatch call site and
:func:`timed_get` wraps the matching ``jax.device_get`` / block point;
together they split every device call into

- ``host_s``  — time inside the dispatch scope NOT spent blocked on the
  device (input staging, python dispatch, readback bookkeeping), and
- ``block_s`` — time blocked waiting for device results.

A ``timed_get`` nested inside a ``dispatch`` scope on the same thread
credits its blocked seconds to the enclosing site (so ``polish.dispatch``
owns the waits its chunk performs inside ops/consensus); a frameless get
(e.g. the fused-assign consumer thread, the UMI distance matrix) records
under its own site. Disarmed, both are one module-attribute check.

**Recompile audit** (ROADMAP 3): a ``jax.monitoring`` duration listener
counts every XLA backend compile and attributes it to the active stage
span (:func:`trace.current_label`) plus the innermost dispatch frame's
shape bucket — ``round1_fused_assign[2048]`` — so "does tenant-to-tenant
traffic recompile" is a committed number, not a hunch. jax has no
listener unregistration, so the hook is installed once per process and
reads the armed registry on every event.

**Memory high-water**: :class:`MemorySampler` (armed at ``telemetry:
full``) periodically records HBM ``bytes_in_use`` across local devices
and host RSS as high-water gauges + trace counter events;
:func:`finalize_memory` additionally one-shots the backend's own
``peak_bytes_in_use`` and the process ``ru_maxrss`` at roll-up time, so
the default ``on`` level still reports true peaks without a sampler
thread.
"""

from __future__ import annotations

import contextlib
import resource
import threading
import time

from ont_tcrconsensus_tpu.obs import metrics, trace, transfers

_tls = threading.local()

#: the jax.monitoring duration event marking one XLA backend compile
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class _Frame:
    __slots__ = ("site", "bucket", "block_s")

    def __init__(self, site: str, bucket):
        self.site = site
        self.bucket = bucket
        self.block_s = 0.0


def _frames() -> list[_Frame]:
    frames = getattr(_tls, "frames", None)
    if frames is None:
        frames = _tls.frames = []
    return frames


@contextlib.contextmanager
def dispatch(site: str, bucket=None):
    """Measure one device-dispatch scope at ``site``.

    ``bucket`` labels the static shape family (e.g. the width bucket) for
    compile attribution. Free no-op when telemetry is off.
    """
    reg = metrics._ARMED
    if reg is None:
        yield
        return
    frames = _frames()
    frame = _Frame(site, bucket)
    frames.append(frame)
    t0 = time.monotonic()
    try:
        yield
    finally:
        elapsed = time.monotonic() - t0
        if frames and frames[-1] is frame:
            frames.pop()
        # stage attribution: the innermost span label on THIS thread (a
        # graph-node/stage name, or the worker's <name>_bg) — the per-node
        # dispatch-tax rollup obs/critical_path.py joins against
        reg.dispatch_add(
            site, dispatches=1,
            host_s=max(elapsed - frame.block_s, 0.0),
            block_s=frame.block_s,
            stage=trace.current_label(),
        )


def timed_get(site: str, value):
    """``jax.device_get(value)`` with the blocked seconds attributed to the
    enclosing :func:`dispatch` frame (or to ``site`` when frameless)."""
    import jax

    reg = metrics._ARMED
    if reg is None:
        return jax.device_get(value)
    t0 = time.monotonic()
    out = jax.device_get(value)
    dt = time.monotonic() - t0
    frames = getattr(_tls, "frames", None)
    if frames:
        # blocked seconds flow to the enclosing frame, whose dispatch exit
        # carries the stage attribution; only the get count lands here
        frames[-1].block_s += dt
        reg.dispatch_add(site, gets=1, stage=trace.current_label())
    else:
        reg.dispatch_add(site, gets=1, block_s=dt,
                         stage=trace.current_label())
    # every instrumented readback also feeds the transfer ledger: the
    # host copy that just materialized is exactly the d2h payload
    transfers.d2h(site, out)
    return out


# --- recompile audit ---------------------------------------------------------

_LISTENER_INSTALLED = False
_listener_lock = threading.Lock()


def _on_event_duration(event: str, duration: float, **kwargs) -> None:
    if event != BACKEND_COMPILE_EVENT:
        return
    reg = metrics._ARMED
    if reg is None:
        return
    label = trace.current_label() or "<unattributed>"
    frames = getattr(_tls, "frames", None)
    if frames and frames[-1].bucket is not None:
        label = f"{label}[{frames[-1].bucket}]"
    reg.compile_add(label, duration)
    trace.instant("xla.compile",
                  args={"stage": label, "seconds": round(duration, 4)})


def install_compile_listener() -> None:
    """Hook the jax.monitoring compile events (once per process; jax offers
    no unregistration, so the listener checks the armed registry). A jax
    build without the monitoring API degrades to no recompile audit —
    telemetry must never fail the run it measures."""
    global _LISTENER_INSTALLED
    with _listener_lock:
        if _LISTENER_INSTALLED:
            return
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(_on_event_duration)
        except Exception as exc:
            import sys

            sys.stderr.write(
                f"telemetry: recompile audit unavailable ({exc!r}); "
                "compile counts will read 0\n"
            )
        _LISTENER_INSTALLED = True


# --- memory high-water -------------------------------------------------------


def _rss_bytes() -> int:
    """Current resident set size (0 when /proc is unavailable)."""
    try:
        with open("/proc/self/statm") as fh:
            return int(fh.read().split()[1]) * resource.getpagesize()
    except (OSError, ValueError, IndexError):
        return 0


def _peak_rss_bytes() -> int:
    """Process-lifetime peak RSS (ru_maxrss is KiB on Linux)."""
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


def _device_bytes_in_use(devices, key: str) -> int | None:
    """Sum ``key`` over devices' memory_stats; None when no backend reports
    it (the CPU backend returns no stats — HBM gauges stay absent there)."""
    total, seen = 0, False
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and key in stats:
            total += int(stats[key])
            seen = True
    return total if seen else None


class MemorySampler:
    """Background HBM/RSS sampler (armed at ``telemetry: full``)."""

    def __init__(self, interval_s: float = 0.2):
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="obs-memory-sampler", daemon=True
        )

    def start(self) -> "MemorySampler":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        # bounded join: a device call wedged inside memory_stats() (the
        # wedged-tunnel scenario) must not hang the run's shutdown path —
        # the thread is a daemon, so an unjoined straggler dies with the
        # process instead of wedging it
        self._thread.join(timeout=2.0)
        if self._thread.is_alive():
            import sys

            sys.stderr.write(
                "telemetry: memory sampler did not stop within 2s "
                "(device stats call wedged?); leaving the daemon thread\n"
            )

    def _run(self) -> None:
        try:
            import jax

            devices = jax.local_devices()
        except Exception:
            devices = []
        while not self._stop.wait(self.interval_s):
            reg = metrics._ARMED
            if reg is None:
                continue
            hbm = _device_bytes_in_use(devices, "bytes_in_use")
            rss = _rss_bytes()
            if hbm is not None:
                reg.gauge_max("device.hbm_bytes_in_use", hbm)
            if rss:
                reg.gauge_max("host.rss_bytes", rss)
            col = trace._ARMED
            if col is not None:
                values = {"host_rss_bytes": rss}
                if hbm is not None:
                    values["hbm_bytes_in_use"] = hbm
                col.add_counter("memory", values)


def start_sampler(interval_s: float = 0.2) -> MemorySampler:
    return MemorySampler(interval_s).start()


def finalize_memory() -> None:
    """One-shot peak capture at roll-up time (any armed level): the
    backend's own peak counter beats sampling — it cannot miss a spike
    between ticks — and ``ru_maxrss`` is the kernel's true host peak."""
    reg = metrics._ARMED
    if reg is None:
        return
    try:
        import jax

        peak = _device_bytes_in_use(jax.local_devices(), "peak_bytes_in_use")
        if peak is None:
            peak = _device_bytes_in_use(jax.local_devices(), "bytes_in_use")
        if peak is not None:
            reg.gauge_max("device.hbm_bytes_in_use", peak)
    except Exception:  # telemetry must never fail the run it measures
        pass
    reg.gauge_max("host.rss_bytes", _peak_rss_bytes())
