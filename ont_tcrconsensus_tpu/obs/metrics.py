"""Process-wide metrics registry (counters / high-water gauges / histograms).

One registry instance is armed per run (pipeline/run.py) when the
``telemetry`` config knob is ``on`` or ``full``; every planted call site
goes through the module-level functions below, which are a single
module-attribute check when disarmed — the same hot-loop discipline as
``faults.inject`` and ``watchdog.heartbeat``. Armed, each update is one
dict operation under a lock (the planted sites are per-batch / per-chunk,
never per-read).

Beyond the generic counter/gauge/histogram families the registry holds
the two structured aggregates the telemetry artifact is for:

- **dispatch sites** (fed by :mod:`.device`): per-site dispatch / get
  counts plus the host-gap vs blocked-on-device seconds split — the
  ROADMAP-1 dispatch-tax attribution.
- **compiles** (fed by the :mod:`.device` ``jax.monitoring`` listener):
  total XLA backend-compile count/seconds plus a per-stage[shape-bucket]
  breakdown — the ROADMAP-3 recompile audit.

Stage span seconds (fed by :mod:`.trace` at span exit — the same clock
read that feeds ``stage_timing.tsv``) accumulate here too, so the
run-level ``telemetry.json`` stage table cannot disagree with the
per-library TSVs.
"""

from __future__ import annotations

import time

from ont_tcrconsensus_tpu.robustness import jobscope, lockcheck


class MetricsRegistry:
    """Thread-safe per-run metric store; see :func:`arm`."""

    def __init__(self):
        self._lock = lockcheck.make_lock()
        self.t0_wall = time.time()
        self.t0_mono = time.monotonic()
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}  # high-water (max) semantics
        # last-value (live) semantics: the serve queue depth NOW, not its
        # high-water — /metrics scrapes between jobs must see it fall
        self.gauges_live: dict[str, float] = {}
        # serve-plane rejection reason -> count (queue_full / over_budget /
        # invalid_config / draining / body_too_large); its own table
        # because the reason is a label dimension, not an OBS_SITES site
        self.serve_rejects: dict[str, float] = {}
        # mesh slice ("cpu:0") -> busy fraction (1.0 carrying work, 0.0
        # lost/idle); last-value semantics like gauges_live — a /metrics
        # scrape after a degradation must show the dead slice at 0. Its
        # own table because the slice is a label dimension, not a site.
        self.mesh_slices: dict[str, float] = {}
        # chaos/fault site ("mesh.device_lost") -> count of degraded-mesh
        # re-executions it caused; label dimension, not an OBS_SITES site
        self.mesh_degraded: dict[str, float] = {}
        # mesh slice ("cpu:0") -> resident tenant job id ("" when idle);
        # written by the serve-plane slice allocator (serve/slices.py) so
        # /metrics shows who owns what — label dimension, not a site
        self.slice_tenants: dict[str, str] = {}
        # mesh slice -> quarantine count (device_lost contained to that
        # slice); label dimension, not an OBS_SITES site
        self.slice_quarantined: dict[str, float] = {}
        # site -> [count, sum, min, max]
        self.hists: dict[str, list[float]] = {}
        # name -> [seconds, calls]
        self.stages: dict[str, list[float]] = {}
        # site -> [n_dispatch, n_get, host_s, block_s]
        self.dispatch: dict[str, list[float]] = {}
        # label -> [count, seconds]
        self.compiles: dict[str, list[float]] = {}
        # graph node -> [critical_s, overlapped_s, runs, skips]
        self.graph_nodes: dict[str, list[float]] = {}
        # graph edge -> placement ("hbm" | "host" | "disk")
        self.graph_edges: dict[str, str] = {}
        # graph node -> {"inputs": [...], "outputs": [...], "units": int}
        # (declared structure; lets obs/critical_path.py rebuild the DAG
        # from the artifact alone)
        self.graph_meta: dict[str, dict] = {}
        # pool site -> [busy_s, idle_s, window_s, slots]
        self.pools: dict[str, list[float]] = {}
        # analyzer name -> verdict summary (e.g. "graftcheck" ->
        # graph.check.Report.summary(); static findings ride the same
        # telemetry artifact so the history ledger tracks them per run)
        self.analysis: dict[str, dict] = {}
        # stage label -> [n_dispatch, n_get, host_s, block_s] (the
        # dispatch-tax split re-keyed by the active stage span, so the
        # per-node rollup needs no trace replay)
        self.dispatch_stages: dict[str, list[float]] = {}
        # --- device data-plane ledger (fed by obs/transfers.py) ---
        # site -> [h2d_bytes, h2d_count, d2h_bytes, d2h_count]
        self.transfers: dict[str, list[float]] = {}
        # graph edge -> [bytes, count, direction, placement]
        self.edge_transfers: dict[str, list] = {}
        # graph edge -> {"verdict": donated|copied|unknown, "node": str}
        self.donations: dict[str, dict] = {}
        # graph node -> [delta_bytes_sum, end_bytes_max, samples]
        self.node_hbm: dict[str, list[float]] = {}
        # graph node -> graftcheck static live-HBM estimate (max over
        # libraries; recorded at run start so --report reconciles from
        # the artifact alone)
        self.static_hbm: dict[str, float] = {}
        # [bytes, last_sample] — bytes that left the device and came
        # back (graftcheck round-trip edges); list so the lock rule sees
        # mutation, not rebinding
        self._round_trip = [0.0]
        self._hbm_prev: float | None = None

    # --- update API (called via the module-level wrappers) -----------------

    def counter_add(self, site: str, n: float = 1) -> None:
        with self._lock:
            self.counters[site] = self.counters.get(site, 0) + n

    def gauge_max(self, site: str, value: float) -> None:
        with self._lock:
            if value > self.gauges.get(site, float("-inf")):
                self.gauges[site] = value

    def gauge_set(self, site: str, value: float) -> None:
        """Live gauge: last value wins AND the high-water table keeps its
        max, so one plant feeds both the /metrics live view and the
        telemetry.json high-water roll-up."""
        with self._lock:
            self.gauges_live[site] = value
            if value > self.gauges.get(site, float("-inf")):
                self.gauges[site] = value

    def reject_add(self, reason: str, n: float = 1) -> None:
        with self._lock:
            self.serve_rejects[reason] = self.serve_rejects.get(reason, 0) + n

    def mesh_slice_set(self, slice_id: str, busy: float) -> None:
        with self._lock:
            self.mesh_slices[slice_id] = busy

    def mesh_degraded_add(self, site: str, n: float = 1) -> None:
        with self._lock:
            self.mesh_degraded[site] = self.mesh_degraded.get(site, 0) + n

    def slice_tenant_set(self, slice_id: str, tenant: str) -> None:
        with self._lock:
            self.slice_tenants[slice_id] = tenant

    def slice_quarantine_add(self, slice_id: str, n: float = 1) -> None:
        with self._lock:
            self.slice_quarantined[slice_id] = (
                self.slice_quarantined.get(slice_id, 0) + n)

    def observe(self, site: str, value: float) -> None:
        with self._lock:
            h = self.hists.get(site)
            if h is None:
                self.hists[site] = [1, value, value, value]
            else:
                h[0] += 1
                h[1] += value
                h[2] = min(h[2], value)
                h[3] = max(h[3], value)

    def stage_add(self, name: str, seconds: float) -> None:
        with self._lock:
            s = self.stages.get(name)
            if s is None:
                self.stages[name] = [seconds, 1]
            else:
                s[0] += seconds
                s[1] += 1

    def dispatch_add(self, site: str, *, dispatches: int = 0, gets: int = 0,
                     host_s: float = 0.0, block_s: float = 0.0,
                     stage: str | None = None) -> None:
        with self._lock:
            d = self.dispatch.setdefault(site, [0, 0, 0.0, 0.0])
            d[0] += dispatches
            d[1] += gets
            d[2] += host_s
            d[3] += block_s
            if stage is not None:
                s = self.dispatch_stages.setdefault(stage, [0, 0, 0.0, 0.0])
                s[0] += dispatches
                s[1] += gets
                s[2] += host_s
                s[3] += block_s

    def compile_add(self, label: str, seconds: float) -> None:
        with self._lock:
            c = self.compiles.setdefault(label, [0, 0.0])
            c[0] += 1
            c[1] += seconds

    def graph_node_add(self, name: str, *, critical_s: float = 0.0,
                       overlapped_s: float = 0.0) -> None:
        with self._lock:
            g = self.graph_nodes.setdefault(name, [0.0, 0.0, 0, 0])
            g[0] += critical_s
            g[1] += overlapped_s
            g[2] += 1

    def graph_node_skip(self, name: str) -> None:
        with self._lock:
            g = self.graph_nodes.setdefault(name, [0.0, 0.0, 0, 0])
            g[3] += 1

    def graph_edge_set(self, name: str, placement: str) -> None:
        with self._lock:
            self.graph_edges[name] = placement

    def graph_node_declare(self, name: str, *, inputs=None, outputs=None,
                           units: int | None = None) -> None:
        """Record a node's declared structure (dependency edges) and its
        evaluated workload units (summed over runs, like the seconds)."""
        with self._lock:
            m = self.graph_meta.setdefault(name, {})
            if inputs is not None:
                m["inputs"] = list(inputs)
            if outputs is not None:
                m["outputs"] = list(outputs)
            if units is not None:
                m["units"] = m.get("units", 0) + int(units)

    def pool_add(self, site: str, *, busy_s: float = 0.0, idle_s: float = 0.0,
                 window_s: float = 0.0, slots: int = 0) -> None:
        with self._lock:
            p = self.pools.setdefault(site, [0.0, 0.0, 0.0, 0])
            p[0] += busy_s
            p[1] += idle_s
            p[2] += window_s
            p[3] = max(p[3], slots)

    def analysis_set(self, name: str, summary: dict) -> None:
        with self._lock:
            self.analysis[name] = dict(summary)

    # --- device data-plane ledger (obs/transfers.py) -----------------------

    def transfer_add(self, site: str, direction: str, nbytes: int,
                     n: int = 1) -> None:
        with self._lock:
            t = self.transfers.setdefault(site, [0, 0, 0, 0])
            i = 0 if direction == "h2d" else 2
            t[i] += nbytes
            t[i + 1] += n

    def edge_transfer_add(self, edge: str, direction: str, nbytes: int,
                          placement: str) -> None:
        with self._lock:
            e = self.edge_transfers.setdefault(
                edge, [0, 0, direction, placement])
            e[0] += nbytes
            e[1] += 1

    def donation_set(self, edge: str, verdict: str, node: str) -> None:
        with self._lock:
            # a single "copied" sighting must survive later "donated"
            # materializations of the same edge — the regression is the
            # finding, not the steady state
            prev = self.donations.get(edge)
            if prev is None or prev["verdict"] != "copied":
                self.donations[edge] = {"verdict": verdict, "node": node}

    def node_hbm_add(self, node: str, end_bytes: float) -> None:
        with self._lock:
            prev = self._hbm_prev
            self._hbm_prev = end_bytes
            h = self.node_hbm.setdefault(node, [0.0, 0.0, 0])
            if prev is not None:
                h[0] += end_bytes - prev
            h[1] = max(h[1], end_bytes)
            h[2] += 1

    def static_hbm_set(self, node: str, bytes_est: float) -> None:
        with self._lock:
            if bytes_est > self.static_hbm.get(node, float("-inf")):
                self.static_hbm[node] = bytes_est

    def round_trip_add(self, nbytes: int) -> None:
        with self._lock:
            self._round_trip[0] += nbytes

    # --- roll-up -----------------------------------------------------------

    def summary(self) -> dict:
        """The ``telemetry.json`` body (times rounded for stable artifacts)."""
        with self._lock:
            compile_n = sum(int(c[0]) for c in self.compiles.values())
            compile_s = sum(c[1] for c in self.compiles.values())
            out = {
                "duration_s": round(time.monotonic() - self.t0_mono, 3),
                "t_wall_start": round(self.t0_wall, 3),
                "t_mono_start": round(self.t0_mono, 3),
                "stages": {
                    k: {"seconds": round(v[0], 3), "calls": int(v[1])}
                    for k, v in sorted(self.stages.items(),
                                       key=lambda kv: -kv[1][0])
                },
                "dispatch": {
                    k: {"dispatches": int(v[0]), "gets": int(v[1]),
                        "host_s": round(v[2], 3), "block_s": round(v[3], 3)}
                    for k, v in sorted(self.dispatch.items())
                },
                "compile": {
                    "count": compile_n,
                    "seconds": round(compile_s, 3),
                    "by_stage": {
                        k: {"count": int(v[0]), "seconds": round(v[1], 3)}
                        for k, v in sorted(self.compiles.items(),
                                           key=lambda kv: -kv[1][1])
                    },
                },
                "counters": {k: self.counters[k] for k in sorted(self.counters)},
                "gauges": {k: self.gauges[k] for k in sorted(self.gauges)},
                **({"gauges_live": {k: self.gauges_live[k]
                                    for k in sorted(self.gauges_live)}}
                   if self.gauges_live else {}),
                **({"serve_rejected_by_reason": {
                        k: int(self.serve_rejects[k])
                        for k in sorted(self.serve_rejects)}}
                   if self.serve_rejects else {}),
                **({"mesh_slice_busy": {
                        k: self.mesh_slices[k]
                        for k in sorted(self.mesh_slices)}}
                   if self.mesh_slices else {}),
                **({"mesh_degraded_by_site": {
                        k: int(self.mesh_degraded[k])
                        for k in sorted(self.mesh_degraded)}}
                   if self.mesh_degraded else {}),
                **({"slice_tenants": {
                        k: self.slice_tenants[k]
                        for k in sorted(self.slice_tenants)}}
                   if self.slice_tenants else {}),
                **({"slice_quarantined": {
                        k: int(self.slice_quarantined[k])
                        for k in sorted(self.slice_quarantined)}}
                   if self.slice_quarantined else {}),
                "histograms": {
                    k: {"count": int(v[0]), "sum": round(v[1], 3),
                        "min": v[2], "max": v[3]}
                    for k, v in sorted(self.hists.items())
                },
            }
            # data-plane ledger: always present when armed, so a
            # --report --memory over any post-upgrade artifact can tell
            # "nothing moved" apart from "nothing was measured"
            transfers: dict = {
                "sites": {
                    k: {"h2d_bytes": int(v[0]), "h2d": int(v[1]),
                        "d2h_bytes": int(v[2]), "d2h": int(v[3])}
                    for k, v in sorted(self.transfers.items())
                },
                "edges": {
                    k: {"bytes": int(v[0]), "count": int(v[1]),
                        "direction": v[2], "placement": v[3]}
                    for k, v in sorted(self.edge_transfers.items())
                },
                "host_round_trip_bytes": int(self._round_trip[0]),
            }
            if self.donations:
                transfers["donation"] = {
                    k: dict(self.donations[k]) for k in sorted(self.donations)
                }
            if self.node_hbm:
                transfers["node_hbm"] = {
                    k: {"delta_bytes": int(v[0]), "end_bytes": int(v[1]),
                        "samples": int(v[2])}
                    for k, v in sorted(self.node_hbm.items())
                }
            if self.static_hbm:
                transfers["static_hbm_by_node"] = {
                    k: int(self.static_hbm[k]) for k in sorted(self.static_hbm)
                }
            out["transfers"] = transfers
            if self.dispatch_stages:
                out["dispatch_by_stage"] = {
                    k: {"dispatches": int(v[0]), "gets": int(v[1]),
                        "host_s": round(v[2], 3), "block_s": round(v[3], 3)}
                    for k, v in sorted(self.dispatch_stages.items())
                }
            if self.analysis:
                out["analysis"] = {
                    k: dict(self.analysis[k]) for k in sorted(self.analysis)
                }
            pool = None
            if self.pools:
                # one merged busy/idle split (a run has one overlap pool
                # vocabulary entry; summing stays correct if more appear)
                pool = {
                    "busy_s": round(sum(p[0] for p in self.pools.values()), 3),
                    "idle_s": round(sum(p[1] for p in self.pools.values()), 3),
                    "window_s": round(
                        sum(p[2] for p in self.pools.values()), 3),
                    "slots": max(int(p[3]) for p in self.pools.values()),
                }
            # graph-executor section: present only when a graph actually
            # ran, so imperative-path telemetry keeps its exact shape
            if self.graph_nodes or self.graph_edges:
                gnodes = {}
                for k in sorted(set(self.graph_nodes) | set(self.graph_meta)):
                    v = self.graph_nodes.get(k, [0.0, 0.0, 0, 0])
                    entry = {"critical_s": round(v[0], 3),
                             "overlapped_s": round(v[1], 3),
                             "runs": int(v[2]), "skips": int(v[3])}
                    meta = self.graph_meta.get(k)
                    if meta:
                        entry["units"] = int(meta.get("units", 0))
                        entry["inputs"] = list(meta.get("inputs", ()))
                        entry["outputs"] = list(meta.get("outputs", ()))
                    gnodes[k] = entry
                out["graph"] = {
                    "nodes": gnodes,
                    "edges": {k: self.graph_edges[k]
                              for k in sorted(self.graph_edges)},
                }
                if pool is not None:
                    out["graph"]["pool"] = pool
            elif pool is not None:
                # imperative executor with overlap_qc: no graph section to
                # host the pool split, so it rides top-level
                out["overlap_pool"] = pool
            return out

    def prometheus_lines(self) -> list[str]:
        """Prometheus text-exposition (v0.0.4) rendering of the registry,
        served live by obs/live.py's /metrics route.

        One locked pass over the same aggregates ``summary()`` rolls up;
        site/stage/node names become label values (dots and all — label
        VALUES are free-form, only metric names are constrained), so the
        exposition vocabulary is exactly :data:`~ont_tcrconsensus_tpu.obs.
        OBS_SITES` and no scrape-side mapping table can drift.
        """
        def fam(lines: list[str], name: str, kind: str, help_: str,
                samples: list[tuple[str, str, float]]) -> None:
            if not samples:
                return
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
            for label, value, v in samples:
                lines.append(f'{name}{{{label}="{prom_label(value)}"}} {v:g}')

        with self._lock:
            lines: list[str] = [
                "# HELP tcr_run_duration_seconds Seconds since the "
                "registry was armed.",
                "# TYPE tcr_run_duration_seconds gauge",
                f"tcr_run_duration_seconds "
                f"{time.monotonic() - self.t0_mono:g}",
            ]
            fam(lines, "tcr_counter_total", "counter",
                "Hot-loop counters (metrics.counter_add sites).",
                [("site", k, self.counters[k])
                 for k in sorted(self.counters)])
            fam(lines, "tcr_gauge", "gauge",
                "High-water gauges (HBM in use, host RSS, ...).",
                [("site", k, self.gauges[k]) for k in sorted(self.gauges)])
            fam(lines, "tcr_gauge_current", "gauge",
                "Live last-value gauges (serve queue depth NOW, not its "
                "high-water).",
                [("site", k, self.gauges_live[k])
                 for k in sorted(self.gauges_live)])
            fam(lines, "tcr_serve_rejected_total", "counter",
                "Serve-plane job rejections by reason (queue_full / "
                "over_budget / invalid_config / draining / body_too_large).",
                [("reason", k, self.serve_rejects[k])
                 for k in sorted(self.serve_rejects)])
            # the slice-busy family carries an OPTIONAL second label
            # (tenant occupancy from the serve-plane allocator), so it's
            # rendered by hand — fam() is the single-label helper
            if self.mesh_slices:
                lines.append("# HELP tcr_mesh_slice_busy Per-mesh-slice "
                             "busy fraction (1 carrying work, 0 lost/idle "
                             "after a degradation).")
                lines.append("# TYPE tcr_mesh_slice_busy gauge")
                for k in sorted(self.mesh_slices):
                    tenant = self.slice_tenants.get(k)
                    labels = f'slice="{prom_label(k)}"'
                    if tenant:
                        labels += f',tenant="{prom_label(tenant)}"'
                    lines.append(
                        f"tcr_mesh_slice_busy{{{labels}}} "
                        f"{self.mesh_slices[k]:g}")
            fam(lines, "tcr_slice_quarantined_total", "counter",
                "Slices quarantined out of the serve-plane free pool "
                "(device_lost contained to one tenant's slice).",
                [("slice", k, self.slice_quarantined[k])
                 for k in sorted(self.slice_quarantined)])
            if "serve.resident_jobs" in self.gauges_live:
                lines.append("# HELP tcr_serve_resident_jobs Tenant jobs "
                             "currently resident on disjoint mesh slices.")
                lines.append("# TYPE tcr_serve_resident_jobs gauge")
                lines.append(
                    f"tcr_serve_resident_jobs "
                    f"{self.gauges_live['serve.resident_jobs']:g}")
            fam(lines, "tcr_mesh_degraded_total", "counter",
                "Degraded-mesh re-executions by the fault site that "
                "caused them.",
                [("site", k, self.mesh_degraded[k])
                 for k in sorted(self.mesh_degraded)])
            for i, (suffix, help_) in enumerate((
                ("count", "Histogram observation counts."),
                ("sum", "Histogram observation sums."),
                ("min", "Histogram observation minima."),
                ("max", "Histogram observation maxima."),
            )):
                fam(lines, f"tcr_observations_{suffix}",
                    "counter" if i < 2 else "gauge", help_,
                    [("site", k, self.hists[k][i])
                     for k in sorted(self.hists)])
            fam(lines, "tcr_stage_seconds_total", "counter",
                "Per-stage span seconds (same clock as stage_timing.tsv).",
                [("stage", k, self.stages[k][0])
                 for k in sorted(self.stages)])
            fam(lines, "tcr_stage_calls_total", "counter",
                "Per-stage span entry counts.",
                [("stage", k, self.stages[k][1])
                 for k in sorted(self.stages)])
            disp = sorted(self.dispatch)
            fam(lines, "tcr_dispatch_total", "counter",
                "Per-site device dispatch counts.",
                [("site", k, self.dispatch[k][0]) for k in disp])
            fam(lines, "tcr_dispatch_gets_total", "counter",
                "Per-site blocking-get counts.",
                [("site", k, self.dispatch[k][1]) for k in disp])
            fam(lines, "tcr_dispatch_host_seconds_total", "counter",
                "Per-site host-gap seconds (dispatch tax).",
                [("site", k, self.dispatch[k][2]) for k in disp])
            fam(lines, "tcr_dispatch_block_seconds_total", "counter",
                "Per-site blocked-on-device seconds.",
                [("site", k, self.dispatch[k][3]) for k in disp])
            fam(lines, "tcr_xla_compiles_total", "counter",
                "XLA backend compiles per stage[shape-bucket].",
                [("stage", k, self.compiles[k][0])
                 for k in sorted(self.compiles)])
            fam(lines, "tcr_xla_compile_seconds_total", "counter",
                "XLA backend compile seconds per stage[shape-bucket].",
                [("stage", k, self.compiles[k][1])
                 for k in sorted(self.compiles)])
            pools = sorted(self.pools)
            fam(lines, "tcr_pool_busy_seconds_total", "counter",
                "Worker-pool busy seconds.",
                [("site", k, self.pools[k][0]) for k in pools])
            fam(lines, "tcr_pool_idle_seconds_total", "counter",
                "Worker-pool idle seconds.",
                [("site", k, self.pools[k][1]) for k in pools])
            fam(lines, "tcr_pool_window_seconds_total", "counter",
                "Worker-pool measurement-window seconds.",
                [("site", k, self.pools[k][2]) for k in pools])
            fam(lines, "tcr_pool_slots", "gauge",
                "Worker-pool slot count.",
                [("site", k, self.pools[k][3]) for k in pools])
            gnodes = sorted(self.graph_nodes)
            fam(lines, "tcr_graph_node_critical_seconds_total", "counter",
                "Per-node critical-path seconds.",
                [("node", k, self.graph_nodes[k][0]) for k in gnodes])
            fam(lines, "tcr_graph_node_overlapped_seconds_total", "counter",
                "Per-node overlapped worker seconds.",
                [("node", k, self.graph_nodes[k][1]) for k in gnodes])
            fam(lines, "tcr_graph_node_runs_total", "counter",
                "Per-node execution counts.",
                [("node", k, self.graph_nodes[k][2]) for k in gnodes])
            fam(lines, "tcr_graph_node_skips_total", "counter",
                "Per-node resume-skip counts.",
                [("node", k, self.graph_nodes[k][3]) for k in gnodes])
            # data-plane families: the edge family carries two labels
            # (edge + direction), so it's rendered by hand — fam() is
            # the single-label helper
            if self.transfers:
                lines.append("# HELP tcr_transfer_site_bytes_total Per-site "
                             "host<->device transfer bytes.")
                lines.append("# TYPE tcr_transfer_site_bytes_total counter")
                for k in sorted(self.transfers):
                    v = self.transfers[k]
                    for direction, b in (("h2d", v[0]), ("d2h", v[2])):
                        if b:
                            lines.append(
                                f'tcr_transfer_site_bytes_total'
                                f'{{site="{prom_label(k)}",'
                                f'direction="{direction}"}} {b:g}')
            if self.edge_transfers:
                lines.append("# HELP tcr_transfer_bytes_total Per-graph-edge "
                             "materialized bytes by direction.")
                lines.append("# TYPE tcr_transfer_bytes_total counter")
                for k in sorted(self.edge_transfers):
                    v = self.edge_transfers[k]
                    lines.append(
                        f'tcr_transfer_bytes_total{{edge="{prom_label(k)}",'
                        f'direction="{prom_label(v[2])}"}} {v[0]:g}')
                lines.append("# HELP tcr_host_round_trip_bytes_total Bytes "
                             "that left the device and came back (graftcheck "
                             "round-trip edges).")
                lines.append("# TYPE tcr_host_round_trip_bytes_total counter")
                lines.append(
                    f"tcr_host_round_trip_bytes_total {self._round_trip[0]:g}")
            hnodes = sorted(self.node_hbm)
            fam(lines, "tcr_node_hbm_delta_bytes", "gauge",
                "Per-node measured HBM delta (bytes-in-use change across "
                "the node's executions).",
                [("node", k, self.node_hbm[k][0]) for k in hnodes])
            fam(lines, "tcr_node_hbm_end_bytes", "gauge",
                "Per-node measured HBM high-water at node exit.",
                [("node", k, self.node_hbm[k][1]) for k in hnodes])
            return lines


def prom_label(value: str) -> str:
    """Escape a Prometheus label VALUE (exposition format: backslash,
    double quote and newline must be escaped inside the quotes)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


# Lock ownership for MetricsRegistry (every table -> _lock) is declared
# in the consolidated registry (ont_tcrconsensus_tpu/robustness/locks.py)
# consumed by graftlint's lock-discipline rule and graftrace.


# --- process-wide armed registry (same discipline as faults/watchdog) -------
#
# Under a jobscope (the slice-packed runner pool) a run's arm() binds its
# registry THREAD-LOCALLY: each resident tenant job rolls its own
# telemetry.json while the daemon's process-global registry keeps serving
# /metrics undisturbed. A scoped job whose telemetry is off (or that has
# already disarmed) falls back to the daemon registry — exactly the
# sharing a serial daemon had.

_ARMED: MetricsRegistry | None = None


def _current() -> MetricsRegistry | None:
    reg = jobscope.get("metrics")
    if reg is not None:
        return reg
    return _ARMED


def arm() -> MetricsRegistry:
    global _ARMED
    reg = MetricsRegistry()
    if jobscope.active():
        jobscope.set("metrics", reg)
        return reg
    _ARMED = reg
    return reg


def disarm() -> None:
    global _ARMED
    if jobscope.active():
        jobscope.set("metrics", None)
        return
    _ARMED = None


def armed() -> bool:
    return _current() is not None


def registry() -> MetricsRegistry | None:
    return _current()


def global_registry() -> MetricsRegistry | None:
    """The process-global armed registry, ignoring any jobscope binding.

    Daemon-plane objects (the slice allocator) plant here even when the
    calling thread happens to be inside a tenant job's scope — the mesh
    degrade hook runs the quarantine on the job's own thread, and those
    gauges/counters must reach the daemon's /metrics, not the tenant's
    per-run telemetry.json."""
    return _ARMED


def counter_add(site: str, n: float = 1) -> None:
    """Count ``n`` at ``site``; free no-op when telemetry is off."""
    reg = _current()
    if reg is not None:
        reg.counter_add(site, n)


def gauge_max(site: str, value: float) -> None:
    """Record a high-water observation; free no-op when telemetry is off."""
    reg = _current()
    if reg is not None:
        reg.gauge_max(site, value)


def observe(site: str, value: float) -> None:
    """Record a histogram observation; free no-op when telemetry is off."""
    reg = _current()
    if reg is not None:
        reg.observe(site, value)


def gauge_set(site: str, value: float) -> None:
    """Record a live (last-value) gauge; free no-op when telemetry is off."""
    reg = _current()
    if reg is not None:
        reg.gauge_set(site, value)


def reject_add(reason: str, n: float = 1) -> None:
    """Count a serve-plane rejection under ``reason``; free no-op when
    telemetry is off. The argument is a label value, not an OBS_SITES
    site — the per-site serve.rejected counter is planted separately."""
    reg = _current()
    if reg is not None:
        reg.reject_add(reason, n)


def mesh_slice_set(slice_id: str, busy: float) -> None:
    """Record a mesh slice's busy fraction (``tcr_mesh_slice_busy``);
    free no-op when telemetry is off. The argument is a label value
    (device id), not an OBS_SITES site — the mesh.slice_busy gauge is
    planted separately (parallel/mesh.py ``mark_mesh_slices``)."""
    reg = _current()
    if reg is not None:
        reg.mesh_slice_set(slice_id, busy)


def mesh_degraded_add(site: str, n: float = 1) -> None:
    """Count a degraded-mesh re-execution under the fault site that
    caused it (``tcr_mesh_degraded_total``); free no-op when telemetry
    is off. The argument is a label value, not an OBS_SITES site — the
    mesh.degraded counter is planted separately (graph/executor.py)."""
    reg = _current()
    if reg is not None:
        reg.mesh_degraded_add(site, n)


def slice_tenant_set(slice_id: str, tenant: str) -> None:
    """Record which tenant job occupies a mesh slice (the tenant label
    on ``tcr_mesh_slice_busy``); free no-op when telemetry is off. Both
    arguments are label values, not OBS_SITES sites — the serve.slice
    ring event is planted separately (serve/slices.py)."""
    reg = _current()
    if reg is not None:
        reg.slice_tenant_set(slice_id, tenant)


def slice_quarantine_add(slice_id: str, n: float = 1) -> None:
    """Count a serve-plane slice quarantine
    (``tcr_slice_quarantined_total``); free no-op when telemetry is off.
    The argument is a label value (device id), not an OBS_SITES site."""
    reg = _current()
    if reg is not None:
        reg.slice_quarantine_add(slice_id, n)


def graph_node_add(name: str, *, critical_s: float = 0.0,
                   overlapped_s: float = 0.0) -> None:
    """Record one graph-node execution (critical-path seconds vs seconds
    spent on a worker thread); free no-op when telemetry is off."""
    reg = _current()
    if reg is not None:
        reg.graph_node_add(name, critical_s=critical_s,
                           overlapped_s=overlapped_s)


def graph_node_skip(name: str) -> None:
    """Record a resume skip of a graph node; free no-op when off."""
    reg = _current()
    if reg is not None:
        reg.graph_node_skip(name)


def graph_edge_set(name: str, placement: str) -> None:
    """Record a graph edge's declared placement; free no-op when off."""
    reg = _current()
    if reg is not None:
        reg.graph_edge_set(name, placement)


def graph_node_declare(name: str, *, inputs=None, outputs=None,
                       units: int | None = None) -> None:
    """Record a graph node's declared edges / evaluated workload units
    into the telemetry graph section; free no-op when telemetry is off."""
    reg = _current()
    if reg is not None:
        reg.graph_node_declare(name, inputs=inputs, outputs=outputs,
                               units=units)


def pool_add(site: str, *, busy_s: float = 0.0, idle_s: float = 0.0,
             window_s: float = 0.0, slots: int = 0) -> None:
    """Record a worker pool's busy/idle split; free no-op when off."""
    reg = _current()
    if reg is not None:
        reg.pool_add(site, busy_s=busy_s, idle_s=idle_s, window_s=window_s,
                     slots=slots)


def analysis_set(name: str, summary: dict) -> None:
    """Record a static-analyzer verdict summary (graftcheck) into the
    telemetry artifact; free no-op when telemetry is off."""
    reg = _current()
    if reg is not None:
        reg.analysis_set(name, summary)
