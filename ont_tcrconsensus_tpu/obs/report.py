"""``telemetry.json`` writer + the ``--report`` artifact renderer.

The writer runs inside the pipeline (pipeline/run.py, next to the
robustness-report write) and rolls the armed registry/collector up into
one machine-readable per-run artifact. The renderer is the inverse: it
reads ONLY committed artifacts — ``telemetry.json``,
``robustness_report.json``, per-library ``stage_timing.tsv``,
``logs/trace.json`` — and prints a human summary. Neither path imports
jax at module scope, and the renderer never imports it at all: like
``--validate``, ``--report`` must work on a host whose device tunnel is
wedged (the exact situation that makes someone reach for the telemetry).
"""

from __future__ import annotations

import glob
import json
import os

from ont_tcrconsensus_tpu.obs import critical_path as critical_path_mod
from ont_tcrconsensus_tpu.obs import history as history_mod
from ont_tcrconsensus_tpu.obs import metrics, trace
from ont_tcrconsensus_tpu.obs import transfers as transfers_mod

TELEMETRY_BASENAME = "telemetry.json"
TRACE_RELPATH = os.path.join("logs", "trace.json")


def write_run_telemetry(nano_dir: str, level: str, suffix: str = "") -> str:
    """Roll the armed registry (+ collector at ``full``) into the per-run
    artifacts under ``nano_dir``; returns the telemetry.json path."""
    from ont_tcrconsensus_tpu.robustness import retry

    reg = metrics.registry()
    if reg is None:
        raise RuntimeError("telemetry registry is not armed")
    body = {"telemetry": level, **reg.summary()}
    body["robustness_events"] = {
        site: s["events"] for site, s in sorted(
            retry.recorder().summary().items()
        )
    }
    col = trace.collector()
    trace_rel = None
    if col is not None:
        trace_rel = (TRACE_RELPATH if not suffix
                     else os.path.join("logs", f"trace{suffix}.json"))
        trace_path = os.path.join(nano_dir, trace_rel)
        os.makedirs(os.path.dirname(trace_path), exist_ok=True)
        col.write(trace_path)
    body["trace_json"] = trace_rel
    path = os.path.join(nano_dir, f"telemetry{suffix}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(body, fh, indent=1)
    os.replace(tmp, path)
    return path


# --- the --report renderer ---------------------------------------------------


def resolve_nano_dir(target: str) -> str | None:
    """Accepts a run-config JSON, a ``fastq_pass`` dir, or the ``nano_tcr``
    dir itself; returns the nano_tcr dir or None."""
    if os.path.isfile(target) and target.endswith(".json"):
        try:
            with open(target) as fh:
                cfg = json.load(fh)
            target = cfg.get("fastq_pass_dir", "")
        except (OSError, ValueError):
            return None
    if not os.path.isdir(target):
        return None
    if (glob.glob(os.path.join(target, "telemetry*.json"))
            or glob.glob(os.path.join(target, "robustness_report*.json"))
            or os.path.basename(os.path.normpath(target)) == "nano_tcr"):
        return target
    child = os.path.join(target, "nano_tcr")
    return child if os.path.isdir(child) else None


def _fmt_bytes(n) -> str:
    if n is None:
        return "n/a"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TiB"  # pragma: no cover


def _render_telemetry(data: dict, lines: list[str]) -> None:
    lines.append(f"telemetry level: {data.get('telemetry', '?')}, "
                 f"run duration {data.get('duration_s', 0):.1f}s")
    stages = data.get("stages", {})
    if stages:
        lines.append("stages (critical-path seconds; *_bg ran overlapped):")
        for name, s in stages.items():
            lines.append(f"  {name:28s} {s['seconds']:9.3f}s  "
                         f"x{s['calls']}")
    graph = data.get("graph") or {}
    gnodes = graph.get("nodes", {})
    if gnodes:
        lines.append("stage graph (per-node critical vs overlapped seconds):")
        for name, g in gnodes.items():
            runs, skips = g.get("runs", 0), g.get("skips", 0)
            if runs:
                status = f"x{runs}"
            elif skips:
                status = "resume-skipped"
            else:
                status = "-"
            units = g.get("units")
            lines.append(
                f"  {name:28s} critical {g.get('critical_s', 0.0):8.3f}s  "
                f"overlapped {g.get('overlapped_s', 0.0):8.3f}s  {status}"
                + (f"  ({units} units)" if units else "")
            )
    pool = graph.get("pool") or data.get("overlap_pool")
    if pool:
        lines.append(
            f"overlap pool: busy {pool.get('busy_s', 0.0):.3f}s idle "
            f"{pool.get('idle_s', 0.0):.3f}s across {pool.get('slots')} "
            "worker slot(s)"
        )
    gedges = graph.get("edges", {})
    if gedges:
        lines.append("graph edges (placement): " + ", ".join(
            f"{name}[{placement}]" for name, placement in gedges.items()
        ))
    disp = data.get("dispatch", {})
    if disp:
        lines.append("dispatch sites (host-gap vs blocked-on-device):")
        for site, d in disp.items():
            lines.append(
                f"  {site:28s} {d['dispatches']:5d} dispatches "
                f"{d['gets']:5d} gets  host {d['host_s']:8.3f}s  "
                f"block {d['block_s']:8.3f}s"
            )
    comp = data.get("compile", {})
    lines.append(f"XLA compiles: {comp.get('count', 0)} "
                 f"({comp.get('seconds', 0.0):.1f}s)")
    for label, c in list(comp.get("by_stage", {}).items())[:8]:
        lines.append(f"  {label:28s} {c['count']:4d}  {c['seconds']:.1f}s")
    gauges = data.get("gauges", {})
    lines.append(
        "memory: HBM high-water "
        f"{_fmt_bytes(gauges.get('device.hbm_bytes_in_use'))}, "
        f"peak host RSS {_fmt_bytes(gauges.get('host.rss_bytes'))}"
    )
    transfers = data.get("transfers")
    if transfers is not None:
        # strict indexing on purpose: a garbage transfers section raises
        # into the malformed-artifact handler like every other section
        sites = transfers.get("sites") or {}
        h2d_b = sum(s["h2d_bytes"] for s in sites.values())
        d2h_b = sum(s["d2h_bytes"] for s in sites.values())
        lines.append(
            f"data plane: h2d {_fmt_bytes(h2d_b)}, d2h {_fmt_bytes(d2h_b)} "
            f"across {len(sites)} site(s); host round-trip "
            f"{_fmt_bytes(transfers['host_round_trip_bytes'])}"
        )
        edges = transfers.get("edges") or {}
        for name, e in list(edges.items())[:12]:
            lines.append(
                f"  edge {name:24s} {e['direction']}[{e['placement']}] "
                f"{_fmt_bytes(e['bytes'])} over {e['count']} "
                "materialization(s)"
            )
        donation = transfers.get("donation") or {}
        if donation:
            counts: dict[str, int] = {}
            for d in donation.values():
                counts[d["verdict"]] = counts.get(d["verdict"], 0) + 1
            lines.append("donation verdicts: " + ", ".join(
                f"{k}={counts[k]}" for k in sorted(counts)))
    rob = data.get("robustness_events", {})
    if rob:
        lines.append("robustness events: " + ", ".join(
            f"{site}={n}" for site, n in rob.items()
        ))
    else:
        lines.append("robustness events: none")


def _render_flight_recorder(base: str, rec: dict, lines: list[str]) -> None:
    """Human rendering of one ``logs/flight_recorder*.json`` flush
    (obs/live.py). Raises on a valid-JSON-but-garbage payload — callers
    degrade that to a named problem, matching the telemetry readers."""
    if rec.get("schema") != 1:
        raise ValueError(f"unsupported flight-recorder schema "
                         f"{rec.get('schema')!r}")
    events = rec["events"]
    dropped = int(rec.get("dropped", 0))
    lines.append(
        f"flight recorder {base}: flushed on {rec['reason']!r}, "
        f"{len(events)} buffered event(s)"
        + (f", {dropped} older dropped" if dropped else "")
    )
    for ev in events[-10:]:
        args = ev.get("args")
        lines.append(
            f"  [{ev['kind']:9s}] {ev['name']} "
            f"t+{float(ev['t_s']):.3f}s ({ev.get('thread', '?')})"
            + (f" {args}" if args else "")
        )


def render_report(nano_dir: str, critical_path: bool = False,
                  memory: bool = False) -> tuple[str, int]:
    """(report text, exit code) from the committed artifacts in
    ``nano_dir``. Exit 1 when no telemetry artifact exists. With
    ``critical_path``, each telemetry artifact's executed-graph section is
    additionally run through :mod:`obs.critical_path` (slack / what-if /
    pool efficiency; analysis problems are informational — they name what
    the artifact cannot support, without failing the report). ``memory``
    adds the static-vs-measured HBM reconciliation
    (:func:`obs.transfers.analyze_memory`) under the same contract."""
    lines = [f"run report: {nano_dir}"]
    tele_paths = sorted(glob.glob(os.path.join(nano_dir, "telemetry*.json")))
    tele_paths = [p for p in tele_paths if not p.endswith(".tmp")]
    rc = 0
    if not tele_paths:
        lines.append(
            "no telemetry*.json found — the run predates the telemetry "
            "layer, ran with telemetry=off, or died before roll-up "
            "(robustness/timing artifacts below may still exist)"
        )
        rc = 1
    for path in tele_paths:
        try:
            with open(path) as fh:
                data = json.load(fh)
        except (OSError, ValueError) as exc:
            lines.append(f"unreadable {os.path.basename(path)}: {exc!r}")
            rc = 1
            continue
        if not isinstance(data, dict):
            lines.append(f"malformed telemetry artifact "
                         f"{os.path.basename(path)}: not a JSON object")
            rc = 1
            continue
        if len(tele_paths) > 1:
            lines.append(f"-- {os.path.basename(path)} --")
        try:
            _render_telemetry(data, lines)
        except Exception as exc:
            # never-crash contract (cf. the PR 5 manifest readers): a
            # valid-JSON-but-garbage artifact — torn write, hand edit,
            # schema drift — degrades to a named problem, not a traceback
            # on the wedged-host diagnosis path
            lines.append(
                f"malformed telemetry artifact {os.path.basename(path)}: "
                f"{exc!r}"
            )
            rc = 1
            continue
        trace_rel = data.get("trace_json")
        trace_payload = None
        if isinstance(trace_rel, str) and trace_rel:
            tpath = os.path.join(nano_dir, trace_rel)
            try:
                with open(tpath) as fh:
                    trace_payload = json.load(fh)
                if not isinstance(trace_payload, dict):
                    trace_payload = None
                n_events = len((trace_payload or {}).get("traceEvents", []))
                lines.append(f"trace: {trace_rel} ({n_events} events; open "
                             "in chrome://tracing or Perfetto)")
            except (OSError, ValueError) as exc:
                lines.append(f"trace: {trace_rel} unreadable ({exc!r})")
                rc = 1
        else:
            lines.append("trace: none (telemetry=full records one)")
        if critical_path:
            lines.append("-- critical path --")
            critical_path_mod.render(
                critical_path_mod.analyze(data, trace_payload), lines)
        if memory:
            lines.append("-- memory reconciliation --")
            transfers_mod.render_memory(
                transfers_mod.analyze_memory(data), lines)
    for rpath in sorted(glob.glob(
        os.path.join(nano_dir, "robustness_report*.json")
    )):
        try:
            with open(rpath) as fh:
                rep = json.load(fh)
            n_events = len(rep.get("events") or [])
            chaos = rep.get("chaos")
        except (OSError, ValueError, AttributeError, TypeError):
            lines.append(f"unreadable {os.path.basename(rpath)}")
            continue
        lines.append(
            f"{os.path.basename(rpath)}: {n_events} event(s), "
            f"chaos {'armed' if chaos else 'off'}"
        )
    for fpath in sorted(glob.glob(
        os.path.join(nano_dir, "logs", "flight_recorder*.json")
    )):
        base = os.path.basename(fpath)
        try:
            with open(fpath) as fh:
                rec = json.load(fh)
            if not isinstance(rec, dict):
                raise ValueError("not a JSON object")
        except (OSError, ValueError) as exc:
            lines.append(f"unreadable flight recorder {base}: {exc!r}")
            rc = 1
            continue
        try:
            _render_flight_recorder(base, rec, lines)
        except Exception as exc:
            # same never-crash contract as the telemetry readers above
            lines.append(f"malformed flight recorder {base}: {exc!r}")
            rc = 1
    tsvs = sorted(glob.glob(
        os.path.join(nano_dir, "*", "logs", "stage_timing.tsv")
    ))
    if tsvs:
        lines.append(f"per-library stage timing: {len(tsvs)} "
                     "stage_timing.tsv file(s)")
    for hpath in sorted(glob.glob(os.path.join(nano_dir, "history*.jsonl"))):
        entries, problems = history_mod.read_entries(hpath)
        lines.append(
            f"run history: {len(entries)} entrie(s) in "
            f"{os.path.basename(hpath)}"
            + (f", {len(problems)} garbage line(s) skipped" if problems
               else "")
        )
    return "\n".join(lines) + "\n", rc


def collect_report(nano_dir: str, critical_path: bool = False,
                   memory: bool = False) -> tuple[dict, int]:
    """Machine-readable twin of :func:`render_report` (``--report --json``).

    Same resolution rules and exit codes: each telemetry artifact is
    validated through the text renderer's own code path (into a discarded
    scratch buffer), so a valid-JSON-but-garbage artifact yields the same
    named problem + exit 1 in both modes instead of laundering garbage
    into a clean-looking JSON dump.
    """
    out: dict = {"nano_dir": nano_dir, "problems": [], "telemetry": {}}
    rc = 0
    tele_paths = sorted(glob.glob(os.path.join(nano_dir, "telemetry*.json")))
    tele_paths = [p for p in tele_paths if not p.endswith(".tmp")]
    if not tele_paths:
        out["problems"].append(
            "no telemetry*.json found — the run predates the telemetry "
            "layer, ran with telemetry=off, or died before roll-up")
        rc = 1
    if critical_path:
        out["critical_path"] = {}
    if memory:
        out["memory"] = {}
    for path in tele_paths:
        base = os.path.basename(path)
        try:
            with open(path) as fh:
                data = json.load(fh)
        except (OSError, ValueError) as exc:
            out["problems"].append(f"unreadable {base}: {exc!r}")
            rc = 1
            continue
        if not isinstance(data, dict):
            out["problems"].append(
                f"malformed telemetry artifact {base}: not a JSON object")
            rc = 1
            continue
        try:
            _render_telemetry(data, [])  # schema check, text discarded
        except Exception as exc:
            out["problems"].append(
                f"malformed telemetry artifact {base}: {exc!r}")
            rc = 1
            continue
        out["telemetry"][base] = data
        trace_rel = data.get("trace_json")
        trace_payload = None
        if isinstance(trace_rel, str) and trace_rel:
            try:
                with open(os.path.join(nano_dir, trace_rel)) as fh:
                    trace_payload = json.load(fh)
                if not isinstance(trace_payload, dict):
                    trace_payload = None
            except (OSError, ValueError) as exc:
                out["problems"].append(f"trace {trace_rel} unreadable "
                                       f"({exc!r})")
                rc = 1
        if critical_path:
            out["critical_path"][base] = critical_path_mod.analyze(
                data, trace_payload)
        if memory:
            out["memory"][base] = transfers_mod.analyze_memory(data)
    robustness: dict = {}
    for rpath in sorted(glob.glob(
        os.path.join(nano_dir, "robustness_report*.json")
    )):
        base = os.path.basename(rpath)
        try:
            with open(rpath) as fh:
                rep = json.load(fh)
            robustness[base] = {"events": len(rep.get("events") or []),
                                "chaos": bool(rep.get("chaos"))}
        except (OSError, ValueError, AttributeError, TypeError):
            robustness[base] = {"problem": "unreadable"}
    out["robustness_reports"] = robustness
    flights: dict = {}
    for fpath in sorted(glob.glob(
        os.path.join(nano_dir, "logs", "flight_recorder*.json")
    )):
        base = os.path.basename(fpath)
        try:
            with open(fpath) as fh:
                rec = json.load(fh)
            if not isinstance(rec, dict):
                raise ValueError("not a JSON object")
            _render_flight_recorder(base, rec, [])  # schema check only
        except (OSError, ValueError) as exc:
            out["problems"].append(f"unreadable flight recorder {base}: "
                                   f"{exc!r}")
            rc = 1
            continue
        except Exception as exc:
            out["problems"].append(f"malformed flight recorder {base}: "
                                   f"{exc!r}")
            rc = 1
            continue
        flights[base] = rec
    out["flight_recorders"] = flights
    out["stage_timing_tsvs"] = len(glob.glob(
        os.path.join(nano_dir, "*", "logs", "stage_timing.tsv")))
    hist: dict = {}
    for hpath in sorted(glob.glob(os.path.join(nano_dir, "history*.jsonl"))):
        entries, problems = history_mod.read_entries(hpath)
        hist[os.path.basename(hpath)] = {
            "entries": len(entries), "problems": problems,
            "last": entries[-1] if entries else None,
        }
    out["history"] = hist
    return out, rc


def report_main(target: str, as_json: bool = False,
                critical_path: bool = False, memory: bool = False) -> int:
    """CLI body for ``tcr-consensus-tpu --report <workdir>``."""
    import sys

    nano = resolve_nano_dir(target)
    if nano is None:
        msg = (f"--report: no run directory found at {target!r} (expected a "
               "run-config JSON, a fastq_pass dir, or its nano_tcr subdir)")
        print(msg, file=sys.stderr)
        if as_json:
            json.dump({"problems": [msg]}, sys.stdout)
            sys.stdout.write("\n")
        return 2
    if as_json:
        data, rc = collect_report(nano, critical_path=critical_path,
                                  memory=memory)
        json.dump(data, sys.stdout, indent=1)
        sys.stdout.write("\n")
        return rc
    text, rc = render_report(nano, critical_path=critical_path, memory=memory)
    sys.stdout.write(text)
    return rc
