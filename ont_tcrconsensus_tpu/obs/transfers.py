"""Device data-plane ledger: measured transfers, donation verdicts, and
the static-vs-measured HBM reconciler.

``tools/graftcheck`` (graph/check.py) *predicts* the data plane — per-step
liveness, donation eligibility, host round-trips — from the declared
GraphSpec alone. This module is its dynamic twin: the runtime instrument
that measures what actually moved, so ROADMAP-1's "nothing round-trips
the host between rounds" has a committed artifact instead of a claim.

Three planes, all riding the armed :mod:`.metrics` registry (one
module-attribute check when telemetry is off — the same hot-loop
discipline as every other plant):

- **transfer ledger** — :func:`h2d` / :func:`d2h` are planted at the
  device boundary (parallel/mesh.py device_puts, obs/device.py
  ``timed_get``) and record per-site bytes/counts; the graph executor
  feeds :func:`edge_materialized` per materialized edge, attributing
  bytes to graph edges and charging host-placed edges on graftcheck's
  round-trip paths to the run-level ``host_round_trip_bytes`` budget
  that ``bench.py --gate`` regresses on.
- **donation auditor** — the executor probes buffer identity
  (``unsafe_buffer_pointer``, guarded per-backend) around each node for
  inputs at their drop point and :func:`audit_donation` turns the probes
  into a ``donated|copied|unknown`` verdict per edge; CPU backends
  degrade to ``unknown`` by design (no donation there to certify).
- **reconciler** — :func:`node_hbm_boundary` samples device
  bytes-in-use at graph-node boundaries; :func:`analyze_memory` /
  :func:`render_memory` (jax-free, consumed by ``--report --memory``)
  join those samples against graftcheck's static per-step liveness and
  name any divergence beyond threshold as a problem.

Every probe only *reads* values — pipeline outputs must stay
byte-identical to a telemetry-off run — and never raises into the
pipeline: a ledger that can crash the run it audits is worse than none.
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Any, Iterator

from ont_tcrconsensus_tpu.obs import metrics

# divergence beyond this fraction of the static estimate is a named
# problem in --report --memory (the static model is a deliberate
# envelope, so the band is wide; a reintroduced per-node copy blows
# through it anyway)
DIVERGENCE_THRESHOLD = 0.5


# --- byte accounting --------------------------------------------------------


def nbytes_of(value: Any, _depth: int = 0) -> int:
    """Conservative byte size of a pytree-ish value.

    Trusts a leaf ``.nbytes`` (numpy / jax arrays), measures
    bytes/str, and recurses ONLY into dict/list/tuple/set/dataclass
    containers — never arbitrary iterables, because consuming a
    generator edge value here would corrupt the pipeline the ledger is
    auditing. Unknown leaves count 0: the ledger under-reports rather
    than guesses.
    """
    if value is None or _depth > 6:
        return 0
    try:
        nb = getattr(value, "nbytes", None)
    except Exception:  # exotic lazy proxy: count 0, never raise
        return 0
    if isinstance(nb, (int, float)) and not isinstance(nb, bool):
        return int(nb)
    if isinstance(value, (bytes, bytearray)):
        return len(value)
    if isinstance(value, str):
        return len(value.encode("utf-8", "replace"))
    if isinstance(value, bool):
        return 1
    if isinstance(value, (int, float)):
        return 8
    if isinstance(value, dict):
        return sum(nbytes_of(v, _depth + 1) for v in value.values())
    if isinstance(value, (list, tuple, set, frozenset)):
        return sum(nbytes_of(v, _depth + 1) for v in value)
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return sum(nbytes_of(getattr(value, f.name, None), _depth + 1)
                   for f in dataclasses.fields(value))
    return 0


def _safe_nbytes(value: Any) -> int:
    try:
        return nbytes_of(value)
    except Exception:  # measurement must never fail the transfer it measures
        return 0


# --- transfer ledger plants -------------------------------------------------


def h2d(site: str, value: Any, nbytes: int | None = None) -> None:
    """Record a host->device transfer at ``site``; free no-op when
    telemetry is off. ``value`` is only sized, never mutated."""
    reg = metrics._ARMED
    if reg is not None:
        nb = _safe_nbytes(value) if nbytes is None else int(nbytes)
        reg.counter_add("transfer.h2d", nb)
        reg.transfer_add(site, "h2d", nb)


def d2h(site: str, value: Any, nbytes: int | None = None) -> None:
    """Record a device->host transfer at ``site``; free no-op when
    telemetry is off. ``value`` is only sized, never mutated."""
    reg = metrics._ARMED
    if reg is not None:
        nb = _safe_nbytes(value) if nbytes is None else int(nbytes)
        reg.counter_add("transfer.d2h", nb)
        reg.transfer_add(site, "d2h", nb)


def edge_materialized(edge: str, placement: str, value: Any, *,
                      round_trip: bool = False) -> None:
    """Record one graph-edge materialization (executor's _absorb).

    Attributes the edge's bytes to its declared placement direction
    ("hbm" edges land on-device -> h2d; "host"/"disk" edges leave the
    producer toward the host -> d2h) and charges edges on graftcheck's
    placement-round-trip paths to the run-level host_round_trip_bytes —
    the number ``bench.py --gate`` holds the line on.
    """
    reg = metrics._ARMED
    if reg is not None:
        nb = _safe_nbytes(value)
        direction = "h2d" if placement == "hbm" else "d2h"
        reg.edge_transfer_add(edge, direction, nb, placement)
        if round_trip:
            reg.round_trip_add(nb)


# --- donation auditor -------------------------------------------------------


def _leaves(value: Any, _depth: int = 0) -> Iterator[Any]:
    """Yield array-ish leaves of a container value; same safe recursion
    set as :func:`nbytes_of` (never consumes iterators)."""
    if value is None or _depth > 6:
        return
    if isinstance(value, dict):
        for v in value.values():
            yield from _leaves(v, _depth + 1)
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _leaves(v, _depth + 1)
    else:
        yield value


def buffer_probe(value: Any) -> tuple[set[int], bool, int] | None:
    """Probe buffer identity of every jax array inside ``value``.

    Returns ``(pointer set, saw_non_cpu_device, n_deleted)`` or None
    when no leaf exposes ``unsafe_buffer_pointer`` at all (non-jax
    values) — the caller degrades that to a ``unknown`` verdict rather
    than guessing.  A leaf whose probe raises the runtime's
    deleted/donated-buffer error is NOT a probe failure: after a
    donating call it is positive evidence that XLA took the buffer, so
    those leaves are counted in ``n_deleted`` (and a probe that finds
    only deleted leaves still returns, with an empty pointer set) while
    genuinely unreadable leaves (sharded arrays refusing the call) are
    skipped as before.
    """
    ptrs: set[int] = set()
    non_cpu = False
    deleted = 0
    probed = False
    for leaf in _leaves(value):
        fn = getattr(leaf, "unsafe_buffer_pointer", None)
        if fn is None:
            continue
        probed = True
        try:
            ptrs.add(int(fn()))
        except RuntimeError as exc:
            # jax raises RuntimeError("Array has been deleted...") /
            # ("...buffer ... deleted") once donation or an explicit
            # .delete() invalidates the buffer — that is testimony of
            # donation, not an opaque failure
            if "delet" in str(exc).lower() or _leaf_is_deleted(leaf):
                deleted += 1
            continue
        except Exception:  # sharded buffer refusing the call: skip the leaf
            if _leaf_is_deleted(leaf):
                deleted += 1
            continue
        try:
            if any(getattr(d, "platform", "cpu") != "cpu"
                   for d in leaf.devices()):
                non_cpu = True
        except Exception:  # device introspection is advisory only
            pass
    if not probed:
        return None
    return (ptrs, non_cpu, deleted) if (ptrs or deleted) else None


def _leaf_is_deleted(leaf: Any) -> bool:
    """Ask the array itself whether its buffer is gone (jax exposes
    ``is_deleted()``); False on any doubt — deletion evidence must be
    positive, never inferred from a probe that merely errored."""
    try:
        is_deleted = getattr(leaf, "is_deleted", None)
        return bool(is_deleted()) if callable(is_deleted) else False
    except Exception:
        return False


def _deleted_count(probe: tuple | None) -> int:
    """Deleted-leaf count from a probe tuple; legacy 2-tuples carry 0."""
    if probe is None or len(probe) < 3:
        return 0
    n = probe[2]
    return int(n) if isinstance(n, int) and not isinstance(n, bool) else 0


def donation_verdict(in_probe: tuple | None,
                     out_probe: tuple | None,
                     post_probe: tuple | None = None) -> str:
    """Pure verdict logic: did a donation-eligible input buffer get
    reused by the node's outputs?

    Probe tuples are ``(pointer set, saw_non_cpu_device[, n_deleted])``;
    the two-element legacy shape is accepted (deleted count 0).
    ``post_probe`` is an optional re-probe of the *input* value after
    the call returned.

    - no readable input pointers -> ``unknown`` (can't testify);
    - CPU-only buffers -> ``unknown`` (XLA:CPU aliasing is not the
      donation ROADMAP-1 certifies; a CPU run must not report a fake
      ``copied`` regression);
    - input pointer reappears among outputs -> ``donated``;
    - input buffer reads *deleted* after the call (post_probe) ->
      ``donated`` — XLA took the buffer even if the output landed at a
      different address (reshaped/fused outputs);
    - readable on-device input, disjoint live outputs -> ``copied``
      (the named finding: the buffer lived on after its drop point).
    """
    if in_probe is None:
        return "unknown"
    in_ptrs, non_cpu = in_probe[0], in_probe[1]
    if not non_cpu:
        return "unknown"
    if out_probe is not None and in_ptrs & out_probe[0]:
        return "donated"
    if _deleted_count(post_probe) > 0:
        return "donated"
    return "copied"


def audit_donation(edge: str, node: str,
                   in_probe: tuple | None,
                   out_probe: tuple | None,
                   post_probe: tuple | None = None) -> None:
    """Record the donation verdict for ``edge`` dropped at ``node``;
    free no-op when telemetry is off."""
    reg = metrics._ARMED
    if reg is not None:
        reg.counter_add("donation.audit")
        reg.donation_set(
            edge, donation_verdict(in_probe, out_probe, post_probe), node)


# --- measured per-node HBM --------------------------------------------------


def node_hbm_boundary(node: str) -> None:
    """Sample device bytes-in-use at a graph-node boundary.

    Free no-op when telemetry is off or jax was never imported (the
    jax-free executor unit tests); backends without memory_stats (CPU)
    yield no sample — --report --memory names that degradation instead
    of inventing numbers.
    """
    reg = metrics._ARMED
    if reg is None:
        return
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return
    try:
        from ont_tcrconsensus_tpu.obs import device as obs_device

        end = obs_device._device_bytes_in_use(jax_mod.local_devices(),
                                              "bytes_in_use")
    except Exception:  # wedged device tunnel: sampling is best-effort
        return
    if end is None:
        return
    reg.counter_add("memory.reconcile")
    reg.node_hbm_add(node, end)


def static_hbm(node: str, bytes_est: int) -> None:
    """Record graftcheck's static live-HBM estimate while ``node`` runs
    (fed from the report's per-step liveness at run start, so --report
    needs no config or jax to reconcile); free no-op when off."""
    reg = metrics._ARMED
    if reg is not None:
        reg.static_hbm_set(node, bytes_est)


# --- static-vs-measured reconciler (jax-free; --report --memory) ------------


def _num(value: Any) -> int | None:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    return int(value)


def analyze_memory(data: Any, *,
                   divergence_threshold: float = DIVERGENCE_THRESHOLD) -> dict:
    """Reconcile a telemetry.json payload's measured data plane against
    graftcheck's static model.

    Pure dict-in/dict-out on the committed artifact (no jax, no config):
    the ``--report --memory`` backend. Follows the --critical-path
    degradation contract — any garbage shape becomes a named problem in
    the result, never an exception.
    """
    out: dict[str, Any] = {"nodes": {}, "problems": []}
    problems: list[str] = out["problems"]
    if not isinstance(data, dict):
        problems.append("telemetry payload is not an object")
        return out
    tr = data.get("transfers")
    if tr is None:
        problems.append(
            "no transfers section — artifact predates the data-plane "
            "ledger or the run had telemetry off")
        return out
    if not isinstance(tr, dict):
        problems.append(
            f"transfers section is not an object ({type(tr).__name__})")
        return out

    hrt = _num(tr.get("host_round_trip_bytes"))
    if hrt is not None:
        out["host_round_trip_bytes"] = hrt
    elif "host_round_trip_bytes" in tr:
        problems.append("host_round_trip_bytes is not a number")

    donation = tr.get("donation")
    if donation is not None and not isinstance(donation, dict):
        problems.append("donation table is not an object")
        donation = None
    if donation:
        counts: dict[str, int] = {}
        for edge, entry in donation.items():
            verdict = entry.get("verdict") if isinstance(entry, dict) else None
            if not isinstance(verdict, str):
                problems.append(f"garbage donation entry {edge!r} dropped")
                continue
            counts[verdict] = counts.get(verdict, 0) + 1
            if verdict == "copied":
                node = entry.get("node")
                problems.append(
                    f"donation regression: edge {edge!r} was COPIED at its "
                    f"drop point ({node}) — the donation-eligible buffer "
                    "lived on in HBM")
        out["donation"] = counts

    static = tr.get("static_hbm_by_node")
    if static is not None and not isinstance(static, dict):
        problems.append("static_hbm_by_node is not an object")
        static = None
    measured = tr.get("node_hbm")
    if measured is not None and not isinstance(measured, dict):
        problems.append("node_hbm table is not an object")
        measured = None
    static = static or {}
    measured = measured or {}

    for node in sorted(set(static) | set(measured)):
        row: dict[str, Any] = {}
        s = _num(static.get(node))
        if node in static and s is None:
            problems.append(f"garbage static HBM entry {node!r} dropped")
        if s is not None:
            row["static_bytes"] = s
        m = measured.get(node)
        end = delta = None
        if node in measured:
            if isinstance(m, dict):
                end = _num(m.get("end_bytes"))
                delta = _num(m.get("delta_bytes"))
            if end is None and delta is None:
                problems.append(f"garbage node_hbm entry {node!r} dropped")
        if end is not None:
            row["measured_end_bytes"] = end
        if delta is not None:
            row["measured_delta_bytes"] = delta
        if s and end is not None:
            div = (end - s) / s
            row["divergence"] = round(div, 3)
            if abs(div) > divergence_threshold:
                problems.append(
                    f"hbm divergence at node {node}: static {s} B vs "
                    f"measured {end} B ({div:+.0%}, threshold "
                    f"±{divergence_threshold:.0%}) — the static model "
                    "and the device disagree about what this node keeps "
                    "live")
        if row:
            out["nodes"][node] = row

    if static and not any("measured_end_bytes" in r
                          for r in out["nodes"].values()):
        problems.append(
            "no measured per-node HBM samples — backend reports no "
            "memory stats (CPU) or the run predates the boundary "
            "sampler; static liveness only")
    if not static and not measured:
        problems.append(
            "no static/measured per-node HBM tables — imperative "
            "executor run or pre-upgrade artifact")
    return out


def _fmt_bytes(n: Any) -> str:
    if not isinstance(n, (int, float)) or isinstance(n, bool):
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} GiB"


def render_memory(analysis: dict, lines: list[str]) -> None:
    """Append the human rendering of :func:`analyze_memory` to ``lines``
    (the --report --memory section body)."""
    nodes = analysis.get("nodes") or {}
    if nodes:
        lines.append("static graftcheck estimate vs measured device "
                     "bytes-in-use, per graph node:")
        for name, row in nodes.items():
            parts = [f"  {name:<26s}"]
            parts.append(f"static {_fmt_bytes(row.get('static_bytes')):>11s}"
                         if "static_bytes" in row else
                         f"static {'-':>11s}")
            parts.append(
                f"measured {_fmt_bytes(row.get('measured_end_bytes')):>11s}"
                if "measured_end_bytes" in row else f"measured {'-':>11s}")
            if "measured_delta_bytes" in row:
                parts.append(
                    f"delta {_fmt_bytes(row['measured_delta_bytes']):>11s}")
            if "divergence" in row:
                parts.append(f"divergence {row['divergence']:+.0%}")
            lines.append(" ".join(parts))
    if "host_round_trip_bytes" in analysis:
        lines.append("measured host round-trip: "
                     f"{_fmt_bytes(analysis['host_round_trip_bytes'])}")
    donation = analysis.get("donation")
    if donation:
        lines.append("donation verdicts: " + ", ".join(
            f"{k}={donation[k]}" for k in sorted(donation)))
    for p in analysis.get("problems", ()):
        lines.append(f"memory problem: {p}")
    if not nodes and not analysis.get("problems"):
        lines.append("nothing to reconcile")
