"""Unified telemetry layer: metrics, traces, and device instrumentation.

The observability fragments that grew alongside the pipeline —
``stage_timing.tsv``, ``robustness_report.json``, watchdog logs, the
optional whole-run jax.profiler trace — cannot answer the questions the
perf and service-mode work is blocked on: where the round1_polish
dispatch/sync tax actually goes, whether tenant-to-tenant traffic
recompiles, and what the HBM high-water / peak host RSS are at scale.
This package is the one instrumentation layer behind all of them:

- :mod:`.metrics` — a process-wide registry of counters / high-water
  gauges / histograms plus per-dispatch-site and per-compile aggregates,
  behind the same one-module-attr-check-when-disarmed discipline as
  ``faults.inject`` and ``watchdog.heartbeat``.
- :mod:`.trace`   — span/instant API emitting a Chrome-trace-format
  ``logs/trace.json`` (thread-named rows for the main loop, overlap
  workers and the watchdog monitor; instant events for retries, chaos
  injections, stalls, contract violations and quarantine hits).
  :class:`~ont_tcrconsensus_tpu.qc.timing.StageTimer` measures THROUGH
  these spans, so the timing table and the trace derive from one clock
  read and cannot disagree.
- :mod:`.device`  — dispatch-site host-gap vs ``block_until_ready``
  split, the ``jax.monitoring`` recompile audit attributing every XLA
  compile to the active stage/shape-bucket, and the HBM / host-RSS
  high-water sampler.
- :mod:`.report`  — the per-run ``telemetry.json`` writer and the
  ``tcr-consensus-tpu --report`` renderer (reads committed artifacts
  only; never imports jax).

Config: ``telemetry: off|on|full`` (pipeline/config.py). ``on`` (default)
arms the metrics registry + compile audit and writes ``telemetry.json``;
``full`` additionally arms the trace collector (``logs/trace.json``) and
the memory sampler; ``off`` disarms everything — the planted call sites
reduce to one module-attribute check.

Every metric/span/dispatch-site name literal planted in the tree must be
an entry of :data:`KNOWN_SITES`, and every entry must be planted
somewhere — both directions are enforced by the graftlint ``obs-sites``
rule (tools/graftlint/rules/obs_sites.py), mirroring the chaos-site
cross-check.
"""

from __future__ import annotations

# The site vocabulary. Defined under its own name (OBS_SITES) so the
# graftlint chaos-site rule — which collects string constants from every
# ``KNOWN_SITES = ...`` assignment in the scanned tree — does not merge
# these into the chaos registry; the public alias below keeps the
# ``obs.KNOWN_SITES`` API symmetric with ``faults.KNOWN_SITES``.
OBS_SITES = frozenset({
    # --- stage spans (qc/timing.StageTimer -> trace.span; every name is
    # also a graph node, declared in graph/pipeline.py — graftlint's
    # graph-sites rule holds GRAPH_NODES ⊆ OBS_SITES) ---
    "round1_fused_assign",
    "round1_error_profile",
    "round1_region_split",
    "write_region_fastas",
    "round1_umi_records",
    "round1_umi_cluster",
    "round1_polish",
    "round1_consensus",
    "round2_fused_assign",
    "round2_error_profile",
    "round2_umi_records",
    "round2_umi_cluster",
    "round2_counts",
    # --- hot-loop counters (metrics.counter_add) ---
    "assign.batches",
    "polish.chunks",
    "cluster.batched",
    # --- histogram observations (metrics.observe) ---
    "polish.chunk_clusters",
    # --- dispatch sites (device.dispatch / device.timed_get) ---
    "assign.dispatch",
    "polish.dispatch",
    "cluster.batched_dispatch",
    "consensus.get",
    "polisher.get",
    "umi.distance",
    # --- worker-pool busy/idle split (metrics.pool_add, overlap.py) ---
    "overlap.pool",
    # --- instant events (trace.instant) ---
    "chaos.inject",
    "xla.compile",
    # --- memory high-water gauges (metrics.gauge_max, device sampler) ---
    "device.hbm_bytes_in_use",
    "host.rss_bytes",
    # --- live observability plane (obs/live.py: endpoint request counter
    # via metrics.counter_add, flight-recorder instants via
    # live.ring_event) ---
    "live.requests",
    "live.serve",
    "flight.flush",
    # --- warm-serving daemon (serve/): queue admission counters + depth
    # gauge + wait/first-stage histograms (metrics.*) and job-lifecycle
    # flight-ring instants (live.ring_event) ---
    "serve.submitted",
    "serve.rejected",
    "serve.requeued",
    "serve.retried",
    "serve.done",
    "serve.failed",
    "serve.poisoned",
    "serve.queue_depth",
    "serve.wait_s",
    "serve.first_stage_s",
    "serve.job",
    "serve.drain",
    # --- slice-packed multi-tenant serving (serve/slices.py +
    # serve/daemon.py runner pool): resident-job live gauge via
    # metrics.gauge_set and slice assign/release/quarantine flight-ring
    # instants via live.ring_event — the per-slice tenant-occupancy and
    # quarantine label tables ride their own families,
    # tcr_mesh_slice_busy{tenant=} / tcr_slice_quarantined_total) ---
    "serve.resident_jobs",
    "serve.slice",
    # --- device data-plane ledger (obs/transfers.py: transfer plants at
    # the device boundary, donation-audit and HBM-reconcile sample
    # counters via metrics.counter_add) ---
    "transfer.h2d",
    "transfer.d2h",
    "donation.audit",
    "memory.reconcile",
    # --- sharded execution (parallel/mesh.py mark_mesh_slices: whole-mesh
    # busy gauge via metrics.gauge_set; graph/executor.py degraded-mesh
    # loop: re-execution counter via metrics.counter_add — the per-slice
    # and per-fault-site label tables ride their own families,
    # tcr_mesh_slice_busy / tcr_mesh_degraded_total) ---
    "mesh.slice_busy",
    "mesh.degraded",
})

KNOWN_SITES = OBS_SITES
