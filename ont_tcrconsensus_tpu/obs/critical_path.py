"""Critical-path analysis over the executed-graph telemetry section.

The telemetry ``graph`` section records what each node cost
(``critical_s`` / ``overlapped_s``) and — since the cross-run
observability PR — each node's declared ``inputs``/``outputs`` edges,
``units``, and the overlap pool's busy/idle split. That is enough to
reconstruct the executed DAG post-hoc and answer the question ROADMAP
items 1-3 keep circling: *which node do we attack next?*

:func:`analyze` computes, from a telemetry.json dict (plus optionally the
Chrome-trace dict for observed wall windows):

- the **critical path** (longest chain of node critical seconds through
  the dependency DAG) and its length;
- per-node **slack** (how much a node could grow before extending the
  run) and an on-critical-path flag;
- **what-if** estimates: how much the critical path shrinks if a given
  node were free — the honest version of "node X takes Y seconds",
  because shortening an overlapped or slack-rich node saves nothing;
- the per-node **dispatch-tax rollup** (host-gap vs blocked-on-device
  seconds from the ``dispatch_by_stage`` table, worker ``_bg`` spans
  folded into their node);
- **overlap-pool efficiency** (worker busy vs idle seconds).

Never-crash contract (cf. the --report renderer and manifest readers):
valid-JSON-but-garbage input degrades to named strings in the returned
``problems`` list — this module raises nothing and imports neither jax
nor anything that does, so it stays safe on wedged-tunnel hosts.
"""

from __future__ import annotations


def _num(value, default: float = 0.0) -> float | None:
    """float(value) when it is a usable non-negative number, else None."""
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    return float(value) if value >= 0 else None


def _toposort(preds: dict[str, list[str]]) -> list[str] | None:
    """Kahn with name tie-break (stable output); None on a cycle."""
    indeg = {n: len(preds[n]) for n in preds}
    consumers: dict[str, list[str]] = {n: [] for n in preds}
    for n, ps in preds.items():
        for p in ps:
            consumers[p].append(n)
    ready = sorted(n for n, d in indeg.items() if d == 0)
    order: list[str] = []
    while ready:
        n = ready.pop(0)
        order.append(n)
        for c in consumers[n]:
            indeg[c] -= 1
            if indeg[c] == 0:
                ready.append(c)
        ready.sort()
    return order if len(order) == len(preds) else None


def _merge_dispatch(*rows) -> dict | None:
    out = {"dispatches": 0, "gets": 0, "host_s": 0.0, "block_s": 0.0}
    seen = False
    for row in rows:
        if not isinstance(row, dict):
            continue
        seen = True
        for key in out:
            v = _num(row.get(key, 0))
            if v is not None:
                out[key] += v
    if not seen:
        return None
    return {"dispatches": int(out["dispatches"]), "gets": int(out["gets"]),
            "host_s": round(out["host_s"], 3),
            "block_s": round(out["block_s"], 3)}


def _trace_windows(trace: dict, node_names: set[str],
                   problems: list[str]) -> dict | None:
    """Observed per-node wall windows from Chrome-trace X events (node
    spans plus their ``_bg`` worker spans), in seconds from the earliest
    matching span — the realized schedule the DAG math predicts."""
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        problems.append("trace has no traceEvents list — skipping the "
                        "span join")
        return None
    windows: dict[str, list[float]] = {}
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        name = ev.get("name")
        if not isinstance(name, str):
            continue
        base = name[:-3] if name.endswith("_bg") else name
        if base not in node_names:
            continue
        ts, dur = _num(ev.get("ts")), _num(ev.get("dur"))
        if ts is None or dur is None:
            continue
        w = windows.setdefault(base, [ts, ts + dur])
        w[0] = min(w[0], ts)
        w[1] = max(w[1], ts + dur)
    if not windows:
        return None
    t0 = min(w[0] for w in windows.values())
    t1 = max(w[1] for w in windows.values())
    return {
        # trace timestamps are microseconds (Chrome trace-event format)
        "makespan_s": round((t1 - t0) / 1e6, 3),
        "node_windows_s": {
            k: [round((w[0] - t0) / 1e6, 3), round((w[1] - t0) / 1e6, 3)]
            for k, w in sorted(windows.items())
        },
    }


def analyze(telemetry: dict, trace: dict | None = None) -> dict:
    """Critical-path report dict from a telemetry.json payload.

    Always returns a dict with a ``problems`` list; the DAG keys
    (``critical_path``, ``nodes``, ...) appear only when the artifact
    carries enough structure to compute them.
    """
    out: dict = {"problems": []}
    problems: list[str] = out["problems"]
    graph = telemetry.get("graph") if isinstance(telemetry, dict) else None
    if not isinstance(graph, dict):
        problems.append(
            "no executed-graph section in telemetry (imperative run, "
            "telemetry=off, or an artifact predating the graph executor)")
        return out
    raw_nodes = graph.get("nodes")
    if not isinstance(raw_nodes, dict) or not raw_nodes:
        problems.append("graph section has no nodes object")
        return out

    nodes: dict[str, dict] = {}
    producer: dict[str, str] = {}
    have_deps = False
    for name, g in raw_nodes.items():
        if not isinstance(g, dict):
            problems.append(f"node {name!r}: entry is not an object (dropped)")
            continue
        crit = _num(g.get("critical_s", 0.0))
        if crit is None:
            problems.append(f"node {name!r}: bad critical_s "
                            f"{g.get('critical_s')!r} (treated as 0)")
            crit = 0.0
        over = _num(g.get("overlapped_s", 0.0)) or 0.0
        ins, outs = g.get("inputs"), g.get("outputs")
        if ins is not None or outs is not None:
            have_deps = True
        nodes[name] = {
            "critical_s": crit,
            "overlapped_s": over,
            "units": g.get("units"),
            "inputs": [e for e in ins if isinstance(e, str)]
            if isinstance(ins, list) else [],
            "outputs": [e for e in outs if isinstance(e, str)]
            if isinstance(outs, list) else [],
        }
        for e in nodes[name]["outputs"]:
            producer[e] = name
    if not nodes:
        problems.append("no usable node entries in the graph section")
        return out

    out["duration_s"] = _num(telemetry.get("duration_s"))
    out["nodes_total_s"] = round(
        sum(n["critical_s"] for n in nodes.values()), 3)

    pool = graph.get("pool")
    if not isinstance(pool, dict):
        pool = telemetry.get("overlap_pool")
    if isinstance(pool, dict):
        busy = _num(pool.get("busy_s")) or 0.0
        idle = _num(pool.get("idle_s")) or 0.0
        eff = busy / (busy + idle) if busy + idle > 0 else None
        out["pool"] = {
            "busy_s": round(busy, 3), "idle_s": round(idle, 3),
            "window_s": _num(pool.get("window_s")),
            "slots": pool.get("slots"),
            "efficiency": round(eff, 4) if eff is not None else None,
        }

    if not have_deps:
        problems.append(
            "graph nodes carry no inputs/outputs metadata (artifact "
            "predates critical-path recording) — per-node slack is not "
            "computable")
        return out

    preds = {
        name: sorted({producer[e] for e in n["inputs"]
                      if e in producer and producer[e] != name})
        for name, n in nodes.items()
    }
    order = _toposort(preds)
    if order is None:
        problems.append("node dependency metadata forms a cycle — "
                        "critical path is not computable")
        return out

    # forward pass: earliest start/finish under the recorded durations
    start: dict[str, float] = {}
    finish: dict[str, float] = {}
    for name in order:
        s = max((finish[p] for p in preds[name]), default=0.0)
        start[name] = s
        finish[name] = s + nodes[name]["critical_s"]
    cp_len = max(finish.values())

    # backward pass: latest finish without extending the makespan
    consumers: dict[str, list[str]] = {n: [] for n in nodes}
    for name, ps in preds.items():
        for p in ps:
            consumers[p].append(name)
    latest_finish: dict[str, float] = {}
    for name in reversed(order):
        latest_finish[name] = min(
            (latest_finish[c] - nodes[c]["critical_s"]
             for c in consumers[name]),
            default=cp_len,
        )
    slack = {n: max(latest_finish[n] - finish[n], 0.0) for n in nodes}

    # the critical chain: walk predecessors whose finish meets our start
    # (one always exists — start IS the max predecessor finish)
    cur = max(finish, key=lambda n: (finish[n], n))
    chain = [cur]
    tol = max(1e-9, 1e-6 * cp_len)
    while preds[cur]:
        cur = next(p for p in preds[cur]
                   if finish[p] >= start[cur] - tol)
        chain.append(cur)
    chain.reverse()

    def longest_with_free(zeroed: str) -> float:
        f: dict[str, float] = {}
        for name in order:
            dur = 0.0 if name == zeroed else nodes[name]["critical_s"]
            f[name] = max((f[p] for p in preds[name]), default=0.0) + dur
        return max(f.values())

    by_stage = telemetry.get("dispatch_by_stage")
    if not isinstance(by_stage, dict):
        by_stage = {}

    out["critical_path_s"] = round(cp_len, 3)
    out["critical_path"] = chain
    chain_set = set(chain)
    out["nodes"] = {
        name: {
            "critical_s": round(n["critical_s"], 3),
            "overlapped_s": round(n["overlapped_s"], 3),
            "slack_s": round(slack[name], 3),
            "on_critical_path": name in chain_set,
            "what_if_saved_s": (
                round(cp_len - longest_with_free(name), 3)
                if n["critical_s"] > 0 else 0.0
            ),
            "units": n["units"],
            "dispatch": _merge_dispatch(by_stage.get(name),
                                        by_stage.get(f"{name}_bg")),
        }
        for name, n in sorted(nodes.items())
    }
    if isinstance(trace, dict):
        tr = _trace_windows(trace, set(nodes), problems)
        if tr is not None:
            out["trace"] = tr
    return out


def render(analysis: dict, lines: list[str]) -> None:
    """Append the human rendering of one :func:`analyze` result."""
    for p in analysis.get("problems", []):
        lines.append(f"  critical-path: {p}")
    chain = analysis.get("critical_path")
    if not chain:
        return
    dur = analysis.get("duration_s")
    lines.append(
        f"critical path: {analysis['critical_path_s']:.3f}s over "
        f"{len(chain)} node(s); all-node critical sum "
        f"{analysis['nodes_total_s']:.3f}s"
        + (f", run duration {dur:.3f}s" if dur is not None else "")
    )
    nodes = analysis.get("nodes", {})
    for name in chain:
        info = nodes.get(name, {})
        extra = ""
        disp = info.get("dispatch")
        if disp:
            extra = (f"  dispatch host {disp['host_s']:.3f}s "
                     f"block {disp['block_s']:.3f}s")
        lines.append(f"  {name:28s} {info.get('critical_s', 0.0):8.3f}s"
                     f"{extra}")
    ranked = sorted(
        ((name, info) for name, info in nodes.items()),
        key=lambda kv: -kv[1].get("what_if_saved_s", 0.0),
    )
    lines.append("what-if (run shrinks by, were the node free) and slack:")
    for name, info in ranked[:8]:
        tag = " [overlapped]" if info.get("overlapped_s", 0.0) > 0 else ""
        lines.append(
            f"  {name:28s} saves {info.get('what_if_saved_s', 0.0):8.3f}s  "
            f"slack {info.get('slack_s', 0.0):8.3f}s{tag}"
        )
    pool = analysis.get("pool")
    if pool:
        eff = pool.get("efficiency")
        lines.append(
            f"overlap pool: busy {pool['busy_s']:.3f}s idle "
            f"{pool['idle_s']:.3f}s across {pool.get('slots')} slot(s)"
            + (f" ({eff:.0%} busy)" if eff is not None else "")
        )
    tr = analysis.get("trace")
    if tr:
        lines.append(f"trace join: observed node-span makespan "
                     f"{tr['makespan_s']:.3f}s")
