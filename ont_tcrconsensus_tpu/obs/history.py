"""Cross-run history ledger: append-only per-run perf/telemetry entries.

Within-run telemetry (telemetry.json) answers *where this run spent its
time*; it cannot answer *did the last change help* — every run's numbers
evaporate with the run directory. This module persists one compact JSON
line per run into an append-only ledger:

- ``<nano_tcr>/history.jsonl`` — always, from the run roll-up path
  (pipeline/run.py) whenever telemetry is armed, and from ``bench.py``;
- an opt-in cross-run ledger (``history_ledger`` config knob /
  ``bench.py --ledger``, conventionally ``BENCH_HISTORY.jsonl`` at the
  repo root) — the baseline pool ``scripts/perf_gate.py`` gates against.

Entries are keyed by **git sha** (what code ran), **config fingerprint**
(a stable hash of the resolved RunConfig minus pure filesystem-location
keys — the same workload run from a different directory must land in the
same baseline pool), **backend** and **n_reads** (what workload ran on
what hardware). The gate compares a run only against entries agreeing on
fingerprint/backend/n_reads, using median + MAD so one noisy historical
sample cannot fail a healthy run.

Contracts, matching the repo's artifact discipline:

- **never-crash**: :func:`read_entries` degrades garbage/torn lines to
  named problems and keeps the readable rest; :func:`record_run` never
  fails the run it records.
- **bounded**: :func:`append_entry` rotates the file down to the newest
  ``max_entries`` lines, so a long-lived ledger cannot grow unbounded.
- **jax-free**: nothing here imports jax (:func:`detect_backend` only
  reads an already-imported module), so ``--report`` and the perf gate
  stay safe on hosts with a wedged device tunnel.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import subprocess
import sys
import time

SCHEMA_VERSION = 1
HISTORY_BASENAME = "history.jsonl"
DEFAULT_MAX_ENTRIES = 512

#: resolved-config keys excluded from the fingerprint: pure filesystem
#: locations (and the ledger path itself) never change the computation,
#: only where it reads/writes — two runs of one workload from different
#: directories or machines must share a baseline pool
FINGERPRINT_EXCLUDED_KEYS = frozenset({
    "reference_file",
    "fastq_pass_dir",
    "nanopore_tcr_seq_primers_fasta",
    "profile_trace_dir",
    "history_ledger",
    # observation endpoints, not workload knobs: a live-armed run must
    # share a baseline pool (and /progress ETA priors) with a live-off
    # run of the same workload
    "live_port",
    # executable-cache location and daemon-mode serving knobs: where
    # compiled programs persist and how deep the serve queue is never
    # change the computation — a job run through the warm daemon must
    # share a baseline pool with the same workload run one-shot
    "compile_cache_dir",
    "serve_queue_max",
    "serve_prewarm",
    "serve_workers",
})

#: MAD -> sigma-equivalent scale for normally-distributed noise
MAD_SCALE = 1.4826


# --- keys ---------------------------------------------------------------------


def config_fingerprint(cfg) -> str:
    """Stable hash of the resolved config (RunConfig or plain dict).

    Every perf-relevant knob participates (batch sizes, executor, chaos,
    polish method, ...); only the :data:`FINGERPRINT_EXCLUDED_KEYS` path
    knobs are dropped. 16 hex chars: collision-safe for a ledger, short
    enough to eyeball-diff in a JSON line.
    """
    d = cfg if isinstance(cfg, dict) else cfg.to_dict()
    d = {k: v for k, v in d.items() if k not in FINGERPRINT_EXCLUDED_KEYS}
    blob = json.dumps(d, sort_keys=True, separators=(",", ":"), default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def git_sha(cwd: str | None = None) -> str | None:
    """Best-effort ``git rev-parse HEAD`` of the package's repo (or
    ``cwd``); None outside a repo / without git — never raises."""
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=5.0,
            cwd=cwd or os.path.dirname(os.path.abspath(__file__)),
        )
    except (OSError, subprocess.SubprocessError):
        return None
    if proc.returncode != 0:
        return None
    return proc.stdout.strip() or None


def mesh_config_str(mesh_shape) -> str | None:
    """Canonical ledger spelling of a mesh shape: ``"data=4"`` /
    ``"data=4,model=2"`` (axis order as declared); None for no mesh.
    A plain string so ledger entries compare by equality in
    :func:`matching_entries` without dict-ordering worries."""
    if not mesh_shape:
        return None
    return ",".join(f"{k}={v}" for k, v in dict(mesh_shape).items())


def detect_backend() -> str | None:
    """The active jax backend WITHOUT importing jax: reads the module only
    when the calling process already loaded it (run/bench paths), so the
    jax-free consumers (--report, perf_gate) stay jax-free."""
    mod = sys.modules.get("jax")
    if mod is None:
        return None
    try:
        return str(mod.default_backend())
    except Exception:
        return None


# --- entries ------------------------------------------------------------------


def build_entry(source: str, telemetry: dict | None = None, *,
                fingerprint: str | None = None, sha: str | None = None,
                backend: str | None = None, n_reads: int | None = None,
                reads_per_sec: float | None = None,
                warmup_s: float | None = None,
                steady_s: float | None = None,
                extra: dict | None = None) -> dict:
    """One ledger entry. ``telemetry`` is a telemetry.json-shaped summary
    (obs.metrics.MetricsRegistry.summary()); the entry keeps only the
    trend-worthy roll-up, not the full per-site tables.

    ``warmup_s``/``steady_s`` split one-time cost (daemon start + AOT
    prewarm + first-job compiles; bench's untimed warm pass) from the
    repeatable per-job seconds, so the serve cold-start goal is
    ledger-tracked separately from throughput and the perf gate can guard
    either. Omitted (None) on entries without a warm/steady split."""
    entry: dict = {
        "schema": SCHEMA_VERSION,
        "t_wall": round(time.time(), 3),
        "source": source,
        "git_sha": sha,
        "fingerprint": fingerprint,
        "backend": backend,
        "n_reads": n_reads,
        "reads_per_sec": reads_per_sec,
    }
    if warmup_s is not None:
        entry["warmup_s"] = round(float(warmup_s), 3)
    if steady_s is not None:
        entry["steady_s"] = round(float(steady_s), 3)
    if telemetry:
        disp = telemetry.get("dispatch") or {}
        comp = telemetry.get("compile") or {}
        gauges = telemetry.get("gauges") or {}
        entry.update({
            "duration_s": telemetry.get("duration_s"),
            "stages": {
                k: v.get("seconds")
                for k, v in (telemetry.get("stages") or {}).items()
                if isinstance(v, dict)
            },
            "dispatch_host_s": round(sum(
                d.get("host_s", 0.0) for d in disp.values()
                if isinstance(d, dict)
            ), 3),
            "dispatch_block_s": round(sum(
                d.get("block_s", 0.0) for d in disp.values()
                if isinstance(d, dict)
            ), 3),
            "compile_count": comp.get("count", 0),
            "compile_s": comp.get("seconds", 0.0),
            "hbm_high_water_bytes": gauges.get("device.hbm_bytes_in_use"),
            "peak_host_rss_bytes": gauges.get("host.rss_bytes"),
        })
        # static-analyzer verdict (additive schema: older readers and the
        # perf gate ignore unknown keys; see test_history garbage test)
        analysis = telemetry.get("analysis") or {}
        if isinstance(analysis, dict) and "graftcheck" in analysis:
            entry["graftcheck"] = analysis["graftcheck"]
        # device data-plane roll-up (additive): total h2d/d2h bytes, the
        # round-trip budget the transfer gate holds the line on, and the
        # per-edge donation verdicts — pre-upgrade entries simply lack
        # these keys and stay valid baselines (evaluate_bytes_gate warns)
        transfers = telemetry.get("transfers")
        if isinstance(transfers, dict):
            sites = transfers.get("sites")
            if isinstance(sites, dict):
                entry["transfer_bytes"] = {
                    "h2d": sum(s.get("h2d_bytes", 0) for s in sites.values()
                               if isinstance(s, dict)),
                    "d2h": sum(s.get("d2h_bytes", 0) for s in sites.values()
                               if isinstance(s, dict)),
                }
            hrt = transfers.get("host_round_trip_bytes")
            if isinstance(hrt, (int, float)) and not isinstance(hrt, bool):
                entry["host_round_trip_bytes"] = int(hrt)
            donation = transfers.get("donation")
            if isinstance(donation, dict) and donation:
                entry["donation"] = {
                    k: v.get("verdict") for k, v in sorted(donation.items())
                    if isinstance(v, dict)
                }
        # executed-graph per-node seconds (additive): the stage roll-up
        # above loses the executor's critical/overlapped attribution, so
        # the critical-path analyzer and the live plane's /progress ETA
        # priors (obs/live.load_node_priors) would otherwise disagree on
        # what a node costs. Seconds/units are summed over the run's
        # libraries; `runs` lets readers recover per-execution pace.
        graph = telemetry.get("graph")
        gnodes = graph.get("nodes") if isinstance(graph, dict) else None
        if isinstance(gnodes, dict):
            nodes = {}
            for name, g in gnodes.items():
                if isinstance(g, dict) and g.get("runs"):
                    nodes[name] = {
                        "s": g.get("critical_s", 0.0),
                        "overlapped_s": g.get("overlapped_s", 0.0),
                        "runs": g.get("runs", 1),
                        "units": g.get("units", 0),
                    }
            if nodes:
                entry["nodes"] = nodes
    if extra:
        entry.update(extra)
    return entry


def append_entry(path: str, entry: dict,
                 max_entries: int = DEFAULT_MAX_ENTRIES) -> None:
    """Append one JSON line; rotate down to the newest ``max_entries``."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "a") as fh:
        fh.write(json.dumps(entry, sort_keys=True,
                            separators=(",", ":")) + "\n")
    _rotate(path, max_entries)


def _rotate(path: str, max_entries: int) -> None:
    try:
        with open(path) as fh:
            lines = fh.readlines()
    except OSError:
        return
    if len(lines) <= max_entries:
        return
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.writelines(lines[-max_entries:])
    os.replace(tmp, path)


def read_entries(path: str) -> tuple[list[dict], list[str]]:
    """(entries, problems). Garbage/torn lines become named problems and
    are dropped; the readable rest survives — a half-written final line
    (the process died mid-append) must not take the whole history down."""
    try:
        with open(path) as fh:
            raw = fh.read()
    except OSError as exc:
        return [], [f"unreadable ledger {path}: {exc!r}"]
    entries: list[dict] = []
    problems: list[str] = []
    for i, line in enumerate(raw.splitlines(), 1):
        if not line.strip():
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            problems.append(f"line {i}: not valid JSON (torn or garbage "
                            "entry dropped)")
            continue
        if not isinstance(obj, dict):
            problems.append(f"line {i}: not a JSON object (dropped)")
            continue
        entries.append(obj)
    return entries, problems


# --- the regression gate ------------------------------------------------------


@dataclasses.dataclass
class GateResult:
    """Outcome of one gate evaluation; ``status`` drives the exit code
    (fail -> nonzero), everything else is the explanation."""

    status: str  # "pass" | "warn" | "fail"
    reason: str
    metric: str | None = None
    current: float | None = None
    baseline_median: float | None = None
    baseline_mad: float | None = None
    allowance: float | None = None
    n_baseline: int = 0


def matching_entries(entries: list[dict], current: dict) -> list[dict]:
    """Baseline pool: entries agreeing with ``current`` on fingerprint,
    backend, n_reads and mesh_config (``current`` itself excluded by
    identity, so gating the ledger's own latest entry works).

    ``mesh_config`` compares via ``.get()`` on both sides: legacy entries
    (written before sharded execution) and single-device runs both lack
    the key, so they pool together — a ``--mesh data=N`` arm's throughput
    only ever gates against the same mesh shape, never against the
    single-device baseline it is allowed to beat or trail."""
    keys = ("fingerprint", "backend", "n_reads", "mesh_config")
    return [e for e in entries
            if e is not current
            and all(e.get(k) == current.get(k) for k in keys)]


def _median(values: list[float]) -> float:
    s = sorted(values)
    mid = len(s) // 2
    return s[mid] if len(s) % 2 else 0.5 * (s[mid - 1] + s[mid])


def _metric_of(entry: dict) -> tuple[str | None, float | None]:
    """Preferred gate metric of one entry: reads_per_sec (higher better,
    bench entries) else duration_s (lower better, run entries)."""
    for name in ("reads_per_sec", "duration_s"):
        v = entry.get(name)
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v > 0:
            return name, float(v)
    return None, None


def evaluate_gate(entries: list[dict], current: dict, *,
                  rel_threshold: float = 0.15, mad_k: float = 4.0,
                  min_samples: int = 3) -> GateResult:
    """Noise-aware regression verdict for ``current`` vs the ledger.

    The allowance is ``max(rel_threshold * median, mad_k * 1.4826 * MAD)``
    over the matching baseline samples: a quiet baseline gates at the
    relative threshold, a noisy one widens to what its own scatter
    justifies — one flaky historical sample cannot fail a healthy run,
    and a machine with inherently noisy timings self-calibrates. Fewer
    than ``min_samples`` usable baselines -> ``warn`` (recorded, not
    gated): a thin ledger must not fail CI on a fresh machine.
    """
    mname, cur = _metric_of(current)
    if mname is None:
        return GateResult(
            "warn", "current entry has no usable metric "
            "(reads_per_sec/duration_s missing or non-positive) — not gated",
        )
    values = [v for e in matching_entries(entries, current)
              for name, v in (_metric_of(e),) if name == mname]
    if len(values) < min_samples:
        return GateResult(
            "warn",
            f"thin ledger: {len(values)} matching baseline sample(s) < "
            f"min_samples={min_samples} — recorded, not gated",
            metric=mname, current=cur, n_baseline=len(values),
        )
    med = _median(values)
    mad = _median([abs(v - med) for v in values])
    allowance = max(rel_threshold * med, mad_k * MAD_SCALE * mad)
    if mname == "duration_s":
        regressed = cur > med + allowance
        side = "above"
    else:
        regressed = cur < med - allowance
        side = "below"
    detail = (f"{mname}={cur:.3f} vs baseline median {med:.3f} "
              f"(MAD {mad:.3f}, allowance {allowance:.3f}, "
              f"{len(values)} sample(s))")
    if regressed:
        return GateResult(
            "fail", f"regression: {detail} — current is {side} the "
            "noise allowance", metric=mname, current=cur,
            baseline_median=med, baseline_mad=mad, allowance=allowance,
            n_baseline=len(values),
        )
    return GateResult(
        "pass", f"within noise allowance: {detail}", metric=mname,
        current=cur, baseline_median=med, baseline_mad=mad,
        allowance=allowance, n_baseline=len(values),
    )


def _bytes_of(entry: dict, metric: str) -> float | None:
    """A byte metric of one entry; unlike :func:`_metric_of`, zero is a
    valid (ideal) value — a baseline of 0 round-trip bytes must gate."""
    v = entry.get(metric)
    if isinstance(v, (int, float)) and not isinstance(v, bool) and v >= 0:
        return float(v)
    return None


def evaluate_bytes_gate(entries: list[dict], current: dict, *,
                        metric: str = "host_round_trip_bytes",
                        rel_threshold: float = 0.15, mad_k: float = 4.0,
                        min_samples: int = 3,
                        abs_budget: float | None = None) -> GateResult:
    """Lower-is-better byte gate over a ledger byte metric (the data-plane
    twin of :func:`evaluate_gate`; same median+MAD allowance).

    Pre-upgrade ledger entries simply lack the byte fields: they are
    skipped (never a crash), and a pool left thinner than ``min_samples``
    degrades to ``warn`` — a legacy ledger stays a valid baseline for the
    timing gate without blocking CI on the new metric. The fail reason
    carries measured vs allowed bytes, so a reintroduced host round-trip
    is a sized finding.

    ``abs_budget`` switches the gate to an absolute ceiling that needs no
    ledger history at all: the production data plane is device-resident,
    so the budget is ~0 bytes and ANY measured round-trip fails
    deterministically — including on a fresh machine whose ledger is too
    thin for the relative gate to arm.
    """
    cur = _bytes_of(current, metric)
    if cur is None:
        return GateResult(
            "warn", f"current entry has no {metric} field (pre-upgrade "
            "telemetry or telemetry off) — not gated", metric=metric,
        )
    if abs_budget is not None:
        if cur > abs_budget:
            return GateResult(
                "fail",
                f"data-plane regression: {metric}={cur:.0f} B vs allowed "
                f"{abs_budget:.0f} B (absolute budget) — "
                f"{cur - abs_budget:.0f} B of new host round-trip traffic",
                metric=metric, current=cur, allowance=float(abs_budget),
            )
        return GateResult(
            "pass", f"within absolute budget: {metric}={cur:.0f} B vs "
            f"allowed {abs_budget:.0f} B", metric=metric, current=cur,
            allowance=float(abs_budget),
        )
    pool = matching_entries(entries, current)
    values = [v for e in pool
              for v in (_bytes_of(e, metric),) if v is not None]
    legacy = len(pool) - len(values)
    if len(values) < min_samples:
        return GateResult(
            "warn",
            f"thin ledger: {len(values)} matching baseline sample(s) with "
            f"{metric} < min_samples={min_samples}"
            + (f" ({legacy} legacy entrie(s) without the field skipped)"
               if legacy else "")
            + " — recorded, not gated",
            metric=metric, current=cur, n_baseline=len(values),
        )
    med = _median(values)
    mad = _median([abs(v - med) for v in values])
    allowance = max(rel_threshold * med, mad_k * MAD_SCALE * mad)
    allowed = med + allowance
    detail = (f"{metric}={cur:.0f} B vs allowed {allowed:.0f} B "
              f"(baseline median {med:.0f} B, MAD {mad:.0f}, allowance "
              f"{allowance:.0f} B, {len(values)} sample(s)"
              + (f", {legacy} legacy skipped" if legacy else "") + ")")
    if cur > allowed:
        return GateResult(
            "fail", f"data-plane regression: {detail} — "
            f"{cur - allowed:.0f} B of new host round-trip traffic",
            metric=metric, current=cur, baseline_median=med,
            baseline_mad=mad, allowance=allowance, n_baseline=len(values),
        )
    return GateResult(
        "pass", f"within byte allowance: {detail}", metric=metric,
        current=cur, baseline_median=med, baseline_mad=mad,
        allowance=allowance, n_baseline=len(values),
    )


def _load_metric(entry: dict, metric: str) -> float | None:
    """A serve_load SLO metric of one entry; zero is valid (an idle p99
    wait of 0s must gate)."""
    v = entry.get(metric)
    if isinstance(v, (int, float)) and not isinstance(v, bool) and v >= 0:
        return float(v)
    return None


def evaluate_load_gate(entries: list[dict], current: dict | None = None, *,
                       rel_threshold: float = 0.15, mad_k: float = 4.0,
                       min_samples: int = 3) -> GateResult:
    """Serving-SLO regression verdict over ``source:"serve_load"``
    entries: p99 job wait (lower is better) and sustained reads_per_sec
    (higher is better), each under the same median+MAD noise allowance
    as :func:`evaluate_gate`.

    ``current=None`` gates the NEWEST serve_load entry against the rest
    (the perf-gate CLI path, where the latest ledger entry is usually a
    run/bench entry); a ledger with no serve_load history degrades to
    ``warn`` — the load gate arms only once a load report has been
    recorded. A ``current`` whose source is not serve_load is never
    load-gated (warn), keeping the verdict additive for existing
    callers. Any gated metric failing fails the gate; otherwise any
    gated metric passing passes it; all-thin stays warn.
    """
    pool = [e for e in entries if isinstance(e, dict)
            and e.get("source") == "serve_load"]
    if current is None:
        if not pool:
            return GateResult(
                "warn", "no serve_load entries in the ledger — load gate "
                "not armed (run scripts/serve_load.py --ledger to record "
                "one)")
        current = pool[-1]
    elif current.get("source") != "serve_load":
        return GateResult(
            "warn", f"current entry source={current.get('source')!r} is "
            "not serve_load — not load-gated")
    baseline = matching_entries(pool, current)
    verdicts: list[GateResult] = []
    for metric, higher_better in (("p99_wait_s", False),
                                  ("reads_per_sec", True)):
        cur = _load_metric(current, metric)
        if cur is None:
            verdicts.append(GateResult(
                "warn", f"current serve_load entry has no {metric} — "
                "not gated", metric=metric))
            continue
        values = [v for e in baseline
                  for v in (_load_metric(e, metric),) if v is not None]
        if len(values) < min_samples:
            verdicts.append(GateResult(
                "warn",
                f"thin ledger: {len(values)} matching serve_load baseline "
                f"sample(s) with {metric} < min_samples={min_samples} — "
                "recorded, not gated",
                metric=metric, current=cur, n_baseline=len(values),
            ))
            continue
        med = _median(values)
        mad = _median([abs(v - med) for v in values])
        allowance = max(rel_threshold * med, mad_k * MAD_SCALE * mad)
        if higher_better:
            regressed = cur < med - allowance
            side = "below"
        else:
            regressed = cur > med + allowance
            side = "above"
        detail = (f"{metric}={cur:.3f} vs baseline median {med:.3f} "
                  f"(MAD {mad:.3f}, allowance {allowance:.3f}, "
                  f"{len(values)} sample(s))")
        if regressed:
            verdicts.append(GateResult(
                "fail", f"serving regression: {detail} — current is "
                f"{side} the noise allowance", metric=metric, current=cur,
                baseline_median=med, baseline_mad=mad, allowance=allowance,
                n_baseline=len(values),
            ))
        else:
            verdicts.append(GateResult(
                "pass", f"within noise allowance: {detail}", metric=metric,
                current=cur, baseline_median=med, baseline_mad=mad,
                allowance=allowance, n_baseline=len(values),
            ))
    for v in verdicts:
        if v.status == "fail":
            return v
    passes = [v for v in verdicts if v.status == "pass"]
    joined = "; ".join(v.reason for v in verdicts)
    if passes:
        return dataclasses.replace(passes[0], reason=joined)
    return GateResult(
        "warn", joined,
        n_baseline=max((v.n_baseline for v in verdicts), default=0))


# --- the run roll-up hook -----------------------------------------------------


def record_run(nano_dir: str, cfg, *, suffix: str = "") -> dict | None:
    """Append this run's entry to ``<nano_dir>/history<suffix>.jsonl``
    (plus ``cfg.history_ledger`` when set) from the armed registry.

    Called from the run roll-up finally-block right after the telemetry
    write; like every telemetry path it must never fail the run it
    records — any trouble degrades to a stderr warning.
    """
    try:
        from ont_tcrconsensus_tpu.obs import metrics

        reg = metrics.registry()
        if reg is None:
            return None
        mesh_shape = getattr(cfg, "mesh_shape", None)
        entry = build_entry(
            "run", reg.summary(),
            fingerprint=config_fingerprint(cfg),
            sha=git_sha(), backend=detect_backend(),
            # per-mesh-config scaling entries: "data=2,model=2" — absent
            # (not null) on single-device runs so they pool with legacy
            # baselines in matching_entries
            extra=({"mesh_config": mesh_config_str(mesh_shape)}
                   if mesh_shape else None),
        )
        name = f"history{suffix}.jsonl" if suffix else HISTORY_BASENAME
        append_entry(os.path.join(nano_dir, name), entry)
        ledger = getattr(cfg, "history_ledger", None)
        if ledger:
            append_entry(ledger, entry)
        return entry
    except Exception as exc:
        sys.stderr.write(
            f"WARNING: could not append run-history entry: {exc!r}\n")
        return None
