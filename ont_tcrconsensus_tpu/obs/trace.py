"""Span/instant API + the Chrome-trace-format ``logs/trace.json`` emitter.

Spans are class-based context managers so callers that need the measured
duration on failure paths (``overlap.DeferredStage``) can hold the object.
Every span exit performs ONE duration computation that feeds all three
consumers — :class:`~ont_tcrconsensus_tpu.qc.timing.StageTimer` (the
``stage_timing.tsv`` rows), the armed :class:`MetricsRegistry` stage
table (the ``telemetry.json`` roll-up), and the armed
:class:`TraceCollector` (the ``trace.json`` timeline) — so the timing
table and the trace derive from one clock read and cannot disagree.

The collector writes the Chrome trace-event JSON object format
(``{"traceEvents": [...]}``): ``X`` complete events per span (one row per
thread, named via ``M``/``thread_name`` metadata), ``i`` instant events
for point-in-time occurrences (retries, chaos injections, watchdog
stalls/cancels, contract violations, quarantine hits — emitted by
``robustness/retry.RobustnessRecorder.record``, so the robustness report
and the trace line up on one timeline), and ``C`` counter events from the
memory sampler. Load in ``chrome://tracing`` / Perfetto; it complements a
``profile_trace_dir`` jax.profiler capture (per-kernel device detail) with
the HOST-side stage/thread structure the profiler does not show.

Each thread also maintains a span-label stack regardless of arming state
(:func:`current_label`); the recompile audit (:mod:`.device`) reads it to
attribute XLA compiles to the active stage.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

from ont_tcrconsensus_tpu.obs import metrics

_tls = threading.local()


def _label_stack() -> list[str]:
    stack = getattr(_tls, "labels", None)
    if stack is None:
        stack = _tls.labels = []
    return stack


def current_label() -> str:
    """Innermost active span name on the calling thread ('' when none)."""
    stack = getattr(_tls, "labels", None)
    return stack[-1] if stack else ""


class Span:
    """One measured scope. ``dur_s`` is valid after exit, also when the
    body raised (the duration still reaches the timer/trace consumers)."""

    __slots__ = ("name", "cat", "args", "t0", "dur_s")

    def __init__(self, name: str, cat: str = "stage", args: dict | None = None):
        self.name = name
        self.cat = cat
        self.args = args
        self.t0 = 0.0
        self.dur_s = 0.0

    def __enter__(self) -> "Span":
        _label_stack().append(self.name)
        self.t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.dur_s = time.monotonic() - self.t0
        stack = _label_stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        reg = metrics._ARMED
        if reg is not None:
            reg.stage_add(self.name, self.dur_s)
        col = _ARMED
        if col is not None:
            col.add_span(self)
        ring = _RING
        if ring is not None:
            ring.add_span(self)
        return False


def span(name: str, cat: str = "stage", args: dict | None = None) -> Span:
    """A measured scope; recorded into the trace/metrics only when armed."""
    return Span(name, cat=cat, args=args)


def instant(name: str, args: dict | None = None) -> None:
    """Point-in-time trace event; free no-op when tracing is disarmed."""
    col = _ARMED
    if col is not None:
        col.add_instant(name, args)
    ring = _RING
    if ring is not None:
        ring.add_instant(name, args)


#: in-memory event cap: a multi-hour ``telemetry: full`` run (sampler
#: counters alone are ~18k events/h) must not grow host RSS without bound
#: or let the trace buffer masquerade as pipeline memory in the RSS gauge.
#: At the cap new events are DROPPED and counted — trace.json reports
#: ``dropped_events`` in otherData so truncation is never silent.
MAX_EVENTS = 1_000_000


class TraceCollector:
    """Chrome-trace event accumulator (armed at ``telemetry: full``)."""

    def __init__(self, max_events: int = MAX_EVENTS):
        self._lock = threading.Lock()
        self.t0_wall = time.time()
        self.t0_mono = time.monotonic()
        self.pid = os.getpid()
        self.max_events = max_events
        self.dropped = 0
        self.events: list[dict] = []
        self._named_tids: set[int] = set()

    def _ts(self, mono: float) -> float:
        """Monotonic seconds -> trace microseconds since collector start.
        The same mapping places robustness events (which carry ``t_mono``,
        see retry.RobustnessRecorder) exactly on this timeline."""
        return (mono - self.t0_mono) * 1e6

    def _base(self, extra: dict) -> dict:
        tid = threading.get_ident()
        ev = {"pid": self.pid, "tid": tid, **extra}
        with self._lock:
            if len(self.events) >= self.max_events:
                if not self.dropped:
                    sys.stderr.write(
                        f"telemetry: trace buffer full ({self.max_events} "
                        "events); dropping further events (count reported "
                        "in trace.json otherData.dropped_events)\n"
                    )
                self.dropped += 1
                return ev
            if tid not in self._named_tids:
                self._named_tids.add(tid)
                self.events.append({
                    "ph": "M", "name": "thread_name", "pid": self.pid,
                    "tid": tid,
                    "args": {"name": threading.current_thread().name},
                })
            self.events.append(ev)
        return ev

    def add_span(self, sp: Span) -> None:
        ev = {
            "ph": "X", "name": sp.name, "cat": sp.cat,
            "ts": self._ts(sp.t0), "dur": sp.dur_s * 1e6,
        }
        if sp.args:
            ev["args"] = dict(sp.args)
        self._base(ev)

    def add_instant(self, name: str, args: dict | None = None) -> None:
        ev = {
            "ph": "i", "name": name, "cat": "event", "s": "t",
            "ts": self._ts(time.monotonic()),
        }
        if args:
            ev["args"] = dict(args)
        self._base(ev)

    def add_counter(self, name: str, values: dict) -> None:
        self._base({
            "ph": "C", "name": name, "cat": "memory",
            "ts": self._ts(time.monotonic()), "args": dict(values),
        })

    def write(self, path: str) -> None:
        with self._lock:
            events = list(self.events)
            dropped = self.dropped
        payload = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "t0_wall": round(self.t0_wall, 6),
                "t0_mono": round(self.t0_mono, 6),
                "dropped_events": dropped,
            },
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, path)


# --- process-wide armed collector -------------------------------------------

_ARMED: TraceCollector | None = None


def arm() -> TraceCollector:
    global _ARMED
    _ARMED = TraceCollector()
    return _ARMED


def disarm() -> None:
    global _ARMED
    _ARMED = None


def collector() -> TraceCollector | None:
    return _ARMED


# --- flight-recorder tap (obs/live.py) --------------------------------------
#
# A SECOND slot, deliberately distinct from the full collector: the live
# plane's bounded ring is armed by ``live_port`` (not ``telemetry: full``),
# so spans and instants reach the crash flight recorder even on runs where
# the unbounded trace collector stays off. Same one-attr-check discipline.

_RING = None


def set_ring(ring) -> None:
    global _RING
    _RING = ring
