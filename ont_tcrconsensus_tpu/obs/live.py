"""Live observability plane: /healthz /metrics /progress + flight recorder.

Every observability layer so far (telemetry.json, the history ledger, the
critical-path analyzer) is POST-HOC — it explains a run after it exits.
This module answers "what is this process doing right now?" while a
500k-read capture is in flight, and "what was it doing right before it
died?" when it never exits cleanly:

- **HTTP endpoint** (:class:`LiveServer`): a read-only, stdlib-only
  ``ThreadingHTTPServer`` bound to ``127.0.0.1:<live_port>`` for the
  run's duration. ``/healthz`` reports liveness plus a watchdog
  heartbeat-staleness verdict (any guarded stage past its soft deadline
  -> ``"stalled"``); ``/metrics`` renders the armed
  :class:`~ont_tcrconsensus_tpu.obs.metrics.MetricsRegistry` as
  Prometheus text exposition (counters, high-water gauges, histograms,
  per-site dispatch host-gap/blocked seconds, overlap-pool busy/idle,
  per-node graph seconds) plus live per-stage watchdog heartbeat ages;
  ``/progress`` is a JSON view of the current library / graph node /
  nodes done vs total, with an ETA from history-ledger per-node priors
  matching the run's config fingerprint (``eta_basis: history_priors``),
  falling back to this run's own measured node seconds
  (``measured_pace``) — the current node's prior is rescaled by its
  declared ``units`` when both are known.
- **Flight recorder** (:class:`FlightRecorder`): a bounded in-memory
  ring of the last N spans / robustness instants / watchdog heartbeats,
  fed from ``trace.py``'s span-exit and instant paths and from
  ``watchdog.heartbeat`` — i.e. populated even at ``telemetry: on``,
  where the full trace collector is NOT armed. It is flushed atomically
  to ``nano_tcr/logs/flight_recorder.json`` on crash, SIGTERM drain,
  watchdog hard expiry, and on demand via SIGUSR1 — post-mortem context
  for a process that died without writing trace.json.

Arming follows the established one-module-attr-check discipline
(``faults.inject`` / ``watchdog.heartbeat`` / ``metrics.counter_add``):
the config knob ``live_port`` defaults to null and every planted site
below (``ring_event``, ``progress_node_start`` /...) reduces to one
module-attribute check when disarmed — nothing listens, nothing buffers.
Security posture: the server binds 127.0.0.1 ONLY; remote scrapes go
through an operator's own port-forward, never a config knob. One-shot
runs serve GET only. Under the warm-serving daemon (serve/daemon.py) the
SAME loopback-only server additionally accepts ``POST /jobs`` and serves
``GET /jobs`` / ``GET /jobs/<id>`` — the single mutating route exists
only while a daemon has armed a jobs controller
(:func:`set_jobs_controller`); without one, POST answers 503 and the
plane stays read-only.
"""

from __future__ import annotations

import json
import os
import signal
import statistics
import sys
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ont_tcrconsensus_tpu.obs import history, metrics, trace
from ont_tcrconsensus_tpu.robustness import jobscope, lockcheck, watchdog

#: flight-recorder ring capacity. Sized for "the last few minutes of a
#: wedged run": heartbeats are per-batch/per-chunk (not per-read), so 512
#: events cover far more context than a post-mortem needs while bounding
#: the ring's RSS to a few hundred KB.
MAX_RING_EVENTS = 512

#: flight_recorder.json schema version (bump on breaking shape changes;
#: the --report reader degrades unknown shapes, never crashes)
FLIGHT_SCHEMA = 1


class FlightRecorder:
    """Bounded ring of recent spans / instants / heartbeats.

    Fed from three always-cheap taps — ``trace.Span.__exit__``,
    ``trace.instant`` (which robustness/retry.RobustnessRecorder.record
    funnels through, so retries / stalls / chaos injections land here
    too) and ``watchdog.heartbeat`` — and snapshotted on flush. The ring
    drops oldest-first at capacity; the drop count is reported in the
    flushed artifact so truncation is never silent.
    """

    def __init__(self, max_events: int = MAX_RING_EVENTS):
        self._lock = lockcheck.make_lock()
        self.t0_wall = time.time()
        self.t0_mono = time.monotonic()
        self.max_events = max_events
        self.events: deque = deque(maxlen=max_events)
        self.total = 0
        self.flush_path: str | None = None
        self.last_flush: dict | None = None

    def _add_locked(self, ev: dict) -> None:
        lockcheck.assert_held(self._lock, "FlightRecorder._add_locked")
        ev["thread"] = threading.current_thread().name
        self.events.append(ev)
        self.total += 1

    def add_span(self, sp: trace.Span) -> None:
        with self._lock:
            self._add_locked({
                "kind": "span", "name": sp.name,
                "t_s": round(sp.t0 - self.t0_mono, 6),
                "dur_s": round(sp.dur_s, 6),
            })

    def add_instant(self, name: str, args: dict | None = None) -> None:
        ev = {
            "kind": "instant", "name": name,
            "t_s": round(time.monotonic() - self.t0_mono, 6),
        }
        if args:
            ev["args"] = dict(args)
        with self._lock:
            self._add_locked(ev)

    def add_beat(self, site: str) -> None:
        with self._lock:
            self._add_locked({
                "kind": "heartbeat", "name": site,
                "t_s": round(time.monotonic() - self.t0_mono, 6),
            })

    def set_flush_path(self, path: str | None) -> None:
        with self._lock:
            self.flush_path = path

    def stats(self) -> dict:
        with self._lock:
            return {
                "buffered": len(self.events),
                "total": self.total,
                "capacity": self.max_events,
                "dropped": max(self.total - len(self.events), 0),
                "last_flush": dict(self.last_flush) if self.last_flush
                else None,
            }

    def flush(self, reason: str) -> str | None:
        """Atomic dump of the ring to ``flush_path`` (tmp + os.replace, so
        a crash mid-flush never leaves a torn artifact). Returns the path
        written, or None when no flush path is configured yet (a crash
        before the output tree exists has nowhere durable to write)."""
        with self._lock:
            path = self.flush_path
            events = list(self.events)
            dropped = max(self.total - len(events), 0)
        if path is None:
            return None
        payload = {
            "schema": FLIGHT_SCHEMA,
            "reason": reason,
            "t_wall": round(time.time(), 3),
            "t0_wall": round(self.t0_wall, 3),
            "t0_mono": round(self.t0_mono, 6),
            "pid": os.getpid(),
            "dropped": dropped,
            "events": events,
        }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, path)
        with self._lock:
            self.last_flush = {"reason": reason, "path": path,
                               "t_wall": payload["t_wall"]}
        return path


class ProgressTracker:
    """Current library / graph node position + the /progress ETA.

    Fed by the library loop (pipeline/run.py) and the graph executor
    (graph/executor.py node start/finish/skip). The ETA estimate for each
    plan node comes from, in order: the history-ledger prior matching
    this run's config fingerprint (``load_node_priors``), this run's own
    measured seconds for that node (a later library reuses the earlier
    library's pace), or the mean of whatever estimates exist. The
    in-flight node subtracts its elapsed time (clamped at 0) and, when
    both its declared ``units`` and the prior's are known, rescales the
    prior linearly — the declared-units fallback for workloads whose
    libraries differ in size.
    """

    def __init__(self):
        self._lock = lockcheck.make_lock()
        self.t0_mono = time.monotonic()
        self.libraries_total = 0
        self.libraries_done = 0
        self.library: str | None = None
        self.plan: list[str] = []
        self.done: set[str] = set()
        self.node: str | None = None
        self.node_units: int = 0
        self.node_t0: float | None = None
        # this run's measured pace: node -> {"s": seconds, "units": n}
        self.node_seconds: dict[str, dict] = {}
        # ledger priors (per-execution seconds), same shape
        self.priors: dict[str, dict] = {}

    def set_totals(self, n_libraries: int) -> None:
        with self._lock:
            self.libraries_total = int(n_libraries)

    def set_priors(self, priors: dict[str, dict]) -> None:
        with self._lock:
            self.priors = dict(priors)

    def start_library(self, name: str) -> None:
        with self._lock:
            self.library = name
            self.plan = []
            self.done = set()
            self.node = None
            self.node_t0 = None
            self.node_units = 0

    def finish_library(self) -> None:
        with self._lock:
            if self.library is not None:
                self.libraries_done += 1
            self.library = None
            self.plan = []
            self.done = set()
            self.node = None
            self.node_t0 = None
            self.node_units = 0

    def set_plan(self, names: list[str]) -> None:
        with self._lock:
            self.plan = list(names)
            self.done = set()

    def node_start(self, name: str, units: int | None = None) -> None:
        with self._lock:
            self.node = name
            self.node_units = int(units or 0)
            self.node_t0 = time.monotonic()

    def node_finish(self, name: str, seconds: float,
                    units: int | None = None) -> None:
        with self._lock:
            self.done.add(name)
            self.node_seconds[name] = {"s": float(seconds),
                                       "units": int(units or 0)}
            if self.node == name:
                self.node = None
                self.node_t0 = None
                self.node_units = 0

    def node_skip(self, name: str) -> None:
        with self._lock:
            self.done.add(name)

    def _node_est_locked(self, name: str, est: dict, avg: float) -> float:
        lockcheck.assert_held(self._lock, "ProgressTracker._node_est_locked")
        v = est.get(name)
        if v is None:
            return avg
        s = float(v.get("s", avg))
        # declared-units rescale for the in-flight node: a prior measured
        # on a differently sized library scales linearly with its units
        if (name == self.node and self.node_units
                and float(v.get("units") or 0) > 0):
            s = s * (self.node_units / float(v["units"]))
        return s

    def snapshot(self) -> dict:
        """The /progress JSON body (one lock hold, no I/O)."""
        now = time.monotonic()
        with self._lock:
            est = dict(self.priors)
            est.update(self.node_seconds)
            eta = None
            basis = None
            if est:
                avg = sum(float(v.get("s", 0.0)) for v in est.values()) / len(est)
                plan = self.plan or sorted(est)
                eta = 0.0
                for name in plan:
                    if name in self.done:
                        continue
                    s = self._node_est_locked(name, est, avg)
                    if name == self.node and self.node_t0 is not None:
                        s = max(s - (now - self.node_t0), 0.0)
                    eta += s
                per_lib = sum(self._node_est_locked(n, est, avg)
                              for n in plan)
                in_flight = 1 if self.library is not None else 0
                libs_left = max(
                    self.libraries_total - self.libraries_done - in_flight, 0
                )
                eta = round(eta + libs_left * per_lib, 3)
                basis = "history_priors" if self.priors else "measured_pace"
            return {
                "uptime_s": round(now - self.t0_mono, 3),
                "library": self.library,
                "libraries_done": self.libraries_done,
                "libraries_total": self.libraries_total,
                "node": self.node,
                "node_units": self.node_units,
                "node_elapsed_s": (round(now - self.node_t0, 3)
                                   if self.node_t0 is not None else None),
                "nodes_done": len(self.done),
                "nodes_total": len(self.plan),
                "eta_s": eta,
                "eta_basis": basis,
            }


# Lock ownership for FlightRecorder / ProgressTracker is declared in the
# consolidated registry (ont_tcrconsensus_tpu/robustness/locks.py)
# consumed by graftlint's lock-discipline rule and graftrace.


def load_node_priors(ledger_paths: list[str],
                     fingerprint: str) -> dict[str, dict]:
    """Per-node {"s": seconds, "units": n} priors from history ledgers.

    Reads every existing ledger in ``ledger_paths`` through the
    never-crash ``history.read_entries`` reader, keeps entries whose
    config fingerprint matches this run's (so a 10k-read bench never
    predicts a 70M-read capture), and takes the per-execution median:
    ledger entries record a node's seconds/units SUMMED over the run's
    libraries plus the run count, so each sample is sum/runs.
    """
    samples: dict[str, list[tuple[float, float]]] = {}
    for path in ledger_paths:
        if not path or not os.path.exists(path):
            continue
        entries, _problems = history.read_entries(path)
        for entry in entries:
            if entry.get("fingerprint") != fingerprint:
                continue
            nodes = entry.get("nodes")
            if not isinstance(nodes, dict):
                continue
            for name, v in nodes.items():
                if not isinstance(v, dict):
                    continue
                s = v.get("s")
                runs = v.get("runs", 1)
                if not (isinstance(s, (int, float))
                        and not isinstance(s, bool) and s >= 0):
                    continue
                if not (isinstance(runs, int) and runs > 0):
                    runs = 1
                units = v.get("units", 0)
                if not isinstance(units, (int, float)) or units < 0:
                    units = 0
                samples.setdefault(str(name), []).append(
                    (float(s) / runs, float(units) / runs)
                )
    return {
        name: {
            "s": statistics.median(s for s, _ in pairs),
            "units": statistics.median(u for _, u in pairs),
        }
        for name, pairs in samples.items()
    }


# --- Prometheus /metrics rendering ------------------------------------------


def _metrics_text() -> str:
    """The /metrics body: registry families + live watchdog ages.

    Always begins with ``tcr_up 1`` so a scrape of a telemetry-off run
    (registry disarmed) is still a valid, non-empty exposition."""
    lines = [
        "# HELP tcr_up Live plane liveness (1 while the endpoint serves).",
        "# TYPE tcr_up gauge",
        "tcr_up 1",
    ]
    reg = metrics.registry()
    if reg is not None:
        lines.extend(reg.prometheus_lines())
    entries = watchdog.snapshot()
    if entries:
        lines.append("# HELP tcr_watchdog_heartbeat_age_seconds Seconds "
                     "since the stage's last heartbeat.")
        lines.append("# TYPE tcr_watchdog_heartbeat_age_seconds gauge")
        for e in entries:
            stage = metrics.prom_label(e["stage"])
            lines.append(
                f'tcr_watchdog_heartbeat_age_seconds{{stage="{stage}"}} '
                f'{e["heartbeat_age_s"]}'
            )
        lines.append("# TYPE tcr_watchdog_hard_deadline_seconds gauge")
        for e in entries:
            stage = metrics.prom_label(e["stage"])
            lines.append(
                f'tcr_watchdog_hard_deadline_seconds{{stage="{stage}"}} '
                f'{e["hard_deadline_s"]}'
            )
    return "\n".join(lines) + "\n"


def _healthz_payload() -> dict:
    """The /healthz JSON body: liveness + watchdog staleness verdict."""
    entries = watchdog.snapshot()
    stalled = [e["stage"] for e in entries or ()
               if e["heartbeat_age_s"] >= e["soft_deadline_s"]]
    srv = _SERVER
    ring = _RING
    return {
        "status": "stalled" if stalled else "ok",
        "pid": os.getpid(),
        "uptime_s": (round(time.monotonic() - srv.t0_mono, 3)
                     if srv is not None else None),
        "watchdog": {
            "armed": entries is not None,
            "stalled_stages": stalled,
            "stages": entries or [],
        },
        "flight_recorder": ring.stats() if ring is not None else None,
    }


#: request-body cap for POST /jobs — a job submission is a small JSON
#: config-overrides object; anything larger is a client bug, not a job
MAX_JOB_BODY_BYTES = 1 << 20


class _Handler(BaseHTTPRequestHandler):
    """Read-only GET routes, plus POST /jobs when a daemon armed a jobs
    controller; access logging silenced (the endpoint is scraped every
    few seconds — stderr noise would drown run logs)."""

    server_version = "tcr-live/1"

    def log_message(self, fmt, *log_args):  # noqa: A003 - stdlib signature
        pass

    def _send(self, status: int, content_type: str, body: bytes) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, status: int, payload: dict) -> None:
        self._send(status, "application/json", json.dumps(payload).encode())

    def do_GET(self) -> None:  # noqa: N802 - stdlib handler name
        metrics.counter_add("live.requests")
        path = self.path.split("?", 1)[0]
        try:
            if path == "/healthz":
                body = json.dumps(_healthz_payload()).encode()
                self._send(200, "application/json", body)
            elif path == "/metrics":
                self._send(
                    200, "text/plain; version=0.0.4; charset=utf-8",
                    _metrics_text().encode(),
                )
            elif path == "/progress":
                tracker = _PROGRESS
                payload = tracker.snapshot() if tracker is not None else {}
                self._send(200, "application/json",
                           json.dumps(payload).encode())
            elif path == "/jobs" or path.startswith("/jobs/"):
                self._get_jobs(path)
            else:
                self._send(404, "text/plain; charset=utf-8",
                           b"unknown route; try /healthz /metrics /progress"
                           b" /jobs\n")
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-write; nothing to serve

    def _get_jobs(self, path: str) -> None:
        ctl = _JOBS
        if ctl is None:
            self._send_json(503, {
                "error": "no jobs controller armed — /jobs exists only "
                         "under the serve daemon (tcr-consensus-tpu serve)",
            })
            return
        if path == "/jobs":
            self._send_json(200, ctl.jobs_snapshot())
            return
        snap = ctl.job_snapshot(path[len("/jobs/"):])
        if snap is None:
            self._send_json(404, {"error": "unknown job id"})
        else:
            self._send_json(200, snap)

    def do_POST(self) -> None:  # noqa: N802 - stdlib handler name
        """The plane's single mutating route: submit a job to the armed
        daemon controller. Loopback bind remains the security boundary;
        without a controller (every one-shot run) this answers 503 and
        the plane is exactly as read-only as before."""
        metrics.counter_add("live.requests")
        path = self.path.split("?", 1)[0]
        try:
            if path != "/jobs":
                self._send_json(404, {"error": "POST supports /jobs only"})
                return
            ctl = _JOBS
            if ctl is None:
                self._send_json(503, {
                    "error": "no jobs controller armed — POST /jobs exists "
                             "only under the serve daemon",
                })
                return
            try:
                length = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                length = -1
            if length <= 0:
                self._send_json(400, {"error": "missing request body"})
                return
            if length > MAX_JOB_BODY_BYTES:
                metrics.counter_add("serve.rejected")
                metrics.reject_add("body_too_large")
                # drain the declared body before answering: responding
                # while the client is still streaming resets the
                # connection (EPIPE client-side) and the machine-readable
                # 413 would be lost to a transport error
                remaining = length
                while remaining > 0:
                    chunk = self.rfile.read(min(remaining, 1 << 16))
                    if not chunk:
                        break
                    remaining -= len(chunk)
                self._send_json(413, {
                    "error": "body_too_large",
                    "detail": f"job body over {MAX_JOB_BODY_BYTES} bytes",
                })
                return
            try:
                obj = json.loads(self.rfile.read(length))
            except ValueError:
                self._send_json(400, {"error": "body is not valid JSON"})
                return
            if not isinstance(obj, dict):
                self._send_json(400, {
                    "error": "body must be a JSON object of config overrides",
                })
                return
            status, payload = ctl.submit(obj)
            self._send_json(status, payload)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-write; nothing to serve


class LiveServer:
    """The 127.0.0.1-only endpoint thread; ``port`` is resolved after
    bind (``live_port: 0`` asks the OS for an ephemeral port — tests)."""

    def __init__(self, port: int):
        self.t0_mono = time.monotonic()
        # loopback bind is the security boundary: the plane is readable
        # by local operators/scrapers only, never the network
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="live-endpoint",
            daemon=True, kwargs={"poll_interval": 0.2},
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


# --- process-wide armed plane (same discipline as metrics/trace) ------------

_RING: FlightRecorder | None = None
_PROGRESS: ProgressTracker | None = None
_SERVER: LiveServer | None = None
# daemon-mode jobs controller (serve/daemon.py duck type: submit(dict) ->
# (status, payload), jobs_snapshot() -> dict, job_snapshot(id) -> dict|None)
_JOBS = None
# one-shot observer of graph-node starts (the daemon's dispatch-to-first-
# stage latency tap); called OUTSIDE the tracker lock, exceptions swallowed
_NODE_START_HOOK = None


def _flush_on_expiry(stage: str) -> None:
    flush_armed(f"watchdog_hard_expiry:{stage}")


def arm(port: int) -> LiveServer:
    """Arm the plane: ring + trace/watchdog taps + the HTTP endpoint."""
    global _RING, _PROGRESS, _SERVER
    ring = FlightRecorder()
    _RING = ring
    _PROGRESS = ProgressTracker()
    trace.set_ring(ring)
    watchdog.set_beat_sink(ring.add_beat)
    watchdog.set_expiry_sink(_flush_on_expiry)
    srv = LiveServer(port)
    srv.start()
    _SERVER = srv
    ring_event("live.serve", {"port": srv.port})
    return srv


def disarm() -> None:
    """Tear the plane down (run.py calls this in its finally): unwire the
    taps FIRST so in-flight spans stop feeding a dead ring, then stop the
    server so the port is released for the next run in-process."""
    global _RING, _PROGRESS, _SERVER, _JOBS, _NODE_START_HOOK
    srv = _SERVER
    _SERVER = None
    _RING = None
    _PROGRESS = None
    _JOBS = None
    _NODE_START_HOOK = None
    trace.set_ring(None)
    watchdog.set_beat_sink(None)
    watchdog.set_expiry_sink(None)
    if srv is not None:
        srv.stop()


def server() -> LiveServer | None:
    return _SERVER


def set_jobs_controller(ctl) -> None:
    """Arm (or with None, disarm) the daemon jobs controller behind
    POST/GET ``/jobs``. Owned by serve/daemon.py; one-shot runs never
    call this, so their plane serves no mutating route."""
    global _JOBS
    _JOBS = ctl


def set_node_start_hook(fn) -> None:
    """Arm (or with None, disarm) a graph-node-start observer. The serve
    daemon uses this as its dispatch-to-first-stage latency tap: armed at
    job dequeue, self-disarming at the first node.

    Under a jobscope (the slice-packed runner pool) the hook binds
    thread-locally — each resident tenant job taps its OWN first node;
    stored as a ``(fn,)`` 1-tuple so the in-scope self-disarm tombstones
    instead of falling back to a neighbor's hook."""
    global _NODE_START_HOOK
    if jobscope.active():
        jobscope.set("node_start_hook", (fn,))
        return
    _NODE_START_HOOK = fn


def ring_event(site: str, args: dict | None = None) -> None:
    """Record an instant into the flight ring; free no-op when disarmed."""
    ring = _RING
    if ring is not None:
        ring.add_instant(site, args)


def set_flush_path(path: str) -> None:
    """Point crash/SIGUSR1 flushes at the run's output tree.

    Under a jobscope this is a no-op on the shared ring: the flight
    recorder is ONE process-wide black box owned by the daemon, and two
    resident tenant jobs re-pointing it at their own output trees would
    race — the daemon's state-dir path stays authoritative."""
    if jobscope.active():
        jobscope.set("flush_path", path)
        return
    ring = _RING
    if ring is not None:
        ring.set_flush_path(path)


def flush_armed(reason: str) -> str | None:
    """Flush the armed flight recorder; no-op when disarmed, and NEVER
    raises — every caller is a failure path (crash handler, signal
    handler, watchdog monitor) where a flush error must not mask the
    original fault."""
    ring = _RING
    if ring is None:
        return None
    ring_event("flight.flush", {"reason": reason})
    try:
        return ring.flush(reason)
    except Exception as exc:
        sys.stderr.write(f"live: flight-recorder flush failed: {exc!r}\n")
        return None


def progress_totals(n_libraries: int) -> None:
    tracker = _PROGRESS
    if tracker is not None:
        tracker.set_totals(n_libraries)


def progress_library(name: str) -> None:
    tracker = _PROGRESS
    if tracker is not None:
        tracker.start_library(name)


def progress_library_done() -> None:
    tracker = _PROGRESS
    if tracker is not None:
        tracker.finish_library()


def progress_plan(names: list[str]) -> None:
    """Declare the library's scheduled node names (graph executor)."""
    tracker = _PROGRESS
    if tracker is not None:
        tracker.set_plan(names)


def progress_node_start(name: str, units: int | None = None) -> None:
    tracker = _PROGRESS
    if tracker is not None:
        tracker.node_start(name, units)
    entry = jobscope.get("node_start_hook")
    hook = entry[0] if entry is not None else _NODE_START_HOOK
    if hook is not None:
        try:
            hook(name)
        except Exception:
            pass  # an observer must never fail the stage it observes


def progress_node_finish(name: str, seconds: float,
                         units: int | None = None) -> None:
    tracker = _PROGRESS
    if tracker is not None:
        tracker.node_finish(name, seconds, units)


def progress_node_skip(name: str) -> None:
    tracker = _PROGRESS
    if tracker is not None:
        tracker.node_skip(name)


def configure_eta_priors(ledger_paths: list[str], fingerprint: str) -> None:
    """Load /progress ETA priors from the run's ledgers; the ledger I/O
    only happens when the plane is armed (progress tracker present)."""
    tracker = _PROGRESS
    if tracker is None:
        return
    tracker.set_priors(load_node_priors(ledger_paths, fingerprint))


class Sigusr1Hook:
    """Per-run SIGUSR1 -> on-demand flight-recorder flush.

    Installed by run.py only when the plane is armed; restores the
    previous disposition in the run's finally. ``signal.signal`` is
    main-thread-only — an embedder driving the pipeline from a worker
    thread just loses the on-demand flush (ValueError swallowed), every
    other flush trigger still works.
    """

    def __init__(self):
        self.installed = False
        self.prev = None

    def install(self) -> None:
        if not hasattr(signal, "SIGUSR1"):
            return  # non-POSIX platform
        try:
            self.prev = signal.signal(signal.SIGUSR1, _on_sigusr1)
        except ValueError:
            return
        self.installed = True

    def restore(self) -> None:
        if not self.installed:
            return
        try:
            signal.signal(
                signal.SIGUSR1,
                self.prev if self.prev is not None else signal.SIG_DFL,
            )
        except (ValueError, TypeError, OSError):
            pass  # restoring a disposition is best-effort cleanup
        self.installed = False
        self.prev = None


def _on_sigusr1(signum, frame) -> None:
    flush_armed("sigusr1")
