"""utils subpackage."""
