"""Bounded tenant job queue with HBM-budget admission control.

The reference's Ray cluster solves multi-tenant scheduling with a resource
scheduler the TPU pipeline doesn't have; the serve daemon's queue is the
minimal sound replacement: FIFO order (tenants share one device, fairness
is arrival order), a hard depth bound, and ADMISSION control from the same
:class:`~ont_tcrconsensus_tpu.parallel.budget.BudgetModel` arithmetic the
pipeline sizes its batches with — a job whose requested shapes cannot fit
the budget even at the minimum device batch is rejected at submit time
with a machine-readable reason, not accepted and OOM-killed forty minutes
in.

Queue state is observable two ways, matching the repo's discipline:
counters / gauges / histograms planted into the armed metrics registry
(``serve.submitted`` / ``serve.rejected`` / ``serve.queue_depth`` /
``serve.wait_s`` — the live plane's ``/metrics`` exposes them between and
during jobs) and a JSON journal (:func:`write_journal` /
:func:`load_journal`) the daemon uses for SIGTERM drain: queued + requeued
jobs survive the process and a restarted daemon resumes them.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import sys
import threading
import time

from ont_tcrconsensus_tpu.io import bucketing
from ont_tcrconsensus_tpu.obs import metrics
from ont_tcrconsensus_tpu.parallel.budget import BudgetModel
from ont_tcrconsensus_tpu.robustness import faults
from ont_tcrconsensus_tpu.robustness import lockcheck

JOURNAL_SCHEMA = 1
JOURNAL_BASENAME = "serve_journal.json"
POISON_SCHEMA = 1
POISON_BASENAME = "serve_poison.json"

#: jobs remembered after they leave the queue (done/failed/rejected) so
#: ``GET /jobs/<id>`` keeps answering; oldest-first eviction past this
MAX_FINISHED_REMEMBERED = 64


class AdmissionError(Exception):
    """A job the queue refuses to accept; ``reason`` is machine-readable
    (``queue_full`` / ``invalid_config`` / ``over_budget`` / ...)."""

    def __init__(self, reason: str, detail: str):
        self.reason = reason
        self.detail = detail
        super().__init__(f"{reason}: {detail}")


@dataclasses.dataclass
class Job:
    """One tenant submission: raw config overrides plus lifecycle state.

    ``raw`` is the tenant's JSON object as submitted (merged over the
    daemon's template config at run time); lifecycle timestamps are wall
    seconds. States: queued -> running -> done | failed | poisoned;
    requeued (drain journaled the job mid-queue; resumes with
    ``resume=true`` forced). ``attempts`` counts executions for the
    retry/poison ladder; ``not_before`` (monotonic seconds) is the retry
    backoff gate the pop side respects — neither survives the drain
    journal, so a restart retries a carried job from attempt 0.
    """

    id: str
    raw: dict
    state: str = "queued"
    submitted_t: float = 0.0
    started_t: float | None = None
    finished_t: float | None = None
    error: str | None = None
    result: dict | None = None
    wait_s: float | None = None
    first_stage_s: float | None = None
    attempts: int = 0
    not_before: float = 0.0

    def snapshot(self) -> dict:
        return {
            "id": self.id,
            "state": self.state,
            "submitted_t": round(self.submitted_t, 3),
            "started_t": (round(self.started_t, 3)
                          if self.started_t is not None else None),
            "finished_t": (round(self.finished_t, 3)
                           if self.finished_t is not None else None),
            "wait_s": (round(self.wait_s, 3)
                       if self.wait_s is not None else None),
            "first_stage_s": (round(self.first_stage_s, 3)
                              if self.first_stage_s is not None else None),
            "error": self.error,
            "result": self.result,
        }


def estimate_admission(cfg, budget: BudgetModel) -> tuple[bool, str]:
    """(admissible, detail) for a validated config against the budget.

    Mirrors the shapes :func:`~..pipeline.run.resolve_batching` and the
    polish tiler actually allocate: the fused read pass at the requested
    (or minimum derivable) read batch, and one polish cluster tile at the
    config's subread bucket. Estimation only — the run still sizes its
    real batches from the same model, so an admitted job cannot exceed
    what admission measured.
    """
    # bucket_width is None past the largest declared width: batches of
    # longer reads pad to max_read_length itself
    width = bucketing.bucket_width(cfg.max_read_length) or cfg.max_read_length
    per_read = budget.read_bytes(width, band_width=cfg.sw_band_width)
    if cfg.read_batch_size is not None:
        need = per_read * cfg.read_batch_size
        if need > budget.budget_bytes:
            return False, (
                f"read_batch_size={cfg.read_batch_size} at width {width} "
                f"needs ~{need / 1e9:.2f} GB > working budget "
                f"{budget.budget_bytes / 1e9:.2f} GB"
            )
    elif per_read * 128 > budget.budget_bytes:
        return False, (
            f"max_read_length={cfg.max_read_length} (width {width}) cannot "
            f"fit even the minimum 128-read batch in the working budget "
            f"{budget.budget_bytes / 1e9:.2f} GB"
        )
    s_bucket = bucketing.pow2_ceil(max(cfg.max_reads_per_cluster, 1))
    if budget.cluster_bytes(s_bucket, width) > budget.budget_bytes:
        return False, (
            f"one polish tile of {s_bucket} subreads x width {width} "
            f"exceeds the working budget {budget.budget_bytes / 1e9:.2f} GB"
        )
    return True, "fits"


class JobQueue:
    """Bounded FIFO with admission control and a drain journal.

    Thread contract: the HTTP handler threads submit and snapshot; the
    daemon loop pops and mutates job state through :meth:`mark`. One lock
    guards every structure (declared in robustness/locks.py for the lock
    analyzers); the condition wakes the pop side on submit/requeue.
    """

    def __init__(self, max_depth: int, budget: BudgetModel):
        self.max_depth = int(max_depth)
        self.budget = budget
        self._lock = lockcheck.make_lock()
        self._nonempty = threading.Condition(self._lock)
        self.pending: list[Job] = []
        self.jobs: dict[str, Job] = {}
        self.finished_order: list[str] = []
        self._seq = itertools.count(1)

    # --- submit side (HTTP handler threads) -------------------------------

    def submit(self, raw: dict, cfg) -> Job:
        """Admit ``raw`` (already merged + validated into ``cfg``) or
        raise :class:`AdmissionError`. Plants the queue metrics either
        way — a rejection storm must be visible on /metrics."""
        ok, detail = estimate_admission(cfg, self.budget)
        with self._lock:
            if not ok:
                metrics.counter_add("serve.rejected")
                metrics.reject_add("over_budget")
                raise AdmissionError("over_budget", detail)
            if len(self.pending) >= self.max_depth:
                metrics.counter_add("serve.rejected")
                metrics.reject_add("queue_full")
                raise AdmissionError(
                    "queue_full",
                    f"queue depth {len(self.pending)} at serve_queue_max="
                    f"{self.max_depth}",
                )
            job = Job(id=f"job-{next(self._seq):04d}", raw=dict(raw),
                      submitted_t=time.time())
            self.pending.append(job)
            self.jobs[job.id] = job
            metrics.counter_add("serve.submitted")
            metrics.gauge_set("serve.queue_depth", len(self.pending))
            self._nonempty.notify()
            return job

    def reject(self, reason: str, detail: str) -> AdmissionError:
        """Count + build an admission error for daemon-side rejections
        (invalid config, draining) so every refusal path meters alike."""
        metrics.counter_add("serve.rejected")
        metrics.reject_add(reason)
        return AdmissionError(reason, detail)

    # --- pop side (daemon loop) -------------------------------------------

    def pop(self, timeout: float | None = None) -> Job | None:
        """Next ELIGIBLE job in FIFO order (state -> running), or None on
        timeout. A job whose retry backoff (``not_before``) has not
        elapsed is skipped — later arrivals run ahead of it, so one
        backing-off job never stalls the loop; among eligible jobs order
        stays strictly FIFO."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                now = time.monotonic()
                idx = next((i for i, j in enumerate(self.pending)
                            if j.not_before <= now), None)
                if idx is not None:
                    job = self.pending.pop(idx)
                    job.state = "running"
                    job.started_t = time.time()
                    job.wait_s = job.started_t - job.submitted_t
                    metrics.observe("serve.wait_s", job.wait_s)
                    metrics.gauge_set("serve.queue_depth", len(self.pending))
                    return job
                wait = None if deadline is None else deadline - now
                if wait is not None and wait <= 0:
                    return None
                if self.pending:
                    # everything queued is backing off: sleep only until
                    # the earliest gate opens (or the caller's timeout)
                    gate = min(j.not_before for j in self.pending) - now
                    gate = max(gate, 0.005)
                    wait = gate if wait is None else min(wait, gate)
                self._nonempty.wait(wait)

    def requeue_front(self, job: Job) -> None:
        """Put a drained in-flight job back at the head (state ->
        requeued; the journal writes it first so restart order is FIFO)."""
        with self._lock:
            job.state = "requeued"
            metrics.counter_add("serve.requeued")
            self.pending.insert(0, job)
            metrics.gauge_set("serve.queue_depth", len(self.pending))
            self._nonempty.notify()

    def requeue_back(self, job: Job, *, delay_s: float = 0.0) -> None:
        """Put a transiently-failed job back at the tail with a retry
        backoff (state -> queued); the pop side skips it until
        ``not_before`` so other tenants' jobs run in the meantime."""
        with self._lock:
            job.state = "queued"
            job.not_before = time.monotonic() + max(float(delay_s), 0.0)
            metrics.counter_add("serve.retried")
            self.pending.append(job)
            metrics.gauge_set("serve.queue_depth", len(self.pending))
            self._nonempty.notify()

    def mark(self, job: Job, state: str, *, error: str | None = None,
             result: dict | None = None) -> None:
        """Terminal transition (done/failed/poisoned) + bounded finished
        memory."""
        with self._lock:
            job.state = state
            job.finished_t = time.time()
            job.error = error
            job.result = result
            if state == "done":
                metrics.counter_add("serve.done")
            elif state == "poisoned":
                metrics.counter_add("serve.poisoned")
            else:
                metrics.counter_add("serve.failed")
            self.finished_order.append(job.id)
            while len(self.finished_order) > MAX_FINISHED_REMEMBERED:
                dead = self.finished_order.pop(0)
                self.jobs.pop(dead, None)

    # --- observation -------------------------------------------------------

    def depth(self) -> int:
        with self._lock:
            return len(self.pending)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [j.snapshot() for j in self.jobs.values()]

    def job(self, job_id: str) -> Job | None:
        with self._lock:
            return self.jobs.get(job_id)

    def drain_jobs(self) -> list[Job]:
        """Every not-yet-terminal job in resume order (requeued in-flight
        first, then FIFO pending) for the drain journal."""
        with self._lock:
            return list(self.pending)


# Lock ownership for JobQueue is declared in the consolidated registry
# (ont_tcrconsensus_tpu/robustness/locks.py) consumed by graftlint's
# lock-discipline rule and graftrace's lockset analysis.


# --- drain journal ------------------------------------------------------------


def journal_path(state_dir: str) -> str:
    return os.path.join(state_dir, JOURNAL_BASENAME)


def write_journal(state_dir: str, jobs: list[Job]) -> str | None:
    """Atomically journal ``jobs`` for a restarted daemon; removes any
    stale journal (and returns None) when there is nothing to carry."""
    path = journal_path(state_dir)
    if not jobs:
        try:
            os.remove(path)
        except OSError:
            pass
        return None
    os.makedirs(state_dir, exist_ok=True)
    payload = {
        "schema": JOURNAL_SCHEMA,
        "t_wall": round(time.time(), 3),
        "jobs": [
            {"id": j.id, "raw": j.raw, "state": j.state,
             "submitted_t": round(j.submitted_t, 3)}
            for j in jobs
        ],
    }
    payload_s = json.dumps(payload, indent=1)
    if faults.tear_write("serve.journal_write", path, payload_s):
        return path  # chaos: half the payload hit the final path directly
    # tmp + fsync + rename (io/layout.py manifest discipline): a crash
    # mid-write must leave either the old journal or the new one, never
    # a torn file — these are accepted tenant jobs
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(payload_s)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return path


def _quarantine_torn(path: str, why: str) -> None:
    """Named degradation for an unreadable drain journal: warn once on
    stderr with a greppable prefix and move the file aside (``.bad``) so
    the evidence survives but the next restart doesn't re-trip."""
    print(f"serve: WARNING: torn/unreadable drain journal {path}: {why}; "
          f"quarantined to {os.path.basename(path)}.bad — starting with "
          "an empty queue", file=sys.stderr)
    try:
        os.replace(path, path + ".bad")
    except OSError:
        pass


def load_journal(state_dir: str) -> list[dict]:
    """Read + consume the drain journal: entries in resume order, the
    file removed (its content now lives in the daemon's queue). Torn or
    garbage payloads degrade to a named warning + empty list with the
    file quarantined to ``*.bad`` — a torn journal must not wedge
    restarts."""
    path = journal_path(state_dir)
    try:
        with open(path) as fh:
            payload = json.load(fh)
    except FileNotFoundError:
        return []
    except (OSError, ValueError) as exc:
        _quarantine_torn(path, repr(exc))
        return []
    jobs = payload.get("jobs") if isinstance(payload, dict) else None
    if not isinstance(jobs, list):
        _quarantine_torn(path, "payload is not {schema, jobs: [...]}")
        return []
    try:
        os.remove(path)
    except OSError:
        pass
    return [j for j in jobs if isinstance(j, dict) and isinstance(
        j.get("raw"), dict)]


# --- poison quarantine --------------------------------------------------------


def poison_path(state_dir: str) -> str:
    return os.path.join(state_dir, POISON_BASENAME)


def append_poison(state_dir: str, job: Job, *, classification: str,
                  error: str) -> str:
    """Quarantine a job that exhausted its retries (or failed fatally) to
    ``serve_poison.json`` with a machine-readable reason. Atomic
    read-modify-replace under the daemon loop (single writer), so one
    bad tenant job is recorded durably and never re-enters the queue."""
    path = poison_path(state_dir)
    os.makedirs(state_dir, exist_ok=True)
    entries = load_poison(state_dir)
    entries.append({
        "id": job.id,
        "raw": job.raw,
        "classification": classification,
        "error": error,
        "attempts": int(job.attempts),
        "submitted_t": round(job.submitted_t, 3),
        "t_wall": round(time.time(), 3),
    })
    payload_s = json.dumps({"schema": POISON_SCHEMA, "jobs": entries},
                           indent=1)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(payload_s)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return path


def load_poison(state_dir: str) -> list[dict]:
    """Poison-quarantine entries (non-consuming — the file is the durable
    record); garbage degrades to an empty list."""
    try:
        with open(poison_path(state_dir)) as fh:
            payload = json.load(fh)
    except (OSError, ValueError):
        return []
    jobs = payload.get("jobs") if isinstance(payload, dict) else None
    if not isinstance(jobs, list):
        return []
    return [j for j in jobs if isinstance(j, dict)]
