"""Mesh slice allocator: disjoint pow2 device slices for packed serving.

ROADMAP item 2's open tail: the serve plane ran one job at a time through
the WHOLE mesh, so a second tenant waited even when the first used one
slice — and any fault anywhere was every tenant's fault. This module is
the packing half of the fix (serve/daemon.py's runner pool is the
concurrency half): the local devices become a buddy-style free pool of
power-of-two, ALIGNED slices, each admitted job leases a disjoint slice
sized by its HBM need, and slices return to the pool as jobs finish.

Sizing: a slice of ``n`` of the host's ``N`` devices gets exactly the
budget fraction :func:`~..parallel.budget.degraded_budget` gives a mesh
that kept ``n`` of ``N`` slices — the SAME arithmetic the degraded-mesh
path already trusts, so per-slice admission control
(:func:`~.queue.estimate_admission` against the slice's allowance) can
never admit a job the run's own batch sizing would overcommit. Admission
becomes per-slice, not whole-mesh: the queue's budget is swapped for the
largest grantable slice's allowance (:meth:`SliceAllocator.admission_budget`).

Alignment: a slice of size ``n`` (always a power of two) may start only
at device index multiples of ``n`` — the buddy invariant. That makes
fragmentation REAL and testable (four singles can be busy such that no
aligned pair is free) and keeps merges implicit: freeing a lease frees
its aligned run, so a later larger request needs no coalescing pass.

Fault containment (the robustness spine):

- ``serve.slice_assign`` fires BEFORE any pool mutation, so a chaos raise
  at the carve site can never leak devices.
- ``serve.pack`` fires AFTER a release has returned its devices to the
  pool, so a chaos raise mid-pack leaves the pool consistent (the lease
  is gone, the devices are free, the waiter was notified).
- :meth:`quarantine` pulls a lost slice's devices OUT of circulation —
  they are neither free nor busy, they are gone until an operator
  restarts — and meters them (``tcr_slice_quarantined_total``, busy
  gauge 0). Tenant B's lease, by disjointness, is untouched.

Thread contract: HTTP submit threads read :meth:`admission_budget`; the
daemon dispatcher assigns/waits; runner workers release/quarantine. One
lock guards the pool tables (declared in robustness/locks.py for the
lock analyzers); the condition wakes the dispatcher on release.
"""

from __future__ import annotations

import dataclasses
import threading

from ont_tcrconsensus_tpu.io import bucketing
from ont_tcrconsensus_tpu.obs import live as obs_live
from ont_tcrconsensus_tpu.obs import metrics as obs_metrics
from ont_tcrconsensus_tpu.parallel.budget import BudgetModel, degraded_budget
from ont_tcrconsensus_tpu.robustness import faults, lockcheck
from ont_tcrconsensus_tpu.serve import queue as queue_mod

#: device-index states in the allocator pool
FREE, BUSY, QUARANTINED = "free", "busy", "quarantined"


def _device_label(dev) -> str:
    """The /metrics slice label for one device (mesh.py's vocabulary)."""
    try:
        return f"{dev.platform}:{dev.id}"
    except AttributeError:  # test doubles: anything stringable works
        return str(dev)


@dataclasses.dataclass
class SliceLease:
    """One tenant job's hold on an aligned device run."""

    job_id: str
    start: int          # first device index (multiple of size)
    size: int           # pow2 device count
    devices: list       # the actual jax devices, in index order

    @property
    def slice_id(self) -> str:
        return f"{self.start}+{self.size}"

    @property
    def labels(self) -> list[str]:
        return [_device_label(d) for d in self.devices]


class SliceAllocator:
    """Buddy-style pow2 slice pool over the local device order."""

    def __init__(self, devices, budget: BudgetModel):
        if not devices:
            raise ValueError("slice allocator needs at least one device")
        self.devices = list(devices)
        self.n_total = len(self.devices)
        # largest pow2 slice the pool can ever grant (aligned at 0)
        self.max_size = 1
        while self.max_size * 2 <= self.n_total:
            self.max_size *= 2
        self.budget = budget
        self._lock = lockcheck.make_lock()
        self._freed = threading.Condition(self._lock)
        self._state: list[str] = [FREE] * self.n_total
        self._leases: dict[str, SliceLease] = {}

    # Lock ownership for the pool tables (_state/_leases -> _lock) is
    # declared in the consolidated registry (robustness/locks.py)
    # consumed by graftlint's lock-discipline rule and graftrace.

    # --- sizing (pure arithmetic; no pool state) ---------------------------

    def allowance(self, size: int) -> BudgetModel:
        """The HBM budget a ``size``-device slice is entitled to: the
        whole-host budget scaled by size/total — byte-for-byte the
        degraded-mesh arithmetic, so slice admission and mid-run
        degradation can never disagree about what fits."""
        return degraded_budget(self.budget, size, self.n_total)

    def size_for(self, cfg) -> tuple[int | None, str]:
        """(slice size, detail) for a validated config; (None, why) when
        no grantable slice can ever admit it.

        An explicit ``mesh_shape`` pins the size: the pow2 ceiling of the
        axis product (the mesh uses the first ``product`` devices of the
        lease). Otherwise the SMALLEST pow2 slice whose allowance admits
        the job wins — small jobs pack many-at-a-time, and a job is never
        handed more of the mesh than its shapes need. ``read_batch_size``
        must stay divisible by the slice's data width, matching
        run.py's mesh-divisibility contract.
        """
        if cfg.mesh_shape:
            need = 1
            for v in cfg.mesh_shape.values():
                need *= int(v)
            size = bucketing.pow2_ceil(max(need, 1))
            if size > self.max_size:
                return None, (
                    f"mesh_shape={dict(cfg.mesh_shape)} needs {need} "
                    f"devices; the largest grantable slice is "
                    f"{self.max_size} of {self.n_total}"
                )
            ok, detail = queue_mod.estimate_admission(
                cfg, self.allowance(size))
            if not ok:
                return None, f"slice of {size}: {detail}"
            return size, f"pinned by mesh_shape ({need} devices)"
        size = 1
        while size <= self.max_size:
            divisible = (cfg.read_batch_size is None
                         or cfg.read_batch_size % size == 0)
            if divisible:
                ok, detail = queue_mod.estimate_admission(
                    cfg, self.allowance(size))
                if ok:
                    return size, f"fits a {size}-device slice"
            size *= 2
        # re-run the max-size estimate for an honest rejection detail
        _, detail = queue_mod.estimate_admission(
            cfg, self.allowance(self.max_size))
        return None, f"largest slice ({self.max_size}): {detail}"

    def admission_budget(self) -> BudgetModel:
        """The submit-side admission budget: the largest grantable
        slice's allowance. Shrinks when quarantines eat the big aligned
        runs — the daemon re-swaps the queue budget after each loss, so
        admission follows the surviving capacity."""
        with self._lock:
            best = self._largest_grantable_locked()
        return self.allowance(max(best, 1))

    def _largest_grantable_locked(self) -> int:
        """Largest pow2 size with an aligned run of non-quarantined
        devices (busy counts: it frees later; quarantined never does)."""
        size = self.max_size
        while size >= 1:
            for start in range(0, self.n_total - size + 1, size):
                if all(self._state[i] != QUARANTINED
                       for i in range(start, start + size)):
                    return size
            size //= 2
        return 0

    # --- assign / release / quarantine -------------------------------------

    def try_assign(self, job_id: str, size: int) -> SliceLease | None:
        """Lease the first free aligned ``size``-run to ``job_id``; None
        when none is free RIGHT NOW (the caller keeps the job queued and
        waits — fragmentation or full residency is a wait, never a
        rejection). Raises whatever ``serve.slice_assign`` chaos injects —
        before any pool mutation, so nothing leaks."""
        faults.inject("serve.slice_assign")
        with self._lock:
            for start in range(0, self.n_total - size + 1, size):
                if all(self._state[i] == FREE
                       for i in range(start, start + size)):
                    for i in range(start, start + size):
                        self._state[i] = BUSY
                    lease = SliceLease(
                        job_id, start, size,
                        self.devices[start:start + size])
                    self._leases[job_id] = lease
                    break
            else:
                return None
        reg = obs_metrics.global_registry()
        if reg is not None:
            for label in lease.labels:
                reg.mesh_slice_set(label, 1.0)
                reg.slice_tenant_set(label, job_id)
        obs_live.ring_event("serve.slice", {
            "event": "assign", "id": job_id, "slice": lease.slice_id,
            "devices": lease.labels,
        })
        return lease

    def can_ever_fit(self, size: int) -> bool:
        """Whether an aligned ``size``-run of non-quarantined devices
        still exists — False means waiting is hopeless (quarantines ate
        the capacity) and the caller should fail the job loudly rather
        than queue it forever."""
        with self._lock:
            return self._largest_grantable_locked() >= size

    def release(self, job_id: str) -> None:
        """Return ``job_id``'s lease to the free pool and wake waiters.
        The ``serve.pack`` chaos site fires AFTER the devices are free —
        a raise mid-pack must leave the pool consistent, never leak a
        slice. No-op for an unknown/already-released job."""
        with self._lock:
            lease = self._leases.pop(job_id, None)
            if lease is not None:
                for i in range(lease.start, lease.start + lease.size):
                    # quarantined devices stay quarantined through the
                    # owner's release: the loss outlives the job
                    if self._state[i] == BUSY:
                        self._state[i] = FREE
                self._freed.notify_all()
        if lease is None:
            return
        reg = obs_metrics.global_registry()
        if reg is not None:
            for label in lease.labels:
                reg.mesh_slice_set(label, 0.0)
                reg.slice_tenant_set(label, "")
        obs_live.ring_event("serve.slice", {
            "event": "release", "id": job_id, "slice": lease.slice_id,
        })
        faults.inject("serve.pack")

    def quarantine(self, job_id: str, lost_devices=None) -> list[str]:
        """Pull ``job_id``'s lease (or just ``lost_devices`` of it) out
        of circulation: device_lost on tenant A's slice must remove that
        capacity from the pool — NOT return it for tenant C to land on —
        while B's disjoint lease never notices. Returns the quarantined
        device labels (for the caller's logs/ledger)."""
        lost_ids = (None if lost_devices is None
                    else {id(d) for d in lost_devices})
        with self._lock:
            lease = self._leases.get(job_id)
            if lease is None:
                return []
            hit: list[int] = []
            for offset, dev in enumerate(lease.devices):
                if lost_ids is None or id(dev) in lost_ids:
                    self._state[lease.start + offset] = QUARANTINED
                    hit.append(offset)
        labels = [lease.labels[o] for o in hit]
        # the degrade hook calls this on the JOB's thread, inside its
        # jobscope — plant via the global registry so the quarantine is
        # visible on the daemon's /metrics, not buried in the tenant's
        # per-run telemetry
        reg = obs_metrics.global_registry()
        if reg is not None:
            for label in labels:
                reg.mesh_slice_set(label, 0.0)
                reg.slice_tenant_set(label, "")
                reg.slice_quarantine_add(label)
        if labels:
            obs_live.ring_event("serve.slice", {
                "event": "quarantine", "id": job_id,
                "slice": lease.slice_id, "devices": labels,
            })
        return labels

    def wait_for_release(self, timeout: float) -> None:
        """Block until some lease is released (or ``timeout`` elapses) —
        the dispatcher's fragmentation wait."""
        with self._lock:
            self._freed.wait(timeout)

    # --- introspection ------------------------------------------------------

    def resident(self) -> int:
        with self._lock:
            return len(self._leases)

    def snapshot(self) -> dict:
        """Pool state for tests/debug endpoints: per-device state plus
        the live leases (job -> slice)."""
        with self._lock:
            return {
                "devices": {
                    _device_label(d): self._state[i]
                    for i, d in enumerate(self.devices)
                },
                "leases": {
                    job_id: {"slice": lease.slice_id,
                             "devices": lease.labels}
                    for job_id, lease in sorted(self._leases.items())
                },
                "quarantined": sum(
                    1 for s in self._state if s == QUARANTINED),
            }
