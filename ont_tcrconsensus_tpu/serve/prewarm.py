"""AOT prewarm of the production shape buckets at daemon start.

The serving-loop discipline: every steady-state dispatch must hit an
executable compiled BEFORE traffic arrived. The pipeline already bounds
its shape space by construction — reads pad into the power-of-two
``DEFAULT_WIDTHS`` buckets at one budget-derived batch size, polish tiles
into (cluster_batch, s_bucket, width) tiles — so the declared bucket set
is enumerable from the config alone, and each entry point can be
``.lower(...).compile()``-d ahead of the first job with
``jax.ShapeDtypeStruct`` stand-ins for the batch arrays.

Compiled programs land in the jitted entry points' in-process caches
(the daemon's steady-state hits) AND in the persistent
``compile_cache_dir`` (a restarted daemon's cold start reads them back
instead of recompiling — that is what makes the ≤10s dispatch-to-first-
stage goal reachable after the very first deployment).

Every bucket compiles under try/except into a report entry: prewarm is an
optimization, and a signature drift between this module and the entry
points must degrade to a visible report line + lazy first-job compile,
never a dead daemon. (The signature is pinned by tests/test_serve.py.)
"""

from __future__ import annotations

import time

from ont_tcrconsensus_tpu.io import bucketing


def declared_width_buckets(cfg) -> list[int]:
    """The read-batch width buckets this config's traffic can produce:
    every declared width up to the one covering ``max_read_length``."""
    cap = (bucketing.bucket_width(cfg.max_read_length)
           or cfg.max_read_length)
    return [w for w in bucketing.DEFAULT_WIDTHS if w <= cap] or [cap]


def _prewarm_fused_assign(cfg, engine, read_batch: int, widths: list[int],
                          report: list[dict]) -> None:
    import jax
    import jax.numpy as jnp

    from ont_tcrconsensus_tpu.pipeline.assign import _fused_pass

    # round-1 fastq serving: quals present; the SW fast path keys the
    # static signature exactly as AssignEngine.run_batch_async does
    fast = engine.fast_denom > 0 and engine.top_k == 2
    statics = engine._static_kwargs(has_quals=True, fast=fast)
    for width in widths:
        t0 = time.monotonic()
        entry = {"kind": "fused_assign", "batch": read_batch,
                 "width": width}
        try:
            args = (
                jax.ShapeDtypeStruct((read_batch, width), jnp.uint8),
                jax.ShapeDtypeStruct((read_batch, width), jnp.uint8),
                jax.ShapeDtypeStruct((read_batch,), jnp.int32),
                engine.panel.d_codes, engine.panel.d_lens,
                engine.panel.d_profiles,
                engine.umi_masks, engine.umi_mask_lens,
                engine.primer_stack, engine.primer_stack_lens,
                engine.primer_max_dists,
                jnp.float32(cfg.max_ee_rate_base),
                jnp.int32(cfg.minimal_length),
                jnp.float32(cfg.minimal_region_overlap),
            )
            _fused_pass.lower(*args, **statics).compile()
            entry["ok"] = True
        except Exception as exc:
            entry["ok"] = False
            entry["error"] = repr(exc)
        entry["seconds"] = round(time.monotonic() - t0, 3)
        report.append(entry)


def _prewarm_polisher(cfg, budget, widths: list[int],
                      report: list[dict]) -> None:
    import jax
    import jax.numpy as jnp

    from ont_tcrconsensus_tpu.models import polisher as polisher_mod

    params = polisher_mod.load_default_params()
    if params is None or cfg.polish_method != "rnn":
        report.append({"kind": "polisher", "ok": False,
                       "error": "skipped: no bundled weights or "
                                "polish_method != rnn", "seconds": 0.0})
        return
    wants_v4 = (polisher_mod.params_feature_dim(params)
                == polisher_mod.FEATURE_DIM_V4)
    use_bf16 = cfg.polish_bf16 and polisher_mod.bf16_serving_certified(
        min_polish_depth=cfg.min_polish_depth)
    s_bucket = bucketing.pow2_ceil(max(cfg.max_reads_per_cluster, 1))
    # the production polish tile: subreads are full-length reads, so the
    # dominant width bucket is the read-length one — prewarm the largest
    # declared width (the expensive program) at the budget-derived tile
    width = max(widths)
    eff_band = (cfg.sw_band_width if width <= 2048
                else max(cfg.sw_band_width, 128))
    cb = cfg.cluster_batch_size or budget.cluster_batch(
        s_bucket, width, eff_band, keep_final_pileup=True,
        keep_pos=wants_v4)
    t0 = time.monotonic()
    entry = {"kind": "polisher", "batch": cb, "s_bucket": s_bucket,
             "width": width, "band": eff_band, "v4": wants_v4}
    try:
        sds = jax.ShapeDtypeStruct
        polisher_mod._device_polish_batch_jit.lower(
            params,
            sds((cb, s_bucket, width), jnp.uint8),   # sub
            sds((cb, s_bucket), jnp.int32),          # lens
            sds((cb, width), jnp.uint8),             # drafts
            sds((cb,), jnp.int32),                   # dlens
            eff_band,
            quals=sds((cb, s_bucket, width), jnp.uint8) if wants_v4 else None,
            is_rev=sds((cb, s_bucket), jnp.bool_) if wants_v4 else None,
            bf16=use_bf16,
        ).compile()
        entry["ok"] = True
    except Exception as exc:
        entry["ok"] = False
        entry["error"] = repr(exc)
    entry["seconds"] = round(time.monotonic() - t0, 3)
    report.append(entry)


def prewarm(cfg, engine, read_batch: int, budget,
            widths: list[int] | None = None) -> dict:
    """Lower+compile the declared bucket set; returns the report dict
    (recorded into the daemon's telemetry via ``analysis_set`` and the
    serve ledger entries' ``warmup_s``).

    ``engine`` is the daemon's round-1 :class:`AssignEngine` (its device
    constants are the real lowering inputs), ``read_batch``/``budget``
    come from :func:`~..pipeline.run.resolve_batching`. ``widths``
    overrides the declared bucket set (tests prewarm one small bucket).
    Mesh-sharded configs are declared unsupported here: the sharded entry
    points cache per-engine, so a daemon restart cannot reuse them
    anyway — they compile lazily on the first job.
    """
    t0 = time.monotonic()
    report: list[dict] = []
    if cfg.mesh_shape:
        return {"skipped": "mesh_shape set — sharded entry points "
                           "prewarm lazily on the first job",
                "entries": [], "seconds": 0.0}
    widths = list(widths) if widths else declared_width_buckets(cfg)
    _prewarm_fused_assign(cfg, engine, read_batch, widths, report)
    _prewarm_polisher(cfg, budget, widths, report)
    return {
        "entries": report,
        "compiled": sum(1 for e in report if e.get("ok")),
        "failed": sum(1 for e in report if not e.get("ok")),
        "seconds": round(time.monotonic() - t0, 3),
    }
