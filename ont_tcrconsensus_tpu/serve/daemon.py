"""The warm-serving daemon loop + its loopback HTTP control plane.

One process arms everything exactly once — the persistent compilation
cache, the daemon-scope metrics registry, the live plane (now with the
``/jobs`` routes) — then runs every accepted job through the unchanged
:func:`~..pipeline.run.run_with_config`. Artifact byte-identity with the
one-shot CLI is therefore structural: jobs execute the same code path;
the daemon only decides WHEN, and keeps the process (and with it every
module-level jitted entry point's compiled executables) alive between
jobs. The second job with production shapes dispatches with ZERO backend
compiles — its own telemetry.json proves it via the PR 6 compile
listener.

Lifecycle:

- start: template config validated -> compile cache armed -> live plane
  + jobs controller up (``serve_info.json`` in the state dir records the
  resolved port + pid) -> drain journal resumed -> AOT prewarm
  (serve/prewarm.py) -> accept loop.
- job: merged overrides revalidated, ``live_port`` forced off (the
  daemon owns the plane), dispatch-to-first-stage latency measured via
  the live plane's node-start hook, a ``source: "serve"`` ledger entry
  appended next to the run's own (warmup_s on the first job, steady_s
  per job).
- SIGTERM: the in-flight job drains at its next stage boundary through
  the standard shutdown coordinator (its committed stages resume), every
  unfinished job is journaled, exit code 143; a restarted daemon loads
  the journal and resumes the jobs with ``resume=true`` forced through
  verified resume.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time

from ont_tcrconsensus_tpu.obs import history as obs_history
from ont_tcrconsensus_tpu.obs import live as obs_live
from ont_tcrconsensus_tpu.obs import metrics as obs_metrics
from ont_tcrconsensus_tpu.pipeline.config import RunConfig
from ont_tcrconsensus_tpu.robustness import faults
from ont_tcrconsensus_tpu.robustness import jobscope
from ont_tcrconsensus_tpu.robustness import lockcheck
from ont_tcrconsensus_tpu.robustness import retry as retry_mod
from ont_tcrconsensus_tpu.robustness import shutdown
from ont_tcrconsensus_tpu.robustness import watchdog as watchdog_mod
from ont_tcrconsensus_tpu.serve import prewarm as prewarm_mod
from ont_tcrconsensus_tpu.serve import queue as queue_mod
from ont_tcrconsensus_tpu.serve import slices as slices_mod

SERVE_INFO_BASENAME = "serve_info.json"


def _log(*parts):
    print("serve:", *parts, file=sys.stderr)


@dataclasses.dataclass
class _JobOutcome:
    state: str
    error: str | None = None
    result: dict | None = None


class Daemon:
    """The long-lived serving loop; also the live plane's jobs controller
    (duck type behind ``POST /jobs`` — :meth:`submit`,
    :meth:`jobs_snapshot`, :meth:`job_snapshot`)."""

    def __init__(self, template: dict, *, port: int, state_dir: str,
                 queue_max: int | None = None, do_prewarm: bool | None = None,
                 prewarm_widths: list[int] | None = None,
                 workers: int | None = None):
        # runtime lockset twin: arm before the JobQueue (and later the
        # daemon-owned metrics/live registries) pick their lock type
        lockcheck.arm_from_env()
        self.template = dict(template)
        # the template must itself be a complete, valid run config: every
        # job inherits it, so a broken template fails at daemon start, not
        # on the first tenant's submit
        self.template_cfg = RunConfig.from_dict(dict(template))
        self.port = port
        self.state_dir = state_dir
        self.prewarm_widths = prewarm_widths
        self.do_prewarm = (self.template_cfg.serve_prewarm
                           if do_prewarm is None else do_prewarm)
        from ont_tcrconsensus_tpu.parallel import budget as budget_mod

        self.budget = budget_mod.BudgetModel(
            self.template_cfg.hbm_budget_gb
            if self.template_cfg.hbm_budget_gb is not None
            else budget_mod.detect_hbm_gb()
        )
        self.queue = queue_mod.JobQueue(
            queue_max if queue_max is not None
            else self.template_cfg.serve_queue_max,
            self.budget,
        )
        # bounded per-job retry, from the SAME config knobs the batch
        # path's stage retries use — transient failures requeue with
        # backoff, anything else (or exhaustion) poison-quarantines
        self.retry_policy = retry_mod.RetryPolicy(
            max_attempts=self.template_cfg.retry_max_attempts,
            base_delay_s=self.template_cfg.retry_base_delay_s,
            max_delay_s=2.0, seed=0,
        )
        self.prewarm_report: dict | None = None
        self.warmup_s: float | None = None
        self.jobs_done = 0
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._coord = shutdown.ShutdownCoordinator()
        # slice-packed runner pool (serve/slices.py): with workers > 1 the
        # local devices become a pool of disjoint pow2 slices and up to
        # `workers` jobs run concurrently, each on its own slice in its
        # own job scope. workers == 1 keeps the serial loop byte-for-byte.
        self.workers = (workers if workers is not None
                        else self.template_cfg.serve_workers)
        self.allocator: slices_mod.SliceAllocator | None = None
        if self.workers > 1:
            import jax

            self.allocator = slices_mod.SliceAllocator(
                jax.local_devices(), self.budget)
            # admission control turns per-slice: a submit is judged
            # against the largest grantable slice's allowance, not the
            # whole mesh (re-swapped as quarantines shrink the pool)
            self.queue.budget = self.allocator.admission_budget()
        self._done_lock = threading.Lock()
        self._preempt_exit = threading.Event()

    # --- jobs controller (HTTP handler threads) ----------------------------

    def submit(self, overrides: dict) -> tuple[int, dict]:
        if (self._draining.is_set() or self._stop.is_set()
                or self._coord.requested()):
            # the SIGTERM window counts too: between the signal and the
            # loop's exit the in-flight job is still draining, and a job
            # accepted now would only journal — refuse it honestly
            err = self.queue.reject(
                "draining", "daemon is draining; resubmit after restart "
                            "(queued jobs are journaled)")
            return 503, {"error": err.reason, "detail": err.detail}
        merged = dict(self.template)
        merged.update(overrides)
        # the daemon owns the live plane; a job must not re-point it
        merged["live_port"] = None
        try:
            cfg = RunConfig.from_dict(merged)
        except Exception as exc:
            err = self.queue.reject("invalid_config", str(exc))
            return 400, {"error": err.reason, "detail": err.detail}
        try:
            job = self.queue.submit(merged, cfg)
        except queue_mod.AdmissionError as exc:
            status = 429 if exc.reason == "queue_full" else 409
            return status, {"error": exc.reason, "detail": exc.detail}
        obs_live.ring_event("serve.job", {"id": job.id, "event": "queued"})
        snap = job.snapshot()
        snap["queue_depth"] = self.queue.depth()
        return 202, snap

    def jobs_snapshot(self) -> dict:
        snap = {
            "jobs": self.queue.snapshot(),
            "queue_depth": self.queue.depth(),
            "draining": self._draining.is_set(),
            "jobs_done": self.jobs_done,
            "warmup_s": self.warmup_s,
            "prewarm": self.prewarm_report,
        }
        if self.allocator is not None:
            # packed serving: tenants can watch residency + the pool map
            # (who holds which slice, what's quarantined) over GET /jobs
            snap["resident_jobs"] = self.allocator.resident()
            snap["slices"] = self.allocator.snapshot()
        return snap

    def job_snapshot(self, job_id: str) -> dict | None:
        job = self.queue.job(job_id)
        return job.snapshot() if job is not None else None

    def request_stop(self) -> None:
        """Programmatic drain (tests / embedders): same path as SIGTERM
        minus the signal, exit code 0."""
        self._stop.set()

    # --- lifecycle ----------------------------------------------------------

    def _write_info(self, srv_port: int) -> None:
        os.makedirs(self.state_dir, exist_ok=True)
        path = os.path.join(self.state_dir, SERVE_INFO_BASENAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"port": srv_port, "pid": os.getpid(),
                       "t_wall": round(time.time(), 3)}, fh, indent=1)
        os.replace(tmp, path)

    def _resume_journal(self) -> None:
        for rec in queue_mod.load_journal(self.state_dir):
            raw = dict(rec["raw"])
            # committed stages of a drained job must resume, not refuse
            # on the existing output tree
            raw["resume"] = True
            raw["live_port"] = None
            try:
                cfg = RunConfig.from_dict(raw)
                self.queue.submit(raw, cfg)
                _log(f"journal: resumed {rec.get('id')} as a fresh job")
            except Exception as exc:
                _log(f"journal: dropped {rec.get('id')}: {exc!r}")

    def _prewarm(self) -> None:
        if not self.do_prewarm:
            self.prewarm_report = {"skipped": "serve_prewarm off",
                                   "entries": [], "seconds": 0.0}
            return
        try:
            faults.inject("serve.prewarm")
            self._prewarm_inner()
        except Exception as exc:
            # prewarm is an optimization, never a gate: a failure degrades
            # to lazy first-job compiles and the daemon stays up
            self.prewarm_report = {"error": repr(exc), "entries": [],
                                   "seconds": 0.0, "failed": 1}
            obs_metrics.analysis_set("serve_prewarm", self.prewarm_report)
            _log(f"WARNING: prewarm failed ({exc!r}); first job with each "
                 "shape compiles lazily")

    def _prewarm_inner(self) -> None:
        from ont_tcrconsensus_tpu.cluster import regions as regions_mod
        from ont_tcrconsensus_tpu.io import fastx
        from ont_tcrconsensus_tpu.pipeline import run as run_mod
        from ont_tcrconsensus_tpu.pipeline import stages

        cfg = self.template_cfg
        reference = fastx.read_fasta_dict(cfg.reference_file)
        homology = regions_mod.self_homology_map(
            reference, cfg.cluster_identity)
        panel = stages.ReferencePanel.build(
            reference, homology.region_cluster)
        read_batch, budget = run_mod.resolve_batching(
            cfg, len(panel.names), None)
        engine = stages.AssignEngine(
            panel, cfg.umi_fwd, cfg.umi_rev,
            primers=cfg.primer_sequences(),
            primer_max_dist_frac=cfg.primer_max_dist_frac,
            a5=cfg.max_softclip_5_end, a3=cfg.max_softclip_3_end,
            trim_window=cfg.trim_window, band_width=cfg.sw_band_width,
            fast_denom=4 if cfg.round1_fast_assign else 0,
        )
        self.prewarm_report = prewarm_mod.prewarm(
            cfg, engine, read_batch, budget, widths=self.prewarm_widths)
        obs_metrics.analysis_set("serve_prewarm", self.prewarm_report)
        _log(f"prewarm: {self.prewarm_report.get('compiled', 0)} program(s) "
             f"in {self.prewarm_report.get('seconds', 0.0)}s")

    def serve_forever(self) -> int:
        """Arm, prewarm, loop until drained; returns the exit code (143
        for a signal-initiated drain, 0 for a programmatic stop)."""
        from ont_tcrconsensus_tpu.pipeline import run as run_mod

        cache_state = run_mod.enable_compilation_cache(
            self.template_cfg.compile_cache_dir)
        # serve-scope chaos: TCR_CHAOS arms drills that fire in the daemon
        # loop itself (each job's run re-declares its own chaos state, so
        # a per-run env plan still fires inside jobs as before)
        faults.arm_from_env()
        obs_metrics.arm()
        obs_metrics.analysis_set("compile_cache", cache_state)
        srv = obs_live.arm(self.port)
        obs_live.set_flush_path(os.path.join(
            self.state_dir, "logs", "flight_recorder.json"))
        obs_live.set_jobs_controller(self)
        self._write_info(srv.port)
        installed = self._coord.install()
        shutdown.activate(self._coord)
        _log(f"daemon up on http://127.0.0.1:{srv.port} "
             f"(/jobs /healthz /metrics /progress; pid {os.getpid()}"
             f"{'' if installed else '; cooperative stop only'})")
        exit_code = 0
        crash: BaseException | None = None
        try:
            self._resume_journal()
            self._prewarm()
            self.warmup_s = round(time.monotonic() - self._t0, 3)
            _log(f"warm after {self.warmup_s}s; accepting jobs"
                 + (f" ({self.workers} packed workers)"
                    if self.allocator is not None else ""))
            if self.allocator is not None:
                exit_code = self._packed_loop()
            else:
                while True:
                    if self._coord.requested():
                        exit_code = 143
                        break
                    if self._stop.is_set():
                        break
                    job = self.queue.pop(timeout=0.25)
                    if job is None:
                        continue
                    try:
                        # loop-crash drill: the popped job must not vanish
                        # — requeue it so the drain journal in `finally`
                        # (and a restarted daemon) still carries it
                        faults.inject("serve.daemon_loop")
                    except BaseException:
                        self.queue.requeue_front(job)
                        raise
                    if self._coord.requested() or self._stop.is_set():
                        # drained between pop and dispatch: back on head
                        self.queue.requeue_front(job)
                        exit_code = 143 if self._coord.requested() else 0
                        break
                    if not self._run_job(job):
                        exit_code = 143
                        break
        except BaseException as exc:
            crash = exc
            raise
        finally:
            self._draining.set()
            drained = self.queue.drain_jobs()
            path = queue_mod.write_journal(self.state_dir, drained)
            if path:
                _log(f"drain: journaled {len(drained)} job(s) to {path}")
            # a crash flushes the flight recorder under a reason naming
            # the exception type, so the black box says WHY it died; each
            # job's run re-pointed the flush path into its own output
            # tree, so re-claim the daemon's before flushing
            obs_live.set_flush_path(os.path.join(
                self.state_dir, "logs", "flight_recorder.json"))
            obs_live.flush_armed(
                "serve_drain" if crash is None
                else f"serve_crash:{type(crash).__name__}")
            obs_live.set_jobs_controller(None)
            obs_live.disarm()
            obs_metrics.disarm()
            faults.disarm()
            shutdown.deactivate(self._coord)
            self._coord.uninstall()
        return exit_code

    # --- the packed (multi-tenant) loop --------------------------------------

    def _packed_loop(self) -> int:
        """The runner-pool accept loop: up to ``serve_workers`` jobs run
        concurrently, each on a disjoint device slice in its own job
        scope. Same drain contract as the serial loop (exit 143 on a
        signal, 0 on a programmatic stop), extended to N residents: a
        SIGTERM preempts EVERY resident job at its next stage boundary
        (each scoped checkpoint also polls the daemon's coordinator) and
        all of them requeue before the caller journals."""
        slots = threading.Semaphore(self.workers)
        workers: list[threading.Thread] = []
        exit_code = 0
        while True:
            if self._coord.requested() or self._preempt_exit.is_set():
                exit_code = 143
                break
            if self._stop.is_set():
                break
            # slot BEFORE pop: a popped job must never sit slotless in
            # dispatcher limbo where a drain would miss it
            if not slots.acquire(timeout=0.25):
                continue
            dispatched = False
            try:
                job = self.queue.pop(timeout=0.25)
                if job is None:
                    continue
                try:
                    # same loop-crash drill as the serial path: the popped
                    # job must not vanish on a dispatcher fault
                    faults.inject("serve.daemon_loop")
                except BaseException:
                    self.queue.requeue_front(job)
                    raise
                if self._coord.requested() or self._stop.is_set():
                    self.queue.requeue_front(job)
                    exit_code = 143 if self._coord.requested() else 0
                    break
                raw = dict(job.raw)
                cfg = RunConfig.from_dict(raw)
                size, detail = self.allocator.size_for(cfg)
                if size is None:
                    # admitted once, but the pool shrank underneath it
                    # (quarantines): fail loudly, never queue forever
                    self._poison_capacity(job, detail)
                    continue
                try:
                    lease = self.allocator.try_assign(job.id, size)
                except Exception as exc:
                    # serve.slice_assign chaos fires before any pool
                    # mutation; the failure rides the normal ladder
                    self._finish_if_terminal(
                        job, self._failure_outcome(job, exc))
                    continue
                if lease is None:
                    if not self.allocator.can_ever_fit(size):
                        self._poison_capacity(
                            job, f"no aligned {size}-device run survives "
                                 f"quarantine")
                        continue
                    # fragmentation or full residency: free slices may
                    # exist but no aligned run this big is free RIGHT NOW
                    # — the job stays queued (not rejected) and the
                    # dispatcher waits for a release
                    self.queue.requeue_front(job)
                    self.allocator.wait_for_release(0.25)
                    continue
                if not raw.get("mesh_shape"):
                    # packed jobs shard over exactly their slice; a
                    # tenant-pinned mesh_shape is honored as-is (the
                    # lease was sized to cover it)
                    raw["mesh_shape"] = {"data": lease.size}
                    cfg = RunConfig.from_dict(raw)
                t = threading.Thread(
                    target=self._slice_worker,
                    args=(job, cfg, lease, slots),
                    name=f"serve-worker-{job.id}", daemon=True)
                workers.append(t)
                dispatched = True
                t.start()
                obs_metrics.gauge_set(
                    "serve.resident_jobs",
                    float(self.allocator.resident()))
            finally:
                if not dispatched:
                    slots.release()
        # stop dispatching, then wait for the residents: they finish
        # (programmatic stop) or preempt at the next stage boundary
        # (signal), and their requeues must land before the drain journal
        for t in workers:
            t.join()
        if self._preempt_exit.is_set():
            exit_code = 143
        return exit_code

    def _slice_worker(self, job: queue_mod.Job, cfg: RunConfig,
                      lease: slices_mod.SliceLease,
                      slots: threading.Semaphore) -> None:
        """One runner-pool worker: run the job on its slice, then return
        the slice to the pool (quarantined devices stay out) and free the
        slot. A drain mid-run (False from _run_job) stops the
        dispatcher."""
        ok = True
        try:
            ok = self._run_job(job, cfg=cfg, lease=lease)
        except BaseException as exc:
            # _run_job owns job failures; anything escaping it is
            # daemon-plane plumbing — log it, keep the pool consistent
            _log(f"{job.id}: worker crashed outside the job ladder: "
                 f"{exc!r}")
        finally:
            try:
                self.allocator.release(job.id)
            except Exception as exc:
                # serve.pack chaos fires AFTER the devices are freed: the
                # pool is consistent, the fault is observability only
                _log(f"{job.id}: pack fault after release: {exc!r}")
            slots.release()
            obs_metrics.gauge_set(
                "serve.resident_jobs", float(self.allocator.resident()))
            if not ok:
                self._preempt_exit.set()

    def _on_slice_degrade(self, job: queue_mod.Job,
                          lease: slices_mod.SliceLease, lost) -> None:
        """Degrade-hook for a packed job's mesh (parallel/mesh.py calls it
        from degrade_mesh, on the job's own thread): the run survived a
        device loss by remeshing WITHIN its slice, so only the dead
        devices leave the pool — no later tenant can land on them, and
        admission shrinks to the surviving capacity. Tenant isolation is
        structural: the hook only ever touches this job's lease."""
        labels = self.allocator.quarantine(job.id, lost_devices=lost)
        self.queue.budget = self.allocator.admission_budget()
        _log(f"{job.id}: degraded within slice {lease.slice_id}; "
             f"quarantined {labels}")

    def _poison_capacity(self, job: queue_mod.Job, detail: str) -> None:
        """No surviving slice can EVER admit this job (quarantines ate
        the capacity it was admitted against): quarantine it durably and
        loudly instead of letting it wait for a release that cannot
        help."""
        path = queue_mod.append_poison(
            self.state_dir, job, classification="capacity_lost",
            error=detail)
        self.queue.mark(job, "poisoned", error=f"capacity_lost: {detail}")
        with self._done_lock:
            self.jobs_done += 1
        obs_live.ring_event("serve.job", {"id": job.id, "event": "poisoned"})
        _log(f"{job.id}: poisoned (capacity_lost): {detail}; -> {path}")

    def _finish_if_terminal(self, job: queue_mod.Job,
                            outcome: _JobOutcome) -> None:
        """Record a terminal outcome produced outside _run_job (dispatch-
        time failures); a "retry" outcome already requeued the job."""
        if outcome.state == "retry":
            return
        self.queue.mark(job, outcome.state, error=outcome.error,
                        result=outcome.result)
        with self._done_lock:
            self.jobs_done += 1
        obs_live.ring_event("serve.job", {"id": job.id,
                                          "event": outcome.state})
        _log(f"{job.id}: {outcome.state}: {outcome.error}")

    # --- one job -------------------------------------------------------------

    def _run_job(self, job: queue_mod.Job, cfg: RunConfig | None = None,
                 lease: slices_mod.SliceLease | None = None) -> bool:
        """Run one job through the unchanged pipeline; False = drained
        mid-job (the job is requeued + the caller exits the loop).

        With ``lease`` (packed serving) the run executes inside its own
        job scope: chaos plans, telemetry registries, watchdog guards,
        contracts and the run's shutdown coordinator bind to this worker
        thread's store, and the mesh comes up over the lease's devices —
        so nothing the job arms or disarms can perturb the daemon plane
        or a neighbor tenant. Daemon bookkeeping (requeue/mark/ledger)
        runs OUTSIDE the scope so it lands in the daemon registries."""
        from ont_tcrconsensus_tpu.pipeline import run as run_mod

        obs_live.ring_event("serve.job", {"id": job.id, "event": "start"})
        _log(f"{job.id}: starting (waited {job.wait_s:.3f}s)")
        if cfg is None:
            cfg = RunConfig.from_dict(dict(job.raw))
        t_dispatch = time.monotonic()

        def first_stage_hook(name: str) -> None:
            job.first_stage_s = time.monotonic() - t_dispatch
            obs_live.set_node_start_hook(None)
            obs_metrics.observe("serve.first_stage_s", job.first_stage_s)

        outcome = _JobOutcome("done")
        try:
            try:
                if lease is not None:
                    from ont_tcrconsensus_tpu.parallel import mesh as mesh_mod

                    # everything from here to the inner finally runs in
                    # THIS job's scope; the daemon plane and the other
                    # residents never see it
                    jobscope.enter()
                    mesh_mod.install_slice_devices(lease.devices)
                    mesh_mod.install_degrade_hook(
                        lambda lost: self._on_slice_degrade(
                            job, lease, lost))
                obs_live.set_node_start_hook(first_stage_hook)
                self._inject_job_chaos(job, cfg)
                if lease is not None:
                    # slice-loss drill: the raise classifies as
                    # device_lost below, quarantining only THIS tenant's
                    # slice and requeuing only this job
                    faults.inject("serve.slice_lost")
                results = run_mod.run_with_config(cfg)
            finally:
                obs_live.set_node_start_hook(None)
                if lease is not None:
                    jobscope.exit()
            outcome.result = {
                "libraries": {
                    lib: sum(regions.values())
                    for lib, regions in sorted(results.items())
                },
            }
        except shutdown.Preempted as preempted:
            # not swallowed: the caller exits the serve loop with code 143
            # on False; finished stages are committed and the restarted
            # daemon resumes the rest through verified resume
            job.raw["resume"] = True
            self.queue.requeue_front(job)
            obs_live.ring_event(
                "serve.drain", {"id": job.id, "reason": str(preempted)})
            _log(f"{job.id}: drained mid-run ({preempted}); requeued with "
                 f"resume=true")
            return False
        except Exception as exc:
            outcome = self._failure_outcome(job, exc, lease=lease)
        finally:
            if lease is None:
                # serial mode: the job's run disarmed the global registry
                # on exit; re-arm a fresh daemon-scope one so between-job
                # /metrics scrapes stay live. A scoped (packed) run
                # disarmed only its OWN registry — re-arming here would
                # instead wipe the daemon's counters mid-flight.
                obs_metrics.arm()
            obs_metrics.gauge_set("serve.queue_depth", self.queue.depth())
        if outcome.state == "retry":
            # back in the queue with backoff — not terminal, not counted
            return True
        job_s = time.monotonic() - t_dispatch
        self.queue.mark(job, outcome.state, error=outcome.error,
                        result=outcome.result)
        with self._done_lock:
            self.jobs_done += 1
        obs_live.ring_event("serve.job", {
            "id": job.id, "event": outcome.state,
        })
        if outcome.state == "done":
            self._record_ledger(job, cfg, job_s)
            _log(f"{job.id}: done in {job_s:.3f}s "
                 f"(first stage after {job.first_stage_s:.3f}s)"
                 if job.first_stage_s is not None else
                 f"{job.id}: done in {job_s:.3f}s")
        else:
            _log(f"{job.id}: {outcome.state}: {outcome.error}")
        return True

    def _inject_job_chaos(self, job: queue_mod.Job, cfg: RunConfig) -> None:
        """Serve-plane chaos plants, free no-ops when disarmed.

        ``serve.job_run`` raises a seeded failure before dispatch (the
        retry/poison ladder's entry point); ``serve.job_slow`` stalls
        under a short-lived serve-scope watchdog armed only for the drill
        (``stage_timeout_s`` template knob), so cancel -> StageTimeout ->
        transient classification -> requeue is exercised end to end.
        """
        if not faults.active():
            return
        faults.inject("serve.job_run")
        if cfg.stage_timeout_s:
            wd = watchdog_mod.Watchdog(cfg.stage_timeout_s)
            wd.start()
            watchdog_mod.activate(wd)
            try:
                with wd.guard(f"serve:{job.id}"):
                    faults.inject("serve.job_slow")
            finally:
                watchdog_mod.deactivate(wd)
                wd.stop()
        else:
            faults.inject("serve.job_slow")

    def _failure_outcome(self, job: queue_mod.Job, exc: Exception,
                         lease: slices_mod.SliceLease | None = None,
                         ) -> _JobOutcome:
        """The retry/poison ladder. Transient failures requeue with
        seeded backoff up to ``retry_max_attempts``; anything fatal — or
        a transient that exhausts its attempts — is quarantined durably
        to ``serve_poison.json`` with a machine-readable reason, so one
        bad tenant job can never wedge the loop.

        Packed serving adds a rung: a ``device_lost`` that ESCAPED a
        leased run (the mesh could not degrade within the slice) means
        the slice is gone but the job is fine — the slice's devices are
        quarantined, admission shrinks to the surviving pool, and the job
        requeues for a fresh slice with ``resume=true`` (its committed
        stages carry over)."""
        job.attempts += 1
        cls = retry_mod.classify(exc)
        if (lease is not None and cls == "device_lost"
                and job.attempts < self.retry_policy.max_attempts):
            labels = self.allocator.quarantine(job.id)
            self.queue.budget = self.allocator.admission_budget()
            delay = self.retry_policy.delay(job.attempts)
            retry_mod.recorder().record(
                "serve.slice_lost", classification=cls,
                outcome="slice_quarantined", attempt=job.attempts,
                error=repr(exc), detail={"devices": labels})
            job.raw["resume"] = True
            self.queue.requeue_back(job, delay_s=delay)
            obs_live.ring_event("serve.job", {
                "id": job.id, "event": "retry", "attempt": job.attempts})
            _log(f"{job.id}: lost slice {lease.slice_id} "
                 f"({len(labels)} device(s) quarantined): {exc!r}; "
                 f"requeued for a fresh slice")
            return _JobOutcome("retry")
        if (cls == "transient"
                and job.attempts < self.retry_policy.max_attempts):
            delay = self.retry_policy.delay(job.attempts)
            retry_mod.recorder().record(
                "serve.job_run", classification=cls, outcome="retry",
                attempt=job.attempts, error=repr(exc))
            self.queue.requeue_back(job, delay_s=delay)
            obs_live.ring_event("serve.job", {
                "id": job.id, "event": "retry", "attempt": job.attempts})
            _log(f"{job.id}: transient failure (attempt {job.attempts}/"
                 f"{self.retry_policy.max_attempts}): {exc!r}; requeued "
                 f"with {delay:.2f}s backoff")
            return _JobOutcome("retry")
        reason = "retry_exhausted" if cls == "transient" else cls
        retry_mod.recorder().record(
            "serve.job_run", classification=cls, outcome="poisoned",
            attempt=job.attempts, error=repr(exc))
        path = queue_mod.append_poison(
            self.state_dir, job, classification=reason, error=repr(exc))
        _log(f"{job.id}: poisoned ({reason}) after {job.attempts} "
             f"attempt(s): {exc!r}; quarantined to {path}")
        return _JobOutcome("poisoned", error=f"{reason}: {exc!r}")

    def _record_ledger(self, job: queue_mod.Job, cfg: RunConfig,
                       job_s: float) -> None:
        """Append the ``source: "serve"`` entry: the dispatch-to-first-
        stage latency and warm/steady split, next to the run's own entry
        (same never-fail contract as every telemetry path)."""
        try:
            entry = obs_history.build_entry(
                "serve",
                fingerprint=obs_history.config_fingerprint(cfg),
                sha=obs_history.git_sha(),
                backend=obs_history.detect_backend(),
                warmup_s=self.warmup_s if self.jobs_done == 1 else None,
                steady_s=job_s,
                extra={
                    "job_id": job.id,
                    "wait_s": round(job.wait_s or 0.0, 3),
                    "dispatch_first_stage_s": (
                        round(job.first_stage_s, 3)
                        if job.first_stage_s is not None else None),
                },
            )
            nano_dir = os.path.join(cfg.fastq_pass_dir, "nano_tcr")
            obs_history.append_entry(
                os.path.join(nano_dir, obs_history.HISTORY_BASENAME), entry)
            if cfg.history_ledger:
                obs_history.append_entry(cfg.history_ledger, entry)
        except Exception as exc:
            _log(f"WARNING: could not append serve ledger entry: {exc!r}")


def serve_main(argv: list[str] | None = None) -> int:
    """``tcr-consensus-tpu serve <template.json>`` entry point."""
    parser = argparse.ArgumentParser(
        prog="tcr-consensus-tpu serve",
        description="Warm-serving daemon: accepts pipeline jobs over a "
                    "loopback-only HTTP control plane (POST /jobs) and "
                    "runs them through one long-lived, prewarmed process.",
    )
    parser.add_argument("template", help="template run-config JSON every "
                                         "job's overrides merge onto")
    parser.add_argument("--port", type=int, default=8765,
                        help="loopback control-plane port (0 = ephemeral; "
                             "resolved port lands in serve_info.json)")
    parser.add_argument("--state-dir", default=None,
                        help="daemon state dir (serve_info.json + drain "
                             "journal); default: serve_state/ next to the "
                             "template")
    parser.add_argument("--queue-max", type=int, default=None,
                        help="override the template's serve_queue_max")
    parser.add_argument("--workers", type=int, default=None,
                        help="override the template's serve_workers "
                             "(>1 = slice-packed runner pool: concurrent "
                             "tenants on disjoint device slices)")
    parser.add_argument("--no-prewarm", action="store_true",
                        help="skip the AOT bucket prewarm (first job "
                             "compiles lazily)")
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (simulation)")
    args = parser.parse_args(argv)
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    with open(args.template) as fh:
        template = json.load(fh)
    state_dir = args.state_dir or os.path.join(
        os.path.dirname(os.path.abspath(args.template)), "serve_state")
    daemon = Daemon(
        template, port=args.port, state_dir=state_dir,
        queue_max=args.queue_max,
        do_prewarm=False if args.no_prewarm else None,
        workers=args.workers,
    )
    return daemon.serve_forever()
