"""Warm-serving daemon: the one-shot CLI turned into an always-on service.

ROADMAP item 3's blocking number is r5's 165.8s of XLA warm-up per process
against a 46.8s timed run: a production service cannot pay compile time
per library. The fix is a LONG-LIVED process that arms jax, the persistent
compilation cache and the live observability plane once, then runs every
submitted job through :func:`~..pipeline.run.run_with_config` in-process —
the module-level ``jax.jit`` entry points (fused assign, targeted assign,
consensus, polisher) keep their compiled executables across jobs, so the
second tenant's traffic triggers ZERO backend compiles (the PR 6
``backend_compile`` listener in each job's own telemetry.json is the
regression sentinel).

Three pieces:

- :mod:`.queue`  — bounded FIFO tenant job queue with admission control
  from the HBM budgeter (:mod:`~..parallel.budget`): a job whose estimated
  device footprint cannot fit the configured budget is rejected with a
  named reason at submit time, not OOM-killed mid-run. Queue depth /
  wait-time land in the metrics registry, so the live plane's ``/metrics``
  exposes them.
- :mod:`.prewarm` — AOT prewarm of the fixed production shape buckets:
  lower+compile the fused-assign and polisher entry points for the
  declared bucket set at daemon start, on top of the persistent
  ``compile_cache_dir`` — a restarted daemon reads executables back from
  disk instead of recompiling.
- :mod:`.daemon` — the long-lived loop plus the loopback-only HTTP
  control plane riding the PR 13 live server (POST ``/jobs``, GET
  ``/jobs`` and ``/jobs/<id>``; same 127.0.0.1 posture). SIGTERM drains:
  the in-flight job stops at the next stage boundary through the existing
  :mod:`~..robustness.shutdown` machinery, the remaining queue is
  journaled, and a restarted daemon resumes the journal through verified
  resume.
"""
