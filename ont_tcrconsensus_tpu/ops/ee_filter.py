"""Expected-error read filtering on device.

TPU-native replacement for ``vsearch --fastq_filter --fastq_maxee_rate R
--fastq_minlen L`` (/root/reference/ont_tcr_consensus/preprocessing.py:129-148):
a read passes iff

    sum_i 10^(-Q_i/10) / len(read) <= max_ee_rate   and   len(read) >= min_len.

The reference pins this to a single CPU per library; here it is one fused
reduction over a padded ``(B, L)`` quality batch — bandwidth-bound, vmapped
over the batch, shardable over a mesh data axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def expected_errors(quals: jax.Array, lengths: jax.Array) -> jax.Array:
    """Per-read expected error count from a padded Phred batch.

    Args:
      quals: (B, L) uint8/int32 Phred scores (padding must be high-Q; it is
        masked out regardless).
      lengths: (B,) true read lengths.

    Returns:
      (B,) float32 expected errors.
    """
    q = quals.astype(jnp.float32)
    pos = jnp.arange(q.shape[1], dtype=jnp.int32)[None, :]
    in_read = pos < lengths[:, None]
    perr = jnp.power(10.0, -q / 10.0)
    return jnp.sum(jnp.where(in_read, perr, 0.0), axis=1)


@jax.jit
def ee_rate_mask(
    quals: jax.Array,
    lengths: jax.Array,
    max_ee_rate: jax.Array | float,
    min_len: jax.Array | int,
) -> jax.Array:
    """Boolean keep-mask implementing the reference's quality+length filter."""
    ee = expected_errors(quals, lengths)
    lens = jnp.maximum(lengths, 1).astype(jnp.float32)
    return (ee / lens <= max_ee_rate) & (lengths >= min_len)


@jax.jit
def ee_rate_mask_span(
    quals: jax.Array,
    t_start: jax.Array,
    t_end: jax.Array,
    max_ee_rate: jax.Array | float,
    min_len: jax.Array | int,
) -> jax.Array:
    """:func:`ee_rate_mask` over the [t_start, t_end) span of each read.

    Lets the fused pass filter on post-trim quality without materializing
    shifted quality arrays (the trim is virtual: reads stay unshifted on
    device, only the span bounds move).
    """
    q = quals.astype(jnp.float32)
    pos = jnp.arange(q.shape[1], dtype=jnp.int32)[None, :]
    in_span = (pos >= t_start[:, None]) & (pos < t_end[:, None])
    ee = jnp.sum(jnp.where(in_span, jnp.power(10.0, -q / 10.0), 0.0), axis=1)
    lens = t_end - t_start
    return (ee / jnp.maximum(lens, 1).astype(jnp.float32) <= max_ee_rate) & (
        lens >= min_len
    )
