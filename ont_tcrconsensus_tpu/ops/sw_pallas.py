"""Pallas TPU kernel for the banded affine SW forward pass.

Same semantics as :func:`.sw_align.align_banded` (verified against the same
numpy oracle), but the whole row recurrence runs inside one kernel with the
DP state resident in VMEM — the XLA scan version writes its ~10 KB/pair
carry to HBM every row, which caps it at ~0.2 Gcell/s; keeping the carry
on-chip removes that traffic entirely.

Layout tricks:
- the reference is pre-shifted on the host into ``ref_shifted[b, k] =
  ref[k + off_b - W/2]`` so every row's band window is ONE contiguous
  ``pl.ds(i, W)`` slice shared by the whole pair-block — no per-pair
  gathers inside the kernel;
- the F (ref-gap) cascade is the shift-doubling max-plus form
  (sw_align._f_cascade) — elementwise selects and static lane shifts only;
- the best cell is tracked per (pair, band-slot) with its row index, and
  the cross-lane argmax + tie-break (earliest row, then smallest slot,
  matching the sequential kernel) happens once, outside the kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ont_tcrconsensus_tpu.ops.sw_align import (
    GAP_EXT,
    GAP_OPEN,
    MATCH,
    MISMATCH,
    PAD_SENTINEL,
    AlignResult,
)

_NEG = -(1 << 24)  # python int: jnp constants get captured by pallas_call
BLK = 16  # pairs per program


def align_banded_auto(*args, **kwargs) -> AlignResult:
    """Pallas on an accelerator backend, the XLA scan kernel on CPU.

    Both kernels are cell-exact equals (asserted by tests on both paths),
    so the dispatch is purely a performance choice.
    """
    import ont_tcrconsensus_tpu.ops.sw_align as sw_align

    if jax.default_backend() == "cpu":
        return sw_align.align_banded(*args, **kwargs)
    return align_banded_pallas(*args, **kwargs)


def _kernel(read_ref, refsh_ref, rlen_ref, tlen_ref, off_ref,
            bestH_ref, bestRow_ref, bm_ref, bc_ref, brs_ref, bfs_ref,
            *, L, W, match, mismatch, gap_open, gap_ext):
    c = W // 2
    iota = jax.lax.broadcasted_iota(jnp.int32, (BLK, W), 1)
    rlen = rlen_ref[:]          # (BLK, 1)
    tlen = tlen_ref[:]
    off = off_ref[:]
    neg = jnp.full((BLK, W), _NEG, jnp.int32)
    zero = jnp.zeros((BLK, W), jnp.int32)

    lane128 = jax.lax.broadcasted_iota(jnp.int32, (BLK, 128), 1)

    def shift_up(x, fill):
        return jnp.concatenate([x[:, 1:], jnp.full((BLK, 1), fill, x.dtype)], axis=1)

    def shift_right(x, step, fill):
        return jnp.concatenate([jnp.full((BLK, step), fill, x.dtype), x[:, :-step]], axis=1)

    def elem_at(ref, k):
        """ref[:, k] as (BLK, 1) — Mosaic needs lane offsets that are
        multiples of 128, so load the aligned 128-chunk and lane-select."""
        base = pl.multiple_of((k // 128) * 128, 128)
        chunk = ref[:, pl.ds(base, 128)].astype(jnp.int32)
        sel = lane128 == (k % 128)
        return jnp.sum(jnp.where(sel, chunk, 0), axis=1, keepdims=True)

    def body(i, carry):
        (H, Hm, Hc, Hrs, Hfs, E, Em, Ec, Ers, Efs,
         bH, bRow, bm, bc, brs, bfs, window) = carry
        jrow = i + off - c + iota                      # (BLK, W)
        valid = (jrow >= 0) & (jrow < tlen) & (i < rlen)
        rbase = elem_at(read_ref, i)                   # (BLK, 1)
        tbase = window                                 # (BLK, W)
        is_match = (tbase == rbase) & (rbase < 4) & (tbase < 4)
        sub = jnp.where(is_match, match, -mismatch)
        # advance the band window one ref position for the next row
        window = jnp.concatenate([window[:, 1:], elem_at(refsh_ref, i + W)], axis=1)

        # E: read-consuming gap from (i-1, j) = prev row, slot b+1
        H_up = shift_up(H, _NEG)
        E_up = shift_up(E, _NEG)
        open_sc = H_up - gap_open - gap_ext
        ext_sc = E_up - gap_ext
        t_open = open_sc >= ext_sc
        E_new = jnp.where(t_open, open_sc, ext_sc)
        Em_n = jnp.where(t_open, shift_up(Hm, 0), shift_up(Em, 0))
        Ec_n = jnp.where(t_open, shift_up(Hc, 0), shift_up(Ec, 0)) + 1
        Ers_n = jnp.where(t_open, shift_up(Hrs, 0), shift_up(Ers, 0))
        Efs_n = jnp.where(t_open, shift_up(Hfs, 0), shift_up(Efs, 0))

        # diagonal (with fresh-at-predecessor 0-clamp)
        t_fresh = 0 > H
        D = jnp.where(t_fresh, 0, H) + sub
        Dm = jnp.where(t_fresh, zero, Hm) + is_match.astype(jnp.int32)
        Dc = jnp.where(t_fresh, zero, Hc) + 1
        Drs = jnp.where(t_fresh, jnp.broadcast_to(jnp.full((BLK, 1), i, jnp.int32), (BLK, W)), Hrs)
        Dfs = jnp.where(t_fresh, jrow, Hfs)

        # tmp = max(D, E, fresh) with priority D >= E >= fresh
        tmp, tm, tc, trs, tfs = D, Dm, Dc, Drs, Dfs
        e_b = E_new > tmp
        tmp = jnp.where(e_b, E_new, tmp)
        tm = jnp.where(e_b, Em_n, tm)
        tc = jnp.where(e_b, Ec_n, tc)
        trs = jnp.where(e_b, Ers_n, trs)
        tfs = jnp.where(e_b, Efs_n, tfs)
        f_b = 0 > tmp
        tmp = jnp.where(f_b, 0, tmp)
        tm = jnp.where(f_b, zero, tm)
        tc = jnp.where(f_b, zero, tc)
        trs = jnp.where(f_b, jnp.broadcast_to(jnp.full((BLK, 1), i + 1, jnp.int32), (BLK, W)), trs)
        tfs = jnp.where(f_b, jrow + 1, tfs)
        tmp = jnp.where(valid, tmp, neg)

        # F cascade: shift-doubling with channel/gap tracking
        g, gm, gc, grs, gfs, gap = tmp, tm, tc, trs, tfs, zero
        step = 1
        while step < W:
            cg = shift_right(g, step, _NEG) - gap_ext * step
            take = cg > g
            g = jnp.where(take, cg, g)
            gm = jnp.where(take, shift_right(gm, step, 0), gm)
            gc = jnp.where(take, shift_right(gc, step, 0), gc)
            grs = jnp.where(take, shift_right(grs, step, 0), grs)
            gfs = jnp.where(take, shift_right(gfs, step, 0), gfs)
            gap = jnp.where(take, shift_right(gap, step, 0) + step, gap)
            step *= 2
        F = shift_right(g, 1, _NEG) - gap_open - gap_ext
        Fgap = shift_right(gap, 1, 0) + 1
        Fm = shift_right(gm, 1, 0)
        Fc = shift_right(gc, 1, 0) + Fgap
        Frs = shift_right(grs, 1, 0)
        Ffs = shift_right(gfs, 1, 0)

        t_f = F > tmp
        H_new = jnp.where(valid, jnp.where(t_f, F, tmp), neg)
        Hm_n = jnp.where(t_f, Fm, tm)
        Hc_n = jnp.where(t_f, Fc, tc)
        Hrs_n = jnp.where(t_f, Frs, trs)
        Hfs_n = jnp.where(t_f, Ffs, tfs)

        # per-slot best (strict improvement keeps the earliest row)
        imp = H_new > bH
        bH = jnp.where(imp, H_new, bH)
        bRow = jnp.where(imp, jnp.broadcast_to(jnp.full((BLK, 1), i, jnp.int32), (BLK, W)), bRow)
        bm = jnp.where(imp, Hm_n, bm)
        bc = jnp.where(imp, Hc_n, bc)
        brs = jnp.where(imp, Hrs_n, brs)
        bfs = jnp.where(imp, Hfs_n, bfs)

        E_new = jnp.where(valid, E_new, neg)
        return (H_new, Hm_n, Hc_n, Hrs_n, Hfs_n,
                E_new, Em_n, Ec_n, Ers_n, Efs_n,
                bH, bRow, bm, bc, brs, bfs, window)

    window0 = refsh_ref[:, 0:W].astype(jnp.int32)
    init = (neg, zero, zero, zero, zero,
            neg, zero, zero, zero, zero,
            jnp.zeros((BLK, W), jnp.int32), jnp.full((BLK, W), -1, jnp.int32),
            zero, zero, zero, zero, window0)
    out = jax.lax.fori_loop(0, L, body, init)
    bestH_ref[:] = out[10]
    bestRow_ref[:] = out[11]
    bm_ref[:] = out[12]
    bc_ref[:] = out[13]
    brs_ref[:] = out[14]
    bfs_ref[:] = out[15]


@functools.partial(
    jax.jit,
    static_argnames=("band_width", "match", "mismatch", "gap_open", "gap_ext", "interpret"),
)
def align_banded_pallas(
    reads: jax.Array,
    read_lens: jax.Array,
    refs: jax.Array,
    ref_lens: jax.Array,
    diag_offsets: jax.Array,
    band_width: int = 256,
    match: int = MATCH,
    mismatch: int = MISMATCH,
    gap_open: int = GAP_OPEN,
    gap_ext: int = GAP_EXT,
    interpret: bool = False,
) -> AlignResult:
    """Drop-in Pallas replacement for ``sw_align.align_banded``.

    The batch is padded up to a multiple of BLK pairs; ``interpret=True``
    runs the kernel in the Pallas interpreter (CPU tests).
    """
    B0, L = reads.shape
    W = band_width
    c = W // 2
    B = ((B0 + BLK - 1) // BLK) * BLK

    def pad_to(x, n, fill):
        if x.shape[0] == n:
            return x
        pad_shape = (n - x.shape[0],) + x.shape[1:]
        return jnp.concatenate([x, jnp.full(pad_shape, fill, x.dtype)])

    reads_p = pad_to(jnp.asarray(reads), B, PAD_SENTINEL)
    refs_p = pad_to(jnp.asarray(refs), B, PAD_SENTINEL)
    rlens = pad_to(jnp.asarray(read_lens, jnp.int32), B, 0)[:, None]
    tlens = pad_to(jnp.asarray(ref_lens, jnp.int32), B, 0)[:, None]
    offs = pad_to(jnp.asarray(diag_offsets, jnp.int32), B, 0)[:, None]

    # host-side pre-shift: ref_shifted[b, k] = ref[b, k + off_b - c]. K is
    # padded to a multiple of 128 (same fix as pileup_pallas): elem_at's
    # aligned chunk loads must never start past K - 128, which a ragged
    # tail would cause for non-multiple-of-128 L + W.
    K = ((L + W + 127) // 128) * 128
    ks = jnp.arange(K, dtype=jnp.int32)[None, :] + offs - c  # (B, K)
    in_range = (ks >= 0) & (ks < refs_p.shape[1])
    ref_shifted = jnp.where(
        in_range,
        jnp.take_along_axis(refs_p, jnp.clip(ks, 0, refs_p.shape[1] - 1), axis=1),
        jnp.uint8(PAD_SENTINEL),
    )

    kernel = functools.partial(
        _kernel, L=L, W=W, match=match, mismatch=mismatch,
        gap_open=gap_open, gap_ext=gap_ext,
    )
    grid = (B // BLK,)
    row_spec = lambda shape_cols: pl.BlockSpec(
        (BLK, shape_cols), lambda g: (g, 0), memory_space=pltpu.VMEM
    )
    out_shapes = [jax.ShapeDtypeStruct((B, W), jnp.int32)] * 6
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            row_spec(L),      # reads
            row_spec(K),      # ref_shifted
            row_spec(1),      # read lens
            row_spec(1),      # ref lens
            row_spec(1),      # offsets
        ],
        out_specs=[row_spec(W)] * 6,
        out_shape=out_shapes,
        interpret=interpret,
    )(reads_p, ref_shifted, rlens, tlens, offs)
    bestH, bestRow, bm, bc, brs, bfs = outs

    # final cross-slot selection with the sequential tie-break:
    # max score, then earliest row, then smallest slot
    score = jnp.max(bestH, axis=1)
    is_max = bestH == score[:, None]
    row_or_inf = jnp.where(is_max, bestRow, jnp.int32(1 << 30))
    best_row = jnp.min(row_or_inf, axis=1)
    cand = is_max & (bestRow == best_row[:, None])
    slot = jnp.argmax(cand, axis=1)  # first matching slot

    def take(x):
        return jnp.take_along_axis(x, slot[:, None], axis=1)[:, 0]

    offs0 = offs[:, 0]
    jrow_best = best_row + offs0 - c + slot.astype(jnp.int32)
    aligned = score > 0
    res = AlignResult(
        score=score[:B0],
        read_start=jnp.where(aligned, take(brs), 0)[:B0],
        read_end=jnp.where(aligned, best_row + 1, 0)[:B0],
        ref_start=jnp.where(aligned, take(bfs), 0)[:B0],
        ref_end=jnp.where(aligned, jrow_best + 1, 0)[:B0],
        n_match=jnp.where(aligned, take(bm), 0)[:B0],
        n_cols=jnp.where(aligned, take(bc), 0)[:B0],
    )
    return res
