"""Base encoding for TPU kernels.

Two representations:

1. **Dense codes** (uint8): A=0, C=1, G=2, T=3, N/unknown=4, PAD=5.
   Used for reads/references on the device; PAD never matches anything,
   N matches nothing under exact comparison (kernels that need IUPAC
   semantics convert codes to masks with :func:`codes_to_masks`).

2. **IUPAC 4-bit masks** (uint8): A=1, C=2, G=4, T=8, degenerate codes are
   ORs (e.g. V = A|C|G = 7, B = C|G|T = 14, N = 15), PAD=0.
   Two masked bases "match" iff ``mask_a & mask_b != 0``. This reproduces the
   60-pair IUPAC equality table the reference feeds edlib
   (/root/reference/ont_tcr_consensus/extract_umis.py:26-87) as a single AND.

All encoders are host-side numpy (they feed padded batches to the device);
mask comparison happens inside jitted kernels.
"""

from __future__ import annotations

import numpy as np

A, C, G, T, N_CODE, PAD_CODE = 0, 1, 2, 3, 4, 5

_IUPAC_MASK = {
    "A": 1, "C": 2, "G": 4, "T": 8, "U": 8,
    "R": 1 | 4, "Y": 2 | 8, "S": 2 | 4, "W": 1 | 8, "K": 4 | 8, "M": 1 | 2,
    "B": 2 | 4 | 8, "D": 1 | 4 | 8, "H": 1 | 2 | 8, "V": 1 | 2 | 4,
    "N": 15,
}

_CODE_LUT = np.full(256, N_CODE, dtype=np.uint8)
for _b, _c in (("A", A), ("C", C), ("G", G), ("T", T), ("U", T)):
    _CODE_LUT[ord(_b)] = _c
    _CODE_LUT[ord(_b.lower())] = _c

_MASK_LUT = np.zeros(256, dtype=np.uint8)
for _b, _m in _IUPAC_MASK.items():
    _MASK_LUT[ord(_b)] = _m
    _MASK_LUT[ord(_b.lower())] = _m

# dense code -> 4-bit mask (PAD -> 0 so padding never matches)
CODE_TO_MASK = np.array([1, 2, 4, 8, 15, 0], dtype=np.uint8)

# dense code -> complement code (A<->T, C<->G); N and PAD map to themselves
COMPLEMENT = np.array([T, G, C, A, N_CODE, PAD_CODE], dtype=np.uint8)

_DECODE = np.array(list("ACGTN-"), dtype="U1")
_DECODE_ASCII = np.frombuffer(b"ACGTN-", dtype=np.uint8)


def encode_seq(seq: str) -> np.ndarray:
    """String -> dense uint8 codes."""
    return _CODE_LUT[np.frombuffer(seq.encode("ascii"), dtype=np.uint8)]


def encode_mask(seq: str) -> np.ndarray:
    """String (may contain IUPAC degenerate bases) -> 4-bit masks."""
    return _MASK_LUT[np.frombuffer(seq.encode("ascii"), dtype=np.uint8)]


def decode_seq(codes: np.ndarray, length: int | None = None) -> str:
    """Dense codes -> string (PAD rendered as '-' then stripped via length)."""
    if length is not None:
        codes = codes[:length]
    return "".join(_DECODE[np.asarray(codes, dtype=np.int64)])


def decode_batch(codes: np.ndarray, lengths: np.ndarray) -> list[str]:
    """(B, W) dense codes + (B,) lengths -> list of strings.

    One vectorized LUT pass + per-row ``tobytes().decode`` — ~50x faster than
    per-character joins, which matters on the artifact-write path.
    """
    ascii_rows = _DECODE_ASCII[np.ascontiguousarray(codes)]
    lens = np.asarray(lengths)
    return [
        ascii_rows[i, : lens[i]].tobytes().decode("ascii")
        for i in range(ascii_rows.shape[0])
    ]


def decode_phred_batch(quals: np.ndarray, lengths: np.ndarray) -> list[str]:
    """(B, W) uint8 Phred batch + lengths -> Phred-33 quality strings."""
    q = np.ascontiguousarray(np.asarray(quals, dtype=np.uint8) + 33)
    lens = np.asarray(lengths)
    return [q[i, : lens[i]].tobytes().decode("ascii") for i in range(q.shape[0])]


def revcomp_codes(codes: np.ndarray, length: int | None = None) -> np.ndarray:
    """Reverse-complement of a dense-code array (host side).

    With ``length`` given, only the first ``length`` entries are the sequence;
    the result keeps padding at the tail.
    """
    if length is None:
        return COMPLEMENT[codes[::-1]]
    out = np.full_like(codes, PAD_CODE)
    out[:length] = COMPLEMENT[codes[:length][::-1]]
    return out


def revcomp_str(seq: str) -> str:
    return decode_seq(revcomp_codes(encode_seq(seq)))


def pad_batch(
    seqs: list[np.ndarray],
    pad_to: int | None = None,
    pad_value: int = PAD_CODE,
    multiple: int = 128,
) -> tuple[np.ndarray, np.ndarray]:
    """Stack variable-length code arrays into a padded (B, L) batch + lengths.

    L is rounded up to ``multiple`` (TPU lane width) for layout friendliness.
    Raises if a sequence exceeds the padded width — callers bucket by length
    and must pick a sufficient ``pad_to``.
    """
    lengths = np.array([len(s) for s in seqs], dtype=np.int32)
    max_len = int(pad_to if pad_to is not None else (lengths.max() if len(seqs) else 0))
    if multiple > 1:
        max_len = ((max_len + multiple - 1) // multiple) * multiple
    max_len = max(max_len, multiple)
    if len(seqs) and lengths.max() > max_len:
        raise ValueError(
            f"sequence of length {int(lengths.max())} exceeds padded width {max_len}"
        )
    out = np.full((len(seqs), max_len), pad_value, dtype=np.uint8)
    for i, s in enumerate(seqs):
        out[i, : len(s)] = s
    return out, lengths


def encode_batch(
    seqs: list[str], pad_to: int | None = None, multiple: int = 128
) -> tuple[np.ndarray, np.ndarray]:
    """List of strings -> (padded dense-code batch, lengths)."""
    return pad_batch([encode_seq(s) for s in seqs], pad_to=pad_to, multiple=multiple)


def encode_mask_batch(
    seqs: list[str], pad_to: int | None = None, multiple: int = 128
) -> tuple[np.ndarray, np.ndarray]:
    """List of (possibly degenerate) strings -> (padded mask batch, lengths)."""
    return pad_batch(
        [encode_mask(s) for s in seqs], pad_to=pad_to, pad_value=0, multiple=multiple
    )


def phred_batch(quals: list[str], pad_to: int | None = None, multiple: int = 128):
    """List of Phred-33 quality strings -> (padded uint8 Q batch, lengths).

    Padding gets Q=93 (error prob ~5e-10) so padded tails contribute nothing
    to expected-error sums.
    """
    arrs = []
    for q in quals:
        raw = np.frombuffer(q.encode("ascii"), dtype=np.uint8)
        if raw.size and raw.min() < 33:
            raise ValueError("quality string contains characters below Phred-33 '!'")
        arrs.append(raw - 33)
    return pad_batch(arrs, pad_to=pad_to, pad_value=93, multiple=multiple)
