"""Device kernels (jnp/Pallas) replacing the reference pipeline's native tools.

Mapping to the reference (/root/reference):
- ``ee_filter``     vsearch --fastq_filter          (preprocessing.py:129-148)
- ``fuzzy_match``   edlib.align(mode="HW", IUPAC)   (extract_umis.py:89-96)
- ``edit_distance`` vsearch pairwise identity       (vsearch_umi_cluster.py:21-54)
- ``sketch``        minimap2 seeding                (minimap2_align.py:90-132)
- ``align``         minimap2 base-level alignment   (minimap2_align.py:13-18, 90-138)
- ``consensus``     spoa draft + pileup             (medaka smolecule --method spoa)
"""

from ont_tcrconsensus_tpu.ops import encode  # noqa: F401
