"""Banded alignment with traceback -> per-column pileups on device.

The consensus stage needs *per-draft-position* alignment columns (which base
of each subread sits over draft position j, what is inserted after j), i.e.
what the reference gets from medaka's spoa POA graph + subread re-alignment
(/root/reference/ont_tcr_consensus/medaka_polish.py:113-134). The stats-only
kernel (:mod:`.sw_align`) cannot provide that, so this kernel stores per-cell
direction planes in the band during the forward scan and walks them back with
a ``lax.while_loop`` (vmapped over subreads; SURVEY §7 "hard parts" #3/#6).

Per-cell planes (band-shaped, (rows, W)):
- ``tdir`` uint8: bits 0-1 = tmp choice (0 diag, 1 read-gap/E, 3 fresh/stop);
  bit 2 = diag predecessor was a fresh start (emit, then stop);
  bit 3 = the E value here OPENED from H (vs extended from the E above).
- ``fjump`` uint8: 0 if H == tmp at this cell, else the ref-gap run length m
  (H chose F; predecessor is tmp at band slot b - m in the same row).

Traceback emits, per subread: ``base_at[j]`` (0-3 base, 4 deletion,
5 uncovered), ``ins_cnt[j]``/``ins_base[j]`` (insertion run length after
draft position j and its first base). These feed :mod:`.consensus`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ont_tcrconsensus_tpu.parallel.mesh import mesh_data_size
from ont_tcrconsensus_tpu.ops.sw_align import (
    GAP_EXT,
    GAP_OPEN,
    MATCH,
    MISMATCH,
    NEG,
    PAD_SENTINEL,
    _shift_right,
    _shift_up,
)

UNCOVERED = 5
DELETION = 4

_DIAG, _EGAP, _FRESH = 0, 1, 3
_DIAG_STOP_BIT = 0b100
_EOPEN_BIT = 0b1000


def _forward_banded(read, read_len, ref, ref_len, diag_offset, band_width, scoring):
    """Banded local DP; returns (best=(score, i, b), tdir, fjump) planes."""
    match, mismatch, gap_open, gap_ext = scoring
    W = band_width
    c = W // 2
    L = read.shape[0]
    iota = jnp.arange(W, dtype=jnp.int32)
    read_len = read_len.astype(jnp.int32)
    ref_len = ref_len.astype(jnp.int32)
    off = diag_offset.astype(jnp.int32)

    shift_up = _shift_up
    # pre-shifted ref: row i's window = ref_shifted[i : i+W], slice start
    # shared across vmapped lanes -> contiguous slice instead of a per-row
    # batched gather (see sw_align._align_one)
    K = L + W
    ks = jnp.arange(K, dtype=jnp.int32) + off - c
    in_range = (ks >= 0) & (ks < ref.shape[0])
    ref_shifted = jnp.where(
        in_range, ref[jnp.clip(ks, 0, ref.shape[0] - 1)],
        jnp.asarray(PAD_SENTINEL, ref.dtype),
    )

    def row_step(carry, i):
        H, E, best = carry
        jrow = i + off - c + iota
        valid = (jrow >= 0) & (jrow < ref_len) & (i < read_len)
        rbase = read[jnp.clip(i, 0, L - 1)]
        tbase = jax.lax.dynamic_slice(ref_shifted, (i,), (W,))
        is_match = (tbase == rbase) & (rbase < 4) & (tbase < 4)
        sub = jnp.where(is_match, match, -mismatch).astype(jnp.int32)

        H_up = shift_up(H, NEG)
        E_up = shift_up(E, NEG)
        open_sc = H_up - gap_open - gap_ext
        ext_sc = E_up - gap_ext
        e_open = open_sc >= ext_sc
        E_new = jnp.where(e_open, open_sc, ext_sc)

        fresh_pred = 0 > H
        D = jnp.where(fresh_pred, 0, H) + sub

        tmp = D
        tdir = jnp.where(fresh_pred, jnp.uint8(_DIAG | _DIAG_STOP_BIT), jnp.uint8(_DIAG))
        e_better = E_new > tmp
        tmp = jnp.where(e_better, E_new, tmp)
        tdir = jnp.where(e_better, jnp.uint8(_EGAP), tdir)
        fresh_better = 0 > tmp
        tmp = jnp.where(fresh_better, 0, tmp)
        tdir = jnp.where(fresh_better, jnp.uint8(_FRESH), tdir)
        tmp = jnp.where(valid, tmp, NEG)
        tdir = tdir | jnp.where(e_open, jnp.uint8(_EOPEN_BIT), jnp.uint8(0))

        # F via shift-doubling (see sw_align._f_cascade); the gap length is
        # tracked alongside so the traceback jump needs no argmax/gather
        g = tmp
        gap = jnp.zeros_like(tmp)
        step = 1
        while step < W:
            cand_g = _shift_right(g, step, NEG) - gap_ext * step
            cand_gap = _shift_right(gap, step, 0) + step
            take = cand_g > g
            g = jnp.where(take, cand_g, g)
            gap = jnp.where(take, cand_gap, gap)
            step *= 2
        F = _shift_right(g, 1, NEG) - gap_open - gap_ext
        jump = (_shift_right(gap, 1, 0) + 1).astype(jnp.uint8)

        take_f = F > tmp
        H_new = jnp.where(valid, jnp.where(take_f, F, tmp), NEG)
        fjump = jnp.where(take_f, jump, jnp.uint8(0))

        b_star = jnp.argmax(H_new).astype(jnp.int32)
        row_best = H_new[b_star]
        improve = row_best > best[0]
        best = jnp.where(improve, jnp.stack([row_best, i, b_star]), best)
        E_new = jnp.where(valid, E_new, NEG)
        return (H_new, E_new, best), (tdir, fjump)

    H0 = jnp.full((W,), NEG, jnp.int32)
    best0 = jnp.array([0, -1, 0], jnp.int32)
    (_, _, best), (tdir, fjump) = jax.lax.scan(
        row_step, (H0, H0, best0), jnp.arange(L, dtype=jnp.int32)
    )
    return best, tdir, fjump


def _traceback_one(best, tdir, fjump, read, diag_offset, band_width, out_len):
    """Walk the direction planes from the best cell, emitting pileup columns.

    Kernel cell (row i, slot b) has consumed read[0..i] / ref[0..jrow], so a
    diag emits read[i] over draft position jrow, an E-step emits read[i]
    inserted after draft position jrow, and an F-run of length m deletes
    draft positions jrow-m+1..jrow.
    """
    W = band_width
    c = W // 2
    off = diag_offset.astype(jnp.int32)
    L = read.shape[0]

    base_at0 = jnp.full((out_len,), UNCOVERED, jnp.uint8)
    ins_cnt0 = jnp.zeros((out_len,), jnp.int32)
    ins_base0 = jnp.zeros((out_len,), jnp.uint8)
    pos_at0 = jnp.full((out_len,), -1, jnp.int32)

    score, i0, b0 = best[0], best[1], best[2]
    jend = i0 + off - c + b0
    # H mode honours an F-jump at the cell; TMP mode (the landing state of an
    # F-run — F's predecessor is tmp, which excludes F) does not; E mode is
    # inside a read-gap chain.
    MODE_H, MODE_E, MODE_TMP = jnp.int32(0), jnp.int32(1), jnp.int32(2)

    # state: (i, b, mode, pending_del, done, base_at, ins_cnt, ins_base,
    #         pos_at, read_start, ref_start) — the *_start fields track the
    # smallest read / draft position the path consumed (emitted) so far;
    # pos_at records WHICH read position produced each base vote (-1 for
    # deletions / uncovered), the index the polisher's quality channels
    # gather through.
    def cond(state):
        return ~state[4]

    def step(state):
        (i, b, mode, pending, done, base_at, ins_cnt, ins_base, pos_at,
         rstart, fstart) = state
        jrow = i + off - c + b
        jc = jnp.clip(jrow, 0, out_len - 1)
        j_ok = (jrow >= 0) & (jrow < out_len)
        rb = read[jnp.clip(i, 0, L - 1)]
        rb_known = rb < 4  # an N aligned over a column carries no base vote
        d = tdir[jnp.clip(i, 0, tdir.shape[0] - 1), jnp.clip(b, 0, W - 1)]
        m = fjump[jnp.clip(i, 0, fjump.shape[0] - 1), jnp.clip(b, 0, W - 1)].astype(jnp.int32)

        # 1. pending deletion run: emit one deletion, move left
        in_del = pending > 0
        # 2. otherwise, entering cell in H mode with an F-jump: start a run
        start_del = ~in_del & (mode == MODE_H) & (m > 0)
        do_del = in_del | start_del
        new_pending = jnp.where(in_del, pending - 1, jnp.where(start_del, m - 1, 0))
        base_at = jnp.where(do_del & j_ok, base_at.at[jc].set(DELETION), base_at)

        # 3. tmp-level choices (valid when not deleting)
        choice = jnp.where(mode == MODE_E, jnp.int32(_EGAP), (d & 3).astype(jnp.int32))
        is_diag = ~do_del & (choice == _DIAG)
        is_egap = ~do_del & (choice == _EGAP)
        is_fresh = ~do_del & (choice == _FRESH)

        base_at = jnp.where(is_diag & j_ok & rb_known, base_at.at[jc].set(rb), base_at)
        pos_at = jnp.where(is_diag & j_ok & rb_known, pos_at.at[jc].set(i), pos_at)
        ins_cnt = jnp.where(is_egap & j_ok & rb_known, ins_cnt.at[jc].add(1), ins_cnt)
        ins_base = jnp.where(is_egap & j_ok & rb_known, ins_base.at[jc].set(rb), ins_base)

        e_open = (d & _EOPEN_BIT) != 0
        diag_stop = is_diag & ((d & _DIAG_STOP_BIT) != 0)

        ni = jnp.where(is_diag | is_egap, i - 1, i)
        nb = jnp.where(do_del, b - 1, jnp.where(is_egap, b + 1, b))
        nmode = jnp.where(
            do_del,
            MODE_TMP,
            jnp.where(is_egap & ~e_open, MODE_E, MODE_H),
        )
        ndone = is_fresh | diag_stop | (ni < 0) | (nb < 0) | (nb >= W)
        rstart = jnp.where(is_diag | is_egap, i, rstart)
        fstart = jnp.where(is_diag | do_del, jrow, fstart)
        return (ni, nb, nmode, new_pending, ndone, base_at, ins_cnt, ins_base,
                pos_at, rstart, fstart)

    init = (
        i0, b0, MODE_H, jnp.int32(0),
        (score <= 0) | (i0 < 0),
        base_at0, ins_cnt0, ins_base0, pos_at0,
        i0 + 1, jend + 1,
    )
    out = jax.lax.while_loop(cond, step, init)
    span = jnp.stack([out[9], i0 + 1, out[10], jend + 1])  # read/ref start,end
    return out[5], out[6], out[7], out[8], span


@functools.partial(jax.jit, static_argnames=("band_width", "out_len"))
def pileup_columns(
    subreads: jax.Array,
    subread_lens: jax.Array,
    draft: jax.Array,
    draft_len: jax.Array,
    diag_offsets: jax.Array,
    band_width: int = 128,
    out_len: int | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Align each subread to the draft and emit per-position columns.

    Args:
      subreads: (S, L) dense codes (canonical orientation); subread_lens: (S,).
      draft: (Ld,) dense codes; draft_len: scalar.
      diag_offsets: (S,) band centers (0 for same-molecule subreads).
      out_len: static output width (defaults to Ld).

    Returns:
      base_at: (S, out_len) uint8 — 0-3 base, 4 deletion, 5 uncovered;
      ins_cnt: (S, out_len) int32 — insertion run length after position j;
      ins_base: (S, out_len) uint8 — first base of that insertion run;
      pos_at: (S, out_len) int32 — read position that cast each base vote
        (-1 where no base: deletion/uncovered) — the index the polisher's
        base-quality channels gather through;
      spans: (S, 4) int32 — [read_start, read_end, ref_start, ref_end)
        of each subread's local alignment (ends exclusive), for end-extension
        voting in the consensus driver.
    """
    if out_len is None:
        out_len = draft.shape[0]
    scoring = (MATCH, MISMATCH, GAP_OPEN, GAP_EXT)

    def one(read, rlen, doff):
        best, tdir, fjump = _forward_banded(
            read, rlen, draft, draft_len, doff, band_width, scoring
        )
        return _traceback_one(best, tdir, fjump, read, doff, band_width, out_len)

    return jax.vmap(one)(
        subreads, subread_lens.astype(jnp.int32), diag_offsets.astype(jnp.int32)
    )


@functools.partial(jax.jit, static_argnames=("band_width",))
def _forward_batch(reads, read_lens, refs, ref_lens, band_width: int):
    """vmapped :func:`_forward_banded` over flat lanes (offsets 0);
    returns (best (N, 3), planes (N, L, W) uint16).

    The two direction planes are packed into one uint16
    (``tdir | fjump << 4``) so the traceback's serial chain pays ONE random
    gather per step instead of two — on TPU a batched random gather
    serializes into per-lane scalar loads, making it the traceback's unit
    of cost.
    """
    scoring = (MATCH, MISMATCH, GAP_OPEN, GAP_EXT)

    def one(read, rlen, ref, tlen):
        best, tdir, fjump = _forward_banded(
            read, rlen, ref, tlen, jnp.int32(0), band_width, scoring
        )
        return best, tdir.astype(jnp.uint16) | (fjump.astype(jnp.uint16) << 4)

    return jax.vmap(one)(
        reads, read_lens.astype(jnp.int32), refs, ref_lens.astype(jnp.int32)
    )


@functools.partial(jax.jit, static_argnames=("band_width", "out_len"))
def _traceback_batch(best, planes, reads, band_width: int, out_len: int):
    """Scan-log traceback over flat lanes (offsets 0).

    The while_loop traceback (:func:`_traceback_one`) scatters into the
    (N, out_len) column arrays at EVERY step and gathers three arrays per
    step — and a data-dependent random gather is the serial unit of cost on
    TPU (it lowers to per-lane scalar loads). This version pays exactly ONE
    gather inside the chain (the packed u16 direction plane from
    :func:`_forward_batch`), keeps 7 scalars of per-lane state, and logs
    each step's move as one packed int32. Everything else happens
    vectorized afterwards:

    - read bases are gathered for the whole log at once (the log stores
      read INDICES — base identity never affects the walk itself);
    - ``base_at``: one set per logged (lane, j) — indices are unique (a
      draft column is consumed at most once per walk);
    - ``ins_cnt``: scatter-add of the logged insertion steps;
    - ``ins_base``: the FIRST base of each insertion run = the run's
      latest traceback step, recovered deterministically as a scatter-max
      of ``t * 4 + base`` (t strictly increases over the scan).

    Step count is the static worst case (read length + draft length); dead
    lanes emit drop-sentinel indices. Bit-identical to the while_loop
    version (asserted by tests).
    """
    N, L = reads.shape
    W = band_width
    c = W // 2
    T = L + out_len
    score, i0, b0 = best[:, 0], best[:, 1], best[:, 2]
    jend = i0 - c + b0
    MODE_H, MODE_E, MODE_TMP = jnp.int32(0), jnp.int32(1), jnp.int32(2)
    lane = jnp.arange(N, dtype=jnp.int32)
    planes_flat = planes.reshape(N, L * W)

    # log op codes (2 bits): 0 none, 1 = deletion at j, 2 = diag read[i]
    # over j, 3 = insertion read[i] after j
    OP_DEL, OP_DIAG, OP_INS = 1, 2, 3
    j_bits = max(out_len.bit_length(), 1)
    i_bits = max(L.bit_length(), 1)

    def step(carry, _):
        i, b, mode, pending, done, rstart, fstart = carry
        live = ~done
        jrow = i - c + b
        jc = jnp.clip(jrow, 0, out_len - 1)
        j_ok = (jrow >= 0) & (jrow < out_len) & live
        ci = jnp.clip(i, 0, L - 1)
        cb = jnp.clip(b, 0, W - 1)
        p = jnp.take_along_axis(
            planes_flat, (ci * W + cb)[:, None], axis=1
        )[:, 0].astype(jnp.int32)
        d = p & 15
        m = p >> 4

        in_del = pending > 0
        start_del = ~in_del & (mode == MODE_H) & (m > 0)
        do_del = in_del | start_del
        new_pending = jnp.where(in_del, pending - 1, jnp.where(start_del, m - 1, 0))

        choice = jnp.where(mode == MODE_E, jnp.int32(_EGAP), d & 3)
        is_diag = ~do_del & (choice == _DIAG)
        is_egap = ~do_del & (choice == _EGAP)
        is_fresh = ~do_del & (choice == _FRESH)

        op = jnp.where(
            do_del & j_ok, OP_DEL,
            jnp.where(
                is_diag & j_ok, OP_DIAG, jnp.where(is_egap & j_ok, OP_INS, 0)
            ),
        )
        log = (op << (j_bits + i_bits)) | (ci << j_bits) | jc

        e_open = (d & _EOPEN_BIT) != 0
        diag_stop = is_diag & ((d & _DIAG_STOP_BIT) != 0)

        ni = jnp.where(is_diag | is_egap, i - 1, i)
        nb = jnp.where(do_del, b - 1, jnp.where(is_egap, b + 1, b))
        nmode = jnp.where(
            do_del, MODE_TMP, jnp.where(is_egap & ~e_open, MODE_E, MODE_H)
        )
        ndone = done | is_fresh | diag_stop | (ni < 0) | (nb < 0) | (nb >= W)
        nrstart = jnp.where(live & (is_diag | is_egap), i, rstart)
        nfstart = jnp.where(live & (is_diag | do_del), jrow, fstart)
        new_carry = (
            jnp.where(live, ni, i), jnp.where(live, nb, b),
            jnp.where(live, nmode, mode), jnp.where(live, new_pending, pending),
            ndone, nrstart, nfstart,
        )
        return new_carry, log

    init = (
        i0, b0, jnp.full((N,), MODE_H), jnp.zeros((N,), jnp.int32),
        (score <= 0) | (i0 < 0),
        i0 + 1, jend + 1,
    )
    (_, _, _, _, _, rstart, fstart), logs = jax.lax.scan(
        step, init, None, length=T
    )

    # vectorized log decode + column materialization
    jc_t = logs & ((1 << j_bits) - 1)
    i_t = (logs >> j_bits) & ((1 << i_bits) - 1)
    op_t = logs >> (j_bits + i_bits)
    rb_t = jnp.take_along_axis(reads, i_t.T.astype(jnp.int32), axis=1).T
    rb_known = rb_t < 4

    set_hit = (op_t == OP_DEL) | ((op_t == OP_DIAG) & rb_known)
    set_j = jnp.where(set_hit, jc_t, out_len)
    set_v = jnp.where(op_t == OP_DEL, jnp.uint8(DELETION), rb_t.astype(jnp.uint8))
    diag_hit = (op_t == OP_DIAG) & rb_known
    diag_j = jnp.where(diag_hit, jc_t, out_len)
    ins_hit = (op_t == OP_INS) & rb_known
    ins_j = jnp.where(ins_hit, jc_t, out_len)
    ts = jnp.arange(T, dtype=jnp.int32)[:, None]
    ins_pk = ts * 4 + (rb_t & 3).astype(jnp.int32)

    lanes_T = jnp.broadcast_to(lane[None, :], (T, N))
    base_at = jnp.full((N, out_len), UNCOVERED, jnp.uint8)
    base_at = base_at.at[lanes_T, set_j].set(set_v, mode="drop")
    pos_at = jnp.full((N, out_len), -1, jnp.int32)
    pos_at = pos_at.at[lanes_T, diag_j].set(i_t.astype(jnp.int32), mode="drop")
    ins_cnt = jnp.zeros((N, out_len), jnp.int32)
    ins_cnt = ins_cnt.at[lanes_T, ins_j].add(1, mode="drop")
    pk0 = jnp.full((N, out_len), -1, jnp.int32)
    pk = pk0.at[lanes_T, ins_j].max(ins_pk, mode="drop")
    ins_base = jnp.where(pk >= 0, (pk % 4).astype(jnp.uint8), jnp.uint8(0))
    spans = jnp.stack([rstart, i0 + 1, fstart, jend + 1], axis=1)
    return base_at, ins_cnt, ins_base, pos_at, spans


@functools.lru_cache(maxsize=None)
def _sharded_pileup_fn(mesh, band_width: int, out_len: int):
    """shard_map-wrapped forward+traceback over the flat lane axis.

    The polish stage is embarrassingly parallel over alignment lanes
    (cluster x subread), so each chip runs the exact single-chip program on
    its lane shard with zero collectives — the same recipe as the fused read
    pass (pipeline/assign.py) and the TPU mapping of the reference's
    node-wide medaka fan-out (ref medaka_polish.py:95-144; VERDICT r2 #3).
    """
    from ont_tcrconsensus_tpu.parallel.mesh import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P

    def base(reads, rlens, refs, reflens):
        best, planes = _forward_batch(
            reads, rlens, refs, reflens, band_width=band_width
        )
        return _traceback_batch(best, planes, reads, band_width, out_len)

    d1, d2 = P("data"), P("data", None)
    return jax.jit(shard_map(
        base, mesh=mesh, in_specs=(d2, d1, d2, d1),
        out_specs=(d2, d2, d2, d2, d2),
        check_vma=False,
    ))


def pileup_columns_batch_auto(
    subreads,
    subread_lens,
    drafts,
    draft_lens,
    band_width: int = 128,
    out_len: int | None = None,
    force_pallas: bool = False,
    mesh=None,
):
    """:func:`pileup_columns_batch` split into flat-lane forward + scan-log
    traceback — the production pileup path.

    The fused vmapped version pays thousands of sequential multi-MB
    scatters in its while_loop traceback; here the forward emits direction
    planes once and :func:`_traceback_batch` logs steps with scalar state,
    scattering the columns in one shot (~3x on the real chip). On CPU the
    fused XLA version runs (small test shapes, no win to split).
    ``force_pallas`` routes the forward through the Pallas kernel
    (:mod:`.pileup_pallas`; interpreter on CPU) — the equivalence-test hook
    for that kernel, which currently trails the XLA forward on the tunneled
    chip and is kept as groundwork, not the default.

    ``mesh`` shards the flat lane axis over the mesh's ``data`` axis
    (lanes = C*S must divide it; callers pad the cluster axis) — the polish
    stage's multi-chip path (VERDICT r2 #3).
    """
    if out_len is None:
        out_len = drafts.shape[-1]
    on_cpu = jax.default_backend() == "cpu"
    C, S, L = subreads.shape
    lanes = C * S
    use_mesh = (
        mesh is not None and not force_pallas
        and lanes % mesh_data_size(mesh) == 0
    )
    if on_cpu and not force_pallas and not use_mesh:
        return pileup_columns_batch(
            subreads, subread_lens, drafts, draft_lens,
            band_width=band_width, out_len=out_len,
        )
    reads = jnp.asarray(subreads).reshape(lanes, L)
    rlens = jnp.asarray(subread_lens).reshape(lanes)
    refs = jnp.repeat(jnp.asarray(drafts), S, axis=0)
    reflens = jnp.repeat(jnp.asarray(draft_lens).astype(jnp.int32), S)
    if force_pallas:
        from ont_tcrconsensus_tpu.ops import pileup_pallas

        best, tdir, fjump = pileup_pallas.forward_planes_pallas(
            reads, rlens, refs, reflens, band_width=band_width,
            interpret=on_cpu,
        )
        planes = tdir.astype(jnp.uint16) | (fjump.astype(jnp.uint16) << 4)
        base_at, ins_cnt, ins_base, pos_at, spans = _traceback_batch(
            best, planes, reads, band_width, out_len
        )
    elif use_mesh:
        base_at, ins_cnt, ins_base, pos_at, spans = _sharded_pileup_fn(
            mesh, band_width, out_len
        )(reads, rlens.astype(jnp.int32), refs, reflens)
    else:
        best, planes = _forward_batch(
            reads, rlens, refs, reflens, band_width=band_width
        )
        base_at, ins_cnt, ins_base, pos_at, spans = _traceback_batch(
            best, planes, reads, band_width, out_len
        )
    return (
        base_at.reshape(C, S, out_len),
        ins_cnt.reshape(C, S, out_len),
        ins_base.reshape(C, S, out_len),
        pos_at.reshape(C, S, out_len),
        spans.reshape(C, S, 4),
    )


@functools.partial(jax.jit, static_argnames=("band_width", "out_len"))
def pileup_columns_batch(
    subreads: jax.Array,
    subread_lens: jax.Array,
    drafts: jax.Array,
    draft_lens: jax.Array,
    band_width: int = 128,
    out_len: int | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Batched :func:`pileup_columns` over clusters.

    Args:
      subreads: (C, S, L); subread_lens: (C, S); drafts: (C, Ld);
      draft_lens: (C,). Diag offsets are 0 (same-molecule subreads).

    Returns (base_at (C,S,out_len), ins_cnt, ins_base, pos_at,
    spans (C,S,4)).
    """
    if out_len is None:
        out_len = drafts.shape[-1]
    scoring = (MATCH, MISMATCH, GAP_OPEN, GAP_EXT)

    def per_cluster(sub, slens, draft, dlen):
        def one(read, rlen):
            best, tdir, fjump = _forward_banded(
                read, rlen, draft, dlen, jnp.int32(0), band_width, scoring
            )
            return _traceback_one(
                best, tdir, fjump, read, jnp.int32(0), band_width, out_len
            )

        return jax.vmap(one)(sub, slens.astype(jnp.int32))

    return jax.vmap(per_cluster)(
        subreads, subread_lens, drafts, draft_lens.astype(jnp.int32)
    )
