"""Hashed k-mer sketching: candidate selection + strand detection on the MXU.

TPU-native replacement for minimap2's seeding stage
(/root/reference/ont_tcr_consensus/minimap2_align.py:90-132): instead of
minimizer hash tables and chaining, every sequence becomes a dense hashed
k-mer count profile, and read->reference candidate selection is one
``(reads, D) @ (D, refs)`` matmul followed by ``top_k`` — exactly the shape
the MXU wants. Strand is decided by scoring both the read and its reverse
complement against the reference panel (minimap2 does this via canonical
minimizers; a dense profile cannot canonicalize, so we score both).

The base-level alignment then runs only on the short-list
(:mod:`.sw_align`), with the band center estimated from the amplicon
geometry (softclip caps, run_config.json:9-10) — see :func:`diag_offset`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# multiplicative hash constant (Knuth); positions k-mers ~uniformly in buckets
_HASH_MULT = 2654435761


@functools.partial(jax.jit, static_argnames=("k", "dim"))
def kmer_profile(
    codes: jax.Array, lengths: jax.Array, k: int = 8, dim: int | None = 4096
) -> jax.Array:
    """(B, L) dense codes -> (B, dim) float32 k-mer count profiles.

    Windows containing N or padding contribute nothing. With ``dim`` set, the
    packed 2-bit k-mer is bucketed via a multiplicative hash (k <= 15 fits
    int32 packing; uint32 wraparound is fine for hashing). ``dim=None``
    means exact 4**k buckets with no hashing — the small-k mode the UMI
    shortlist uses.
    """
    B, L = codes.shape
    c = codes.astype(jnp.int32)
    valid = (c < 4) & (jnp.arange(L)[None, :] < lengths[:, None])
    packed = jnp.zeros((B, L - k + 1), dtype=jnp.int32)
    ok = jnp.ones((B, L - k + 1), dtype=bool)
    for off in range(k):
        packed = packed * 4 + c[:, off : L - k + 1 + off]
        ok = ok & valid[:, off : L - k + 1 + off]
    if dim is None:
        dim = 4**k
        bucket = packed
    else:
        bucket = (
            (packed.astype(jnp.uint32) * jnp.uint32(_HASH_MULT)) % jnp.uint32(dim)
        ).astype(jnp.int32)
    bucket = jnp.where(ok, bucket, dim)  # overflow bucket, dropped below
    # scatter-add instead of a (B, L-k+1, dim+1) one-hot materialization:
    # at B=1024, L=4096, dim=4096 the one-hot is a ~64-billion-element
    # intermediate; the scatter writes L-k+1 updates per row.
    rows = jnp.broadcast_to(jnp.arange(B, dtype=jnp.int32)[:, None], bucket.shape)
    out = jnp.zeros((B, dim + 1), jnp.float32)
    out = out.at[rows, bucket].add(1.0)
    return out[:, :dim]


@functools.partial(jax.jit, static_argnames=("top_k",))
def top_candidates(q_profiles, t_profiles, top_k: int):
    """Rank targets by raw profile dot product on the MXU; (Q, top_k) indices."""
    scores = q_profiles @ t_profiles.T
    _, idx = jax.lax.top_k(scores, top_k)
    return idx.astype(jnp.int32)


@jax.jit
def revcomp_batch(codes: jax.Array, lengths: jax.Array) -> jax.Array:
    """Length-aware reverse complement of a padded dense-code batch."""
    B, L = codes.shape
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    src = lengths[:, None] - 1 - pos
    in_seq = src >= 0
    gathered = jnp.take_along_axis(codes, jnp.clip(src, 0, L - 1).astype(jnp.int32), axis=1)
    comp = jnp.where(gathered < 4, 3 - gathered.astype(jnp.int32), gathered.astype(jnp.int32))
    return jnp.where(in_seq, comp, gathered.astype(jnp.int32)).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("top_k", "k", "dim"))
def candidates_both_strands(
    read_codes: jax.Array,
    read_lens: jax.Array,
    ref_profiles: jax.Array,
    top_k: int = 4,
    k: int = 8,
    dim: int = 4096,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Score reads (both strands) against a reference profile panel.

    Args:
      read_codes: (B, L) dense codes as read from the instrument.
      ref_profiles: (R, dim) panel from :func:`kmer_profile` (L2-normalized
        or raw counts — cosine used either way).

    Returns:
      (cand_idx, cand_score, is_reverse): (B, top_k) int32 candidate ref
      indices ranked best-first, (B, top_k) float32 cosine scores, and (B,)
      bool — True where the reverse-complemented read scores higher (i.e.
      the read is a '-' strand molecule).
    """
    fwd = kmer_profile(read_codes, read_lens, k=k, dim=dim)
    rev = kmer_profile(revcomp_batch(read_codes, read_lens), read_lens, k=k, dim=dim)

    def norm(x):
        return x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-6)

    refs_n = norm(ref_profiles)
    fwd_scores = norm(fwd) @ refs_n.T  # (B, R) on the MXU
    rev_scores = norm(rev) @ refs_n.T
    is_reverse = jnp.max(rev_scores, axis=1) > jnp.max(fwd_scores, axis=1)
    scores = jnp.where(is_reverse[:, None], rev_scores, fwd_scores)
    best, idx = jax.lax.top_k(scores, top_k)
    return idx.astype(jnp.int32), best, is_reverse


@jax.jit
def similarity_matrix(profiles_a: jax.Array, profiles_b: jax.Array) -> jax.Array:
    """Cosine similarity panel-vs-panel — the self-homology prefilter
    (replaces minimap2 -DP all-vs-all, minimap2_align.py:40-73)."""

    def norm(x):
        return x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-6)

    return norm(profiles_a) @ norm(profiles_b).T


def diag_offset(read_lens, ref_lens):
    """Band-center estimate for :func:`..ops.sw_align.align_banded`.

    The amplicon bounds softclips to <= ~90 nt per side (config
    max_softclip_5/3_end), so centering the band on the symmetric overhang
    ``-(read_len - ref_len) / 2`` keeps the true diagonal within a 256-wide
    band for any split of the overhang between the two ends.
    """
    return -((read_lens - ref_lens) // 2)
