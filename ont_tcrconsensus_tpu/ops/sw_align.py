"""Batched banded affine-gap local alignment with traceback-free stats.

TPU-native replacement for minimap2's base-level alignment
(/root/reference/ont_tcr_consensus/minimap2_align.py:90-138) and the
blast-identity computation it feeds (:13-18, blast_id = matches / alignment
columns). Instead of CIGAR + NM tags, every DP cell carries four auxiliary
channels — match count, column count, read start, ref start — that follow the
same predecessor the score picked, so the best cell directly yields
(score, read_start/end, ref_start/end, n_match, n_cols) with no traceback
(SURVEY §7 "hard parts" #6).

Banding: rows are read positions; within a row the band covers ref positions
``j = i + diag_offset + [-W/2, W/2)``. The amplicon design bounds softclips
(config max_softclip_5/3_end: 81/76), so a 256-wide band centered near
``-(expected 5' overhang)`` covers real data; the k-mer seeder
(:mod:`.minimizer`) estimates per-pair ``diag_offset`` when the geometry is
less constrained. All in-row dependencies (affine gap cascade) are min-plus
prefix scans — no scalar loops; one ``lax.scan`` over rows, vmapped over
pairs, shardable over a mesh data axis.

Recurrence (Gotoh, priorities diag/up/fresh >= left on ties):
  E[i][j] = max(H[i-1][j] - open, E[i-1][j]) - ext        (read-consuming gap)
  tmp     = max(H[i-1][j-1] + sub, E[i][j], 0·fresh)
  F[i][j] = max_{l<j}(tmp[i][l] - open - (j-l)·ext)       (ref-consuming gap)
  H[i][j] = max(tmp, F)
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

NEG = -(1 << 24)  # plain int (jnp.full/where promote it); a jnp constant
#                   here would initialize the XLA backend at import time
PAD_SENTINEL = 5  # encode.PAD_CODE: never matches (tbase < 4 check)

MATCH = 2
MISMATCH = 4   # penalty (positive)
GAP_OPEN = 4   # first gap base costs OPEN + EXT
GAP_EXT = 2


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class AlignResult:
    """Batched alignment outcome; all fields (B,) arrays.

    ``read_end``/``ref_end`` are exclusive. ``n_cols`` counts alignment
    columns (matches + mismatches + gap bases), so
    ``blast_id = n_match / n_cols`` matches the reference's
    matches/(M+I+D) definition (minimap2_align.py:13-18).
    """

    score: np.ndarray | jax.Array
    read_start: np.ndarray | jax.Array
    read_end: np.ndarray | jax.Array
    ref_start: np.ndarray | jax.Array
    ref_end: np.ndarray | jax.Array
    n_match: np.ndarray | jax.Array
    n_cols: np.ndarray | jax.Array

    @property
    def blast_id(self):
        cols = jnp.maximum(self.n_cols, 1) if isinstance(self.n_cols, jax.Array) else np.maximum(self.n_cols, 1)
        return self.n_match / cols


def _pairmax(a, b):
    """Associative op on (value, index): keep larger value, larger index on tie."""
    av, ai = a
    bv, bi = b
    take_b = (bv > av) | ((bv == av) & (bi > ai))
    return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)


def _shift_up(x, fill):
    """x[b] -> x[b+1]: the (i-1, j) predecessor lives one band slot right."""
    return jnp.concatenate([x[1:], jnp.full((1,), fill, x.dtype)])


def _shift_right(x, step, fill):
    """x[b] -> x[b-step] (bring the value from `step` slots left)."""
    return jnp.concatenate([jnp.full((step,), fill, x.dtype), x[:-step]])


def _f_cascade(tmp, tch, gap_open, gap_ext, band_width):
    """Ref-gap (F) values + channels via log2(W) shift-doubling.

    R[b] = max_{l<=b}(tmp[l] - ext*(b-l)) with the origin's channels carried
    through the selects and the gap length accumulated — no prefix scan, no
    gathers, only elementwise ops and static shifts (TPU-friendly; the same
    structure maps directly onto a future Pallas kernel). Ties keep the
    shorter gap, matching the sequential Gotoh tie-break.
    Then F[b] = R[b-1] - open - ext with one more gap column.
    """
    g = tmp
    ch = tch
    gap = jnp.zeros_like(tmp)
    step = 1
    while step < band_width:
        cand_g = _shift_right(g, step, NEG) - gap_ext * step
        cand_ch = jnp.stack([_shift_right(ch[k], step, 0) for k in range(ch.shape[0])])
        cand_gap = _shift_right(gap, step, 0) + step
        take = cand_g > g
        g = jnp.where(take, cand_g, g)
        ch = jnp.where(take[None, :], cand_ch, ch)
        gap = jnp.where(take, cand_gap, gap)
        step *= 2
    F = _shift_right(g, 1, NEG) - gap_open - gap_ext
    Fch = jnp.stack([_shift_right(ch[k], 1, 0) for k in range(ch.shape[0])])
    Fgap = _shift_right(gap, 1, 0) + 1
    Fch = Fch.at[1].add(Fgap)  # the gap run adds Fgap columns
    return F, Fch


def _align_one(read, read_len, ref, ref_len, diag_offset, band_width, scoring):
    match, mismatch, gap_open, gap_ext = scoring
    W = band_width
    c = W // 2
    L = read.shape[0]
    iota = jnp.arange(W, dtype=jnp.int32)
    read_len = read_len.astype(jnp.int32)
    ref_len = ref_len.astype(jnp.int32)
    off = diag_offset.astype(jnp.int32)

    shift_up = _shift_up

    # Pre-shift the ref ONCE so row i's band window is ref_shifted[i : i+W]:
    # the slice start is then the scan counter — SHARED across vmapped
    # lanes — and XLA lowers it to a contiguous slice. The previous
    # per-lane start (i + off - c) made every row a batched gather:
    # ~L*W gathered elements per lane per pass, the entire runtime of the
    # CPU path at bench shapes (same trick as sw_pallas's host pre-shift).
    K = L + W
    ks = jnp.arange(K, dtype=jnp.int32) + off - c
    in_range = (ks >= 0) & (ks < ref.shape[0])
    ref_shifted = jnp.where(
        in_range, ref[jnp.clip(ks, 0, ref.shape[0] - 1)],
        jnp.asarray(PAD_SENTINEL, ref.dtype),
    )

    def row_step(carry, i):
        H, Hch, E, Ech, best = carry
        jrow = i + off - c + iota
        valid = (jrow >= 0) & (jrow < ref_len) & (i < read_len)
        rbase = read[jnp.clip(i, 0, L - 1)]
        tbase = jax.lax.dynamic_slice(ref_shifted, (i,), (W,))
        is_match = (tbase == rbase) & (rbase < 4) & (tbase < 4)
        sub = jnp.where(is_match, match, -mismatch).astype(jnp.int32)

        # E: read-consuming gap from (i-1, j) = prev row, band slot b+1
        H_up = shift_up(H, NEG)
        E_up = shift_up(E, NEG)
        Hch_up = jnp.stack([shift_up(Hch[k], 0) for k in range(4)])
        Ech_up = jnp.stack([shift_up(Ech[k], 0) for k in range(4)])
        open_sc = H_up - gap_open - gap_ext
        ext_sc = E_up - gap_ext
        take_open = open_sc >= ext_sc
        E_new = jnp.where(take_open, open_sc, ext_sc)
        Ech_new = jnp.where(take_open[None, :], Hch_up, Ech_up)
        Ech_new = Ech_new.at[1].add(1)  # one more (gap) column

        # diagonal from (i-1, j-1) = prev row, same band slot. A fresh
        # (empty) alignment at the predecessor — score 0, starting at
        # (i, jrow) — is allowed too: that is the local-SW 0-clamp, and it
        # covers DP-border starts (ref_start=0 / read_start=0) the band
        # cannot hold as cells.
        pred_fresh_ch = jnp.stack([
            jnp.zeros((W,), jnp.int32),
            jnp.zeros((W,), jnp.int32),
            jnp.full((W,), i, jnp.int32),
            jrow,
        ])
        take_fresh_pred = 0 > H
        Dbase = jnp.where(take_fresh_pred, 0, H)
        Dch = jnp.where(take_fresh_pred[None, :], pred_fresh_ch, Hch)
        D = Dbase + sub
        Dch = Dch.at[0].add(is_match.astype(jnp.int32)).at[1].add(1)

        # tmp = max(D, E, fresh) with priority D >= E >= fresh
        # channel layout: 0=n_match, 1=n_cols, 2=read_start, 3=ref_start.
        # A fresh (empty) alignment at band cell (i, jrow) has consumed
        # read[0..i] / ref[0..jrow], so it starts at (i+1, jrow+1).
        fch = jnp.stack([
            jnp.zeros((W,), jnp.int32),
            jnp.zeros((W,), jnp.int32),
            jnp.full((W,), i + 1, jnp.int32),
            jrow + 1,
        ])
        tmp = D
        tch = Dch
        e_better = E_new > tmp
        tmp = jnp.where(e_better, E_new, tmp)
        tch = jnp.where(e_better[None, :], Ech_new, tch)
        f_better = 0 > tmp
        tmp = jnp.where(f_better, 0, tmp)
        tch = jnp.where(f_better[None, :], fch, tch)
        tmp = jnp.where(valid, tmp, NEG)

        # F: ref-consuming gap within the row, via shift-doubling
        F, Fch = _f_cascade(tmp, tch, gap_open, gap_ext, W)

        take_f = F > tmp
        H_new = jnp.where(valid, jnp.where(take_f, F, tmp), NEG)
        Hch_new = jnp.where(take_f[None, :], Fch, tch)

        # best-cell tracking: first (smallest j) strict improvement wins
        b_star = jnp.argmax(H_new).astype(jnp.int32)
        row_best = H_new[b_star]
        improve = row_best > best[0]
        cand = jnp.stack([
            row_best,
            Hch_new[2, b_star],            # read_start
            i + 1,                         # read_end (exclusive)
            Hch_new[3, b_star],            # ref_start
            jrow[b_star] + 1,              # ref_end (exclusive)
            Hch_new[0, b_star],            # n_match
            Hch_new[1, b_star],            # n_cols
        ])
        best = jnp.where(improve, cand, best)
        E_new = jnp.where(valid, E_new, NEG)
        return (H_new, Hch_new, E_new, Ech_new, best), None

    H0 = jnp.full((W,), NEG, jnp.int32)
    ch0 = jnp.zeros((4, W), jnp.int32)
    best0 = jnp.concatenate([jnp.array([0], jnp.int32), jnp.zeros((6,), jnp.int32)])
    init = (H0, ch0, H0, ch0, best0)
    (_, _, _, _, best), _ = jax.lax.scan(
        init=init, xs=jnp.arange(L, dtype=jnp.int32), f=row_step
    )
    return best


@functools.partial(
    jax.jit, static_argnames=("band_width", "match", "mismatch", "gap_open", "gap_ext")
)
def align_banded(
    reads: jax.Array,
    read_lens: jax.Array,
    refs: jax.Array,
    ref_lens: jax.Array,
    diag_offsets: jax.Array,
    band_width: int = 256,
    match: int = MATCH,
    mismatch: int = MISMATCH,
    gap_open: int = GAP_OPEN,
    gap_ext: int = GAP_EXT,
) -> AlignResult:
    """Elementwise batched local alignment.

    Args:
      reads: (B, L) uint8 dense codes; read_lens: (B,).
      refs: (B, Lr) uint8 dense codes; ref_lens: (B,).
      diag_offsets: (B,) int32 — expected ``ref_pos - read_pos`` of the
        alignment; the band is centered on this diagonal.
      band_width: static band width (multiple of 128 for TPU lanes).

    Returns an :class:`AlignResult` of (B,) arrays.
    """
    scoring = (match, mismatch, gap_open, gap_ext)
    best = jax.vmap(
        lambda r, rl, t, tl, d: _align_one(r, rl, t, tl, d, band_width, scoring)
    )(reads, read_lens.astype(jnp.int32), refs, ref_lens.astype(jnp.int32),
      diag_offsets.astype(jnp.int32))
    return AlignResult(
        score=best[:, 0], read_start=best[:, 1], read_end=best[:, 2],
        ref_start=best[:, 3], ref_end=best[:, 4],
        n_match=best[:, 5], n_cols=best[:, 6],
    )


def align_np(read, ref, match=MATCH, mismatch=MISMATCH, gap_open=GAP_OPEN, gap_ext=GAP_EXT):
    """Full (unbanded) numpy local alignment with identical semantics.

    Reference implementation for tests: same scoring, same tie priorities
    (diag/up/fresh over left; on the global max, the earlier row then the
    smaller column wins).
    """
    n, m = len(read), len(ref)
    H = np.zeros((n + 1, m + 1), np.int64)
    E = np.full((n + 1, m + 1), int(NEG), np.int64)
    F = np.full((n + 1, m + 1), int(NEG), np.int64)
    # channels: (n_match, n_cols, read_start, ref_start)
    Hch = np.zeros((n + 1, m + 1, 4), np.int64)
    Ech = np.zeros((n + 1, m + 1, 4), np.int64)
    Fch = np.zeros((n + 1, m + 1, 4), np.int64)
    for i in range(n + 1):
        Hch[i, :, 2] = i
        Hch[i, :, 3] = np.arange(m + 1)
    best = (0, 0, 0, 0, 0, 0, 0)
    for i in range(1, n + 1):
        for j in range(1, m + 1):
            eo = H[i - 1, j] - gap_open - gap_ext
            ee = E[i - 1, j] - gap_ext
            if eo >= ee:
                E[i, j], Ech[i, j] = eo, Hch[i - 1, j].copy()
            else:
                E[i, j], Ech[i, j] = ee, Ech[i - 1, j].copy()
            Ech[i, j, 1] += 1
            is_m = read[i - 1] == ref[j - 1] and read[i - 1] < 4 and ref[j - 1] < 4
            d = H[i - 1, j - 1] + (match if is_m else -mismatch)
            tmp, tch = d, Hch[i - 1, j - 1].copy()
            tch[0] += int(is_m)
            tch[1] += 1
            if E[i, j] > tmp:
                tmp, tch = E[i, j], Ech[i, j].copy()
            if 0 > tmp:
                tmp, tch = 0, np.array([0, 0, i, j])
            fopen = H[i, j - 1] - gap_open - gap_ext
            fext = F[i, j - 1] - gap_ext
            if fopen >= fext:
                F[i, j], Fch[i, j] = fopen, Hch[i, j - 1].copy()
            else:
                F[i, j], Fch[i, j] = fext, Fch[i, j - 1].copy()
            Fch[i, j, 1] += 1
            if F[i, j] > tmp:
                H[i, j], Hch[i, j] = F[i, j], Fch[i, j].copy()
            else:
                H[i, j], Hch[i, j] = tmp, tch
            if H[i, j] > best[0]:
                best = (int(H[i, j]), int(Hch[i, j, 2]), i, int(Hch[i, j, 3]), j,
                        int(Hch[i, j, 0]), int(Hch[i, j, 1]))
    return AlignResult(*[np.array(x) for x in best])
