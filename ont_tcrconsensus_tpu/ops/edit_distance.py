"""Batched global edit distance / identity for short sequences (UMIs).

TPU-native replacement for the pairwise alignment inside
``vsearch --cluster_fast`` (/root/reference/ont_tcr_consensus/
vsearch_umi_cluster.py:21-54): combined UMIs are 56-68 nt, so a full
unit-cost Needleman-Wunsch fits comfortably in one 128-wide DP column per
pair. The column recurrence's in-column cascade is a min-plus prefix scan
(see :mod:`.fuzzy_match`), so the whole (Q, T) distance matrix is two nested
vmaps over a ``lax.scan`` — no scalar loops.

Identity definition (documented divergence): ``1 - d / max(len_a, len_b)``
with ``d`` the **budgeted-dovetail** distance (:func:`pairwise_dovetail`):
terminal gaps up to ``k_end`` bases per sequence end are free, mirroring
vsearch's free end gaps (``--gapopen 0E``) under its custom UMI scoring
(``--mismatch -40 --match 10``, vsearch_umi_cluster.py:44-53) and its
--iddef 2 identity, which excludes terminal gaps. The free-end budget
matters because UMI extraction fuzz (edlib k<=3 boundary drift, IUPAC
window slop) shifts the combined-UMI boundaries by a few bases per read;
charging those terminal bases as edits splits true molecules at the 0.93
threshold (observed at bench scale). Beyond the budget, terminal gaps cost
1/base, so the degenerate empty overlap keeps its full price and distinct
molecules (d ~ 25+ on 64 nt) stay far below threshold. Equivalence with
vsearch is asserted at the UMI-counts level by the end-to-end tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _nw_pair(a: jax.Array, a_len: jax.Array, b: jax.Array, b_len: jax.Array) -> jax.Array:
    """Unit-cost global edit distance between two padded code sequences.

    Padded positions are excluded by clamping the DP to the true lengths:
    we compute the full padded DP but read the result at (a_len, b_len) via
    masked bookkeeping on the scan outputs.
    """
    La = a.shape[0]
    iota = jnp.arange(La + 1, dtype=jnp.int32)
    init = iota  # D[i][0] = i

    def step(carry, inp):
        col, j = carry
        ch, = inp
        sub = jnp.where(a == ch, 0, 1).astype(jnp.int32)
        diag = col[:-1] + sub
        up = col[1:] + 1
        tmp = jnp.minimum(diag, up)
        base = jnp.concatenate([jnp.array([j + 1], jnp.int32), tmp])
        cascaded = iota + jax.lax.associative_scan(jnp.minimum, base - iota)
        new = jnp.minimum(base, cascaded)
        # freeze columns beyond b's true end so the final column equals
        # the column at j == b_len
        new = jnp.where(j < b_len, new, col)
        return (new, j + 1), None

    (col, _), _ = jax.lax.scan(step, (init, jnp.int32(0)), (b,))
    return col[a_len]


@jax.jit
def pairwise(a, a_lens, b, b_lens):
    """(B, La) x (B, Lb) -> (B,) elementwise edit distances."""
    return jax.vmap(_nw_pair)(a, a_lens.astype(jnp.int32), b, b_lens.astype(jnp.int32))


@jax.jit
def many_vs_many(queries, q_lens, targets, t_lens):
    """(Q, L) x (T, L) -> (Q, T) edit-distance matrix."""
    q_lens = q_lens.astype(jnp.int32)
    t_lens = t_lens.astype(jnp.int32)

    def one_q(q, ql):
        return jax.vmap(lambda t, tl: _nw_pair(q, ql, t, tl))(targets, t_lens)

    return jax.vmap(one_q)(queries, q_lens)


@jax.jit
def identity_matrix(queries, q_lens, targets, t_lens):
    """(Q, T) identity = 1 - d / max(len_q, len_t); 0 if either side is empty."""
    d = many_vs_many(queries, q_lens, targets, t_lens).astype(jnp.float32)
    longest = jnp.maximum(q_lens[:, None], t_lens[None, :]).astype(jnp.float32)
    either_empty = (q_lens[:, None] == 0) | (t_lens[None, :] == 0)
    ident = 1.0 - d / jnp.maximum(longest, 1.0)
    return jnp.where(either_empty, 0.0, ident)


_BIG = 1 << 20  # plain int: promoted inside traced code; a jnp constant
#                 here would initialize the XLA backend at import time


def _dovetail_pair(a: jax.Array, a_len: jax.Array, b: jax.Array, b_len: jax.Array,
                   k_end: int) -> jax.Array:
    """Unit-cost edit distance with free terminal gaps up to ``k_end``.

    Same column-scan structure as :func:`_nw_pair`, but boundary cells charge
    ``relu(overhang - k_end)`` instead of the full overhang, and the answer
    is the min over ALL cells of ``D[i][j] + relu(a_len-i-k) + relu(b_len-j-k)``
    — i.e. any alignment may leave up to ``k_end`` unaligned bases per end of
    either sequence for free.
    """
    La = a.shape[0]
    k = jnp.int32(k_end)
    iota = jnp.arange(La + 1, dtype=jnp.int32)
    a_len = a_len.astype(jnp.int32)
    b_len = b_len.astype(jnp.int32)
    mask_a = iota <= a_len
    tail_a = jnp.maximum(a_len - iota - k, 0)  # trailing overhang of a, past budget
    init = jnp.maximum(iota - k, 0)            # D[i][0]: leading overhang of a
    best = (
        jnp.min(jnp.where(mask_a, init + tail_a, _BIG))
        + jnp.maximum(b_len - k, 0)
    )

    def step(carry, inp):
        col, j, best = carry
        ch, = inp
        sub = jnp.where(a == ch, 0, 1).astype(jnp.int32)
        diag = col[:-1] + sub
        up = col[1:] + 1
        tmp = jnp.minimum(diag, up)
        base = jnp.concatenate([jnp.maximum(j + 1 - k, 0)[None], tmp])
        cascaded = iota + jax.lax.associative_scan(jnp.minimum, base - iota)
        new = jnp.minimum(base, cascaded)
        new = jnp.where(j < b_len, new, col)
        cand = (
            jnp.min(jnp.where(mask_a, new + tail_a, _BIG))
            + jnp.maximum(b_len - (j + 1) - k, 0)
        )
        best = jnp.minimum(best, jnp.where(j < b_len, cand, _BIG))
        return (new, j + 1, best), None

    (_, _, best), _ = jax.lax.scan(
        step, (init, jnp.int32(0), best.astype(jnp.int32)), (b,)
    )
    return best


@jax.jit
def pairwise_dovetail(a, a_lens, b, b_lens, k_end: int = 8):
    """(B, La) x (B, Lb) -> (B,) budgeted-dovetail distances."""
    return jax.vmap(lambda x, xl, y, yl: _dovetail_pair(x, xl, y, yl, k_end))(
        a, a_lens.astype(jnp.int32), b, b_lens.astype(jnp.int32)
    )


@jax.jit
def many_vs_many_dovetail(queries, q_lens, targets, t_lens, k_end: int = 8):
    """(Q, L) x (T, L) -> (Q, T) budgeted-dovetail distance matrix."""
    q_lens = q_lens.astype(jnp.int32)
    t_lens = t_lens.astype(jnp.int32)

    def one_q(q, ql):
        return jax.vmap(lambda t, tl: _dovetail_pair(q, ql, t, tl, k_end))(
            targets, t_lens
        )

    return jax.vmap(one_q)(queries, q_lens)


@functools.lru_cache(maxsize=None)
def _sharded_pairwise_dovetail(mesh, k_end: int):
    """Pair-axis-sharded :func:`pairwise_dovetail` (zero collectives)."""
    from ont_tcrconsensus_tpu.parallel.mesh import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P

    fn = jax.vmap(lambda x, xl, y, yl: _dovetail_pair(x, xl, y, yl, k_end))
    d1, d2 = P("data"), P("data", None)
    return jax.jit(shard_map(
        fn, mesh=mesh, in_specs=(d2, d1, d2, d1), out_specs=d1,
        check_vma=False,
    ))


@functools.lru_cache(maxsize=None)
def _sharded_mvm_dovetail(mesh, k_end: int):
    """Query-axis-sharded :func:`many_vs_many_dovetail` (targets replicated)."""
    from ont_tcrconsensus_tpu.parallel.mesh import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P

    def fn(queries, q_lens, targets, t_lens):
        def one_q(q, ql):
            return jax.vmap(
                lambda t, tl: _dovetail_pair(q, ql, t, tl, k_end)
            )(targets, t_lens.astype(jnp.int32))

        return jax.vmap(one_q)(queries, q_lens.astype(jnp.int32))

    d1, d2, rep = P("data"), P("data", None), P()
    return jax.jit(shard_map(
        fn, mesh=mesh, in_specs=(d2, d1, rep, rep),
        out_specs=P("data", None), check_vma=False,
    ))


def pairwise_dovetail_auto(a, a_lens, b, b_lens, k_end: int = 8, mesh=None):
    """:func:`pairwise_dovetail`, sharded over ``mesh``'s data axis when the
    pair count divides it (UMI distance chunks; VERDICT r2 #3)."""
    from ont_tcrconsensus_tpu.parallel.mesh import mesh_data_size

    if mesh is not None and a.shape[0] % mesh_data_size(mesh) == 0:
        return _sharded_pairwise_dovetail(mesh, k_end)(
            jnp.asarray(a), jnp.asarray(a_lens, jnp.int32),
            jnp.asarray(b), jnp.asarray(b_lens, jnp.int32),
        )
    return pairwise_dovetail(a, a_lens, b, b_lens, k_end)


def many_vs_many_dovetail_auto(queries, q_lens, targets, t_lens,
                               k_end: int = 8, mesh=None):
    """:func:`many_vs_many_dovetail`, query-axis-sharded when possible."""
    from ont_tcrconsensus_tpu.parallel.mesh import mesh_data_size

    if mesh is not None and queries.shape[0] % mesh_data_size(mesh) == 0:
        return _sharded_mvm_dovetail(mesh, k_end)(
            jnp.asarray(queries), jnp.asarray(q_lens, jnp.int32),
            jnp.asarray(targets), jnp.asarray(t_lens, jnp.int32),
        )
    return many_vs_many_dovetail(queries, q_lens, targets, t_lens, k_end)


# k-mer profile prefilters live in :mod:`.sketch` (exact mode: dim=None).
