"""Batched global edit distance / identity for short sequences (UMIs).

TPU-native replacement for the pairwise alignment inside
``vsearch --cluster_fast`` (/root/reference/ont_tcr_consensus/
vsearch_umi_cluster.py:21-54): combined UMIs are 56-68 nt, so a full
unit-cost Needleman-Wunsch fits comfortably in one 128-wide DP column per
pair. The column recurrence's in-column cascade is a min-plus prefix scan
(see :mod:`.fuzzy_match`), so the whole (Q, T) distance matrix is two nested
vmaps over a ``lax.scan`` — no scalar loops.

Identity definition (documented divergence): ``1 - d / max(len_a, len_b)``.
vsearch's --iddef 2 (matching columns / alignment columns) depends on its
affine scoring (``--gapopen 0E/40I --mismatch -40 --match 10``); at the
pipeline's thresholds (0.93 round 1 / 0.97 round 2 over 56-68 nt) both
definitions admit the same ~4 edit radius. Equivalence is asserted at the
UMI-counts level by the end-to-end tests instead of per-alignment.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _nw_pair(a: jax.Array, a_len: jax.Array, b: jax.Array, b_len: jax.Array) -> jax.Array:
    """Unit-cost global edit distance between two padded code sequences.

    Padded positions are excluded by clamping the DP to the true lengths:
    we compute the full padded DP but read the result at (a_len, b_len) via
    masked bookkeeping on the scan outputs.
    """
    La = a.shape[0]
    iota = jnp.arange(La + 1, dtype=jnp.int32)
    init = iota  # D[i][0] = i

    def step(carry, inp):
        col, j = carry
        ch, = inp
        sub = jnp.where(a == ch, 0, 1).astype(jnp.int32)
        diag = col[:-1] + sub
        up = col[1:] + 1
        tmp = jnp.minimum(diag, up)
        base = jnp.concatenate([jnp.array([j + 1], jnp.int32), tmp])
        cascaded = iota + jax.lax.associative_scan(jnp.minimum, base - iota)
        new = jnp.minimum(base, cascaded)
        # freeze columns beyond b's true end so the final column equals
        # the column at j == b_len
        new = jnp.where(j < b_len, new, col)
        return (new, j + 1), None

    (col, _), _ = jax.lax.scan(step, (init, jnp.int32(0)), (b,))
    return col[a_len]


@jax.jit
def pairwise(a, a_lens, b, b_lens):
    """(B, La) x (B, Lb) -> (B,) elementwise edit distances."""
    return jax.vmap(_nw_pair)(a, a_lens.astype(jnp.int32), b, b_lens.astype(jnp.int32))


@jax.jit
def many_vs_many(queries, q_lens, targets, t_lens):
    """(Q, L) x (T, L) -> (Q, T) edit-distance matrix."""
    q_lens = q_lens.astype(jnp.int32)
    t_lens = t_lens.astype(jnp.int32)

    def one_q(q, ql):
        return jax.vmap(lambda t, tl: _nw_pair(q, ql, t, tl))(targets, t_lens)

    return jax.vmap(one_q)(queries, q_lens)


@jax.jit
def identity_matrix(queries, q_lens, targets, t_lens):
    """(Q, T) identity = 1 - d / max(len_q, len_t); 0 if either side is empty."""
    d = many_vs_many(queries, q_lens, targets, t_lens).astype(jnp.float32)
    longest = jnp.maximum(q_lens[:, None], t_lens[None, :]).astype(jnp.float32)
    either_empty = (q_lens[:, None] == 0) | (t_lens[None, :] == 0)
    ident = 1.0 - d / jnp.maximum(longest, 1.0)
    return jnp.where(either_empty, 0.0, ident)


# k-mer profile prefilters live in :mod:`.sketch` (exact mode: dim=None).
