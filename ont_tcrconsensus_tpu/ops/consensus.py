"""Iterative pileup-vote consensus (the spoa/medaka-draft replacement).

The reference builds per-UMI-cluster drafts with spoa's POA graph and
polishes them with medaka's RNN (/root/reference/ont_tcr_consensus/
medaka_polish.py:113-134). POA is graph-shaped and irregular — hostile to
XLA — so this module uses the banded-DP-on-padded-batches reformulation
SURVEY §7 anticipates ("hard parts" #3): star alignment against a draft +
per-column majority vote, iterated. Each round: align all subreads to the
current draft (:mod:`.pileup`), vote per column over {A,C,G,T,deletion} and
over single-base insertions, splice the winners in, repeat. With
same-molecule subreads (>= ~4x depth) two rounds converge to the true
sequence at ONT error rates; the Flax polisher (:mod:`..models.polisher`)
then consumes the final pileup counts for extra precision.

Vote semantics (deterministic): per column the plurality of covering
subreads wins; ties prefer a base over a deletion and the
smaller base code. An insertion is spliced when strictly more than half of
the covering subreads report one; the inserted base is the plurality
``ins_base`` (ties: smaller code).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ont_tcrconsensus_tpu.obs import device as obs_device
from ont_tcrconsensus_tpu.ops import pileup
from ont_tcrconsensus_tpu.parallel.mesh import mesh_data_size
from ont_tcrconsensus_tpu.ops.encode import PAD_CODE

# The ONE band width of the polish path — consensus rounds, polisher serving
# AND polisher training/eval all build pileups with it (skew between them
# would feed the model features it never saw). Same-molecule subreads drift
# only by their own indels (sigma ~6 nt over 2 kb at ONT rates), so +/-32 is
# >4 sigma while halving the pileup kernel's per-row work vs 128.
POLISH_BAND_WIDTH = 64


@functools.partial(jax.jit, static_argnames=())
def vote_columns(
    base_at: jax.Array,
    ins_cnt: jax.Array,
    ins_base: jax.Array,
    draft: jax.Array,
    draft_len: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """One voting round; returns (new_draft (2*Ld,), new_len).

    The output interleaves kept/substituted draft positions with voted
    insertions (slot 2j = position j, slot 2j+1 = insertion after j),
    then compacts; deletions drop their slot.
    """
    S, Ld = base_at.shape
    covered = base_at != pileup.UNCOVERED  # (S, Ld)
    depth = jnp.sum(covered, axis=0)  # (Ld,)

    # per-column votes over {A,C,G,T,del}
    counts = jnp.stack(
        [jnp.sum(base_at == code, axis=0) for code in range(5)], axis=0
    )  # (5, Ld)
    # tie-breaks: bases (smaller code) beat deletion on ties -> argmax over
    # counts with del slightly disadvantaged via lexicographic trick
    order_bonus = jnp.array([4, 3, 2, 1, 0], jnp.int32)[:, None]  # prefer A<C<G<T<del
    winner = jnp.argmax(counts * 8 + order_bonus, axis=0).astype(jnp.uint8)  # (Ld,)
    in_draft = jnp.arange(Ld) < draft_len
    # uncovered positions keep the draft base verbatim (even N); only a voted
    # deletion at a covered position drops a slot
    keep_base = jnp.where(depth > 0, winner, draft[:Ld].astype(jnp.uint8))
    slot_base = jnp.where(in_draft, keep_base, jnp.uint8(PAD_CODE))
    slot_keep = in_draft & ~((depth > 0) & (winner == pileup.DELETION))

    # insertion vote: strict majority of covering subreads
    has_ins = jnp.sum((ins_cnt > 0) & covered, axis=0)
    do_ins = (has_ins * 2 > depth) & (depth > 0) & in_draft
    ins_counts = jnp.stack(
        [jnp.sum((ins_base == code) & (ins_cnt > 0) & covered, axis=0) for code in range(4)],
        axis=0,
    )
    ins_winner = jnp.argmax(ins_counts * 8 + order_bonus[:4], axis=0).astype(jnp.uint8)

    # interleave and compact
    slots = jnp.stack([slot_base, jnp.where(do_ins, ins_winner, PAD_CODE)], axis=1).reshape(-1)
    keep = jnp.stack([slot_keep, do_ins], axis=1).reshape(-1)
    new_len = jnp.sum(keep).astype(jnp.int32)
    pos = jnp.cumsum(keep) - 1
    out = jnp.full((2 * Ld,), PAD_CODE, jnp.uint8)
    # non-kept slots scatter out of bounds and are dropped
    out = out.at[jnp.where(keep, pos, 2 * Ld)].set(slots, mode="drop")
    return out, new_len


def _extend_ends(draft, draft_len, subreads, subread_lens, spans, aligned_draft_len):
    """Majority-vote single-base extension at each draft end.

    A local alignment cannot report insertions before draft position 0 (or
    after the last position): a seed draft that eroded a terminal base would
    never recover it from the pileup alone. Among subreads whose alignment
    reaches the draft boundary, a strict majority carrying extra read bases
    beyond it votes the plurality base onto the end (one base per round;
    iteration regrows deeper erosion).
    """
    spans = np.asarray(spans)
    r_start, r_end, f_start, f_end = spans[:, 0], spans[:, 1], spans[:, 2], spans[:, 3]

    # left end
    at_left = f_start == 0
    has_more = at_left & (r_start > 0)
    if at_left.sum() and has_more.sum() * 2 > at_left.sum() and draft_len < draft.shape[0]:
        bases = subreads[has_more, np.maximum(r_start[has_more] - 1, 0)]
        bc = np.bincount(bases[bases < 4], minlength=4)
        if bc.sum():
            draft = np.concatenate([[np.uint8(bc.argmax())], draft[:-1]])
            draft_len += 1
    # right end (spans were computed against the pre-vote draft)
    at_right = f_end == aligned_draft_len
    has_more = at_right & (r_end < subread_lens)
    if at_right.sum() and has_more.sum() * 2 > at_right.sum():
        idx = np.minimum(r_end[has_more], subreads.shape[1] - 1)
        bases = subreads[has_more, idx]
        bc = np.bincount(bases[bases < 4], minlength=4)
        if bc.sum() and draft_len < draft.shape[0]:
            draft = draft.copy()
            draft[draft_len] = np.uint8(bc.argmax())
            draft_len += 1
    return draft, draft_len


def consensus_cluster(
    subreads: np.ndarray,
    subread_lens: np.ndarray,
    rounds: int = 4,
    band_width: int = POLISH_BAND_WIDTH,
    pad_to: int | None = None,
) -> tuple[np.ndarray, int]:
    """Host driver: consensus of one UMI cluster's subreads.

    Args:
      subreads: (S, L) uint8 dense codes, all in canonical (+) orientation —
        orientation is known from the alignment stage, unlike medaka which
        must re-orient internally.
      subread_lens: (S,)
      rounds: maximum align->vote rounds; stops early once the draft is a
        fixed point.

    Returns (consensus_codes (width,) padded, consensus_len).

    Draft seed: the subread of median length (stable pick: lower median),
    mirroring "a representative read" rather than spoa's MSA seed.
    """
    S, L = subreads.shape
    real = np.where(np.asarray(subread_lens) > 0)[0]  # callers pad with 0-length rows
    if len(real) == 0:
        return np.full((int(pad_to or L),), PAD_CODE, np.uint8), 0
    order = real[np.argsort(np.asarray(subread_lens)[real], kind="stable")]
    seed = int(order[(len(real) - 1) // 2])
    width = int(pad_to or L)
    draft = np.full((width,), PAD_CODE, np.uint8)
    n = int(subread_lens[seed])
    draft[:n] = subreads[seed, :n]
    draft_len = np.int32(n)

    offsets = np.zeros((S,), np.int32)
    for _ in range(rounds):
        base_at, ins_cnt, ins_base, _, spans = pileup.pileup_columns(
            subreads, subread_lens, jnp.asarray(draft), jnp.asarray(draft_len),
            offsets, band_width=band_width, out_len=width,
        )
        new_draft, new_len = vote_columns(
            base_at, ins_cnt, ins_base, jnp.asarray(draft), jnp.asarray(draft_len)
        )
        new_len = int(new_len)
        if new_len > width:
            raise ValueError("consensus grew past the padded width")
        cand = np.full((width,), PAD_CODE, np.uint8)
        cand[:width] = np.asarray(new_draft)[:width]
        cand, new_len = _extend_ends(
            cand, new_len, subreads, subread_lens, spans, int(draft_len)
        )
        unchanged = new_len == draft_len and (cand[:new_len] == draft[:new_len]).all()
        draft = cand
        draft_len = np.int32(new_len)
        if unchanged:
            break
    return draft, int(draft_len)


_vote_columns_batch = jax.jit(jax.vmap(vote_columns))


@functools.lru_cache(maxsize=None)
def _sharded_vote_fn(mesh):
    """Cluster-axis-sharded :func:`vote_columns` (zero collectives)."""
    from ont_tcrconsensus_tpu.parallel.mesh import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P

    specs = (P("data"),) * 5
    return jax.jit(shard_map(
        jax.vmap(vote_columns), mesh=mesh,
        in_specs=specs, out_specs=(P("data"), P("data")),
        check_vma=False,
    ))


@functools.lru_cache(maxsize=None)
def _fused_round_fn(band_width: int, out_len: int, S: int, mesh,
                    with_pos: bool = True, donate: bool = False):
    """ONE device dispatch per consensus round: banded forward + scan-log
    traceback + column vote fused into a single jitted program.

    The unfused path pays 3 dispatches + a host sync per round per chunk —
    hundreds of round trips per library over a tunneled TPU. Fusing also
    lets XLA keep the direction planes on device between forward and
    traceback. Returns (new_drafts (C, 2W), new_lens, spans (C,S,4),
    base_at, ins_cnt, ins_base, pos_at) — the pileup columns stay on
    device for the polisher's reuse path (pos_at feeds its v4 quality
    channels; XLA DCEs it in rounds where the caller drops it).

    Inputs are FLAT lanes (C folded into the leading axis; ``S`` static),
    so the compiled-program count scales with (band, width, S) — the
    cluster-axis chunk size C never forces a recompile.
    """
    from ont_tcrconsensus_tpu.ops.pileup import _forward_batch, _traceback_batch

    def round_impl(reads, rlens, drafts, dlens):
        lanes, L = reads.shape
        C = lanes // S
        refs = jnp.repeat(drafts, S, axis=0)
        reflens = jnp.repeat(dlens.astype(jnp.int32), S)
        best, planes = _forward_batch(
            reads, rlens.astype(jnp.int32), refs, reflens,
            band_width=band_width,
        )
        base_at, ins_cnt, ins_base, pos_at, spans = _traceback_batch(
            best, planes, reads, band_width, out_len
        )
        base_at = base_at.reshape(C, S, out_len)
        ins_cnt = ins_cnt.reshape(C, S, out_len)
        ins_base = ins_base.reshape(C, S, out_len)
        new_drafts, new_lens = jax.vmap(vote_columns)(
            base_at, ins_cnt, ins_base, drafts, dlens
        )
        out = (new_drafts, new_lens, spans.reshape(C, S, 4),
               base_at, ins_cnt, ins_base)
        if with_pos:
            # pos_at feeds only the v4 feature encoding; dropping it here
            # lets XLA DCE its scatter AND spares the (C,S,W) int32 HBM
            # buffer on the v1/v3 serving path (code-review r5)
            out = out + (pos_at.reshape(C, S, out_len),)
        return out

    # drafts/dlens are fresh per-call uploads whose numpy sources the
    # caller retains, so donating them (the graph-derived discipline; the
    # output drafts reuse the input buffer's HBM) has no use-after-donate
    # hazard even across a transient retry
    jit_kwargs = {"donate_argnums": (2, 3)} if donate else {}
    if mesh is None:
        return jax.jit(round_impl, **jit_kwargs)
    from ont_tcrconsensus_tpu.parallel.mesh import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P

    d = P("data")
    d2, d3 = P("data", None), P("data", None, None)
    n_out = 7 if with_pos else 6
    return jax.jit(shard_map(
        round_impl, mesh=mesh,
        in_specs=(d2, d, d2, d),
        out_specs=(d2, d) + (d3,) * (n_out - 2),
        check_vma=False,
    ), **jit_kwargs)


def _extend_ends_device(drafts, dlens, subreads, subread_lens, spans,
                        aligned_dlens):
    """jnp mirror of :func:`_extend_ends_batch`, bit-identical by
    construction (same vote order, same first-max argmax tie-break, same
    width/do gates), so the fused pair program (:func:`_fused_pair_fn`) can
    run vote -> extend -> vote without a host round trip between rounds.

    Args/semantics exactly as :func:`_extend_ends_batch`; returns
    (drafts, dlens) instead of mutating.
    """
    C, S, W = subreads.shape
    r_start, r_end = spans[:, :, 0], spans[:, :, 1]
    f_start, f_end = spans[:, :, 2], spans[:, :, 3]
    dlens = dlens.astype(jnp.int32)

    def vote(bases, voters):
        votes = jnp.stack(
            [((bases == code) & voters).sum(axis=1) for code in range(4)],
            axis=1,
        )
        return votes.sum(axis=1) > 0, jnp.argmax(votes, axis=1).astype(jnp.uint8)

    # left end
    at_left = f_start == 0
    has_more = at_left & (r_start > 0)
    n_at, n_more = at_left.sum(axis=1), has_more.sum(axis=1)
    idx = jnp.maximum(r_start - 1, 0)
    bases = jnp.take_along_axis(subreads, idx[:, :, None], axis=2)[:, :, 0]
    have, win = vote(bases, has_more)
    do = (n_at > 0) & (n_more * 2 > n_at) & (dlens < W) & have
    shifted = jnp.concatenate([win[:, None], drafts[:, :-1]], axis=1)
    drafts = jnp.where(do[:, None], shifted, drafts)
    dlens = dlens + do.astype(jnp.int32)

    # right end (spans were computed against the pre-vote draft)
    at_right = f_end == aligned_dlens[:, None]
    has_more = at_right & (r_end < subread_lens)
    n_at, n_more = at_right.sum(axis=1), has_more.sum(axis=1)
    idx = jnp.minimum(r_end, W - 1)
    bases = jnp.take_along_axis(subreads, idx[:, :, None], axis=2)[:, :, 0]
    have, win = vote(bases, has_more)
    do = (n_at > 0) & (n_more * 2 > n_at) & (dlens < W) & have
    pos = jnp.arange(W, dtype=jnp.int32)[None, :]
    drafts = jnp.where(
        do[:, None] & (pos == dlens[:, None]), win[:, None], drafts
    )
    dlens = dlens + do.astype(jnp.int32)
    return drafts, dlens


@functools.lru_cache(maxsize=None)
def _fused_pair_fn(band_width: int, out_len: int, S: int, mesh,
                   with_pos: bool = True, donate: bool = False):
    """TWO consensus rounds per device dispatch: (forward + traceback +
    vote + end-extension) x 2, fused into one jitted program.

    The per-round host round trip (device_get of drafts/spans, numpy end
    extension, convergence check) is the polish stage's per-dispatch tax —
    decisive over a tunneled TPU, where each sync pays WAN latency. ~94% of
    clusters converge by round 2, so fusing rounds in pairs halves the
    dispatch/sync count of the common case while the converged-cluster
    compaction still kicks in between pairs.

    Bit-exactness: the round sequence is identical to the unfused loop —
    the end extension runs in-program via :func:`_extend_ends_device` (the
    jnp mirror of the host extension), and a cluster whose round-2 output
    equals its round-2 input is a deterministic vote fixed point, so its
    returned round-2 pileup IS the final draft's pileup (the same argument
    the converged-cluster compaction rests on). Clusters already stable at
    round 1 re-run round 2 at the same fixed point — identical output,
    identical pileup.

    Returns (drafts2, lens2, over1, over2, stable2, base_at, ins_cnt,
    ins_base[, pos_at]) — over*: per-cluster width-overflow flags (the
    host raises, preserving the unfused error), stable2: round-2 fixed
    point (the convergence/compaction signal), pileup planes from round 2
    (valid as final exactly when stable2).
    """
    from ont_tcrconsensus_tpu.ops.pileup import _forward_batch, _traceback_batch

    def one_round(reads, rlens, drafts, dlens):
        lanes, L = reads.shape
        C = lanes // S
        refs = jnp.repeat(drafts, S, axis=0)
        reflens = jnp.repeat(dlens.astype(jnp.int32), S)
        best, planes = _forward_batch(
            reads, rlens.astype(jnp.int32), refs, reflens,
            band_width=band_width,
        )
        base_at, ins_cnt, ins_base, pos_at, spans = _traceback_batch(
            best, planes, reads, band_width, out_len
        )
        base_at = base_at.reshape(C, S, out_len)
        ins_cnt = ins_cnt.reshape(C, S, out_len)
        ins_base = ins_base.reshape(C, S, out_len)
        new_drafts, new_lens = jax.vmap(vote_columns)(
            base_at, ins_cnt, ins_base, drafts, dlens
        )
        return (new_drafts, new_lens, spans.reshape(C, S, 4),
                base_at, ins_cnt, ins_base, pos_at.reshape(C, S, out_len))

    def half(reads, rlens, sub, slens, drafts, dlens):
        """One round + the host loop's per-round bookkeeping (dead-cluster
        restore, overflow flag, end extension, stability), in-program."""
        W = drafts.shape[1]
        nd, nl, spans, ba, ic, ib, pa = one_round(reads, rlens, drafts, dlens)
        live = dlens > 0
        nd = nd[:, :W]
        nl = nl.astype(jnp.int32)
        # empty/padding clusters keep their draft (host loop line-for-line)
        nd = jnp.where(live[:, None], nd, drafts)
        nl = jnp.where(live, nl, dlens)
        over = live & (nl > W)
        d_ext, l_ext = _extend_ends_device(nd, nl, sub, slens, spans, dlens)
        stable = (l_ext == dlens) & (d_ext == drafts).all(axis=1)
        return d_ext, l_ext, over, stable, ba, ic, ib, pa

    def pair_impl(reads, rlens, drafts, dlens):
        lanes, L = reads.shape
        C = lanes // S
        sub = reads.reshape(C, S, L)
        slens = rlens.reshape(C, S).astype(jnp.int32)
        d1, l1, over1, _, _, _, _, _ = half(
            reads, rlens, sub, slens, drafts, dlens.astype(jnp.int32)
        )
        d2, l2, over2, stable2, ba, ic, ib, pa = half(
            reads, rlens, sub, slens, d1, l1
        )
        out = (d2, l2, over1, over2, stable2, ba, ic, ib)
        if with_pos:
            out = out + (pa,)
        return out

    # same donation contract as _fused_round_fn: drafts/dlens are fresh
    # uploads (numpy masters stay host-side), d2/l2 match their
    # shape/dtype exactly, so XLA aliases input->output in place
    jit_kwargs = {"donate_argnums": (2, 3)} if donate else {}
    if mesh is None:
        return jax.jit(pair_impl, **jit_kwargs)
    from ont_tcrconsensus_tpu.parallel.mesh import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P

    d = P("data")
    d2, d3 = P("data", None), P("data", None, None)
    n_planes = 4 if with_pos else 3
    return jax.jit(shard_map(
        pair_impl, mesh=mesh,
        in_specs=(d2, d, d2, d),
        out_specs=(d2, d, d, d, d) + (d3,) * n_planes,
        check_vma=False,
    ), **jit_kwargs)


def _extend_ends_batch(drafts, dlens, subreads, subread_lens, spans,
                       aligned_dlens):
    """Vectorized :func:`_extend_ends` across the cluster axis.

    Args: drafts (C, W), dlens (C,), subreads (C, S, W), subread_lens (C, S),
    spans (C, S, 4), aligned_dlens (C,). Mutates and returns (drafts, dlens).
    Padded subread rows are excluded naturally: their spans sit far outside
    [0, aligned_dlen] (see the traceback init), so they never count as
    boundary-reaching.
    """
    C, S, W = subreads.shape
    r_start, r_end = spans[:, :, 0], spans[:, :, 1]
    f_start, f_end = spans[:, :, 2], spans[:, :, 3]

    def vote(bases, voters):
        votes = np.stack(
            [((bases == code) & voters).sum(axis=1) for code in range(4)], axis=1
        )
        return votes.sum(axis=1) > 0, votes.argmax(axis=1).astype(np.uint8)

    # left end
    at_left = f_start == 0
    has_more = at_left & (r_start > 0)
    n_at, n_more = at_left.sum(axis=1), has_more.sum(axis=1)
    idx = np.maximum(r_start - 1, 0)
    bases = np.take_along_axis(subreads, idx[:, :, None], axis=2)[:, :, 0]
    have, win = vote(bases, has_more)
    do = (n_at > 0) & (n_more * 2 > n_at) & (dlens < W) & have
    if do.any():
        drafts[do] = np.concatenate(
            [win[do, None], drafts[do, :-1]], axis=1
        )
        dlens[do] += 1

    # right end (spans were computed against the pre-vote draft)
    at_right = f_end == aligned_dlens[:, None]
    has_more = at_right & (r_end < subread_lens)
    n_at, n_more = at_right.sum(axis=1), has_more.sum(axis=1)
    idx = np.minimum(r_end, W - 1)
    bases = np.take_along_axis(subreads, idx[:, :, None], axis=2)[:, :, 0]
    have, win = vote(bases, has_more)
    do = (n_at > 0) & (n_more * 2 > n_at) & (dlens < W) & have
    if do.any():
        drafts[do, dlens[do]] = win[do]
        dlens[do] += 1
    return drafts, dlens


def consensus_clusters_batch(
    subreads: np.ndarray,
    subread_lens: np.ndarray,
    rounds: int = 4,
    band_width: int = POLISH_BAND_WIDTH,
    keep_final_pileup: bool = False,
    keep_pos: bool = True,
    mesh=None,
    force_fused: bool = False,
    donate: bool = False,
) -> tuple[np.ndarray, np.ndarray] | tuple[np.ndarray, np.ndarray, tuple | None]:
    """Batched :func:`consensus_cluster` over C same-shape clusters.

    Args:
      subreads: (C, S, W) uint8 dense codes (0-length rows = padding);
      subread_lens: (C, S).
      keep_final_pileup: also return the last round's device pileup
        ``(base_at, ins_cnt, ins_base, pos_at)`` when it was computed against the FINAL drafts.
        ``keep_pos=False`` returns ``pos_at=None`` and skips its scatter +
        (C,S,W) int32 buffer entirely — the v1/v3 polisher features never
        read it, only the v4 quality channels do (code-review r5)
        (i.e. the loop exited via convergence, so the pre-vote drafts equal
        the returned ones) — the RNN polisher consumes exactly that pileup
        and can skip recomputing it. ``None`` when the loop hit the rounds
        cap still changing.
      mesh: optional jax Mesh — shards the pileup lanes and the vote's
        cluster axis over its ``data`` axis (C must divide the axis size;
        otherwise the call silently runs single-device). VERDICT r2 #3.
      force_fused: run the fused-dispatch path even on plain CPU — the
        parity-test hook for the fused pair program (like force_pallas on
        the pileup side).
      donate: hand the per-round drafts/dlens uploads to XLA via
        ``donate_argnums`` so each round's output drafts reuse the input
        buffer's HBM instead of allocating a second copy (the
        graph-executor donation discipline). Safe because those are
        fresh per-call uploads whose numpy masters stay host-side; the
        cached full-shape read upload (``d_sub_full``) is deliberately
        NEVER donated — it is reused across rounds. Ignored on the CPU
        backend, where XLA does not honor donation and would warn.

    Returns (drafts (C, W), draft_lens (C,)[, final_pileup]). On the fused
    path, rounds run in PAIRS of one device dispatch each
    (:func:`_fused_pair_fn`: vote -> extend -> vote -> extend in-program),
    so the common converge-by-round-2 case pays ONE dispatch + sync; the
    per-cluster host loop only handles seed selection and convergence
    bookkeeping between pairs.
    """
    C, S, W = subreads.shape
    if mesh is not None and C % mesh_data_size(mesh) != 0:
        mesh = None
    subread_lens = np.asarray(subread_lens)
    # vectorized seed pick (lower-median length among real rows, stable):
    # a per-cluster Python loop here was O(C) host work on the lane-scale
    # path (VERDICT r2 weak #7)
    real = subread_lens > 0
    nreal = real.sum(axis=1)
    key = np.where(real, subread_lens, np.iinfo(np.int32).max)
    order = np.argsort(key, axis=1, kind="stable")  # (C, S)
    mid = (np.maximum(nreal, 1) - 1) // 2
    seed = np.take_along_axis(order, mid[:, None], axis=1)[:, 0]  # (C,)
    dlens = np.where(
        nreal > 0, subread_lens[np.arange(C), seed], 0
    ).astype(np.int32)
    pos = np.arange(W, dtype=np.int32)[None, :]
    drafts = np.where(
        pos < dlens[:, None], subreads[np.arange(C), seed], PAD_CODE
    ).astype(np.uint8)

    # Fused rounds (forward+traceback+vote+extend, dispatched in PAIRS) on
    # accelerator or mesh runs — and on plain CPU at production widths,
    # where the scan-log traceback + in-program extension beats the
    # vmapped while_loop pileup 1.69x steady-state ((16,16,2048) clusters,
    # band 64: 4.68s vs 7.91s/batch; the old CPU-stays-unfused heuristic
    # was tuned on small test shapes, which keep the unfused path below
    # the 1024 width floor).
    use_fused = (
        force_fused or mesh is not None
        or jax.default_backend() != "cpu" or W >= 1024
    )
    vote_fn = _vote_columns_batch if mesh is None else _sharded_vote_fn(mesh)
    n_data = mesh_data_size(mesh) if mesh is not None else 1

    # Converged-cluster compaction: the vote is deterministic, so a cluster
    # whose round produced no change is a fixed point — later rounds can
    # skip it exactly. Measured on ONT-rate depth-4..12 clusters, ~94%
    # stabilize by round 2, so round 3+ runs at a fraction of C (pow2
    # sub-batches keep compile shapes bounded, like the tail batches).
    # Per-cluster final pileups (the polisher's reuse path) are gathered the
    # round each cluster converges and scattered into full-size buffers at
    # the end. Compaction needs pow2 sub-batches to divide the mesh axis,
    # so a non-pow2 data axis keeps every alive cluster active instead.
    from ont_tcrconsensus_tpu.io.bucketing import pow2_ceil

    can_compact = mesh is None or (n_data & (n_data - 1)) == 0
    active = np.where(nreal > 0)[0]
    pile_parts: list[tuple[np.ndarray, tuple]] = []
    d_sub_full = d_lens_full = None
    with_pos = keep_final_pileup and keep_pos
    donate = donate and jax.default_backend() != "cpu"
    pair_fn = round_fn = None
    if use_fused:
        if rounds >= 2:
            pair_fn = _fused_pair_fn(band_width, W, S, mesh, with_pos,
                                     donate)
        if rounds % 2:  # odd trailing round keeps the single-round program
            round_fn = _fused_round_fn(band_width, W, S, mesh, with_pos,
                                       donate)

    rounds_left = rounds
    while rounds_left > 0:
        if len(active) == 0:
            break
        # fused path consumes rounds in pairs (one dispatch); the unfused
        # CPU path and an odd trailing fused round consume one at a time
        take = 2 if (use_fused and rounds_left >= 2) else 1
        rounds_left -= take
        Ca = max(pow2_ceil(len(active)), n_data) if can_compact else C
        if Ca >= C:
            # full-size round: reuse the original arrays (and the cached
            # device upload) instead of gathering a same-size copy; the
            # bookkeeping below still tracks only `active` members
            full, Ca, idx = True, C, np.arange(C)
            n_act = C
        else:
            full = False
            n_act = len(active)
            idx = np.concatenate(
                [active, np.zeros(Ca - n_act, np.int64)]
            ) if Ca > n_act else active
        sub_a = subreads if full else subreads[idx]
        lens_a = subread_lens if full else subread_lens[idx]
        drafts_a = drafts if full else drafts[idx]
        dlens_a = dlens if full else dlens[idx]
        # compacted rounds carry exactly `active` in idx[:n_act]; a full
        # round revisits every cluster, so mask the non-active ones out of
        # the convergence/scatter bookkeeping below (padding slots repeat
        # cluster 0 and are excluded the same way)
        if full:
            in_active = np.zeros(C, bool)
            in_active[active] = True
        else:
            in_active = np.ones(n_act, bool)
        if use_fused:
            if full:
                if d_sub_full is None:  # lazy: tail chunks may never run full
                    d_sub_full = jnp.asarray(subreads).reshape(C * S, W)
                    d_lens_full = (
                        jnp.asarray(subread_lens).reshape(C * S).astype(jnp.int32)
                    )
                d_sub, d_lens = d_sub_full, d_lens_full
            else:
                d_sub = jnp.asarray(sub_a).reshape(Ca * S, W)
                d_lens = jnp.asarray(lens_a).reshape(Ca * S).astype(jnp.int32)
        if use_fused and take == 2:
            # TWO rounds in one dispatch; extension/overflow/stability ran
            # in-program, so the sync below is the pair's ONLY round trip
            (new_drafts, new_lens, over1, over2, stable_d,
             base_at, ins_cnt, ins_base, *maybe_pos) = pair_fn(
                d_sub, d_lens, jnp.asarray(drafts_a), jnp.asarray(dlens_a)
            )
            pos_at = maybe_pos[0] if maybe_pos else None
            # blocked-on-device seconds credit the enclosing dispatch
            # frame (polish.dispatch) — the ROADMAP-1 tax split
            new_drafts, new_lens, over1, over2, stable = obs_device.timed_get(
                "consensus.get", (new_drafts, new_lens, over1, over2, stable_d)
            )
            if over1.any() or over2.any():
                raise ValueError("consensus grew past the padded width")
            new_drafts = np.asarray(new_drafts).copy()
            new_lens = np.asarray(new_lens).astype(np.int32).copy()
            stable = np.asarray(stable)[:n_act]
        else:
            if use_fused:
                (new_drafts, new_lens, spans,
                 base_at, ins_cnt, ins_base, *maybe_pos) = round_fn(
                    d_sub, d_lens, jnp.asarray(drafts_a), jnp.asarray(dlens_a)
                )
                pos_at = maybe_pos[0] if maybe_pos else None
            else:
                base_at, ins_cnt, ins_base, pos_at, spans = pileup.pileup_columns_batch_auto(
                    sub_a, lens_a, jnp.asarray(drafts_a), jnp.asarray(dlens_a),
                    band_width=band_width, out_len=W, mesh=mesh,
                )
                new_drafts, new_lens = vote_fn(
                    base_at, ins_cnt, ins_base,
                    jnp.asarray(drafts_a), jnp.asarray(dlens_a),
                )
            # one coalesced device->host transfer (per-array readback pays a
            # flat round-trip each; decisive over a tunneled TPU)
            new_drafts, new_lens, spans = obs_device.timed_get(
                "consensus.get", (new_drafts, new_lens, spans)
            )
            new_drafts = new_drafts[:, :W].copy()
            new_lens = new_lens.astype(np.int32).copy()
            live_a = dlens_a > 0
            if (new_lens[live_a] > W).any():
                raise ValueError("consensus grew past the padded width")
            # empty/padding clusters keep their draft
            new_drafts[~live_a] = drafts_a[~live_a]
            new_lens[~live_a] = dlens_a[~live_a]
            new_drafts, new_lens = _extend_ends_batch(
                new_drafts, new_lens, sub_a, lens_a, spans, dlens_a
            )
            # vote output + extensions keep PAD beyond new_lens by
            # construction, so whole-row equality == content equality up to
            # the lengths
            stable = (
                (new_lens == dlens_a) & (new_drafts == drafts_a).all(axis=1)
            )[:n_act]
        drafts[idx[:n_act]] = new_drafts[:n_act]
        dlens[idx[:n_act]] = new_lens[:n_act]
        newly_stable = stable & in_active
        if keep_final_pileup and newly_stable.any():
            local = jnp.asarray(np.where(newly_stable)[0])
            planes = (base_at, ins_cnt, ins_base) + (
                (pos_at,) if with_pos and pos_at is not None else ()
            )
            pile_parts.append((
                idx[:n_act][newly_stable],
                tuple(jnp.take(p, local, axis=0) for p in planes),
            ))
        active = idx[:n_act][in_active & ~stable]

    converged = len(active) == 0
    if not keep_final_pileup:
        return drafts, dlens
    final_pileup = None
    if converged:
        # scatter each cluster's convergence-round pileup into full-size
        # buffers; clusters never polished (empty) read as fully uncovered,
        # matching what a pileup against an empty draft produces
        buf_ba = jnp.full((C, S, W), pileup.UNCOVERED, jnp.uint8)
        buf_ic = jnp.zeros((C, S, W), jnp.int32)
        buf_ib = jnp.zeros((C, S, W), jnp.uint8)
        buf_pa = jnp.full((C, S, W), -1, jnp.int32) if with_pos else None
        while pile_parts:  # pop-consume so each part frees after scatter
            idxs, (pba, pic, pib, *ppa) = pile_parts.pop(0)
            d_idx = jnp.asarray(idxs)
            buf_ba = buf_ba.at[d_idx].set(pba.astype(buf_ba.dtype))
            buf_ic = buf_ic.at[d_idx].set(pic.astype(buf_ic.dtype))
            buf_ib = buf_ib.at[d_idx].set(pib.astype(buf_ib.dtype))
            if with_pos and ppa:
                buf_pa = buf_pa.at[d_idx].set(ppa[0].astype(buf_pa.dtype))
        final_pileup = (buf_ba, buf_ic, buf_ib, buf_pa)
    return drafts, dlens, final_pileup


@functools.partial(jax.jit, static_argnames=())
def pileup_features(
    base_at: jax.Array, ins_cnt: jax.Array, ins_base: jax.Array,
    draft: jax.Array,
) -> jax.Array:
    """(S, Ld) columns -> (Ld, 15) float32 polisher features.

    Channels: A/C/G/T/del counts (5), per-base inserted-base counts (4 —
    how many subreads report an insertion STARTING with each base after
    this position; the evidence the insertion head needs to call WHICH
    base the draft missed), insertion-reporting count (1), depth (1), all
    log1p-scaled; draft base one-hot (4). Mirrors medaka's counts-matrix
    feature family (its pileup counts encoding incl. insert columns), not
    its exact layout — our polisher is trained in-repo.
    """
    S, Ld = base_at.shape
    covered = base_at != pileup.UNCOVERED
    counts = jnp.stack(
        [jnp.sum(base_at == code, axis=0) for code in range(5)], axis=1
    ).astype(jnp.float32)  # (Ld, 5)
    has_ins = (ins_cnt > 0) & covered
    ins_counts = jnp.stack(
        [jnp.sum(has_ins & (ins_base == code), axis=0) for code in range(4)],
        axis=1,
    ).astype(jnp.float32)  # (Ld, 4)
    ins = jnp.sum(has_ins, axis=0).astype(jnp.float32)[:, None]
    depth = jnp.sum(covered, axis=0).astype(jnp.float32)[:, None]
    draft_oh = jax.nn.one_hot(jnp.minimum(draft[:Ld], 4), 4, dtype=jnp.float32)
    return jnp.concatenate(
        [jnp.log1p(counts), jnp.log1p(ins_counts), jnp.log1p(ins),
         jnp.log1p(depth), draft_oh], axis=1
    )


FEATURE_DIM_V4 = 25
# phred fill when the input carried no qualities (FASTA): mid-range for the
# regimes the model trains on; training applies the same fill on a fraction
# of examples (qual dropout) so serving without quals stays in-distribution
QUAL_FILL = 18


@functools.partial(jax.jit, static_argnames=())
def pileup_features_v4(
    base_at: jax.Array, ins_cnt: jax.Array, ins_base: jax.Array,
    draft: jax.Array, pos_at: jax.Array, quals: jax.Array,
    is_rev: jax.Array,
) -> jax.Array:
    """(S, Ld) columns -> (Ld, 25) float32 polisher-v4 features.

    The medaka capability gap the 15-channel encoding left open (VERDICT r4
    #6): medaka's counts matrix is STRAND-STRATIFIED and its pileups carry
    base qualities; ours collapsed strands and ignored quals. Channels:

    - 0-4   A/C/G/T/del counts from forward-strand subreads (log1p);
    - 5-9   the same from reverse-strand subreads (log1p) — a systematic
            context error hits only one strand (the simulator mutates the
            sequenced strand), so a strand-split disagreement is the
            polisher's strongest correction signal;
    - 10-13 quality-weighted base counts: sum of phred/10 over the subreads
            voting each base (log1p) — a high-qual minority can outweigh a
            low-qual majority, exactly medaka's weighted-counts trick;
    - 14    mean phred/10 over the base votes at this column;
    - 15-18 per-base inserted-base counts (log1p), as v1;
    - 19    insertion-reporting count (log1p); 20 depth (log1p);
    - 21-24 draft base one-hot.

    Args beyond the v1 set: ``pos_at`` (S, Ld) int32 read position of each
    base vote (-1 for deletion/uncovered; from the traceback), ``quals``
    (S, Lr) uint8 phred (ALREADY in canonical orientation: callers reverse
    the qual string of '-' reads alongside the revcomp), ``is_rev`` (S,)
    bool sequenced-strand flags.
    """
    S, Ld = base_at.shape
    covered = base_at != pileup.UNCOVERED
    rev = is_rev.astype(bool)[:, None]  # (S, 1)
    counts_f = jnp.stack(
        [jnp.sum((base_at == code) & ~rev, axis=0) for code in range(5)],
        axis=1,
    ).astype(jnp.float32)  # (Ld, 5)
    counts_r = jnp.stack(
        [jnp.sum((base_at == code) & rev, axis=0) for code in range(5)],
        axis=1,
    ).astype(jnp.float32)  # (Ld, 5)

    has_base = base_at < 4  # a real base vote (not deletion/uncovered)
    q = jnp.take_along_axis(
        quals, jnp.clip(pos_at, 0, quals.shape[1] - 1).astype(jnp.int32),
        axis=1,
    ).astype(jnp.float32) / 10.0
    q = jnp.where(has_base & (pos_at >= 0), q, 0.0)  # (S, Ld)
    qw = jnp.stack(
        [jnp.sum(q * (base_at == code), axis=0) for code in range(4)], axis=1
    )  # (Ld, 4)
    n_base = jnp.sum(has_base & (pos_at >= 0), axis=0).astype(jnp.float32)
    q_mean = (jnp.sum(q, axis=0) / jnp.maximum(n_base, 1.0))[:, None]

    has_ins = (ins_cnt > 0) & covered
    ins_counts = jnp.stack(
        [jnp.sum(has_ins & (ins_base == code), axis=0) for code in range(4)],
        axis=1,
    ).astype(jnp.float32)
    ins = jnp.sum(has_ins, axis=0).astype(jnp.float32)[:, None]
    depth = jnp.sum(covered, axis=0).astype(jnp.float32)[:, None]
    draft_oh = jax.nn.one_hot(jnp.minimum(draft[:Ld], 4), 4, dtype=jnp.float32)
    return jnp.concatenate(
        [jnp.log1p(counts_f), jnp.log1p(counts_r), jnp.log1p(qw), q_mean,
         jnp.log1p(ins_counts), jnp.log1p(ins), jnp.log1p(depth), draft_oh],
        axis=1,
    )
