"""Pallas TPU kernel for the pileup forward pass (direction planes).

Cell-exact equal to :func:`.pileup._forward_banded` (asserted by tests via
the interpreter and the ``-m tpu`` lane), but the row recurrence runs with
its DP carry resident in VMEM instead of round-tripping HBM every scan step
— the same trade :mod:`.sw_pallas` makes for the stats-only kernel. The
direction planes (``tdir``/``fjump``) are emitted row-by-row into one
lane-packed uint8 output block, and the existing XLA ``lax.while_loop``
traceback (:func:`.pileup._traceback_one`) consumes them unchanged.

Layout tricks (see sw_pallas for the pattern):
- drafts are pre-shifted host-side into ``ref_shifted[lane, k] =
  draft[k - W/2]`` so each row's band window is one contiguous slice;
- **full-lane packing**: the VPU's native tile is (8, 128) lanes, so a
  64-lane band leaves half of every vector register idle. The production
  polish band (W=64) therefore packs TWO reads side by side per sublane
  row — read A on lanes [0, 64), read B on [64, 128) — and every band
  shift masks the half boundary so the two bands never leak into each
  other. Per-instruction lane occupancy doubles at the SAME VMEM
  footprint per read (the planes block stays 32 KiB/read-row), which is
  the whole gap the pre-packing kernel left: its (16, 64) arrays occupied
  2 half-empty tiles per op. W=128 degenerates to one read per row
  (pack=1), the old layout exactly;
- both planes share one output ref: per packed row the minor axis holds
  ``[tdir_A | tdir_B | fjump_A | fjump_B]`` (W lanes each), unpacked
  host-side into the (N, L, W) planes the traceback expects;
- the per-slot best (score, earliest row) is tracked in VMEM and the
  sequential tie-break (max score -> earliest row -> smallest slot) is
  reproduced outside the kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ont_tcrconsensus_tpu.ops.pileup import (
    _DIAG,
    _DIAG_STOP_BIT,
    _EGAP,
    _EOPEN_BIT,
    _FRESH,
)
from ont_tcrconsensus_tpu.ops.sw_align import (
    GAP_EXT,
    GAP_OPEN,
    MATCH,
    MISMATCH,
    PAD_SENTINEL,
)

_NEG = -(1 << 24)
BLK = 16   # reads (subread alignments) per program
LANES = 128  # VPU lane tile; pack = LANES // W reads share one sublane row


def _forward_kernel(*refs, L, W, p, match, mismatch, gap_open, gap_ext):
    """``refs``: p read refs, p refsh refs, p rlen refs, p tlen refs, then
    planes/bestH/bestRow outputs. ``p`` reads are packed along the lane
    axis (read k of a row owns lanes [k*W, (k+1)*W))."""
    reads_r = refs[:p]
    refsh_r = refs[p : 2 * p]
    rlen_r = refs[2 * p : 3 * p]
    tlen_r = refs[3 * p : 4 * p]
    planes_ref, bestH_ref, bestRow_ref = refs[4 * p : 4 * p + 3]

    rows = BLK // p
    c = W // 2
    lane = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1)
    band_pos = lane % W                     # slot within each read's band
    half = lane // W                        # which packed read owns the lane
    lane128 = lane  # elem_at's 128-chunk selector (LANES == 128)
    neg = jnp.full((rows, LANES), _NEG, jnp.int32)

    def by_half(vals):
        """(rows, 1) per-read scalars -> (rows, LANES) lane-selected."""
        out = jnp.broadcast_to(vals[0], (rows, LANES))
        for k in range(1, p):
            out = jnp.where(half == k, jnp.broadcast_to(vals[k], (rows, LANES)), out)
        return out

    def elem_at(ref, k):
        base = pl.multiple_of((k // 128) * 128, 128)
        chunk = ref[:, pl.ds(base, 128)].astype(jnp.int32)
        sel = lane128 == (k % 128)
        return jnp.sum(jnp.where(sel, chunk, 0), axis=1, keepdims=True)

    def shift_up(x, fill):
        """Band-slot b <- b+1 within each packed read; fill at each band's
        top slot (the half boundary must not leak read B into read A)."""
        rolled = jnp.concatenate(
            [x[:, 1:], jnp.full((rows, 1), fill, x.dtype)], axis=1
        )
        return jnp.where(band_pos == W - 1, fill, rolled) if p > 1 else rolled

    def shift_right(x, step, fill):
        """Band-slot b <- b-step within each packed read; fill the first
        ``step`` slots of every band."""
        rolled = jnp.concatenate(
            [jnp.full((rows, step), fill, x.dtype), x[:, :-step]], axis=1
        )
        return jnp.where(band_pos < step, fill, rolled) if p > 1 else rolled

    rlen = by_half([r[:] for r in rlen_r])
    tlen = by_half([r[:] for r in tlen_r])

    def row_step(i, carry):
        H, E, bH, bRow, window = carry
        jrow = i - c + band_pos                     # offsets are 0
        valid = (jrow >= 0) & (jrow < tlen) & (i < rlen)
        rbase = by_half([elem_at(r, i) for r in reads_r])
        tbase = window
        is_match = (tbase == rbase) & (rbase < 4) & (tbase < 4)
        sub = jnp.where(is_match, match, -mismatch)
        # advance each packed band's window by one: slot w-1 of read k
        # takes refsh_k[i + W]
        nexts = [elem_at(r, i + W) for r in refsh_r]
        rolled = jnp.concatenate(
            [window[:, 1:],
             jnp.broadcast_to(nexts[-1], (rows, 1)).astype(window.dtype)],
            axis=1,
        )
        window = rolled
        for k in range(p - 1):
            window = jnp.where(
                (band_pos == W - 1) & (half == k),
                jnp.broadcast_to(nexts[k], (rows, LANES)).astype(window.dtype),
                window,
            )

        H_up = shift_up(H, _NEG)
        E_up = shift_up(E, _NEG)
        open_sc = H_up - gap_open - gap_ext
        ext_sc = E_up - gap_ext
        e_open = open_sc >= ext_sc
        E_new = jnp.where(e_open, open_sc, ext_sc)

        fresh_pred = 0 > H
        D = jnp.where(fresh_pred, 0, H) + sub

        # direction planes stay int32 inside the kernel (i1 masks from
        # 32-bit compares cannot relayout onto 8-bit (32,128) tiles); one
        # cast happens at the aligned group store
        tmp = D
        tdir = jnp.where(fresh_pred, _DIAG | _DIAG_STOP_BIT, _DIAG)
        e_better = E_new > tmp
        tmp = jnp.where(e_better, E_new, tmp)
        tdir = jnp.where(e_better, _EGAP, tdir)
        fresh_better = 0 > tmp
        tmp = jnp.where(fresh_better, 0, tmp)
        tdir = jnp.where(fresh_better, _FRESH, tdir)
        tmp = jnp.where(valid, tmp, neg)
        tdir = tdir | jnp.where(e_open, _EOPEN_BIT, 0)

        # F cascade (shift-doubling) with ref-gap run length tracking;
        # shifts are per-band, so the cascade never crosses the half
        # boundary and runs log2(W) passes exactly as unpacked
        g = tmp
        gap = jnp.zeros_like(tmp)
        step = 1
        while step < W:
            cand_g = shift_right(g, step, _NEG) - gap_ext * step
            cand_gap = shift_right(gap, step, 0) + step
            take = cand_g > g
            g = jnp.where(take, cand_g, g)
            gap = jnp.where(take, cand_gap, gap)
            step *= 2
        F = shift_right(g, 1, _NEG) - gap_open - gap_ext
        jump = shift_right(gap, 1, 0) + 1

        take_f = F > tmp
        H_new = jnp.where(valid, jnp.where(take_f, F, tmp), neg)
        fjump = jnp.where(take_f, jump, 0)

        imp = H_new > bH
        bH = jnp.where(imp, H_new, bH)
        bRow = jnp.where(
            imp,
            jnp.broadcast_to(jnp.full((rows, 1), i, jnp.int32), (rows, LANES)),
            bRow,
        )
        E_new = jnp.where(valid, E_new, neg)
        return (H_new, E_new, bH, bRow, window), tdir, fjump

    # Mosaic only allows VMEM stores at statically-aligned sublane offsets,
    # so rows are buffered in registers and written in aligned groups of G.
    G = 8

    def group_body(gi, carry):
        i0 = gi * G
        rows_out = []
        for k in range(G):
            carry, tdir, fjump = row_step(i0 + k, carry)
            rows_out.append(jnp.concatenate([tdir, fjump], axis=1))
        block = jnp.stack(rows_out, axis=1)  # (rows, G, 2*LANES) int32
        planes_ref[:, pl.ds(pl.multiple_of(i0, G), G), :] = block.astype(jnp.uint8)
        return carry

    window0 = jnp.concatenate(
        [r[:, 0:W].astype(jnp.int32) for r in refsh_r], axis=1
    )
    init = (
        neg, neg,
        jnp.zeros((rows, LANES), jnp.int32),
        jnp.full((rows, LANES), -1, jnp.int32),
        window0,
    )
    out = jax.lax.fori_loop(0, L // G, group_body, init)
    bestH_ref[:] = out[2]
    bestRow_ref[:] = out[3]


@functools.partial(
    jax.jit,
    static_argnames=("band_width", "interpret"),
)
def forward_planes_pallas(
    reads: jax.Array,
    read_lens: jax.Array,
    refs: jax.Array,
    ref_lens: jax.Array,
    band_width: int = 64,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Banded forward DP for N lanes; returns (best (N, 3), tdir, fjump).

    Args:
      reads: (N, L) uint8; refs: (N, Lr) uint8 (the draft of each lane's
        cluster); band centered on the main diagonal (offsets 0, the
        same-molecule case the pileup path uses).

    Returns:
      best: (N, 3) int32 rows of (score, row, slot) matching
        :func:`.pileup._forward_banded`'s sequential selection;
      tdir/fjump: (N, L, W) uint8 planes.
    """
    N0, L = reads.shape
    if L % 128:
        raise ValueError(
            f"read width {L} must be a multiple of 128: elem_at() loads "
            "128-aligned lane chunks from the read block, so any ragged "
            "tail sends the last chunk load out of the block "
            "(pad_batch pads to multiples of 128 upstream)"
        )
    if band_width not in (64, 128):
        raise ValueError(
            f"band_width {band_width} unsupported: the kernel's band window "
            "advance assumes a 64- or 128-lane tile"
        )
    W = band_width
    c = W // 2
    p = LANES // W              # reads packed per sublane row (2 at W=64)
    rows = BLK // p
    N = ((N0 + BLK - 1) // BLK) * BLK

    def pad_to(x, n, fill):
        if x.shape[0] == n:
            return x
        pad_shape = (n - x.shape[0],) + x.shape[1:]
        return jnp.concatenate([x, jnp.full(pad_shape, fill, x.dtype)])

    reads_p = pad_to(jnp.asarray(reads), N, PAD_SENTINEL)
    refs_p = pad_to(jnp.asarray(refs), N, PAD_SENTINEL)
    rlens = pad_to(jnp.asarray(read_lens, jnp.int32), N, 0)[:, None]
    tlens = pad_to(jnp.asarray(ref_lens, jnp.int32), N, 0)[:, None]

    # host-side pre-shift: ref_shifted[n, k] = ref[n, k - c]. K is padded to
    # a multiple of 128: elem_at loads aligned 128-column chunks, and a
    # ragged tail would send the last rows' loads out of the block (silently
    # clamped/garbage — wrong band windows for near-full-width drafts).
    K = ((L + W + 127) // 128) * 128
    ks = jnp.arange(K, dtype=jnp.int32)[None, :] - c
    in_range = (ks >= 0) & (ks < refs_p.shape[1])
    ref_shifted = jnp.where(
        jnp.broadcast_to(in_range, (N, K)),
        jnp.take_along_axis(
            refs_p, jnp.broadcast_to(jnp.clip(ks, 0, refs_p.shape[1] - 1), (N, K)),
            axis=1,
        ),
        jnp.uint8(PAD_SENTINEL),
    )

    kernel = functools.partial(
        _forward_kernel, L=L, W=W, p=p, match=MATCH, mismatch=MISMATCH,
        gap_open=GAP_OPEN, gap_ext=GAP_EXT,
    )
    grid = (N // BLK,)
    # packed read k of program g occupies row-block p*g + k of the (N, ...)
    # inputs: rows [16g, 16g+8) are half A, [16g+8, 16g+16) half B
    def row_spec(cols, k):
        return pl.BlockSpec(
            (rows, cols), lambda g, k=k: (p * g + k, 0),
            memory_space=pltpu.VMEM,
        )

    planes_spec = pl.BlockSpec(
        (rows, L, 2 * LANES), lambda g: (g, 0, 0), memory_space=pltpu.VMEM
    )
    best_spec = pl.BlockSpec(
        (rows, LANES), lambda g: (g, 0), memory_space=pltpu.VMEM
    )
    in_specs = (
        [row_spec(L, k) for k in range(p)]
        + [row_spec(K, k) for k in range(p)]
        + [row_spec(1, k) for k in range(p)]
        + [row_spec(1, k) for k in range(p)]
    )
    planes, bestH, bestRow = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[planes_spec, best_spec, best_spec],
        out_shape=[
            jax.ShapeDtypeStruct((N // p, L, 2 * LANES), jnp.uint8),
            jax.ShapeDtypeStruct((N // p, LANES), jnp.int32),
            jax.ShapeDtypeStruct((N // p, LANES), jnp.int32),
        ],
        interpret=interpret,
    )(*([reads_p] * p + [ref_shifted] * p + [rlens] * p + [tlens] * p))

    # unpack the lane-packed halves back to per-read (N, L, W) planes and
    # (N, W) best rows: read r = BLK*g + W_half*rows' ... i.e. row-major
    # (program, half, row) ordering by construction of row_spec
    G_n = N // BLK
    if p > 1:
        planes = planes.reshape(G_n, rows, L, 2, p, W)
        # [..., 0, k, :] = tdir of half k; [..., 1, k, :] = fjump of half k
        planes = jnp.moveaxis(planes, 4, 1)          # (G, p, rows, L, 2, W)
        planes = planes.reshape(N, L, 2, W)
        tdir = planes[:, :, 0, :]
        fjump = planes[:, :, 1, :]
        bh = jnp.moveaxis(bestH.reshape(G_n, rows, p, W), 2, 1).reshape(N, W)
        br = jnp.moveaxis(bestRow.reshape(G_n, rows, p, W), 2, 1).reshape(N, W)
    else:
        tdir = planes[:, :, :W]
        fjump = planes[:, :, W:]
        bh, br = bestH, bestRow

    # sequential tie-break: max score -> earliest row -> smallest slot
    score = jnp.max(bh, axis=1)
    is_max = bh == score[:, None]
    row_or_inf = jnp.where(is_max, br, jnp.int32(1 << 30))
    best_row = jnp.min(row_or_inf, axis=1)
    cand = is_max & (br == best_row[:, None])
    slot = jnp.argmax(cand, axis=1).astype(jnp.int32)
    # _forward_banded reports best0 = (0, -1, 0) when nothing scored > 0
    aligned = score > 0
    best = jnp.stack(
        [
            jnp.where(aligned, score, 0),
            jnp.where(aligned, best_row, -1),
            jnp.where(aligned, slot, 0),
        ],
        axis=1,
    )
    return best[:N0], tdir[:N0], fjump[:N0]
