"""Infix (semi-global) fuzzy pattern matching with IUPAC degeneracy.

TPU-native replacement for ``edlib.align(pattern, window, mode="HW", k=k,
additionalEqualities=<60 IUPAC pairs>)`` used by the reference to locate
degenerate UMI patterns inside fixed-size adapter windows
(/root/reference/ont_tcr_consensus/extract_umis.py:19-107) and, in spirit, by
``dorado trim`` for primer location (preprocessing.py:25-57).

Semantics: find the substring of ``window`` minimizing the Levenshtein
distance to ``pattern``, where a pattern/text base pair matches iff their
4-bit IUPAC masks intersect (see :mod:`..ops.encode`). Deterministic
tie-breaking (documented; the reference inherits edlib's undocumented one):

- among optimal end positions, the smallest end is chosen;
- among optimal start positions for that end, the smallest start is chosen.

Algorithm: anti-dependency-free column DP. The text axis is a ``lax.scan``;
inside a column the insertion cascade ``D[i][j] = min_l<=i (tmp[l] + i - l)``
is a min-plus prefix scan computed as ``i + cummin(tmp - i)`` — no scalar
loops, fully vectorized over (batch, pattern) on the VPU. A second scan on the
reversed prefix recovers the match start exactly. Work per read window is
O(L * m) with L ~ 128 and m ~ 32, vmapped over the batch and shardable over a
mesh data axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

BIG = 1 << 20  # plain int: promoted inside traced code; a jnp constant
#               here would initialize the XLA backend at import time,
#               breaking jax.distributed.initialize for importers


def _column_step(col, text_char, pattern_mask):
    """One DP column update for semi-global (free text start) alignment.

    col: (m+1,) int32 previous column; text_char: scalar uint8 mask;
    pattern_mask: (m,) uint8. Returns new column (m+1,).
    """
    sub = jnp.where((pattern_mask & text_char) != 0, 0, 1).astype(jnp.int32)
    diag = col[:-1] + sub
    up = col[1:] + 1
    tmp = jnp.minimum(diag, up)
    base = jnp.concatenate([jnp.zeros((1,), jnp.int32), tmp])
    idx = jnp.arange(base.shape[0], dtype=jnp.int32)
    cascaded = idx + jax.lax.associative_scan(jnp.minimum, base - idx)
    return jnp.minimum(base, cascaded)


def _final_row(pattern_mask: jax.Array, window: jax.Array,
               pattern_len: jax.Array | None = None) -> jax.Array:
    """Distance of pattern vs best substring ending at each text position.

    Returns (L+1,) int32: entry j = min edit distance over substrings of
    window[:j] that end exactly at j (0 = empty prefix => distance m).

    ``pattern_len`` supports padded patterns (mask rows past the true length
    are ignored): DP row i only reads rows <= i, so reading the final row at
    ``pattern_len`` instead of m is exact.
    """
    m = pattern_mask.shape[0]
    p_len = jnp.int32(m) if pattern_len is None else pattern_len.astype(jnp.int32)
    init = jnp.arange(m + 1, dtype=jnp.int32)

    def step(col, ch):
        new = _column_step(col, ch, pattern_mask)
        return new, new[p_len]

    _, tail = jax.lax.scan(step, init, window)
    return jnp.concatenate([p_len[None], tail])


def _find_one(pattern_mask, rev_pattern_mask, window, window_len,
              pattern_len=None):
    """(dist, start, end_exclusive) for one window.

    An empty window yields dist=m (the whole pattern deleted) — always above
    any sane k threshold, so callers' ``dist <= k`` gate rejects it.
    """
    L = window.shape[0]
    row = _final_row(pattern_mask, window, pattern_len)  # (L+1,)
    j = jnp.arange(L + 1, dtype=jnp.int32)
    valid = j <= window_len
    masked = jnp.where(valid, row, BIG)
    dist = jnp.min(masked)
    end = jnp.argmin(masked).astype(jnp.int32)  # first minimum => smallest end

    # Recover the smallest start for this end: align the reversed pattern
    # against the reversed window prefix [0, end); the largest reversed end
    # position j2 with distance == dist gives start = end - j2.
    r = jnp.arange(L, dtype=jnp.int32)
    src = jnp.clip(end - 1 - r, 0, L - 1)
    rev_prefix = jnp.where(r < end, window[src], jnp.uint8(0))
    rrow = _final_row(rev_pattern_mask, rev_prefix, pattern_len)
    rvalid = j <= end
    hits = rvalid & (rrow == dist)
    j2 = jnp.max(jnp.where(hits, j, -1))
    start = end - j2
    return dist, start, end


@jax.jit
def fuzzy_find_multi(
    pattern_masks: jax.Array,
    pattern_lens: jax.Array,
    windows: jax.Array,
    window_lens: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Multi-pattern batched infix fuzzy match — ONE device dispatch.

    Args:
      pattern_masks: (P, m) uint8 IUPAC masks, zero-padded past each true
        length; pattern_lens: (P,) int32.
      windows: (B, L) uint8 mask windows; window_lens: (B,) int32.

    Returns (dist, start, end), each (P, B) int32 — the per-pattern results
    of :func:`fuzzy_find`. Stacking patterns widens the per-step DP tensor
    instead of multiplying dispatches: the scan is latency-bound at
    realistic (B, m), so P patterns cost ~the same wall time as one.
    """
    m = pattern_masks.shape[1]
    idx = jnp.arange(m, dtype=jnp.int32)
    p_lens = pattern_lens.astype(jnp.int32)
    # reverse each pattern within its true length (padding stays at the tail)
    src = jnp.clip(p_lens[:, None] - 1 - idx[None, :], 0, m - 1)
    revs = jnp.where(
        idx[None, :] < p_lens[:, None],
        jnp.take_along_axis(pattern_masks, src, axis=1),
        jnp.uint8(0),
    )

    def one_pattern(pm, rev, p_len):
        return jax.vmap(lambda w, n: _find_one(pm, rev, w, n, p_len))(
            windows, window_lens.astype(jnp.int32)
        )

    return jax.vmap(one_pattern)(pattern_masks, revs, p_lens)


@jax.jit
def fuzzy_find(
    pattern_mask: jax.Array,
    windows: jax.Array,
    window_lens: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Batched infix fuzzy match.

    Args:
      pattern_mask: (m,) uint8 IUPAC masks of the pattern.
      windows: (B, L) uint8 IUPAC masks of text windows (0 = padding).
      window_lens: (B,) int32 true window lengths.

    Returns:
      (dist, start, end): each (B,) int32. ``dist`` is the optimal edit
      distance (compare against k on the caller side, mirroring edlib's
      ``editDistance == -1`` contract); the match is ``window[start:end]``.
    """
    rev = pattern_mask[::-1]
    return jax.vmap(lambda w, n: _find_one(pattern_mask, rev, w, n))(
        windows, window_lens.astype(jnp.int32)
    )


def fuzzy_find_np(pattern: str, text: str):
    """Pure-python reference with identical tie-breaking (for tests/debug)."""
    import numpy as np

    from ont_tcrconsensus_tpu.ops import encode

    p = encode.encode_mask(pattern)
    t = encode.encode_mask(text)
    m, n = len(p), len(t)
    D = np.zeros((m + 1, n + 1), dtype=np.int64)
    D[:, 0] = np.arange(m + 1)
    for jj in range(1, n + 1):
        for ii in range(1, m + 1):
            sub = 0 if (p[ii - 1] & t[jj - 1]) else 1
            D[ii, jj] = min(D[ii - 1, jj - 1] + sub, D[ii - 1, jj] + 1, D[ii, jj - 1] + 1)
        D[0, jj] = 0
    dist = int(D[m].min())
    end = int(D[m].argmin())
    starts = [
        s
        for s in range(end + 1)
        if _lev_np(p, t[s:end]) == dist
    ]
    return dist, min(starts), end


def _lev_np(pmask, tmask):
    import numpy as np

    m, n = len(pmask), len(tmask)
    D = np.zeros((m + 1, n + 1), dtype=np.int64)
    D[:, 0] = np.arange(m + 1)
    D[0, :] = np.arange(n + 1)
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            sub = 0 if (pmask[i - 1] & tmask[j - 1]) else 1
            D[i, j] = min(D[i - 1, j - 1] + sub, D[i - 1, j] + 1, D[i, j - 1] + 1)
    return int(D[m, n])
