"""Failure classification + bounded retry policy + the robustness report.

On a real TPU fleet three failure families reach the pipeline's dispatch
sites, and they want three different answers:

- **transient** device/transport faults (XLA ``UNAVAILABLE`` /
  ``DEADLINE_EXCEEDED`` / ``ABORTED``, dropped tunnel connections, torn
  RPCs): retry the same dispatch with bounded exponential backoff — the
  work is deterministic, so a successful retry is byte-identical.
- **oom** (``RESOURCE_EXHAUSTED``, HBM exhaustion): retrying the same
  shape fails forever; the caller must shrink the batch (re-enter
  parallel/budget.py with a smaller budget) and retry the smaller shape.
- **device_lost** (``DEVICE_LOST`` — a mesh slice died mid-dispatch):
  neither retrying the same mesh nor shrinking the batch can succeed —
  the fault escalates to the graph executor, which shrinks the data
  axis to the surviving slices, recomputes the HBM allowance, and
  re-dispatches the node on the degraded mesh (recorded as a
  ``mesh.degraded`` event).
- **fatal** (everything else — a deterministic bug): never retry; fall
  through to the existing skip-and-report degradation immediately.

Every classify/retry/degrade decision is recorded by the process-wide
:class:`RobustnessRecorder` and written to ``robustness_report.json`` next
to the run's other QC artifacts, so "the pipeline recovered" is an
auditable claim, not a log line that scrolled away.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import threading
import time

from ont_tcrconsensus_tpu.obs import trace
from ont_tcrconsensus_tpu.robustness import faults, jobscope, watchdog

#: substrings marking an exception as HBM/host memory exhaustion. Checked
#: BEFORE the transient markers: XLA OOM messages often also mention the
#: allocator/transfer machinery.
OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "resource_exhausted",
    "out of memory",
    "Out of memory",
    "OOM",
    "hbm",
    "HBM",
)

#: substrings marking an exception as the loss of a mesh slice/device.
#: Checked BEFORE both other marker sets: a dead device's message may also
#: mention the allocator or the transport, but the device being gone is
#: the binding fact — neither a same-shape retry nor a smaller batch can
#: ever land on it again.
DEVICE_LOST_MARKERS = (
    "DEVICE_LOST",
    "device_lost",
    "Device lost",
    "device halted",
)

#: substrings marking an exception as a retryable device/transport fault
TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "CANCELLED",
    "connection reset",
    "Connection reset",
    "socket closed",
    "Socket closed",
    "transfer to device",
    "device to host",
    "premature end of",
)


def classify(exc: BaseException) -> str:
    """``"transient" | "oom" | "device_lost" | "fatal"`` for an exception
    from a dispatch site. Unknown exceptions are fatal: retrying a
    deterministic bug only burns the retry budget and delays the
    skip-and-report degradation."""
    if isinstance(exc, faults.DeviceLostChaosError):
        return "device_lost"
    if isinstance(exc, faults.OomChaosError) or isinstance(exc, MemoryError):
        return "oom"
    if isinstance(exc, faults.TransientChaosError):
        return "transient"
    if isinstance(exc, watchdog.StageTimeout):
        # a watchdog-cancelled stall: retrying the dispatch is exactly the
        # MapReduce straggler answer (the message also carries the
        # DEADLINE_EXCEEDED marker, but the isinstance is authoritative)
        return "transient"
    if isinstance(exc, (ConnectionError, TimeoutError, BrokenPipeError)):
        return "transient"
    msg = f"{type(exc).__name__}: {exc}"
    if any(m in msg for m in DEVICE_LOST_MARKERS):
        return "device_lost"
    if any(m in msg for m in OOM_MARKERS):
        return "oom"
    if any(m in msg for m in TRANSIENT_MARKERS):
        return "transient"
    return "fatal"


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff with deterministic seeded jitter.

    ``max_attempts`` counts the first try: 3 means one dispatch plus at
    most two retries. Jitter decorrelates a fleet of workers retrying the
    same stalled service, but stays a pure function of ``(seed, attempt)``
    so a replayed run waits identically.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.1
    max_delay_s: float = 5.0
    jitter: float = 0.25
    seed: int = 0

    def delay(self, attempt: int) -> float:
        """Seconds to wait after failed attempt ``attempt`` (1-based)."""
        d = min(self.base_delay_s * (2.0 ** (attempt - 1)), self.max_delay_s)
        if self.jitter:
            rng = random.Random(f"{self.seed}:{attempt}")
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return d


class RobustnessRecorder:
    """Per-site attempt/outcome counters + the event log behind
    ``robustness_report.json``. Thread-safe: overlapped QC commits and the
    polish chunk loop record concurrently."""

    def __init__(self):
        self._lock = threading.Lock()
        self.events: list[dict] = []

    def reset(self) -> None:
        with self._lock:
            self.events = []

    def record(self, site: str, *, classification: str, outcome: str,
               attempt: int = 1, error: str = "", detail: dict | None = None) -> None:
        ev = {
            "site": site,
            "attempt": attempt,
            "classification": classification,
            "outcome": outcome,
            # every event carries BOTH clocks: t_wall for humans/cross-run
            # correlation, t_mono to place the event exactly on the
            # monotonic trace.json timeline (obs/trace.py maps monotonic
            # seconds onto trace microseconds)
            "t_wall": round(time.time(), 6),
            "t_mono": round(time.monotonic(), 6),
        }
        if error:
            ev["error"] = error
        if detail:
            ev["detail"] = detail
        with self._lock:
            self.events.append(ev)
        # mirrored onto the trace timeline as an instant event (free no-op
        # below `telemetry: full`): retries, stalls, contract violations
        # and quarantine hits land on the same ruler as the stage spans
        trace.instant(site, args={
            "classification": classification, "outcome": outcome,
            "attempt": attempt,
        })

    def summary(self) -> dict:
        """{site: {attempts, by_classification, by_outcome}} aggregates."""
        out: dict[str, dict] = {}
        with self._lock:
            events = list(self.events)
        for ev in events:
            s = out.setdefault(ev["site"], {
                "events": 0, "by_classification": {}, "by_outcome": {},
            })
            s["events"] += 1
            for key, field in (("by_classification", "classification"),
                               ("by_outcome", "outcome")):
                v = ev[field]
                s[key][v] = s[key].get(v, 0) + 1
        return out

    def write(self, path: str, policy: "RetryPolicy | None" = None,
              contracts: dict | None = None) -> None:
        with self._lock:
            events = list(self.events)
        report = {
            "policy": dataclasses.asdict(policy) if policy is not None else None,
            "chaos": faults.describe(),
            # conservation-contract counters (robustness/contracts.py): a
            # top-level summary, NOT events — only actual violations appear
            # in sites/events, so a clean run's event log stays empty
            "contracts": contracts,
            "sites": self.summary(),
            "events": events,
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(report, fh, indent=1)
        os.replace(tmp, path)


# process-wide active policy/recorder: the deep dispatch sites (stages.py
# chunk loops, overlap commits) reach them without signature plumbing;
# run.py swaps in the config-derived policy at run start. Under a jobscope
# (the slice-packed runner pool) each resident tenant job binds its OWN
# recorder/policy thread-locally so concurrent runs never clobber each
# other's robustness events — the first scoped access creates the scoped
# recorder, and child stage workers adopt the same store by reference.
_RECORDER = RobustnessRecorder()
_POLICY = RetryPolicy()


def _active_recorder() -> RobustnessRecorder:
    if jobscope.active():
        rec = jobscope.get("retry_recorder")
        if rec is None:
            rec = RobustnessRecorder()
            jobscope.set("retry_recorder", rec)
        return rec
    return _RECORDER


def _active_policy() -> RetryPolicy:
    pol = jobscope.get("retry_policy")
    if pol is not None:
        return pol
    return _POLICY


def recorder() -> RobustnessRecorder:
    return _active_recorder()


def policy() -> RetryPolicy:
    return _active_policy()


def set_policy(p: RetryPolicy) -> RetryPolicy:
    global _POLICY
    if jobscope.active():
        jobscope.set("retry_policy", p)
        return p
    _POLICY = p
    return p


def call_with_retry(site: str, fn, *, policy: RetryPolicy | None = None,
                    recorder: RobustnessRecorder | None = None,
                    sleep=time.sleep, reset=None):
    """Run ``fn()`` under the transient-retry policy.

    ONLY transient failures back off and retry (up to
    ``policy.max_attempts`` total attempts). Fatal failures raise
    immediately, and so do oom failures: these call sites have no
    shrinkable batch, so re-dispatching the identical shape into an
    exhausted HBM is guaranteed to fail again — the caller's degradation
    path (skip/fallback) is the right answer, not burned retries (sites
    WITH a shrinkable batch, like the polish chunk loop, run their own
    shrink-and-requeue loop instead). ``reset`` runs before every retry so
    the callable can clear partial side effects (e.g. a half-filled QC row
    list). The last failure re-raises when the budget is exhausted —
    callers keep their existing degradation paths.
    """
    pol = policy if policy is not None else _active_policy()
    rec = recorder if recorder is not None else _active_recorder()
    attempt = 1
    while True:
        try:
            result = fn()
        except Exception as exc:
            cls = classify(exc)
            if cls != "transient" or attempt >= pol.max_attempts:
                rec.record(site, classification=cls,
                           outcome=("fatal" if cls == "fatal"
                                    else "not_retryable" if cls == "oom"
                                    else "escalated" if cls == "device_lost"
                                    else "exhausted"),
                           attempt=attempt, error=repr(exc))
                raise
            rec.record(site, classification=cls, outcome="retried",
                       attempt=attempt, error=repr(exc))
            sleep(pol.delay(attempt))
            attempt += 1
            if reset is not None:
                reset()
        else:
            if attempt > 1:
                rec.record(site, classification="transient",
                           outcome="recovered", attempt=attempt)
            return result
