"""Liveness watchdog: per-stage soft/hard deadlines over a heartbeat API.

PR 2/3 hardened every *loud* failure family (transients, OOM, kills,
preemption, corrupt input) — but a production jax_graft service also dies
quietly: a hung XLA dispatch, a stalled overlapped worker, a prefetch
thread wedged on a dead filesystem. Nothing raises, the run just stops.
This module is the MapReduce-style straggler/hang detector for that
failure family (cf. MegaScale's hang diagnosis, PAPERS.md):

- Long-running loops call :func:`heartbeat` (one module-attribute check
  when disarmed — the same discipline as ``faults.inject``). A heartbeat
  resets the watched stage's stall clock, so steady progress never fires
  regardless of total stage length.
- Stage scopes register via :func:`guard` (a context manager). Deadlines
  derive from the config base (``stage_timeout_s``) through
  :func:`scaled_timeout`, so a 10x workload gets a 10x deadline instead
  of a spurious cancel.
- **Soft deadline** (``SOFT_FRACTION`` of the hard deadline) expiry emits
  a ``watchdog.stall`` event into ``robustness_report.json`` and writes a
  faulthandler all-thread stack dump to the library log — the post-hoc
  diagnosis artifact for a wedged run.
- **Hard deadline** expiry cancels the stage: the monitor delivers
  :class:`StageTimeout` into the stalled thread via
  ``PyThreadState_SetAsyncExc``. The exception carries the
  ``DEADLINE_EXCEEDED`` marker, so the existing retry classifier
  (robustness/retry.py) treats it as a TRANSIENT fault and the stage
  re-enters the bounded retry / degrade path instead of hanging the run.
  The stall clock resets at cancel, so the retry gets a fresh deadline.

Honest limitation: an async exception is delivered between Python
bytecodes. A thread stalled in a Python loop (the common case for host
logic — and what the ``stall`` chaos kind simulates) is cancelled
promptly; a thread wedged inside one long C call (a truly hung XLA
dispatch — the ``hang`` chaos kind) is *detected* and *diagnosed* on
time (stall event + stack dump), but the cancel only lands when the call
returns. There is no portable way to interrupt arbitrary C from Python;
the dump is exactly what an operator needs to kill and resume.
"""

from __future__ import annotations

import contextlib
import ctypes
import faulthandler
import os
import sys
import threading
import time

from ont_tcrconsensus_tpu.robustness import jobscope, lockcheck

#: soft deadline (stall REPORT) as a fraction of the hard deadline (CANCEL)
SOFT_FRACTION = 0.5

#: workload units one ``stage_timeout_s`` base covers; larger workloads
#: scale the deadline linearly (see :func:`scaled_timeout`)
UNITS_PER_BASE = 1000


class StageTimeout(RuntimeError):
    """A stage exceeded its hard deadline and was cancelled.

    The default message carries ``DEADLINE_EXCEEDED`` so
    ``retry.classify`` marks it transient even when the instance is
    constructed argument-less by the async-exc machinery (which can only
    deliver a TYPE, not an instance).
    """

    def __init__(self, message: str = "DEADLINE_EXCEEDED: stage hard "
                 "deadline expired (watchdog cancelled a stalled stage)"):
        super().__init__(message)


def scaled_timeout(base_s: float, units: int = 0,
                   units_per_base: int = UNITS_PER_BASE) -> float:
    """Hard deadline for a stage processing ``units`` work items.

    The configured base covers up to ``units_per_base`` units (and all
    fixed overhead — compiles, cache warmup), so tiny workloads keep the
    full base as headroom; beyond that the deadline scales linearly.
    Monotone in ``units``, never below ``base_s``.
    """
    if units <= units_per_base:
        return float(base_s)
    return float(base_s) * (units / float(units_per_base))


class _StageEntry:
    """One guarded stage scope on one thread."""

    __slots__ = ("name", "ident", "thread_name", "hard_s", "soft_s",
                 "last_beat", "last_site", "soft_fired", "cancel_count",
                 "prev")

    def __init__(self, name: str, ident: int, thread_name: str,
                 hard_s: float, soft_s: float, prev: "_StageEntry | None"):
        self.name = name
        self.ident = ident
        self.thread_name = thread_name
        self.hard_s = hard_s
        self.soft_s = soft_s
        self.last_beat = time.monotonic()
        self.last_site = ""
        self.soft_fired = False
        self.cancel_count = 0
        self.prev = prev


def _async_raise(ident: int, exc_type: type | None) -> int:
    """Queue ``exc_type`` (or clear the pending exception with ``None``)
    on the thread with id ``ident``; returns the number of threads hit."""
    return ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(ident),
        ctypes.py_object(exc_type) if exc_type is not None else None,
    )


class Watchdog:
    """Monitor thread + per-thread stage registry behind :func:`guard`."""

    def __init__(self, base_timeout_s: float,
                 soft_fraction: float = SOFT_FRACTION,
                 tick_s: float | None = None,
                 log_path: str | None = None):
        self.base_timeout_s = float(base_timeout_s)
        self.soft_fraction = soft_fraction
        # tick fast enough to resolve the shortest plausible deadline
        # (tests run with seconds-scale bases), slow enough to be free
        self.tick_s = tick_s if tick_s is not None else max(
            0.05, min(0.5, self.base_timeout_s / 16.0)
        )
        self.log_path = log_path
        self._entries: dict[int, _StageEntry] = {}
        self._lock = lockcheck.make_lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # --- stage registration -------------------------------------------------

    @contextlib.contextmanager
    def guard(self, name: str, units: int = 0):
        """Register the calling thread's stage scope for the monitor.

        The hard deadline is ``scaled_timeout(base, units)`` measured from
        the LAST heartbeat (not stage start); the soft deadline is
        ``soft_fraction`` of it. Nested guards stack per thread.
        """
        ident = threading.get_ident()
        hard = scaled_timeout(self.base_timeout_s, units)
        with self._lock:
            entry = _StageEntry(
                name, ident, threading.current_thread().name,
                hard, hard * self.soft_fraction, self._entries.get(ident),
            )
            self._entries[ident] = entry
        try:
            yield entry
        finally:
            # an async StageTimeout can land while THIS cleanup runs (the
            # stage completed right as the monitor fired, before the lock
            # below was acquired): catch it and redo the cleanup — the
            # entry MUST come off the registry, or the monitor would keep
            # cancelling this thread in unrelated code forever. A cancel
            # that lands here is swallowed on purpose: the stage body
            # already finished its work.
            while True:
                try:
                    with self._lock:
                        if self._entries.get(ident) is entry:
                            if entry.prev is not None:
                                # the outer scope's clock was frozen while
                                # the inner guard was registered: restart
                                # it NOW, or the first monitor tick would
                                # see the whole inner stage's duration as
                                # an outer stall and cancel a healthy scope
                                entry.prev.last_beat = time.monotonic()
                                entry.prev.soft_fired = False
                                self._entries[ident] = entry.prev
                            else:
                                del self._entries[ident]
                        if entry.cancel_count:
                            # a cancel was issued for this scope: if its
                            # async exc was never delivered (the thread sat
                            # in C code until the stage completed anyway),
                            # clear it so it cannot land in unrelated code
                            # later. No-op when it already surfaced.
                            _async_raise(ident, None)
                    break
                except StageTimeout:
                    continue

    def beat(self, site: str) -> None:
        # under the registry lock: _on_hard's staleness recheck + delivery
        # run under the same lock, so a heartbeat can never land between
        # the recheck and the cancel — a stage that just made progress is
        # genuinely safe, not just probabilistically
        with self._lock:
            entry = self._entries.get(threading.get_ident())
            if entry is not None:
                entry.last_beat = time.monotonic()
                entry.last_site = site
                # progress resumed: re-arm the soft report so a SECOND
                # stall in this scope is diagnosed (event + dump) again,
                # not only at its hard cancel
                entry.soft_fired = False

    def current_deadline_s(self) -> float | None:
        entry = self._entries.get(threading.get_ident())
        return entry.hard_s if entry is not None else None

    def entries_snapshot(self) -> list[dict]:
        """Per-stage heartbeat ages for the live plane (/healthz verdict,
        /metrics gauges): one locked pass, age measured against a single
        clock read so the staleness comparison is self-consistent."""
        now = time.monotonic()
        with self._lock:
            return [
                {
                    "stage": e.name,
                    "thread": e.thread_name,
                    "heartbeat_age_s": round(now - e.last_beat, 3),
                    "soft_deadline_s": round(e.soft_s, 3),
                    "hard_deadline_s": round(e.hard_s, 3),
                    "last_heartbeat_site": e.last_site,
                    "soft_fired": e.soft_fired,
                }
                for e in self._entries.values()
            ]

    # --- monitor ------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._monitor, name="stage-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _monitor(self) -> None:
        while not self._stop.wait(self.tick_s):
            with self._lock:
                entries = list(self._entries.values())
            for entry in entries:
                # fresh clock read per entry: an earlier entry's synchronous
                # stack-dump I/O in this same tick must not widen a later
                # entry's apparent stall
                stalled = time.monotonic() - entry.last_beat
                if stalled >= entry.soft_s and not entry.soft_fired:
                    entry.soft_fired = True
                    self._on_soft(entry, stalled)
                if stalled >= entry.hard_s:
                    self._on_hard(entry, stalled)

    def _record(self, outcome: str, entry: _StageEntry, stalled: float) -> None:
        from ont_tcrconsensus_tpu.robustness import retry

        retry.recorder().record(
            "watchdog.stall", classification="stall", outcome=outcome,
            detail={
                "stage": entry.name,
                "thread": entry.thread_name,
                "stalled_s": round(stalled, 3),
                "soft_deadline_s": round(entry.soft_s, 3),
                "hard_deadline_s": round(entry.hard_s, 3),
                "last_heartbeat_site": entry.last_site,
            },
        )

    def _dump_stacks(self, header: str) -> None:
        """All-thread faulthandler dump to the library log (post-hoc
        diagnosis for a wedged run) and a one-line notice to stderr."""
        sys.stderr.write(header + "\n")
        if not self.log_path:
            faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
            return
        try:
            with open(self.log_path, "a") as fh:
                fh.write(f"{header} (unix time {time.time():.1f})\n")
                faulthandler.dump_traceback(file=fh, all_threads=True)
                fh.write("\n")
        except OSError as exc:  # diagnosis must never kill the monitor
            sys.stderr.write(f"watchdog: cannot write {self.log_path}: {exc!r}\n")
            faulthandler.dump_traceback(file=sys.stderr, all_threads=True)

    def _on_soft(self, entry: _StageEntry, stalled: float) -> None:
        self._record("stall_detected", entry, stalled)
        self._dump_stacks(
            f"watchdog: stage {entry.name!r} ({entry.thread_name}) has not "
            f"heartbeat for {stalled:.1f}s (soft deadline "
            f"{entry.soft_s:.1f}s, hard {entry.hard_s:.1f}s; last site "
            f"{entry.last_site or '<none>'}) — dumping all thread stacks"
        )

    def _on_hard(self, entry: _StageEntry, stalled: float) -> None:
        # the send happens under the registry lock, mutually exclusive with
        # the guard's unregister: a cancel can never target a scope that
        # already exited (the async exc would land in unrelated code)
        with self._lock:
            if self._entries.get(entry.ident) is not entry:
                return
            # recheck staleness under the lock: a heartbeat may have landed
            # since the monitor's snapshot — cancelling a stage that just
            # made progress would discard completed work and burn a retry
            stalled = time.monotonic() - entry.last_beat
            if stalled < entry.hard_s:
                return
            entry.cancel_count += 1
            # reset the stall clock BEFORE delivering: the retry attempt
            # that catches the StageTimeout runs inside the same guard
            # scope and must start with a fresh deadline, and soft_fired
            # re-arms so a second stall is reported again
            entry.last_beat = time.monotonic()
            entry.soft_fired = False
            _async_raise(entry.ident, StageTimeout)
        self._record("hard_cancel", entry, stalled)
        self._dump_stacks(
            f"watchdog: stage {entry.name!r} exceeded its hard deadline "
            f"({stalled:.1f}s > {entry.hard_s:.1f}s); cancelled "
            f"(StageTimeout -> the transient retry/degrade path)"
        )
        # hard expiry is a likely prelude to a dead run: flush the flight
        # recorder NOW (obs/live.py sink) while the process still can.
        # Best-effort — a flush failure must never kill the monitor.
        sink = _EXPIRY_SINK
        if sink is not None:
            try:
                sink(entry.name)
            except Exception as exc:
                sys.stderr.write(
                    f"watchdog: expiry sink failed: {exc!r}\n")


# Lock ownership for Watchdog._entries (-> _lock) is declared in the
# consolidated registry (ont_tcrconsensus_tpu/robustness/locks.py)
# consumed by graftlint's lock-discipline rule and graftrace.


# --- process-wide active watchdog (same discipline as faults/retry) ---------
#
# Under a jobscope (slice-packed runner pool) each resident tenant job
# binds its own watchdog thread-locally: two concurrent runs each get
# their own monitor with their own deadlines, and neither run's
# activate/deactivate perturbs the other. The scoped entry is a
# ``(wd,)`` 1-tuple so an in-scope deactivate tombstones (the scoped
# thread must NOT fall back to some other run's global watchdog).

_ACTIVE: Watchdog | None = None


def _current() -> Watchdog | None:
    entry = jobscope.get("watchdog")
    if entry is not None:
        return entry[0]
    return _ACTIVE


def activate(wd: Watchdog) -> Watchdog:
    global _ACTIVE
    if jobscope.active():
        jobscope.set("watchdog", (wd,))
        return wd
    _ACTIVE = wd
    return wd


def deactivate(wd: Watchdog | None = None) -> None:
    global _ACTIVE
    if jobscope.active():
        entry = jobscope.get("watchdog")
        if entry is not None and (wd is None or entry[0] is wd):
            jobscope.set("watchdog", (None,))
        return
    if wd is None or _ACTIVE is wd:
        _ACTIVE = None


def active() -> bool:
    return _current() is not None


def heartbeat(site: str) -> None:
    """Reset the calling thread's stage stall clock; free no-op when the
    watchdog is disarmed or the thread holds no guard. Independently, a
    live-plane beat sink (obs/live.py flight recorder) sees every beat —
    heartbeats are progress evidence worth keeping post-mortem even on
    runs where the watchdog itself is disarmed."""
    wd = _current()
    if wd is not None:
        wd.beat(site)
    sink = _BEAT_SINK
    if sink is not None:
        sink(site)


def guard(name: str, units: int = 0):
    """Stage scope context manager; ``nullcontext`` when disarmed."""
    wd = _current()
    if wd is None:
        return contextlib.nullcontext()
    return wd.guard(name, units)


def active_deadline_s() -> float | None:
    """The calling thread's current hard deadline (None when unguarded /
    disarmed) — the chaos ``hang`` kind sizes its wedge from this."""
    wd = _current()
    return wd.current_deadline_s() if wd is not None else None


def set_log_path(path: str | os.PathLike[str]) -> None:
    """Point stall stack dumps at the current library's log file."""
    wd = _current()
    if wd is not None:
        wd.log_path = os.fspath(path)


def snapshot() -> list[dict] | None:
    """Per-stage heartbeat ages (None when the watchdog is disarmed) —
    the live plane's /healthz staleness verdict and /metrics gauges."""
    wd = _current()
    return wd.entries_snapshot() if wd is not None else None


# --- live-plane sinks (obs/live.py; same one-attr-check discipline) ---------

_BEAT_SINK = None
_EXPIRY_SINK = None


def set_beat_sink(sink) -> None:
    """Install/remove a callable(site) fed every heartbeat (flight ring)."""
    global _BEAT_SINK
    _BEAT_SINK = sink


def set_expiry_sink(sink) -> None:
    """Install/remove a callable(stage) fired after a hard-deadline
    cancel (the flight recorder's crash-prelude flush trigger)."""
    global _EXPIRY_SINK
    _EXPIRY_SINK = sink
