"""Stage-boundary conservation contracts (runtime accounting self-checks).

The counts contract (BASELINE.md: UMI counts bit-identical to the CPU
pipeline) had no runtime self-check that reads are actually conserved
across the rescue/skip/degrade branches the robustness layer added. This
module adds cheap invariant checks at every stage boundary:

- **ingest**: records parsed == reads entering the device pass + reads
  dropped by the length buckets (+ quarantined records, counted upstream)
- **assign**: the fused-pass filter categories partition the batch total,
  and the columnar store holds exactly the passing reads
- **umi**: per-group cluster-stats member totals equal the eligible UMI
  records — conserved across the r5 sub-threshold rescue merge
- **consensus**: consensus records == selected clusters per (non-failed)
  group, and the merged FASTA holds exactly those records
- **counts**: the counts CSV reads back equal to the in-memory totals

Modes (config key ``contracts``): ``off`` (checks skipped), ``warn``
(default: violations logged + recorded in ``robustness_report.json``),
``strict`` (violations additionally raise :class:`ContractViolation`,
failing the run). A check is a handful of integer compares — warn mode is
free on the hot path.
"""

from __future__ import annotations

import sys
import threading

MODES = ("off", "warn", "strict")

_MODE = "warn"
_lock = threading.Lock()
_checked: dict[str, int] = {}
_violated: dict[str, int] = {}


class ContractViolation(RuntimeError):
    """A conservation invariant failed under ``contracts=strict``."""


def mode() -> str:
    return _MODE


def set_mode(new_mode: str) -> str:
    global _MODE
    if new_mode not in MODES:
        raise ValueError(f"contracts mode {new_mode!r} not in {MODES}")
    _MODE = new_mode
    return _MODE


def reset() -> None:
    """Clear the per-run check/violation counters (run start)."""
    with _lock:
        _checked.clear()
        _violated.clear()


def summary() -> dict:
    """{checked: {name: n}, violated: {name: n}} for the robustness report."""
    with _lock:
        return {"mode": _MODE, "checked": dict(_checked),
                "violated": dict(_violated)}


def check_equal(name: str, lhs_desc: str, lhs, rhs_desc: str, rhs,
                detail: dict | None = None) -> bool:
    """Assert ``lhs == rhs`` under the active mode; returns whether it held.

    ``off`` skips entirely. Violations are recorded in the robustness
    recorder (site ``contracts.<name>``), logged to stderr under ``warn``,
    and raised as :class:`ContractViolation` under ``strict``.
    """
    if _MODE == "off":
        return True
    with _lock:
        _checked[name] = _checked.get(name, 0) + 1
    if lhs == rhs:
        return True
    with _lock:
        _violated[name] = _violated.get(name, 0) + 1
    message = (f"conservation contract {name!r} violated: "
               f"{lhs_desc} ({lhs!r}) != {rhs_desc} ({rhs!r})")
    from ont_tcrconsensus_tpu.robustness import retry

    retry.recorder().record(
        f"contracts.{name}", classification="contract", outcome="violation",
        error=message, detail=detail,
    )
    if _MODE == "strict":
        raise ContractViolation(message)
    print(f"WARNING: {message}", file=sys.stderr)
    return False
