"""Stage-boundary conservation contracts (runtime accounting self-checks).

The counts contract (BASELINE.md: UMI counts bit-identical to the CPU
pipeline) had no runtime self-check that reads are actually conserved
across the rescue/skip/degrade branches the robustness layer added. This
module adds cheap invariant checks at every stage boundary:

- **ingest**: records parsed == reads entering the device pass + reads
  dropped by the length buckets (+ quarantined records, counted upstream)
- **assign**: the fused-pass filter categories partition the batch total,
  and the columnar store holds exactly the passing reads
- **umi**: per-group cluster-stats member totals equal the eligible UMI
  records — conserved across the r5 sub-threshold rescue merge
- **consensus**: consensus records == selected clusters per (non-failed)
  group, and the merged FASTA holds exactly those records
- **counts**: the counts CSV reads back equal to the in-memory totals

Modes (config key ``contracts``): ``off`` (checks skipped), ``warn``
(default: violations logged + recorded in ``robustness_report.json``),
``strict`` (violations additionally raise :class:`ContractViolation`,
failing the run). A check is a handful of integer compares — warn mode is
free on the hot path.
"""

from __future__ import annotations

import sys
import threading

from ont_tcrconsensus_tpu.robustness import jobscope

MODES = ("off", "warn", "strict")

# process-wide mode + counters; under a jobscope (slice-packed runner
# pool) each resident tenant job binds its OWN {mode, checked, violated}
# state thread-locally so a concurrent run's reset/set_mode never wipes
# another tenant's counters mid-flight. The module lock guards counter
# mutation for both shapes — contention is a handful of int bumps.
_MODE = "warn"
_lock = threading.Lock()
_checked: dict[str, int] = {}
_violated: dict[str, int] = {}


class ContractViolation(RuntimeError):
    """A conservation invariant failed under ``contracts=strict``."""


def _scoped_state() -> dict | None:
    return jobscope.get("contracts")


def _ensure_scoped() -> dict:
    st = jobscope.get("contracts")
    if st is None:
        st = {"mode": _MODE, "checked": {}, "violated": {}}
        jobscope.set("contracts", st)
    return st


def mode() -> str:
    st = _scoped_state()
    if st is not None:
        return st["mode"]
    return _MODE


def set_mode(new_mode: str) -> str:
    global _MODE
    if new_mode not in MODES:
        raise ValueError(f"contracts mode {new_mode!r} not in {MODES}")
    if jobscope.active():
        _ensure_scoped()["mode"] = new_mode
        return new_mode
    _MODE = new_mode
    return _MODE


def reset() -> None:
    """Clear the per-run check/violation counters (run start)."""
    if jobscope.active():
        st = _ensure_scoped()
        with _lock:
            st["checked"].clear()
            st["violated"].clear()
        return
    with _lock:
        _checked.clear()
        _violated.clear()


def summary() -> dict:
    """{checked: {name: n}, violated: {name: n}} for the robustness report."""
    st = _scoped_state()
    with _lock:
        if st is not None:
            return {"mode": st["mode"], "checked": dict(st["checked"]),
                    "violated": dict(st["violated"])}
        return {"mode": _MODE, "checked": dict(_checked),
                "violated": dict(_violated)}


def check_equal(name: str, lhs_desc: str, lhs, rhs_desc: str, rhs,
                detail: dict | None = None) -> bool:
    """Assert ``lhs == rhs`` under the active mode; returns whether it held.

    ``off`` skips entirely. Violations are recorded in the robustness
    recorder (site ``contracts.<name>``), logged to stderr under ``warn``,
    and raised as :class:`ContractViolation` under ``strict``.
    """
    active_mode = mode()
    st = _scoped_state()
    checked = st["checked"] if st is not None else _checked
    violated = st["violated"] if st is not None else _violated
    if active_mode == "off":
        return True
    with _lock:
        checked[name] = checked.get(name, 0) + 1
    if lhs == rhs:
        return True
    with _lock:
        violated[name] = violated.get(name, 0) + 1
    message = (f"conservation contract {name!r} violated: "
               f"{lhs_desc} ({lhs!r}) != {rhs_desc} ({rhs!r})")
    from ont_tcrconsensus_tpu.robustness import retry

    retry.recorder().record(
        f"contracts.{name}", classification="contract", outcome="violation",
        error=message, detail=detail,
    )
    if active_mode == "strict":
        raise ContractViolation(message)
    print(f"WARNING: {message}", file=sys.stderr)
    return False
