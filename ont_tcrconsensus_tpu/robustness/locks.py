"""Process-wide lock-ownership registry: every cross-thread shared
location in the tree and the lock that owns it, in ONE table.

This used to live as five scattered ``LOCK_OWNERSHIP`` dicts next to
their classes (serve/queue.py, obs/metrics.py, obs/live.py,
pipeline/overlap.py, robustness/watchdog.py). Consolidating them here
gives every analyzer one source of truth:

- graftlint's ``lock-discipline`` rule (lexical: a mutation of a
  declared attr outside ``with self.<lock>:`` is a finding) merges
  every ``LOCK_OWNERSHIP`` dict literal it can see, so it consumes this
  table with no rule change;
- graftlint's ``lock-registry`` sweep checks BOTH directions — a
  declared attr that no longer exists in its class, and an undeclared
  mutable container in a registered class — so the table cannot rot
  (same discipline as the chaos/obs site cross-checks);
- graftrace (tools/graftrace) reads it as the shared-location universe
  for Eraser-style lockset analysis across thread roots;
- the runtime twin (robustness/lockcheck.py, ``TCR_LOCKCHECK=1``) arms
  owner-assertions on exactly these locks.

Keys are ``"ClassName.attr"``; values are the lock attribute on the same
object that must be held for every access. Module-level globals that are
only ever REBOUND (``_ACTIVE = wd`` style atomic-reference hand-off) are
deliberately absent: rebinding is atomic under the GIL and is the
documented arming discipline — only container *mutations* need a lock.
"""

from __future__ import annotations

LOCK_OWNERSHIP = {
    # --- serve/queue.py: HTTP handler threads and the daemon loop both
    # mutate these; any mutation outside the lock loses jobs under load
    "JobQueue.pending": "_lock",
    "JobQueue.jobs": "_lock",
    "JobQueue.finished_order": "_lock",
    # --- obs/metrics.py: worker threads + the watchdog monitor both
    # feed this object
    "MetricsRegistry.counters": "_lock",
    "MetricsRegistry.gauges": "_lock",
    "MetricsRegistry.gauges_live": "_lock",
    "MetricsRegistry.serve_rejects": "_lock",
    "MetricsRegistry.mesh_slices": "_lock",
    "MetricsRegistry.mesh_degraded": "_lock",
    "MetricsRegistry.slice_tenants": "_lock",
    "MetricsRegistry.slice_quarantined": "_lock",
    "MetricsRegistry.hists": "_lock",
    "MetricsRegistry.stages": "_lock",
    "MetricsRegistry.dispatch": "_lock",
    "MetricsRegistry.dispatch_stages": "_lock",
    "MetricsRegistry.compiles": "_lock",
    "MetricsRegistry.graph_nodes": "_lock",
    "MetricsRegistry.graph_edges": "_lock",
    "MetricsRegistry.graph_meta": "_lock",
    "MetricsRegistry.pools": "_lock",
    "MetricsRegistry.analysis": "_lock",
    "MetricsRegistry.transfers": "_lock",
    "MetricsRegistry.edge_transfers": "_lock",
    "MetricsRegistry.donations": "_lock",
    "MetricsRegistry.node_hbm": "_lock",
    "MetricsRegistry.static_hbm": "_lock",
    "MetricsRegistry._round_trip": "_lock",
    # --- obs/live.py: the ring is fed from every guarded stage thread
    # plus overlap workers while HTTP handler threads snapshot it; the
    # tracker is fed from the main loop and read by handler threads
    "FlightRecorder.events": "_lock",
    "FlightRecorder.total": "_lock",
    "FlightRecorder.flush_path": "_lock",
    "FlightRecorder.last_flush": "_lock",
    "ProgressTracker.libraries_total": "_lock",
    "ProgressTracker.libraries_done": "_lock",
    "ProgressTracker.library": "_lock",
    "ProgressTracker.plan": "_lock",
    "ProgressTracker.done": "_lock",
    "ProgressTracker.node": "_lock",
    "ProgressTracker.node_units": "_lock",
    "ProgressTracker.node_t0": "_lock",
    "ProgressTracker.node_seconds": "_lock",
    "ProgressTracker.priors": "_lock",
    # --- pipeline/overlap.py: the pool counters are fed by every worker
    # thread's completion callback; an unlocked write loses busy seconds
    "StageExecutor._t_first_submit": "_stats_lock",
    "StageExecutor._t_last_done": "_stats_lock",
    "StageExecutor._busy_s": "_stats_lock",
    "StageExecutor._pool_recorded": "_stats_lock",
    # --- robustness/watchdog.py: mutated by guarded stage threads and
    # raced by the monitor; _on_hard's cancel-safety proof relies on
    # every write being locked
    "Watchdog._entries": "_lock",
    # --- serve/slices.py: the slice pool is mutated by the dispatcher
    # (assign), runner workers (release/quarantine — including the mesh
    # degrade hook firing mid-run on a job thread) and read by HTTP
    # submit threads (admission_budget); an unlocked write double-leases
    # a slice across tenants
    "SliceAllocator._state": "_lock",
    "SliceAllocator._leases": "_lock",
}

#: Mutable containers on registered classes that are deliberately NOT
#: lock-owned, with the one-line reason the analyzers echo. The
#: lock-registry sweep fails on any undeclared container that is in
#: neither table, so "forgot to think about it" is impossible.
LOCK_EXEMPT = {
    "StageExecutor._pending": (
        "main-thread only: submit/commit/wait_all all run on the "
        "library loop thread; workers never touch the pending list"
    ),
    "SliceAllocator.devices": (
        "written once in __init__ before any thread sees the allocator; "
        "read-only (index order IS the slice address space) afterwards"
    ),
}


def ownership_by_class() -> dict[str, dict[str, str]]:
    """``{"JobQueue": {"pending": "_lock", ...}, ...}`` for runtime
    consumers (the AST analyzers parse the literal instead)."""
    out: dict[str, dict[str, str]] = {}
    for key, lock in LOCK_OWNERSHIP.items():
        cls, attr = key.split(".", 1)
        out.setdefault(cls, {})[attr] = lock
    return out
