"""Per-job thread scope for the process-global observability/robustness
singletons (the slice-packed serving concurrency contract).

Every run arms a set of process-global registries — the metrics registry
(obs/metrics.py), the chaos plan (faults.py), the retry policy/recorder
(retry.py), the watchdog (watchdog.py), the contract counters
(contracts.py), the shutdown coordinator (shutdown.py) and the live
plane's node-start hook (obs/live.py). One job at a time, that is
exactly the right shape: deep stage code reaches its run's state with a
single module-attribute check, no signature plumbing.

The serve plane's slice-packed worker pool (serve/daemon.py +
serve/slices.py) breaks the one-at-a-time assumption: two tenant jobs
run :func:`~..pipeline.run.run_with_config` CONCURRENTLY on disjoint
mesh slices, and each run's arm/disarm of those globals would clobber
the other tenant mid-flight (job B's recorder reset wiping job A's
robustness events is a correctness bug, not a cosmetic one).

This module is the fix: a thread-local OVERLAY store. A runner-pool
worker enters the scope before dispatching its job; while the scope is
active, each singleton module's ``arm``/``set_*`` binds into the
thread's store instead of the module global, and its resolution helper
reads the store first. Threads outside any scope — the daemon loop, the
HTTP handlers, every one-shot CLI run — see the module globals exactly
as before: unscoped behavior is byte-for-byte the status quo.

Scope inheritance: threads SPAWNED by a scoped run (the overlap
executor's deferred-stage workers) adopt the submitting thread's store
via :func:`current`/:func:`adopt`, so a background QC stage's telemetry
and chaos plants land in its own job's scope, not a random tenant's.
The store is shared by reference on purpose — one scope per job, however
many threads serve it.

Known boundary: module globals that are process-wide by NATURE (the
live plane's HTTP server and flight ring, the compilation cache) stay
shared; the daemon owns them and jobs only feed them.
"""

from __future__ import annotations

import threading

_TLS = threading.local()

#: store keys are owned by the scoped modules; listed here only as the
#: vocabulary of the overlay ("metrics", "faults", "retry_policy",
#: "retry_recorder", "watchdog", "contracts", "shutdown",
#: "node_start_hook", "flush_path", "slice_devices", "degrade_hook").


def enter() -> None:
    """Enter a job scope on the calling thread (runner-pool worker,
    immediately before dispatching a tenant job)."""
    _TLS.store = {}


def exit() -> None:
    """Leave the scope; the thread sees the module globals again."""
    _TLS.store = None


def active() -> bool:
    return getattr(_TLS, "store", None) is not None


def current() -> dict | None:
    """The calling thread's store (None outside any scope) — capture at
    spawn time to hand a child worker via :func:`adopt`."""
    return getattr(_TLS, "store", None)


def adopt(store: dict | None) -> None:
    """Adopt a parent thread's store (child workers of a scoped run).
    ``None`` is a no-op so unscoped submitters stay unscoped."""
    if store is not None:
        _TLS.store = store


def set(key: str, value) -> None:
    """Bind ``key`` in the active scope; silently a no-op when unscoped
    (callers decide between global and scoped via :func:`active`)."""
    store = getattr(_TLS, "store", None)
    if store is not None:
        store[key] = value


def get(key: str, default=None):
    """Scoped value for ``key``; ``default`` when unscoped or unset.

    Scoped modules distinguish "unset" (fall back to the module global)
    from an explicit tombstone (the scope armed then disarmed) by
    storing ``(value,)`` tuples or sentinel defaults as they see fit.
    """
    store = getattr(_TLS, "store", None)
    if store is None:
        return default
    return store.get(key, default)
