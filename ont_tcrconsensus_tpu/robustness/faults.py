"""Deterministic, seeded fault-injection registry (chaos mode).

Every degradation path in the pipeline used to be dead code: the
``except`` ladders in run.py were structurally present but nothing ever
exercised them. This module turns them into tested behavior by letting a
test (or an operator, via the ``TCR_CHAOS`` env var / the ``chaos`` config
key) arm named faults at named injection points:

======================== ====================================================
site                     planted at
======================== ====================================================
``assign.dispatch``      the fused-pass batch dispatch loop
                         (pipeline/assign.py, run_assign)
``polish.dispatch``      the batched consensus/polish chunk dispatch
                         (pipeline/stages.py, polish_clusters_all)
``cluster.batched_round1`` the library-wide batched UMI clustering pass,
``cluster.batched_round2`` rounds 1 / 2 (pipeline/run.py)
``overlap.worker``       the background-stage worker body
                         (pipeline/overlap.py, DeferredStage._run)
``layout.manifest_write`` the stage-manifest write (io/layout.py) —
                         ``torn`` kind tears the file mid-write
``run.round1_checkpoint`` immediately after the round-1 consensus
                         checkpoint commits (pipeline/run.py) — the
                         mid-stage ``kill`` / ``preempt`` site
``graph.node``           every critical-path node body under the graph
                         executor (graph/executor.py) — the per-node
                         generalization of the hand-placed sites
``serve.job_run``        the daemon's per-job dispatch, immediately before
                         the pipeline runs (serve/daemon.py) — the
                         job-crash drill behind the bounded-retry /
                         poison-quarantine ladder
``serve.job_slow``       a slow tenant job: fires a ``stall`` inside a
                         serve-level watchdog guard (serve/daemon.py), so
                         the cancel classifies transient and the job
                         retries instead of wedging the loop
``serve.daemon_loop``    the serve accept loop between pop and dispatch
                         (serve/daemon.py) — an ``error`` here escapes the
                         loop: the drain finally still journals the queue
                         and flushes the flight recorder, simulating a
                         daemon crash mid-load
``serve.journal_write``  the drain-journal commit (serve/queue.py) —
                         ``torn`` tears the journal mid-write
``serve.prewarm``        the AOT bucket prewarm (serve/daemon.py) — a
                         failed prewarm must degrade to a report line,
                         never a dead daemon
``serve.slice_assign``   the slice allocator's carve, after sizing and
                         before the devices leave the free pool
                         (serve/slices.py) — a ``transient`` here rides
                         the job retry ladder, never leaks a slice
``serve.slice_lost``     the runner-pool worker between slice assignment
                         and job dispatch (serve/daemon.py) —
                         ``device-lost`` simulates losing the whole
                         assigned slice: the slice quarantines, the
                         tenant's job requeues, every OTHER tenant is
                         provably untouched
``serve.pack``           the allocator's release/repack as a job's
                         devices return to the free pool
                         (serve/slices.py) — a fault mid-pack must leave
                         the pool consistent (no leaked devices)
``mesh.dispatch``        the sharded placement/dispatch boundary: batch
                         shard placement (parallel/mesh.py, shard_batch)
                         and the engine's shard_map dispatch
                         (pipeline/assign.py) — a ``transient`` here
                         rides the existing bounded-retry ladder
``mesh.device_lost``     the sharded polish chunk dispatch
                         (pipeline/stages.py, mesh armed only) —
                         ``device-lost`` raises
                         :class:`DeviceLostChaosError`, which escalates
                         past the chunk ladder to the graph executor's
                         degraded-mesh re-execution path
``mesh.slice_oom``       same boundary — an ``oom`` on one slice of the
                         mesh rides the existing shrink-and-requeue
                         ladder (the per-chip allowance is the binding
                         one under sharding)
======================== ====================================================

Fault kinds:

- ``transient`` — raises :class:`TransientChaosError` (classified as a
  retryable device/transport fault, message carries ``UNAVAILABLE``)
- ``oom``       — raises :class:`OomChaosError` (classified as HBM
  exhaustion, message carries ``RESOURCE_EXHAUSTED``)
- ``device-lost`` — raises :class:`DeviceLostChaosError` (a mesh slice
  died mid-dispatch, message carries ``DEVICE_LOST``; retrying the same
  mesh cannot succeed — the executor shrinks the data axis to the
  surviving slices and re-dispatches)
- ``error``     — raises a plain ``RuntimeError`` (a deterministic bug:
  never retried, exercises the skip/degrade paths)
- ``kill``      — ``os._exit(137)``: unflushable process death, exactly
  what a preempted VM looks like to the filesystem
- ``preempt``   — triggers the active shutdown coordinator as if SIGTERM
  had arrived (the next stage-boundary checkpoint raises ``Preempted``)
- ``torn``      — only meaningful at write sites driven through
  :func:`tear_write`: the payload is truncated mid-write, simulating a
  crash between ``write`` and ``os.replace``

Determinism: a spec fires on exact hit counts (``skip`` pass-throughs,
then ``times`` fires), or — for soak-style runs — with probability ``p``
drawn from a generator seeded by ``(plan seed, site)``, so a given plan
replays identically. Disarmed, :func:`inject` is one global check.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import sys
import threading
import time

from ont_tcrconsensus_tpu.obs import trace as obs_trace
from ont_tcrconsensus_tpu.robustness import jobscope

ENV_VAR = "TCR_CHAOS"

#: ``corrupt-input`` / ``truncate-file`` are FILE-level data faults: they
#: fire through :func:`mutate_input` at ingest sites (the pipeline reads a
#: seeded-mutated sibling copy of the input file; the original is never
#: touched), exercising the record-quarantine path end to end.
#: ``corrupt-input`` splices malformed records BETWEEN the real ones, so
#: with ``on_bad_record=quarantine`` the clean-read subset — and therefore
#: every downstream artifact — must stay byte-identical to an uncorrupted
#: run. ``truncate-file`` cuts the file mid-stream (for ``.gz`` inputs:
#: mid gzip stream), losing the tail.
#: ``stall`` / ``hang`` are LIVENESS faults: the injection point stops
#: making progress instead of raising, and only the stage watchdog
#: (robustness/watchdog.py, config ``stage_timeout_s``) can end it.
#: ``stall`` wedges in an interruptible Python loop (the watchdog's
#: hard-deadline cancel lands promptly); ``hang`` wedges in ONE long
#: C-level call, like a hung XLA dispatch — detected and stack-dumped on
#: time, cancelled only when the call returns. ``corrupt-artifact`` is a
#: RESUME-integrity fault: it flips a byte of a completed stage's artifact
#: in place (size-preserving, so only ``verify_resume=full`` checksums can
#: catch it) through :func:`corrupt_artifact` at ``resume.verify``.
KINDS = ("transient", "oom", "device-lost", "error", "kill", "preempt",
         "torn", "corrupt-input", "truncate-file", "stall", "hang",
         "corrupt-artifact")

#: every injection point planted in the pipeline; arming an unknown site is
#: an error so chaos-plan typos fail fast instead of silently never firing
KNOWN_SITES = frozenset({
    "assign.dispatch",
    "polish.dispatch",
    "cluster.batched_round1",
    "cluster.batched_round2",
    "overlap.worker",
    "layout.manifest_write",
    "run.round1_checkpoint",
    "ingest.library_fastq",
    "resume.verify",
    "graph.node",
    "serve.job_run",
    "serve.job_slow",
    "serve.daemon_loop",
    "serve.journal_write",
    "serve.prewarm",
    "serve.slice_assign",
    "serve.slice_lost",
    "serve.pack",
    "mesh.dispatch",
    "mesh.device_lost",
    "mesh.slice_oom",
})

KILL_EXIT_CODE = 137


class TransientChaosError(RuntimeError):
    """Injected transient device/transport fault (retryable)."""


class OomChaosError(RuntimeError):
    """Injected HBM exhaustion (degradable: shrink the batch and retry)."""


class DeviceLostChaosError(RuntimeError):
    """Injected mesh-slice loss (degradable: shrink the data axis to the
    surviving slices and re-dispatch — retrying the dead mesh cannot
    succeed, and no smaller batch fits a device that is gone)."""


@dataclasses.dataclass
class FaultSpec:
    """One armed fault: fire ``times`` times at ``site`` after ``skip``
    pass-through hits (or i.i.d. with probability ``p`` when set)."""

    site: str
    kind: str = "transient"
    skip: int = 0
    times: int = 1
    p: float | None = None
    message: str = ""

    def __post_init__(self):
        if self.site not in KNOWN_SITES:
            raise ValueError(
                f"unknown chaos site {self.site!r}; known: {sorted(KNOWN_SITES)}"
            )
        if self.kind not in KINDS:
            raise ValueError(f"unknown chaos kind {self.kind!r}; known: {KINDS}")
        if self.p is not None and not (0.0 <= self.p <= 1.0):
            raise ValueError(f"chaos p={self.p} outside [0, 1]")


class FaultPlan:
    """Armed specs + per-site hit/fire counters (thread-safe: injection
    points sit on worker threads too)."""

    def __init__(self, specs: list[FaultSpec], seed: int = 0):
        self.specs = list(specs)
        self.seed = seed
        self._lock = threading.Lock()
        self._hits: dict[str, int] = {}
        self._fired: dict[str, int] = {}
        self._spec_fired: dict[int, int] = {}
        self._rng: dict[str, random.Random] = {}

    def hit(self, site: str) -> FaultSpec | None:
        """Count one arrival at ``site``; return the spec to fire, if any."""
        with self._lock:
            n = self._hits.get(site, 0)
            self._hits[site] = n + 1
            for i, spec in enumerate(self.specs):
                if spec.site != site:
                    continue
                fired = self._spec_fired.get(i, 0)
                if spec.times > 0 and fired >= spec.times:
                    continue
                if spec.p is not None:
                    rng = self._rng.setdefault(
                        site, random.Random(f"{self.seed}:{site}")
                    )
                    if rng.random() >= spec.p:
                        continue
                elif n < spec.skip:
                    continue
                self._spec_fired[i] = fired + 1
                self._fired[site] = self._fired.get(site, 0) + 1
                return spec
            return None

    def describe(self) -> dict:
        with self._lock:
            return {
                "seed": self.seed,
                "specs": [dataclasses.asdict(s) for s in self.specs],
                "hits": dict(self._hits),
                "fired": dict(self._fired),
            }


# process-wide plan; under a jobscope (the slice-packed runner pool)
# each tenant job's run arms/disarms a THREAD-SCOPED plan instead, so
# tenant A's chaos declaration can never fire inside (or be disarmed by)
# tenant B's concurrent run. The scope stores a 1-tuple so an explicit
# in-scope disarm (a job declaring "no chaos") tombstones rather than
# falling back to the daemon's serve-scope plan.
_PLAN: FaultPlan | None = None


def _current_plan() -> FaultPlan | None:
    entry = jobscope.get("faults")
    if entry is not None:
        return entry[0]
    return _PLAN


def active() -> bool:
    return _current_plan() is not None


def arm(specs, seed: int = 0) -> FaultPlan:
    """Arm a chaos plan from a list of spec dicts (or FaultSpecs)."""
    global _PLAN
    parsed = [
        s if isinstance(s, FaultSpec) else FaultSpec(**s) for s in specs
    ]
    plan = FaultPlan(parsed, seed=seed)
    if jobscope.active():
        jobscope.set("faults", (plan,))
    else:
        _PLAN = plan
    return plan


def arm_from_env() -> FaultPlan | None:
    """Arm a FRESH plan from the ``TCR_CHAOS`` env JSON (a spec list, or
    ``{"seed": n, "faults": [...]}``); returns None — leaving any current
    plan untouched — when the variable is unset. Each pipeline run
    re-declares its chaos state (run.py), so an env-armed plan fires anew
    per run and never silently bleeds exhausted counters across runs."""
    raw = os.environ.get(ENV_VAR)
    if not raw:
        return None
    data = json.loads(raw)
    if isinstance(data, dict):
        return arm(data.get("faults", []), seed=int(data.get("seed", 0)))
    return arm(data)


def disarm() -> None:
    global _PLAN
    if jobscope.active():
        jobscope.set("faults", (None,))
        return
    _PLAN = None


def describe() -> dict | None:
    plan = _current_plan()
    return plan.describe() if plan is not None else None


def fired(site: str) -> int:
    """How many times any spec fired at ``site`` (0 when disarmed)."""
    plan = _current_plan()
    if plan is None:
        return 0
    with plan._lock:
        return plan._fired.get(site, 0)


def _note_fire(site: str, kind: str) -> None:
    """Chaos firings become trace instants (no-op below telemetry=full),
    so an injected fault sits on the same timeline as the stage spans and
    the retry/stall events it provokes."""
    obs_trace.instant("chaos.inject", args={"site": site, "kind": kind})


def _fire(spec: FaultSpec, site: str) -> None:
    _note_fire(site, spec.kind)
    msg = spec.message or f"injected {spec.kind} fault at {site}"
    if spec.kind == "transient":
        raise TransientChaosError(f"UNAVAILABLE: {msg}")
    if spec.kind == "oom":
        raise OomChaosError(f"RESOURCE_EXHAUSTED: {msg}")
    if spec.kind == "device-lost":
        raise DeviceLostChaosError(f"DEVICE_LOST: {msg}")
    if spec.kind == "error":
        raise RuntimeError(msg)
    if spec.kind == "kill":
        # a preempted VM does not flush buffers or run atexit hooks;
        # os._exit is the honest simulation of that
        sys.stderr.write(f"CHAOS: killing process at {site}\n")
        sys.stderr.flush()
        os._exit(KILL_EXIT_CODE)
    if spec.kind == "preempt":
        from ont_tcrconsensus_tpu.robustness import shutdown

        shutdown.request(reason=f"chaos preempt at {site}")
        return
    if spec.kind in ("stall", "hang"):
        _stall_until_cancelled(spec.kind, site)
    raise AssertionError(f"unhandled chaos kind {spec.kind!r}")  # pragma: no cover


#: safety cap on an injected stall/hang: if the watchdog is disarmed or
#: dead, the wedge self-reports instead of hanging the test suite forever
STALL_CAP_S = 60.0


def _stall_until_cancelled(kind: str, site: str) -> None:
    """Stop making progress until the watchdog cancels this thread.

    ``stall``: an interruptible Python sleep loop — the watchdog's
    hard-deadline ``StageTimeout`` (PyThreadState_SetAsyncExc) is
    delivered between the slices, promptly. ``hang``: ONE long C-level
    ``time.sleep`` sized past the active hard deadline, like a wedged XLA
    dispatch — the cancel is queued on time but only lands when the call
    returns. Either way the pending StageTimeout raises at the next
    bytecode after the sleep, so the code below the sleeps is reached
    only when the watchdog never cancelled us.
    """
    from ont_tcrconsensus_tpu.robustness import watchdog

    sys.stderr.write(f"CHAOS: injected {kind} at {site} "
                     "(progress stops; only the watchdog can end this)\n")
    sys.stderr.flush()
    hard = watchdog.active_deadline_s()
    if hard is not None and hard + 2.0 > STALL_CAP_S:
        # the wedge would end BEFORE the watchdog's hard deadline and the
        # fallthrough below would wrongly diagnose a disarmed watchdog —
        # refuse the drill loudly instead
        raise RuntimeError(
            f"injected {kind} at {site}: active hard deadline {hard:.0f}s "
            f"exceeds the {STALL_CAP_S:.0f}s stall safety cap — shrink "
            "stage_timeout_s for this chaos drill"
        )
    if kind == "hang":
        time.sleep((hard or 5.0) + 2.0)
    else:
        deadline = time.monotonic() + STALL_CAP_S
        while time.monotonic() < deadline:
            time.sleep(0.02)
    raise RuntimeError(
        f"injected {kind} at {site} was never cancelled — is the stage "
        f"watchdog armed (stage_timeout_s) and the site inside a guard?"
    )


def inject(site: str) -> None:
    """Raise/kill/preempt per the armed plan; free no-op when disarmed."""
    plan = _current_plan()
    if plan is None:
        return
    spec = plan.hit(site)
    if spec is not None:
        _fire(spec, site)


#: malformed blocks spliced between records by ``corrupt-input``. Each is
#: self-contained damage the tolerant parser quarantines WITHOUT eating a
#: neighboring real record: the junk line resyncs at the next record, the
#: length-mismatch and sub-Phred records consume exactly their own four
#: lines, and the headerless fragment resyncs at the following '@' header.
_CORRUPT_BLOCKS = (
    b"THIS IS NOT A FASTQ LINE \xff\xfe\x00 chaos\n",
    b"@chaos_len_mismatch\nACGTACGT\n+\nIII\n",
    b"@chaos_bad_qual\nACGT\n+\n\x05\x05\x05\x05\n",
    b"@chaos_headerless_fragment\nACGTACGTACGT\n",
)


def _read_file_bytes(path: str) -> tuple[bytes, bool]:
    """(decoded text bytes, was_gzip) — gzip-transparent like the parsers."""
    import gzip

    with open(path, "rb") as fh:
        raw = fh.read()
    if raw[:2] == b"\x1f\x8b":
        return gzip.decompress(raw), True
    return raw, False


def _chaos_sibling_path(path: str, tag: str) -> str:
    """Mutated-copy path next to ``path``: '<stem>.<tag>[.gz]'. The name
    must NOT contain 'fastq' — input discovery globs '*fastq*'
    (pipeline/run.py), and a leftover chaos copy must never be picked up
    as an extra library on a later resume."""
    d, base = os.path.split(path)
    # ONT's standard naming puts 'fastq' in the STEM too (fastq_runid_*),
    # so the stem itself must be scrubbed, not just the extensions
    stem = base.split(".")[0].replace("fastq", "fq")
    suffix = ".gz" if path.endswith(".gz") else ""
    return os.path.join(d, f"{stem}.{tag}{suffix}")


def mutate_input(site: str, path: str) -> str:
    """File-level chaos for ingest sites: returns the path to read.

    When a ``corrupt-input`` / ``truncate-file`` spec fires at ``site``, a
    mutated sibling copy is written next to ``path`` (named without
    'fastq' so input discovery never globs it on a resume) and its path is
    returned; the original file is never modified. Other armed kinds fire
    through :func:`_fire` as usual. No-op (returns ``path``) when
    disarmed.
    """
    plan = _current_plan()
    if plan is None:
        return path
    spec = plan.hit(site)
    if spec is None:
        return path
    if spec.kind not in ("corrupt-input", "truncate-file"):
        _fire(spec, site)
        return path
    _note_fire(site, spec.kind)
    import gzip

    rng = random.Random(f"{plan.seed}:{site}:{spec.kind}")
    if spec.kind == "truncate-file":
        # cut the RAW file bytes mid-stream: for .gz inputs this truncates
        # the gzip stream itself (the BadGzipFile/gzread-error path)
        with open(path, "rb") as fh:
            raw = fh.read()
        cut = max(1, int(len(raw) * (0.5 + 0.3 * rng.random())))
        out_path = _chaos_sibling_path(path, "chaos-trunc")
        with open(out_path, "wb") as fh:
            fh.write(raw[:cut])
        sys.stderr.write(f"CHAOS: truncated input copy {out_path} "
                         f"({cut}/{len(raw)} bytes) at {site}\n")
        return out_path
    data, was_gz = _read_file_bytes(path)
    lines = data.splitlines(keepends=True)
    # record boundaries every 4 lines (chaos stages well-formed FASTQ)
    n_rec = len(lines) // 4
    slots = sorted(rng.sample(range(n_rec + 1), k=min(3, n_rec + 1)))
    parts: list[bytes] = []
    prev = 0
    for k, slot in enumerate(slots):
        parts.append(b"".join(lines[prev * 4:slot * 4]))
        parts.append(_CORRUPT_BLOCKS[(k + rng.randrange(len(_CORRUPT_BLOCKS)))
                                     % len(_CORRUPT_BLOCKS)])
        prev = slot
    parts.append(b"".join(lines[prev * 4:]))
    mutated = b"".join(parts)
    out_path = _chaos_sibling_path(path, "chaos-corrupt")
    with open(out_path, "wb") as fh:
        fh.write(gzip.compress(mutated) if was_gz else mutated)
    sys.stderr.write(f"CHAOS: corrupted input copy {out_path} "
                     f"({len(slots)} bad blocks) at {site}\n")
    return out_path


def corrupt_artifact(site: str, path: str) -> bool:
    """Resume-integrity chaos for verification sites: mutate a COMPLETED
    artifact in place, simulating disk/firmware corruption between a run
    and its resume.

    When a ``corrupt-artifact`` spec fires at ``site``, the middle byte of
    ``path`` is flipped to an ASCII digit. Size-preserving on purpose:
    ``verify_resume=fast`` (size check) must MISS it and only ``full``
    (sha256) may catch it — and a digit keeps a counts CSV parseable, so
    ``verify_resume=off`` demonstrates true blind trust (valid-looking
    garbage flows through) instead of a parse crash. Returns True when it
    fired; other armed kinds at the site fire through :func:`_fire`.
    """
    plan = _current_plan()
    if plan is None:
        return False
    spec = plan.hit(site)
    if spec is None:
        return False
    if spec.kind != "corrupt-artifact":
        _fire(spec, site)
        return False
    _note_fire(site, spec.kind)
    if not os.path.exists(path):
        sys.stderr.write(f"CHAOS: corrupt-artifact at {site}: {path} "
                         "does not exist; nothing to corrupt\n")
        return False
    with open(path, "r+b") as fh:
        data = fh.read()
        if not data:
            sys.stderr.write(f"CHAOS: corrupt-artifact at {site}: {path} "
                             "is empty; nothing to corrupt\n")
            return False
        pos = len(data) // 2
        new = b"7" if data[pos:pos + 1] != b"7" else b"8"
        fh.seek(pos)
        fh.write(new)
    sys.stderr.write(f"CHAOS: corrupted artifact {path} "
                     f"(byte {pos} -> {new!r}) at {site}\n")
    return True


def tear_write(site: str, path: str, payload: str) -> bool:
    """Torn-write injection for file-commit sites.

    Returns True when a ``torn`` fault fired: the first half of ``payload``
    was written DIRECTLY to ``path`` (no tmp + rename), simulating a crash
    mid-write — the caller must skip its own atomic write. Other armed
    kinds at the site fire through :func:`_fire` as usual.
    """
    plan = _current_plan()
    if plan is None:
        return False
    spec = plan.hit(site)
    if spec is None:
        return False
    if spec.kind != "torn":
        _fire(spec, site)
        return False
    _note_fire(site, spec.kind)
    with open(path, "w") as fh:
        fh.write(payload[: max(1, len(payload) // 2)])
    sys.stderr.write(f"CHAOS: tore write of {path} at {site}\n")
    return True
