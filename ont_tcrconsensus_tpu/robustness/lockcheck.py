"""Runtime lockset twin for the static race analyzer (tools/graftrace).

graftrace proves lock discipline *statically* — every LOCK_OWNERSHIP
access path carries a non-empty lockset intersection. This module is the
*dynamic* half of that proof: ``TCR_LOCKCHECK=1`` arms cheap runtime
owner-assertions on the same locks, so the existing chaos e2es validate
the static model against real interleavings.

Discipline mirrors ``faults.inject`` exactly: disarmed (the default) the
hot path pays ONE module-attribute check and nothing else; armed,
:func:`make_lock` hands out ``threading.RLock`` (whose CPython
``_is_owned()`` lets any thread ask "do I hold this?") and
:func:`assert_held` records a violation instead of crashing — a chaos
run must finish byte-identical, with violations reported at the end.

Arming must happen BEFORE the guarded objects are constructed (their
locks are chosen at ``__init__`` time): the pipeline arms from the env in
``_run_with_config`` ahead of ``obs_metrics.arm()`` / ``obs_live.arm()``,
the serve daemon in its startup path, and the module itself arms at
import when ``TCR_LOCKCHECK`` is already set so subprocess e2es need no
code hook. :func:`assert_held` skips locks that predate arming (a plain
``Lock`` has no ``_is_owned``) rather than false-positive on them.
"""

from __future__ import annotations

import os
import sys
import threading

ENV_VAR = "TCR_LOCKCHECK"

#: bounded so a hot loop with a broken caller cannot grow without limit
MAX_VIOLATIONS = 100

_ARMED: bool = os.environ.get(ENV_VAR, "") not in ("", "0")
#: guards _VIOLATIONS — assert_held fires from any instrumented thread.
#: RLock, not Lock: the SIGUSR1 flush path can re-enter assert_held on
#: the main thread mid-append; reentrancy turns self-deadlock into a
#: harmless nested (GIL-atomic) append.
_VLOCK = threading.RLock()
_VIOLATIONS: list[str] = []


def armed() -> bool:
    return _ARMED


def arm() -> None:
    """Arm owner-assertions; locks made AFTER this call are checkable."""
    global _ARMED
    _ARMED = True


def disarm() -> None:
    global _ARMED
    _ARMED = False


def arm_from_env() -> bool | None:
    """Arm when ``TCR_LOCKCHECK`` is set (same hook shape as
    ``faults.arm_from_env``); returns None untouched when it is not."""
    if os.environ.get(ENV_VAR, "") in ("", "0"):
        return None
    arm()
    return True


def make_lock():
    """The lock constructor for every LOCK_OWNERSHIP lock.

    Disarmed: a plain ``threading.Lock`` — zero overhead, zero behavior
    change. Armed: a ``threading.RLock``, which (a) exposes
    ``_is_owned()`` for :func:`assert_held` and (b) stays
    ``threading.Condition``-compatible, so ``Condition(self._lock)``
    users (JobQueue) work identically under either.
    """
    return threading.RLock() if _ARMED else threading.Lock()


def assert_held(lock, label: str) -> None:
    """Record a violation if the calling thread does not own ``lock``.

    Planted in the ``*_locked`` caller-holds-the-lock contract methods.
    Disarmed this is one module-attribute check; armed it never raises
    (the run must complete so outputs can be compared byte-for-byte) —
    violations land on stderr and in :func:`violations`.
    """
    if not _ARMED:
        return
    is_owned = getattr(lock, "_is_owned", None)
    if is_owned is None or is_owned():
        return  # pre-arming plain Lock, or properly held
    msg = (f"lockcheck: {label} entered without owning its lock "
           f"(thread {threading.current_thread().name})")
    with _VLOCK:
        if len(_VIOLATIONS) < MAX_VIOLATIONS:
            _VIOLATIONS.append(msg)
    sys.stderr.write(msg + "\n")


def violations() -> list[str]:
    with _VLOCK:
        return list(_VIOLATIONS)


def reset() -> None:
    with _VLOCK:
        _VIOLATIONS.clear()
