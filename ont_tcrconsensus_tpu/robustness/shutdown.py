"""Preemption-safe shutdown: SIGTERM/SIGINT -> drain -> resumable exit.

Preemptible TPU VMs get SIGTERM with a short grace window. Dying mid-write
is already survivable (the manifest only marks FULLY complete stages, and
io/layout.py commits it atomically), but an uncontrolled death wastes the
whole in-flight library and can leave overlapped QC workers' failures
unreported. The coordinator turns the signal into a cooperative stop:

1. the first SIGTERM/SIGINT sets a flag (and logs); work in progress is
   NOT interrupted mid-dispatch,
2. the pipeline polls :func:`checkpoint` at stage boundaries and raises
   :class:`Preempted` at the first one after the flag,
3. the per-library guard in run.py drains the overlap executor's
   background stages (its existing BaseException path), the driver writes
   the robustness report, and the process exits with every committed
   checkpoint intact — ``resume=true`` continues byte-identically,
4. a second signal restores the default disposition and re-delivers, for
   operators who really mean "now".

:class:`Preempted` derives from ``BaseException`` on purpose: the
per-library ``except Exception`` degradation guard must never swallow a
preemption into "library failed, skipped".
"""

from __future__ import annotations

import os
import signal
import sys
import threading

from ont_tcrconsensus_tpu.robustness import jobscope


class Preempted(BaseException):
    """Raised at a stage-boundary checkpoint after a shutdown request."""

    def __init__(self, reason: str, site: str = ""):
        self.reason = reason
        self.site = site
        super().__init__(f"{reason} (observed at {site or 'checkpoint'})")


class ShutdownCoordinator:
    """Installable SIGTERM/SIGINT-to-checkpoint bridge (context manager)."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self._flag = threading.Event()
        self._reason: str | None = None
        self._previous: dict[int, object] = {}
        self._installed = False
        self._signals_seen = 0

    # --- request side -----------------------------------------------------

    def request(self, reason: str) -> None:
        """Ask for a stop at the next checkpoint (signal-handler and
        chaos-injection entry point; safe from any thread)."""
        self._reason = self._reason or reason
        self._flag.set()

    def _on_signal(self, signum, frame) -> None:
        # count REAL signals separately from cooperative requests (chaos
        # preempt, request()): the first actual SIGTERM after a cooperative
        # stop must still take the drain path, not the kill-now escalation
        self._signals_seen += 1
        if self._signals_seen > 1:
            # second signal: the operator means NOW — restore defaults and
            # re-deliver so the default disposition (terminate) applies
            sys.stderr.write(
                f"shutdown: second signal {signum}; exiting immediately\n"
            )
            self.uninstall()
            os.kill(os.getpid(), signum)
            return
        sys.stderr.write(
            f"shutdown: signal {signum} received; draining to the next "
            "stage boundary (resume=true continues this run)\n"
        )
        self.request(f"signal {signum}")

    # --- poll side --------------------------------------------------------

    def requested(self) -> bool:
        return self._flag.is_set()

    def checkpoint(self, site: str) -> None:
        if self._flag.is_set():
            raise Preempted(self._reason or "shutdown requested", site)

    # --- installation -----------------------------------------------------

    def install(self) -> bool:
        """Register handlers; False when not on the main thread (signal
        registration is main-thread-only — worker-thread pipelines still
        get cooperative stops via :func:`request`)."""
        if self._installed:
            return True
        try:
            for sig in self.SIGNALS:
                self._previous[sig] = signal.signal(sig, self._on_signal)
        except ValueError:  # not the main thread
            self._previous.clear()
            return False
        self._installed = True
        return True

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._previous.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass
        self._previous.clear()
        self._installed = False

    def __enter__(self) -> "ShutdownCoordinator":
        self.install()
        return activate(self)

    def __exit__(self, *exc) -> None:
        self.uninstall()
        deactivate(self)


# process-wide active coordinator, mirroring faults/retry: deep stage code
# polls checkpoints without plumbing the coordinator through signatures.
# Kept as a STACK so nesting works: the warm-serving daemon (serve/) holds
# an outer coordinator for its accept loop while each job's run.py guard
# activates an inner one — when the job deactivates, the daemon's
# coordinator must become active again, not None.
#
# Under a jobscope (the slice-packed runner pool) a run's coordinator
# binds THREAD-LOCALLY instead: each resident tenant job drains on its
# own coordinator, and a scoped checkpoint ALSO polls the process-global
# active one — that is how one SIGTERM on the daemon's coordinator
# preempts every resident job at its next stage boundary while a
# cooperative request() inside one job never touches its neighbors.
_ACTIVE: ShutdownCoordinator | None = None
_STACK: list[ShutdownCoordinator] = []


def activate(coord: ShutdownCoordinator) -> ShutdownCoordinator:
    global _ACTIVE
    if jobscope.active():
        jobscope.set("shutdown", coord)
        return coord
    _STACK.append(coord)
    _ACTIVE = coord
    return coord


def deactivate(coord: ShutdownCoordinator | None = None) -> None:
    """Pop ``coord`` (default: the top) off the active stack; the previous
    coordinator — if any — becomes active again."""
    global _ACTIVE
    if jobscope.active() and jobscope.get("shutdown") is coord:
        jobscope.set("shutdown", None)
        return
    if coord is None:
        if _STACK:
            _STACK.pop()
    elif coord in _STACK:
        _STACK.remove(coord)
    _ACTIVE = _STACK[-1] if _STACK else None


def request(reason: str) -> None:
    """Request a cooperative stop on the active coordinator (no-op when
    none is active — e.g. library code called outside run.py)."""
    coord = jobscope.get("shutdown")
    if coord is not None:
        coord.request(reason)
        return
    if _ACTIVE is not None:
        _ACTIVE.request(reason)


def checkpoint(site: str) -> None:
    """Raise :class:`Preempted` here if a stop was requested; free no-op
    otherwise (one global check, same discipline as faults.inject)."""
    coord = jobscope.get("shutdown")
    if coord is not None:
        coord.checkpoint(site)
    if _ACTIVE is not None:
        _ACTIVE.checkpoint(site)
