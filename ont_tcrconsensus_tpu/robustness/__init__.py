"""Fault-tolerant execution layer.

Three cooperating pieces, each a process-wide singleton so the deep call
sites (stages.py chunk loops, overlap.py workers, layout.py manifest
writes) need no config plumbing:

- :mod:`.faults` — deterministic fault-injection registry. Named injection
  points are planted at the pipeline's dispatch/commit/checkpoint sites;
  a chaos plan (config ``chaos`` key or ``TCR_CHAOS`` env JSON) arms
  specific faults at specific hit counts. Disarmed cost is one module
  attribute check per site.
- :mod:`.retry` — failure classification (transient device error vs HBM
  OOM vs deterministic bug), bounded exponential-backoff-plus-jitter
  retry, and the :class:`~.retry.RobustnessRecorder` behind the
  ``robustness_report.json`` artifact.
- :mod:`.shutdown` — preemption-safe SIGTERM/SIGINT handling: the first
  signal requests a stop, the pipeline raises :class:`~.shutdown.Preempted`
  at the next stage boundary, drains overlapped workers, and exits with
  every fully-committed checkpoint intact so ``resume=true`` continues
  byte-identically.
- :mod:`.watchdog` — liveness watchdog: per-stage soft/hard deadlines
  over a cheap ``heartbeat(site)`` API planted in the long-running loops
  (config ``stage_timeout_s``, auto-scaled by workload size). A soft
  expiry emits a ``watchdog.stall`` report event plus an all-thread stack
  dump to the library log; a hard expiry cancels the stalled stage with
  :class:`~.watchdog.StageTimeout`, which the classifier treats as a
  retryable transient — a hung dispatch re-enters the retry/degrade path
  instead of wedging the run.
- :mod:`.contracts` — stage-boundary conservation contracts: runtime
  accounting invariants (reads ingested == assigned + filtered +
  quarantined, UMI counts conserved across the rescue pass, consensus
  records == selected clusters, counts CSV == in-memory totals) in
  ``off|warn|strict`` modes, violations recorded in the same report.
"""

from ont_tcrconsensus_tpu.robustness import (  # noqa: F401
    contracts,
    faults,
    retry,
    shutdown,
    watchdog,
)
