"""graftcheck: semantic analysis of a built :class:`GraphSpec`.

Where :mod:`tools.graftlint` checks *source text*, this module checks the
*constructed* graph — the same object the executor schedules — by
abstract-interpreting the topological schedule with the executor's exact
residency rule (a value lives from its producer to its last consumer and
is dropped immediately after, unless it is a graph result).  Everything
here is jax-free, so ``--validate`` and ``python -m tools.graftcheck``
can prove properties of the production graph on machines with no
accelerator stack, before a single XLA compile.

Four analyses:

- **liveness** — the per-step live-hbm-edge set and a static HBM
  high-water model.  Per-edge byte estimates come from a ``byte_model``
  mapping (see :func:`production_byte_model`); the serial schedule is the
  lower bound — overlapped side sinks can only extend lifetimes.
- **donation safety** — the proof that buffer donation at each drop
  point is sound: every hbm edge has at least one consumer and is not a
  graph result, so no reference to its value can exist after the last
  consumer runs.  Each node's donation-eligible inputs (hbm edges whose
  last consumer it is) are reported; violations are ``donation-hazard``
  findings (an hbm edge the executor would never drop pins device memory
  until process exit).
- **placement flow** — every implicit device→host round-trip: a device
  node (one touching any hbm edge) produces a host edge whose value,
  possibly flowing through further host-only nodes, a later device node
  consumes.  Each such path is a ``placement-round-trip`` advisory — the
  ROADMAP-1 worklist, and its regression guard once the round1→round2
  hand-offs go device-resident.
- **sharding pairing** — ROADMAP-2 groundwork: a node whose hbm inputs
  and hbm outputs declare different :attr:`Edge.sharding` specs is a
  ``reshard-site`` violation (an implicit cross-device shuffle nothing
  asked for).

Severity is two-valued: ``violation`` (graph breaks a contract; callers
exit non-zero) and ``advisory`` (true, useful, not fatal — the
round-trip worklist).  :meth:`Report.summary` is the compact verdict
recorded in ``telemetry.json`` and the run-history ledger.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from ont_tcrconsensus_tpu.graph.ir import GraphSpec

SEVERITIES = ("violation", "advisory")

# Coarse planning constants for the production byte model: one padded
# read row is `2 * max_read_length` bytes (int8 codes + quals planes).
_PLANES = 2


@dataclasses.dataclass(frozen=True)
class Finding:
    """One semantic finding against the analyzed graph.

    ``path`` is the node/edge chain for flow findings (alternating node,
    edge, node, ...); for point findings it holds just the subject.
    """

    kind: str
    severity: str
    subject: str
    message: str
    path: tuple[str, ...] = ()

    def format(self) -> str:
        return f"[{self.severity}] {self.kind} at {self.subject}: {self.message}"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["path"] = list(self.path)
        return d

    def key(self) -> tuple:
        return (self.kind, self.subject, self.path)


@dataclasses.dataclass
class Report:
    """Everything :func:`analyze` proved about one graph."""

    graph: str
    findings: list[Finding]
    # [{"step", "node", "live_hbm", "hbm_bytes_est"}] per schedule step
    liveness: list[dict]
    hbm_high_water_bytes: int
    hbm_high_water_node: str | None
    # node -> hbm input edges whose buffers may be donated into the node
    donation_eligible: dict[str, list[str]]

    @property
    def violations(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == "violation"]

    @property
    def advisories(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == "advisory"]

    @property
    def verdict(self) -> str:
        if self.violations:
            return "violations"
        return "advisories" if self.advisories else "clean"

    def summary(self) -> dict:
        """Compact verdict for telemetry.json / the history ledger."""
        kinds: dict[str, int] = {}
        for f in self.findings:
            kinds[f.kind] = kinds.get(f.kind, 0) + 1
        return {
            "graph": self.graph,
            "verdict": self.verdict,
            "violations": len(self.violations),
            "advisories": len(self.advisories),
            "kinds": {k: kinds[k] for k in sorted(kinds)},
            "hbm_high_water_bytes_est": self.hbm_high_water_bytes,
            "hbm_high_water_node": self.hbm_high_water_node,
            "donation_safe": "donation-hazard" not in kinds,
        }

    def to_dict(self) -> dict:
        return {
            "summary": self.summary(),
            "findings": [f.to_dict() for f in self.findings],
            "liveness": self.liveness,
            "donation_eligible": {
                k: list(v) for k, v in sorted(self.donation_eligible.items())
            },
        }


def _is_device_node(spec: GraphSpec, name: str) -> bool:
    node = spec.nodes[name]
    return any(
        e in spec.edges and spec.edges[e].placement == "hbm"
        for e in node.inputs + node.outputs
    )


def _liveness(spec: GraphSpec, byte_model: dict[str, int],
              ) -> tuple[list[dict], int, str | None, dict[str, list[str]]]:
    """Walk the schedule with the executor's drop rule; returns the
    per-step table, the high-water, its node, and the donation table."""
    order = {n.name: i for i, n in enumerate(spec.schedule)}
    last_consumer: dict[str, int] = {}
    for e, users in spec.consumers.items():
        last_consumer[e] = max(order[u] for u in users)

    live: set[str] = set(spec.inputs)
    steps: list[dict] = []
    high_water, high_node = 0, None
    donation: dict[str, list[str]] = {}
    for i, node in enumerate(spec.schedule):
        live |= set(e for e in node.outputs if e in spec.edges)
        live_hbm = sorted(
            e for e in live
            if e in spec.edges and spec.edges[e].placement == "hbm"
        )
        hbm_bytes = sum(byte_model.get(e, 0) for e in live_hbm)
        steps.append({
            "step": i, "node": node.name, "live_hbm": live_hbm,
            "hbm_bytes_est": hbm_bytes,
        })
        if hbm_bytes > high_water or high_node is None:
            high_water, high_node = hbm_bytes, node.name
        eligible = []
        for e in node.inputs:
            if e not in spec.edges or e in spec.results:
                continue
            if last_consumer.get(e) == i:
                live.discard(e)
                if spec.edges[e].placement == "hbm":
                    eligible.append(e)
        if eligible:
            donation[node.name] = sorted(eligible)
    return steps, high_water, high_node, donation


def _donation_hazards(spec: GraphSpec) -> list[Finding]:
    out: list[Finding] = []
    for e, edge in sorted(spec.edges.items()):
        if edge.placement != "hbm":
            continue
        if e in spec.results:
            out.append(Finding(
                "donation-hazard", "violation", e,
                f"hbm edge {e!r} is a graph result — the executor never "
                "drops it, so its buffer cannot be donated and pins device "
                "memory through the whole remaining schedule",
                (e,),
            ))
        elif not spec.consumers.get(e) and e in spec.producer:
            out.append(Finding(
                "donation-hazard", "violation", e,
                f"hbm edge {e!r} (produced by "
                f"{spec.producer[e]!r}) has no consumer — the executor "
                "drops values at their last consumer, so this one is "
                "never dropped",
                (e,),
            ))
    return out


def _round_trips(spec: GraphSpec, max_hops: int = 8) -> list[Finding]:
    """DFS host-edge flows from each device node to the first device
    node downstream; each simple path is one round-trip finding."""
    device = {n.name for n in spec.schedule if _is_device_node(spec, n.name)}
    findings: list[Finding] = []

    def host_outputs(name: str) -> list[str]:
        # meta host edges (Edge.meta) carry orchestration metadata — stats,
        # groupings, selections — not device-derived bulk payload, so they
        # are not round-trip carriers; the transfer ledger still measures
        # their bytes per edge, keeping the declaration falsifiable
        return [e for e in spec.nodes[name].outputs
                if e in spec.edges and spec.edges[e].placement == "host"
                and not getattr(spec.edges[e], "meta", False)]

    def walk(path: tuple[str, ...], node: str) -> None:
        # path alternates node, edge, node, ... and starts at a device node
        if len(path) > 2 * max_hops:
            return
        for e in host_outputs(node):
            for consumer in spec.consumers.get(e, ()):
                if consumer in path:
                    continue
                nxt = path + (e, consumer)
                if consumer in device:
                    findings.append(Finding(
                        "placement-round-trip", "advisory", path[0],
                        "device value leaves hbm at "
                        + " -> ".join(
                            (f"[{p}]" if i % 2 else p)
                            for i, p in enumerate(nxt)
                        )
                        + f" — {consumer!r} pays an implicit host "
                        "round-trip re-upload",
                        nxt,
                    ))
                else:
                    walk(nxt, consumer)

    for name in sorted(device):
        walk((name,), name)
    findings.sort(key=lambda f: f.path)
    return findings


def round_trip_edges(spec: GraphSpec) -> set[str]:
    """Host-placed edges that sit on any placement-round-trip path.

    The measured twin of :func:`_round_trips`: the graph executor charges
    these edges' materialized bytes to the run-level
    ``host_round_trip_bytes`` ledger (obs/transfers.py), so the static
    advisory and the runtime number name the same flows. Finding paths
    alternate node, edge, node, ... — the edges sit at odd indices.
    """
    out: set[str] = set()
    for f in _round_trips(spec):
        out.update(p for i, p in enumerate(f.path) if i % 2)
    return out


def donation_plan(spec: GraphSpec) -> dict[str, frozenset[str]]:
    """node name -> hbm input edges whose buffers it may consume in place.

    The executor-facing face of the liveness donation proof: an hbm edge
    whose last consumer is ``node`` (and which is not a graph result) is
    dropped by the executor immediately after ``node`` runs, so no live
    reference to its value can exist afterwards and the node's jitted
    entry may take the buffer via ``donate_argnums``.  Byte estimates are
    irrelevant to the proof, so no byte model is consulted.
    """
    donation = _liveness(spec, {})[3]
    return {node: frozenset(edges) for node, edges in donation.items()}


def _reshard_sites(spec: GraphSpec) -> list[Finding]:
    out: list[Finding] = []
    for node in spec.schedule:
        in_specs = sorted({
            spec.edges[e].sharding for e in node.inputs
            if e in spec.edges and spec.edges[e].placement == "hbm"
            and spec.edges[e].sharding is not None
        })
        out_specs = sorted({
            spec.edges[e].sharding for e in node.outputs
            if e in spec.edges and spec.edges[e].placement == "hbm"
            and spec.edges[e].sharding is not None
        })
        if in_specs and out_specs and in_specs != out_specs:
            out.append(Finding(
                "reshard-site", "violation", node.name,
                f"node {node.name!r} consumes hbm sharding "
                f"{in_specs} but produces {out_specs} — an implicit "
                "cross-device reshard nothing declared",
                (node.name,),
            ))
    return out


def reshard_sites(spec: GraphSpec) -> list[Finding]:
    """Public face of the reshard-pairing proof, for the executor.

    The sharded execution layer refuses to run a graph with reshard-site
    violations: the executor derives each node's paired in/out shardings
    from the declared edges (parallel/mesh.py ``node_sharding_plan``), and
    that pairing is only a *plan* — not a proof — if some node's declared
    inputs and outputs disagree. Same findings ``analyze`` reports; this
    entry point skips the liveness walk so the runtime gate stays cheap.
    """
    return _reshard_sites(spec)


def analyze(spec: GraphSpec, byte_model: dict[str, int] | None = None,
            ) -> Report:
    """Run every semantic analysis over one built graph."""
    model = byte_model or {}
    steps, high_water, high_node, donation = _liveness(spec, model)
    findings = (
        _donation_hazards(spec) + _reshard_sites(spec) + _round_trips(spec)
    )
    findings.sort(key=lambda f: (f.severity, f.kind, f.subject, f.path))
    return Report(
        graph=spec.name, findings=findings, liveness=steps,
        hbm_high_water_bytes=high_water, hbm_high_water_node=high_node,
        donation_eligible=donation,
    )


def production_byte_model(cfg: Any, n_reads: int = 10_000) -> dict[str, int]:
    """Coarse per-edge HBM byte estimates for the production graph.

    A planning model, not an accountant: one padded read row costs
    ``_PLANES * cfg.max_read_length`` bytes (int8 code + qual planes) and
    round-2 holds one consensus row per round-1 cluster at the configured
    minimum depth.  Good for the *shape* of the liveness curve and for
    cross-run regression ratios; the runtime HBM high-water sampler
    (obs/device.py) remains the ground truth.
    """
    row = _PLANES * int(getattr(cfg, "max_read_length", 4096))
    depth = max(1, int(getattr(cfg, "min_reads_per_cluster", 4)))
    n_cons = max(1, int(n_reads) // depth)
    return {
        "read_store": int(n_reads) * row,
        "cons_store": n_cons * row,
    }
