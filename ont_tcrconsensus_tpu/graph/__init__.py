"""Declarative stage dataflow graph (ROADMAP item 5).

The pipeline's round1→round2 stage chain is declared as a graph of nodes
(stages with typed inputs/outputs, workload units, resume keys) connected
by edges (named artifacts with a placement of ``hbm`` / ``host`` /
``disk``).  :mod:`.ir` holds the IR and the validating builder,
:mod:`.nodes` the stage bodies, :mod:`.pipeline` the production graph
declaration, and :mod:`.executor` the topological scheduler that runs it
— attaching watchdog guards, chaos injection, obs spans/metrics, and
manifest-v2 resume per node instead of per call site, and deriving which
nodes run off the critical path from edge consumption alone (subsuming
overlap.py's hand-wired QC special case).

Everything here except the node *bodies* is jax-free, so ``--validate``
and ``--report`` can build and check graphs on machines without an
accelerator stack.

``GRAPH_NODES`` is the closed vocabulary of production node names,
cross-checked by graftlint's graph-sites rule against declarations and
the obs registry (the distinct assignment name keeps the chaos rule,
which collects every ``KNOWN_SITES = ...`` literal, from merging the two
vocabularies).
"""

GRAPH_NODES = frozenset({
    # round 1
    "round1_fused_assign",
    "round1_error_profile",
    "round1_region_split",
    "write_region_fastas",
    "round1_umi_records",
    "round1_umi_cluster",
    "round1_polish",
    "round1_consensus",
    # round 2
    "round2_fused_assign",
    "round2_error_profile",
    "round2_umi_records",
    "round2_umi_cluster",
    "round2_counts",
})

KNOWN_NODES = GRAPH_NODES  # public alias; see module docstring
